"""Incremental delta-driven solve: the persistent candidate cache must be
EXACT, not just safe.

Two layers of property coverage:

- ops level: for random node/pod delta sequences, the dirty-column merge
  (+ dirty-pod rescore) must reproduce ``select_candidates``'s output
  bit-for-bit (valid slots: same nodes, same keys, same order) and the
  propose/accept rounds must produce identical assignments;
- scheduler level: a scheduler with the incremental path on must make the
  SAME acceptance decisions as one with it off, round for round, across
  arrivals, binds, node churn and usage refreshes — with the incremental
  path actually taken (asserted via ``last_solve_path``).

The cache-invalidation contract under test: a stale candidate may cost
recall, never correctness — acceptance re-checks fit and quota exactly
(no assignment may overcommit a node, asserted every round), and the
dirty tracking is what keeps recall exact.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import prop_seeds
from tests.problem_helpers import build_problem

from koordinator_tpu.api.resources import resource_vector
from koordinator_tpu.ops.batch_assign import (
    CandidateCache,
    _assign_rounds,
    align_candidate_cache,
    refresh_candidates,
    scatter_candidate_rows,
    select_candidates,
)
from koordinator_tpu.scheduler.scheduler import Scheduler
from koordinator_tpu.scheduler.snapshot import (
    ClusterSnapshot,
    NodeSpec,
    PodSpec,
)
from koordinator_tpu.state.cluster_state import _bucket

K = 8
N_NODES = 64


# jitted once per process: the ops-level property loop re-invokes these
# dozens of times across steps and seeds — the jit cache amortizes the
# compile the way the scheduler's persistent wrappers do
_align_j = jax.jit(align_candidate_cache)
_refresh_j = jax.jit(refresh_candidates, static_argnames=("k",))
_scatter_j = jax.jit(scatter_candidate_rows)
_select_j = jax.jit(select_candidates,
                    static_argnames=("k", "method", "with_scores"))
_rounds_j = jax.jit(_assign_rounds, static_argnames=("rounds",))


def _incremental_step(state, pods, cache, dirty_rows, dirty_pod_rows):
    """One ops-level incremental refresh: merge dirty columns, rescore
    dirty pods, return the new cache — the same sequence
    Scheduler._solve_batch_incremental drives."""
    from koordinator_tpu.ops.assignment import ScoringConfig

    cfg = ScoringConfig.default()
    n = state.capacity
    p = pods.capacity
    dirty_np = np.zeros(n, bool)
    dirty_np[dirty_rows] = True
    dpad = _bucket(max(len(dirty_rows), 1), minimum=8)
    drows = np.zeros(dpad, np.int32)
    drows[: len(dirty_rows)] = dirty_rows
    dvalid = np.zeros(dpad, bool)
    dvalid[: len(dirty_rows)] = True
    aligned, touch = _align_j(
        cache, jnp.arange(p, dtype=jnp.int32), jnp.ones(p, bool),
        jnp.asarray(dirty_np))
    dirty_pods = np.asarray(touch).copy()
    dirty_pods[dirty_pod_rows] = True
    cand_key, cache = _refresh_j(
        state, pods, cfg, aligned, jnp.asarray(drows), jnp.asarray(dvalid),
        k=K)
    if dirty_pods.any():
        small, idx = pods.compact(dirty_pods)
        sk, sn, ss = _select_j(state, small, cfg, k=K,
                               method="exact", with_scores=True)
        rows_pad = np.full(small.capacity, p, np.int32)
        rows_pad[: len(idx)] = idx
        cache = _scatter_j(cache, jnp.asarray(rows_pad), sk, sn, ss)
    return cache


@pytest.mark.parametrize("seed", prop_seeds(2))
def test_refresh_matches_full_selection_random_deltas(seed):
    """Random delta sequences: merged candidates == full-pass candidates
    bit-for-bit, and the propose/accept assignments are identical."""
    from koordinator_tpu.ops.assignment import ScoringConfig

    cfg = ScoringConfig.default()
    rng = np.random.default_rng(seed)
    state, pods = build_problem(n_nodes=N_NODES, n_pods=192,
                                seed=seed, invalid_tail=4)
    ck, cn, cs = _select_j(state, pods, cfg, k=K, method="exact",
                           with_scores=True)
    cache = CandidateCache(ck, cn, cs)

    for step in range(6):
        # node delta: usage / requested / allocatable / validity flips
        rows = np.unique(rng.integers(0, N_NODES, rng.integers(1, 6)))
        usage = np.asarray(state.node_usage).copy()
        req = np.asarray(state.node_requested).copy()
        valid = np.asarray(state.node_valid).copy()
        usage[rows] = (usage[rows] * rng.uniform(0.3, 1.7)).astype(np.int32)
        alloc = np.asarray(state.node_allocatable)
        req[rows] = np.clip(
            req[rows] + rng.integers(-2_000, 4_000, req[rows].shape),
            0, alloc[rows]).astype(np.int32)
        flip = rows[rng.random(len(rows)) < 0.2]
        valid[flip] = ~valid[flip]
        state = state.replace(node_usage=jnp.asarray(usage),
                              node_requested=jnp.asarray(req),
                              node_valid=jnp.asarray(valid))
        # pod delta: a few pods change their requests ("new" pods)
        pd = np.unique(rng.integers(0, 192, rng.integers(0, 4)))
        if len(pd):
            preq = np.asarray(pods.requests).copy()
            preq[pd, 0] = rng.integers(100, 6_000, len(pd))
            pods = pods.replace(requests=jnp.asarray(preq))

        cache = _incremental_step(state, pods, cache, rows, pd)
        fk, fn = _select_j(state, pods, cfg, k=K, method="exact")

        fk_np, fn_np = np.asarray(fk), np.asarray(fn)
        ik_np, in_np = np.asarray(cache.cand_key), np.asarray(cache.cand_node)
        valid_slots = fk_np >= 0
        assert (valid_slots == (ik_np >= 0)).all(), f"step {step}: validity"
        assert (fk_np[valid_slots] == ik_np[valid_slots]).all(), \
            f"step {step}: keys diverged"
        assert (fn_np[valid_slots] == in_np[valid_slots]).all(), \
            f"step {step}: nodes diverged"

        fa, fst, _ = _rounds_j(state, pods, None, fk, fn, rounds=12)
        ia, ist, _ = _rounds_j(state, pods, None, cache.cand_key,
                               cache.cand_node, rounds=12)
        assert (np.asarray(fa) == np.asarray(ia)).all(), \
            f"step {step}: assignments diverged"
        # acceptance exactness: never overcommit, stale cache or not
        assert (np.asarray(ist.node_requested)
                <= np.asarray(ist.node_allocatable)
                ).all(axis=-1)[np.asarray(ist.node_valid)].all()


def _mk_sched(incremental: bool, quota_tree=None, **kw):
    # mesh="off" keeps this module's parity pairs on the single-device
    # path; tests/test_sharded_solve.py overrides with mesh="auto" +
    # shard_min_nodes=0 to run the same drivers over the 8-way mesh
    kw.setdefault("mesh", "off")
    sched = Scheduler(ClusterSnapshot(capacity=32),
                      quota_tree=quota_tree,
                      batch_solver_threshold=1,   # force the batch engine
                      incremental_solve=incremental,
                      **kw)
    return sched


def _feed_nodes(sched, rng, n=12):
    for i in range(n):
        sched.snapshot.upsert_node(NodeSpec(
            name=f"n{i}",
            allocatable=resource_vector(
                cpu=int(rng.integers(8_000, 32_000)),
                memory=int(rng.integers(16_384, 65_536))),
            usage=resource_vector(cpu=int(rng.integers(0, 2_000)),
                                  memory=int(rng.integers(0, 4_096)))))


def _pod(rng, name):
    return PodSpec(
        name=name,
        requests=resource_vector(cpu=int(rng.integers(200, 4_000)),
                                 memory=int(rng.integers(256, 8_192))),
        priority=int(rng.integers(3_000, 9_999)))


def _assert_no_overcommit(sched):
    st = sched.snapshot.state
    ok = (np.asarray(st.node_requested)
          <= np.asarray(st.node_allocatable)).all(axis=-1)
    assert ok[np.asarray(st.node_valid)].all(), "node overcommitted"


@pytest.mark.parametrize("seed", prop_seeds(1))
def test_scheduler_incremental_equals_full(seed):
    """Round-for-round identical acceptance decisions between a scheduler
    with the incremental candidate cache and one without, across a random
    churn sequence (arrivals, binds draining the queue, node add/remove,
    usage refreshes)."""
    rng_a, rng_b = (np.random.default_rng(seed),
                    np.random.default_rng(seed))
    inc, full = _mk_sched(True), _mk_sched(False)
    # the small 12-node cluster makes bind deltas a large node FRACTION;
    # force the incremental path so churn exercises the merge machinery
    # (the fallback flip has its own test)
    inc.incremental_dirty_threshold = 1.0
    _feed_nodes(inc, rng_a)
    _feed_nodes(full, rng_b)

    pod_i = 0
    took_incremental = False
    for rnd in range(6):
        # arrivals (same on both sides)
        for _ in range(int(np.random.default_rng(seed * 101 + rnd
                                                 ).integers(1, 6))):
            name = f"p{pod_i}"
            pod_seed = seed * 1_000_003 + pod_i
            pod_i += 1
            inc.enqueue(_pod(np.random.default_rng(pod_seed), name))
            full.enqueue(_pod(np.random.default_rng(pod_seed), name))
        drv = np.random.default_rng(seed * 7919 + rnd)
        if rnd >= 2 and drv.random() < 0.5:
            # usage refresh on a couple of nodes
            for i in np.unique(drv.integers(0, 12, 2)):
                name = f"n{i}"
                if name not in inc.snapshot.node_specs:
                    continue
                spec = inc.snapshot.node_specs[name]
                import dataclasses as _dc

                new_usage = resource_vector(
                    cpu=int(drv.integers(0, 6_000)),
                    memory=int(drv.integers(0, 8_192)))
                inc.snapshot.upsert_node(_dc.replace(spec, usage=new_usage))
                full.snapshot.upsert_node(
                    _dc.replace(full.snapshot.node_specs[name],
                                usage=new_usage))
        if rnd == 5:
            # node churn: remove one, add a fresh one
            inc.snapshot.remove_node("n3")
            full.snapshot.remove_node("n3")
            extra = NodeSpec(name="n-extra",
                             allocatable=resource_vector(cpu=24_000,
                                                         memory=49_152))
            inc.snapshot.upsert_node(extra)
            full.snapshot.upsert_node(extra)

        ra = inc.schedule_round()
        rb = full.schedule_round()
        assert ra.assignments == rb.assignments, f"round {rnd}"
        assert set(ra.failures) == set(rb.failures), f"round {rnd}"
        _assert_no_overcommit(inc)
        if inc.last_solve_path == "incremental":
            took_incremental = True
    assert took_incremental, \
        "the incremental path never engaged over the steady-state rounds"


def test_scheduler_incremental_equals_full_with_quota():
    """Same equality under elastic-quota admission + charging."""
    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
    from koordinator_tpu.quota.tree import QuotaTree

    def tree():
        total = np.zeros(NUM_RESOURCE_DIMS, np.int64)
        total[0], total[1] = 200_000, 400_000
        t = QuotaTree(total_resource=total)
        mn = np.zeros(NUM_RESOURCE_DIMS, np.int64)
        mn[0] = 20_000
        mx = np.full(NUM_RESOURCE_DIMS, 60_000, np.int64)
        t.add("qa", min=mn, max=mx)
        t.add("qb", min=mn, max=mx)
        t.refresh_runtime()
        return t

    rng = np.random.default_rng(11)
    inc, full = _mk_sched(True, tree()), _mk_sched(False, tree())
    _feed_nodes(inc, np.random.default_rng(11))
    _feed_nodes(full, np.random.default_rng(11))
    for rnd in range(4):
        for j in range(4):
            name = f"q{rnd}-{j}"
            quota = "qa" if j % 2 == 0 else "qb"
            pod = PodSpec(
                name=name,
                requests=resource_vector(
                    cpu=int(rng.integers(500, 8_000)),
                    memory=int(rng.integers(512, 8_192))),
                priority=5_000 + j, quota=quota)
            import copy

            inc.enqueue(pod)
            full.enqueue(copy.deepcopy(pod))
        ra = inc.schedule_round()
        rb = full.schedule_round()
        assert ra.assignments == rb.assignments, f"round {rnd}"
        assert set(ra.failures) == set(rb.failures), f"round {rnd}"
        _assert_no_overcommit(inc)


def test_dirty_fraction_fallback_flips_to_full_pass():
    """Crossing incremental_dirty_threshold must fall back to the full
    selection (observable via last_solve_path + the metrics counter) and
    still produce full-pass decisions."""
    from koordinator_tpu import metrics

    rng = np.random.default_rng(5)
    inc, full = _mk_sched(True), _mk_sched(False)
    inc.incremental_dirty_threshold = 0.0   # any delta ⇒ fallback
    _feed_nodes(inc, np.random.default_rng(5))
    _feed_nodes(full, np.random.default_rng(5))
    for i in range(3):
        p = _pod(np.random.default_rng(100 + i), f"p{i}")
        import copy

        inc.enqueue(p)
        full.enqueue(copy.deepcopy(p))
    before = metrics.incremental_solve_total.value(
        labels={"path": "full_fallback"})
    assert inc.schedule_round().assignments == \
        full.schedule_round().assignments
    assert inc.last_solve_path == "full_cold"
    # second round: cache exists, but threshold 0 forces the fallback
    # (the bind deltas from round 1 dirtied the assigned nodes)
    p = _pod(rng, "late")
    import copy

    inc.enqueue(p)
    full.enqueue(copy.deepcopy(p))
    ra, rb = inc.schedule_round(), full.schedule_round()
    assert ra.assignments == rb.assignments
    assert inc.last_solve_path == "full_fallback"
    assert metrics.incremental_solve_total.value(
        labels={"path": "full_fallback"}) == before + 1


def test_unchanged_queue_rounds_reuse_cache_without_rescore():
    """Repeated rounds over an unchanged, unschedulable queue must take
    the incremental path with ZERO dirty pods (the whole point: O(delta)
    instead of O(P·N) per steady-state round)."""
    from koordinator_tpu import metrics

    sched = _mk_sched(True)
    sched.snapshot.upsert_node(NodeSpec(
        name="small", allocatable=resource_vector(cpu=1_000, memory=1_024)))
    for i in range(4):
        sched.enqueue(PodSpec(
            name=f"big{i}",
            requests=resource_vector(cpu=50_000, memory=100_000),
            priority=5_000))
    r = sched.schedule_round()
    assert not r.assignments and sched.last_solve_path == "full_cold"
    r = sched.schedule_round()
    assert not r.assignments and sched.last_solve_path == "incremental"
    assert metrics.incremental_dirty_pods.value() == 0.0


@pytest.mark.slow
def test_incremental_speedup_at_shape():
    """The delta-scaling claim at 12,800p × 2,560n on CPU: a steady-state
    round with ≤1% dirty nodes/pods must run ≥5× faster than the full
    pass (the bench records the same numbers as extras)."""
    import time

    from koordinator_tpu.ops.assignment import ScoringConfig
    from koordinator_tpu.ops.batch_assign import (
        assign_round_pass,
        batch_assign,
    )

    cfg = ScoringConfig.default()
    state, pods = build_problem(n_nodes=2_560, n_pods=12_800, seed=42,
                                factored=False, classes=1)
    full = jax.jit(lambda s, p: batch_assign(s, p, cfg, k=16,
                                             method="exact")[0])
    np.asarray(full(state, pods))
    t_full = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(full(state, pods))
        t_full.append(time.perf_counter() - t0)

    ck, cn, cs = select_candidates(state, pods, cfg, k=16, method="exact",
                                   with_scores=True)
    cache = CandidateCache(ck, cn, cs)
    dirty = np.arange(25)          # ~1% of 2,560 nodes
    dirty_pod_rows = np.arange(0)  # no pod churn
    refresh = jax.jit(lambda st, p, c, dr, dv: refresh_candidates(
        st, p, cfg, c, dr, dv, k=16))
    rounds = jax.jit(lambda st, p, ck_, cn_: assign_round_pass(
        st, p, None, ck_, cn_, cfg)[0])
    dpad = _bucket(len(dirty), minimum=8)
    drows = np.zeros(dpad, np.int32)
    drows[: len(dirty)] = dirty
    dvalid = np.zeros(dpad, bool)
    dvalid[: len(dirty)] = True

    def inc_round():
        k2, c2 = refresh(state, pods, cache, jnp.asarray(drows),
                         jnp.asarray(dvalid))
        return np.asarray(rounds(state, pods, k2, c2.cand_node))

    inc_round()  # compile
    t_inc = []
    for _ in range(3):
        t0 = time.perf_counter()
        inc_round()
        t_inc.append(time.perf_counter() - t0)
    speedup = float(np.median(t_full)) / max(float(np.median(t_inc)), 1e-9)
    assert speedup >= 5.0, (
        f"incremental round only {speedup:.1f}x faster "
        f"(full {np.median(t_full):.3f}s, inc {np.median(t_inc):.3f}s)")


def test_conservative_rebuild_after_donated_state_loss():
    """The donation disaster path: if a jitted solve fails at EXECUTION
    time its donated state buffers are gone.  rebuild_conservative must
    leave a live, never-overcommitting scheduler (fully-booked nodes,
    no crash) that recovers capacity through node churn/resync."""
    sched = _mk_sched(True)
    _feed_nodes(sched, np.random.default_rng(3))
    sched.enqueue(_pod(np.random.default_rng(1), "a"))
    sched.schedule_round()

    # simulate the post-donation failure: every state buffer deleted
    for leaf in jax.tree.leaves(sched.snapshot.state):
        leaf.delete()
    sched.snapshot.rebuild_conservative()
    sched._cand_cache = None

    sched.enqueue(_pod(np.random.default_rng(2), "b"))
    r = sched.schedule_round()
    assert "b" in r.failures and not r.assignments
    _assert_no_overcommit(sched)

    # a fresh node restores schedulability (its row starts clean)
    sched.snapshot.upsert_node(NodeSpec(
        name="fresh",
        allocatable=resource_vector(cpu=8_000, memory=16_384)))
    r2 = sched.schedule_round()
    assert r2.assignments.get("b") == "fresh"
    _assert_no_overcommit(sched)
