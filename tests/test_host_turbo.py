"""Host-plane turbo (ISSUE 19): the acceptance suite.

The contracts under test:

- **wire codec v2**: the columnar event packing round-trips to the
  exact v1 entry list for every event kind; corrupt columns fail with
  typed ``WireSchemaError``; unknown kinds fall back to v1;
- **protocol negotiation**: HELLO speaks min(peer, local) within the
  supported window — v4 peers get columnar DELTA/SNAPSHOT frames, v3
  peers keep the per-event JSON lists, out-of-window peers are
  rejected loud; a mixed-version fleet converges under the chaos
  fault layer (duplicated/reordered pushes);
- **decode zero-copy policy**: a small decoded array no longer pins
  the whole frame payload (the 4-byte-array-holds-a-multi-MB-snapshot
  aliasing bug);
- **vectorized deltasync apply**: contiguous same-kind event runs
  route through one batched binding apply that is bit-identical to
  the per-event loop;
- **batched bind commits**: one batched commit per round produces the
  same bound registry, quota charges, and per-pod surfaces as the
  sequential ``_commit_bind`` loop;
- **quality tenants in the tenant-axis program**: ``lp``-mode tenants
  join the batched cycle (their own vmapped ``lp_pack_assign``
  program) and bind exactly what serial per-tenant execution binds.
"""

import json
import socket
import struct
import sys
import threading
import time

import numpy as np
import pytest

from koordinator_tpu.transport import deltasync, wire
from koordinator_tpu.transport.channel import (
    RpcClient,
    RpcError,
    RpcRemoteError,
    RpcServer,
)
from koordinator_tpu.transport.deltasync import (
    SchedulerBinding,
    StateSyncClient,
    StateSyncService,
    _decode_events,
    _dispatch_event,
    _dispatch_events,
    _pack_events,
    _pack_events_v2,
    _unpack_event_arrays,
)
from koordinator_tpu.transport.wire import FrameType, WireSchemaError


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _r(**kw):
    from koordinator_tpu.api.resources import resource_vector

    return resource_vector(**kw)


def _all_kind_events():
    """One event of every kind, with both default and non-default doc
    fields exercised."""
    return [
        (1, {"kind": deltasync.NODE_UPSERT, "name": "n0",
             "labels": {"rack": "r1"}, "taints": {}, "annotations": {},
             "devices": {}},
         {"allocatable": np.arange(4, dtype=np.int32),
          "usage": np.zeros(4, np.int32)}),
        (2, {"kind": deltasync.NODE_USAGE, "name": "n0"},
         {"usage": np.ones(4, np.int32),
          "agg_usage": np.full(4, 2, np.int32)}),
        (3, {"kind": deltasync.NODE_ALLOC, "name": "n0"},
         {"allocatable": np.full(4, 9, np.int32)}),
        (4, {"kind": deltasync.NODE_DEVICES, "name": "n0",
             "devices": {"gpu": [{"core": 100, "memory": 8,
                                  "group": "g0"}]}}, {}),
        (5, {"kind": deltasync.POD_ADD, "name": "p0", "priority": 7,
             "quota": "q", "gang": None, "node_selector": {},
             "labels": {"team": "x"}, "owner": None, "qos": 0},
         {"requests": np.ones(4, np.int32)}),
        (6, {"kind": deltasync.POD_REMOVE, "name": "p0"}, {}),
        (7, {"kind": deltasync.RSV_UPSERT, "name": "rsv0",
             "owners": [{"labels": {"team": "x"}}],
             "allocate_once": False, "ttl_sec": None, "node": None,
             "node_selector": {}, "tolerations": {},
             "restricted": True},
         {"requests": np.ones(4, np.int64)}),
        (8, {"kind": deltasync.RSV_REMOVE, "name": "rsv0"}, {}),
        (9, {"kind": deltasync.NODE_REMOVE, "name": "n0"}, {}),
    ]


def _sync_server(tmp_path, name="sync.sock", faults=None):
    path = str(tmp_path / name)
    server = RpcServer(path, faults=faults)
    service = StateSyncService()
    service.attach(server)
    server.start()
    return path, server, service


def _scheduler(capacity=16, quota=False, **kw):
    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
    from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree
    from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler

    tree = None
    if quota:
        total = np.zeros(NUM_RESOURCE_DIMS, np.int64)
        total[0] = 500_000
        tree = QuotaTree(total)
        mx = np.full(NUM_RESOURCE_DIMS, UNBOUNDED, np.int64)
        tree.add("q", min=np.zeros(NUM_RESOURCE_DIMS, np.int64), max=mx)
        tree.add("q2", min=np.zeros(NUM_RESOURCE_DIMS, np.int64), max=mx)
    return Scheduler(ClusterSnapshot(capacity=capacity),
                     quota_tree=tree, **kw)


def _feed_nodes(sched, n=8, seed=5):
    from koordinator_tpu.scheduler.snapshot import NodeSpec

    rng = np.random.default_rng(seed)
    for i in range(n):
        sched.snapshot.upsert_node(NodeSpec(
            name=f"n{i}",
            allocatable=_r(cpu=int(rng.integers(8_000, 32_000)),
                           memory=int(rng.integers(16_384, 65_536))),
            usage=_r(cpu=int(rng.integers(0, 1_000)),
                     memory=int(rng.integers(0, 2_048)))))


def _pod(seed, name, quota=None, non_preemptible=False):
    from koordinator_tpu.scheduler.snapshot import PodSpec

    rng = np.random.default_rng(seed)
    return PodSpec(
        name=name,
        requests=_r(cpu=int(rng.integers(200, 2_000)),
                    memory=int(rng.integers(256, 4_096))),
        priority=int(rng.integers(3_000, 9_999)),
        quota=quota, non_preemptible=non_preemptible)


# ---------------------------------------------------------------------------
# wire codec v2
# ---------------------------------------------------------------------------


class TestWireCodecV2:
    def test_columnar_roundtrip_identical_all_kinds(self):
        """v2 pack -> wire encode -> decode -> unpack reconstructs the
        EXACT v1 entry list (docs and arrays), for every event kind."""
        events = _all_kind_events()
        d1, a1 = _pack_events(events)
        packed = _pack_events_v2(events)
        assert packed is not None
        d2, a2 = packed
        d2r, a2r = wire.decode_payload(wire.encode_payload(dict(d2), a2))
        assert _decode_events(d2r, a2r) == d1["events"]
        for key, block in a1.items():
            np.testing.assert_array_equal(block, a2r[key])
        # per-event array extraction works unchanged on v2 blocks
        for entry in _decode_events(d2r, a2r):
            _unpack_event_arrays(entry, a2r)

    def test_hot_kinds_carry_no_extras(self):
        """Steady-state kinds (node_usage, pod_remove) must ride pure
        columns — zero per-event JSON."""
        events = [(i, {"kind": deltasync.NODE_USAGE, "name": f"n{i}"},
                   {"usage": np.ones(4, np.int32)}) for i in range(64)]
        doc, _ = _pack_events_v2(events)
        assert doc == {"events_v2": 64}

    def test_unknown_kind_falls_back_to_v1(self):
        assert _pack_events_v2(
            [(1, {"kind": "future_kind", "name": "x"}, {})]) is None

    def test_missing_column_raises_schema_error(self):
        doc, arrays = _pack_events_v2(_all_kind_events())
        broken = {k: v for k, v in arrays.items() if k != "__kinds__"}
        with pytest.raises(WireSchemaError, match="__kinds__"):
            _decode_events(doc, broken)

    def test_corrupt_string_column_raises_schema_error(self):
        doc, arrays = _pack_events_v2(_all_kind_events())
        arrays = dict(arrays)
        arrays["__name_blob__"] = arrays["__name_blob__"][:-2]
        with pytest.raises(WireSchemaError, match="lengths sum"):
            _decode_events(doc, arrays)


# ---------------------------------------------------------------------------
# decode_payload zero-copy policy (satellite)
# ---------------------------------------------------------------------------


class TestDecodeAliasing:
    def test_small_array_does_not_pin_payload(self):
        """The regression this satellite fixes: decoding a payload that
        carries one huge and one tiny array must not leave the tiny
        array's lifetime pinning the whole payload buffer."""
        big = np.arange(1 << 20, dtype=np.uint8)
        small = np.arange(4, dtype=np.int32)
        payload = wire.encode_payload({}, {"big": big, "small": small})
        base_refs = sys.getrefcount(payload)
        _doc, arrays = wire.decode_payload(payload)
        # the small array was copied out: no buffer aliasing at all
        assert arrays["small"].base is None
        np.testing.assert_array_equal(arrays["small"], small)
        # keep ONLY the small array; the payload's refcount must fall
        # back to its baseline (nothing but our local name holds it)
        keep = arrays["small"]
        del arrays, _doc
        assert sys.getrefcount(payload) == base_refs
        np.testing.assert_array_equal(keep, small)

    def test_dominant_array_stays_zero_copy(self):
        """The majority block keeps the zero-copy view — copying a
        multi-MB snapshot block would re-introduce the codec cost the
        framing exists to avoid."""
        big = np.arange(1 << 20, dtype=np.uint8)
        payload = wire.encode_payload({}, {"big": big})
        _doc, arrays = wire.decode_payload(payload)
        assert arrays["big"].base is not None
        np.testing.assert_array_equal(arrays["big"], big)


# ---------------------------------------------------------------------------
# protocol negotiation (satellite)
# ---------------------------------------------------------------------------


class TestHelloNegotiation:
    def test_v4_peer_gets_columnar_snapshot(self, tmp_path):
        path, server, service = _sync_server(tmp_path)
        try:
            service.upsert_node("n0", _r(cpu=1000, memory=1024))
            client = RpcClient(path)
            client.connect()
            ftype, doc, arrays = client.call(
                FrameType.HELLO,
                {"last_rv": -1, "proto": wire.PROTOCOL_VERSION})
            assert ftype is FrameType.SNAPSHOT
            assert doc["proto"] == wire.PROTOCOL_VERSION
            assert "events_v2" in doc and "events" not in doc
            assert "__kinds__" in arrays
            client.close()
        finally:
            server.stop()

    def test_v3_peer_gets_v1_events(self, tmp_path):
        path, server, service = _sync_server(tmp_path)
        try:
            service.upsert_node("n0", _r(cpu=1000, memory=1024))
            client = RpcClient(path)
            client.connect()
            ftype, doc, arrays = client.call(
                FrameType.HELLO,
                {"last_rv": -1, "proto": wire.MIN_PROTOCOL_VERSION})
            assert ftype is FrameType.SNAPSHOT
            assert doc["proto"] == wire.MIN_PROTOCOL_VERSION
            assert "events" in doc and "events_v2" not in doc
            client.close()
        finally:
            server.stop()

    def test_outside_window_rejected(self, tmp_path):
        path, server, _service = _sync_server(tmp_path)
        try:
            client = RpcClient(path)
            client.connect()
            for bad in (wire.MIN_PROTOCOL_VERSION - 1,
                        wire.PROTOCOL_VERSION + 1):
                with pytest.raises(RpcError, match="incompatible"):
                    client.call(FrameType.HELLO,
                                {"last_rv": -1, "proto": bad})
            client.close()
        finally:
            server.stop()

    def test_v3_conn_receives_legacy_delta_broadcasts(self, tmp_path):
        """A negotiated-down peer must keep receiving DELTA pushes it
        can decode: the broadcast dual-frame path."""
        path, server, service = _sync_server(tmp_path)
        try:
            sched = _scheduler()
            sync = StateSyncClient(SchedulerBinding(sched))
            frames: list[dict] = []
            seen = threading.Event()

            def on_push(frame):
                doc, arrays = wire.decode_payload(frame.payload)
                frames.append(doc)
                sync._apply(doc, arrays)
                seen.set()

            client = RpcClient(path, on_push=on_push)
            client.connect()
            # manual v3 bootstrap (the shape an old client's HELLO has)
            ftype, doc, arrays = client.call(
                FrameType.HELLO,
                {"last_rv": -1, "proto": wire.MIN_PROTOCOL_VERSION})
            sync._apply(doc, arrays, from_bootstrap=True)
            service.upsert_node("n0", _r(cpu=4000, memory=4096))
            assert seen.wait(5.0)
            # the push was the LEGACY v1 form, and it applied
            assert all("events" in f and "events_v2" not in f
                       for f in frames)
            assert "n0" in sched.snapshot.node_index
            client.close()
        finally:
            server.stop()

    def test_mixed_version_soak_under_faults(self, tmp_path):
        """A v4 client and a v3 client ride the same broadcast stream
        while the chaos layer duplicates/delays pushes; both must
        converge to the service's exact state (duplicates are absorbed
        by the rv guard on BOTH protocol versions). Reorder faults are
        deliberately absent: they require the full gap->resync re-dial
        machinery, which the hand-rolled v3 half of this harness does
        not implement (test_chaos covers that path end to end)."""
        from koordinator_tpu.transport.faults import (
            FaultConfig,
            FaultInjector,
        )

        inj = FaultInjector(seed=7, config=FaultConfig(
            push_duplicate_p=0.3, push_delay_p=0.2, push_delay_ms=1.0))
        path, server, service = _sync_server(tmp_path, faults=inj)
        clients = []
        try:
            scheds = [_scheduler(), _scheduler()]
            syncs = [StateSyncClient(SchedulerBinding(s)) for s in scheds]
            # client 0: modern v4 bootstrap; client 1: v3 peer
            c0 = RpcClient(path, on_push=syncs[0].on_push)
            c0.connect()
            clients.append(c0)
            syncs[0].bootstrap(c0)
            assert syncs[0].proto == wire.PROTOCOL_VERSION

            def v3_push(frame):
                if frame.type is FrameType.DELTA:
                    doc, arrays = wire.decode_payload(frame.payload)
                    assert "events_v2" not in doc  # legacy stream
                    syncs[1]._apply(doc, arrays)

            c1 = RpcClient(path, on_push=v3_push)
            c1.connect()
            clients.append(c1)
            ftype, doc, arrays = c1.call(
                FrameType.HELLO,
                {"last_rv": -1, "proto": wire.MIN_PROTOCOL_VERSION})
            if ftype is not FrameType.ACK:
                syncs[1]._apply(doc, arrays, from_bootstrap=True)

            for i in range(24):
                service.upsert_node(f"n{i % 6}",
                                    _r(cpu=1000 + i, memory=1024))
                service.update_node_usage(f"n{i % 6}",
                                          _r(cpu=i * 7, memory=i))
                if i % 3 == 0:
                    service.add_pod(f"p{i}", _r(cpu=100, memory=64))
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if all(s.rv == service.rv for s in syncs):
                    break
                time.sleep(0.05)
            inj.heal()
            assert sum(inj.injected.values()) > 0, "no faults fired"
            for sync, sched in zip(syncs, scheds):
                assert sync.rv == service.rv
                assert set(sched.snapshot.node_index) == set(service.nodes)
                assert set(sched.pending) == set(service.pods)
            # the two replicas agree row-for-row with each other
            for name in scheds[0].snapshot.node_index:
                s0 = scheds[0].snapshot.node_specs[name]
                s1 = scheds[1].snapshot.node_specs[name]
                np.testing.assert_array_equal(s0.usage, s1.usage)
                np.testing.assert_array_equal(s0.allocatable,
                                              s1.allocatable)
        finally:
            for c in clients:
                c.close()
            server.stop()

    def test_corrupt_manifest_frame_typed_rejection(self, tmp_path):
        """A frame whose array manifest points outside the payload must
        fail THAT call with a schema-flagged ERROR frame — the
        connection survives and keeps serving."""
        path, server, service = _sync_server(tmp_path)
        try:
            meta = {"kind": "node_upsert", "name": "x", "__arrays__": [
                {"key": "allocatable", "dtype": "<i4", "shape": [4],
                 "offset": 1 << 20, "nbytes": 16}]}
            j = json.dumps(meta).encode()
            payload = struct.pack("<I", len(j)) + j
            frame = wire.Frame(FrameType.STATE_PUSH, 3, payload)
            sock = socket.socket(socket.AF_UNIX)
            sock.connect(path)
            sock.sendall(frame.encode())

            def recv_exact(n):
                buf = b""
                while len(buf) < n:
                    chunk = sock.recv(n - len(buf))
                    if not chunk:
                        raise ConnectionError("peer closed")
                    buf += chunk
                return buf

            reply = wire.read_frame(recv_exact)
            assert reply.type is FrameType.ERROR
            err_doc, _ = wire.decode_payload(reply.payload)
            assert err_doc.get("schema") is True
            assert "payload" in err_doc["message"]
            # same socket, valid frame: the connection was NOT torn down
            hello = wire.Frame(FrameType.HELLO, 4, wire.encode_payload(
                {"last_rv": -1, "proto": wire.PROTOCOL_VERSION}))
            sock.sendall(hello.encode())
            reply2 = wire.read_frame(recv_exact)
            assert reply2.type is FrameType.SNAPSHOT
            sock.close()
            # and the corrupt push never entered the log
            assert service.rv == 0
        finally:
            server.stop()

    def test_new_client_downgrades_against_old_server(self, tmp_path):
        """bootstrap() retries once at MIN_PROTOCOL_VERSION when the
        server rejects our advertised version as incompatible — the
        new-client-vs-old-server half of the mixed-version matrix."""
        path = str(tmp_path / "old.sock")
        server = RpcServer(path)

        def old_hello(doc, arrays):
            # a pre-negotiation server: equality or bust
            if int(doc.get("proto", 1)) != wire.MIN_PROTOCOL_VERSION:
                raise WireSchemaError(
                    f"incompatible message protocol: peer "
                    f"{doc.get('proto')}, local "
                    f"{wire.MIN_PROTOCOL_VERSION}")
            out, arrs = _pack_events([])
            out["__type__"] = int(FrameType.DELTA)
            out["rv"] = -1
            return out, arrs

        server.register(FrameType.HELLO, old_hello)
        server.start()
        try:
            sync = StateSyncClient(SchedulerBinding(_scheduler()))
            client = RpcClient(path, on_push=sync.on_push)
            client.connect()
            sync.bootstrap(client)
            assert sync.proto == wire.MIN_PROTOCOL_VERSION
            client.close()
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# vectorized deltasync apply
# ---------------------------------------------------------------------------


def _usage_items(k=16, nodes=4, seed=3):
    rng = np.random.default_rng(seed)
    items = []
    for i in range(k):
        entry = {"kind": deltasync.NODE_USAGE, "name": f"n{i % nodes}",
                 "rv": i + 1}
        arrs = {"usage": _r(cpu=int(rng.integers(0, 4_000)),
                            memory=int(rng.integers(0, 8_192)))}
        items.append((entry, arrs))
    return items


class TestRunBatchedApply:
    def test_node_usage_run_identical_to_sequential(self):
        batched, serial = _scheduler(), _scheduler()
        _feed_nodes(batched), _feed_nodes(serial)
        items = _usage_items(k=24)
        _dispatch_events(SchedulerBinding(batched), items)
        for entry, arrs in items:
            _dispatch_event(SchedulerBinding(serial), entry, arrs)
        for name in serial.snapshot.node_index:
            np.testing.assert_array_equal(
                batched.snapshot.node_specs[name].usage,
                serial.snapshot.node_specs[name].usage)
        np.testing.assert_array_equal(
            np.asarray(batched.snapshot.state.node_usage),
            np.asarray(serial.snapshot.state.node_usage))

    def test_pod_add_run_identical_to_sequential(self):
        batched, serial = _scheduler(), _scheduler()
        items = []
        for i in range(12):
            items.append((
                {"kind": deltasync.POD_ADD, "name": f"p{i}",
                 "priority": i, "rv": i + 1},
                {"requests": _r(cpu=100 + i, memory=64)}))
        _dispatch_events(SchedulerBinding(batched), items)
        for entry, arrs in items:
            _dispatch_event(SchedulerBinding(serial), entry, arrs)
        assert list(batched.pending) == list(serial.pending)
        for name in serial.pending:
            assert (batched.pending[name].priority
                    == serial.pending[name].priority)
            np.testing.assert_array_equal(
                batched.pending[name].requests,
                serial.pending[name].requests)

    def test_mixed_kind_stream_preserves_order(self):
        """Runs never cross a kind boundary: a usage refresh AFTER a
        node upsert must see the upsert's allocatable (and vice versa),
        exactly as sequential dispatch orders them."""
        batched, serial = _scheduler(), _scheduler()
        stream = []
        rv = 0
        for i in range(4):
            rv += 1
            stream.append((
                {"kind": deltasync.NODE_UPSERT, "name": f"n{i}",
                 "rv": rv, "labels": {}, "taints": {},
                 "annotations": {}, "devices": {}},
                {"allocatable": _r(cpu=10_000, memory=16_384),
                 "usage": _r()}))
        for i in range(8):
            rv += 1
            stream.append((
                {"kind": deltasync.NODE_USAGE, "name": f"n{i % 4}",
                 "rv": rv},
                {"usage": _r(cpu=100 * i, memory=50 * i)}))
        rv += 1
        stream.append((
            {"kind": deltasync.NODE_UPSERT, "name": "n1", "rv": rv,
             "labels": {}, "taints": {}, "annotations": {},
             "devices": {}},
            {"allocatable": _r(cpu=20_000, memory=32_768),
             "usage": _r(cpu=1, memory=1)}))
        for i in range(6):
            rv += 1
            stream.append((
                {"kind": deltasync.POD_ADD, "name": f"p{i}", "rv": rv,
                 "priority": 1},
                {"requests": _r(cpu=100, memory=64)}))
        _dispatch_events(SchedulerBinding(batched), stream)
        for entry, arrs in stream:
            _dispatch_event(SchedulerBinding(serial), entry, arrs)
        np.testing.assert_array_equal(
            np.asarray(batched.snapshot.state.node_usage),
            np.asarray(serial.snapshot.state.node_usage))
        np.testing.assert_array_equal(
            np.asarray(batched.snapshot.state.node_allocatable),
            np.asarray(serial.snapshot.state.node_allocatable))
        assert list(batched.pending) == list(serial.pending)

    def test_run_takes_one_lock_roundtrip(self):
        sched = _scheduler()
        _feed_nodes(sched)
        binding = SchedulerBinding(sched)
        acquisitions = []
        real_lock = sched.lock

        class CountingLock:
            def __enter__(self):
                acquisitions.append(1)
                return real_lock.__enter__()

            def __exit__(self, *a):
                return real_lock.__exit__(*a)

        sched.lock = CountingLock()
        _dispatch_events(binding, _usage_items(k=24))
        assert len(acquisitions) == 1

    def test_client_apply_routes_batched(self):
        """A DELTA batch arriving through StateSyncClient._apply (the
        replay/bootstrap path) hits the run-batched dispatch."""
        sched = _scheduler()
        _feed_nodes(sched)
        binding = SchedulerBinding(sched)
        sync = StateSyncClient(binding)
        calls = []
        orig = binding.node_usage_run
        binding.node_usage_run = (
            lambda items: (calls.append(len(items)), orig(items)))
        events = [(i + 1, e, a)
                  for i, (e, a) in enumerate(_usage_items(k=10))]
        for rv, e, a in events:
            e.pop("rv")
        doc, arrays = _pack_events(events)
        doc["rv"] = len(events)
        applied = sync._apply(doc, arrays)
        assert applied == 10
        assert calls == [10]


# ---------------------------------------------------------------------------
# batched bind commits
# ---------------------------------------------------------------------------


class TestBatchedBindCommit:
    def _seeded_pair(self):
        pair = []
        for _ in range(2):
            sched = _scheduler(quota=True)
            _feed_nodes(sched)
            pair.append(sched)
        binds = []
        for i in range(12):
            quota = ("q" if i % 3 == 0 else "q2" if i % 3 == 1 else None)
            pod = _pod(100 + i, f"p{i}", quota=quota,
                       non_preemptible=(i % 4 == 0))
            binds.append((pod, f"n{i % 8}"))
        return pair, binds

    def test_batch_identical_to_sequential_loop(self):
        from koordinator_tpu.scheduler.scheduler import SchedulingResult

        (batched, serial), binds = self._seeded_pair()
        for sched in (batched, serial):
            for pod, _node in binds:
                sched.enqueue(pod)
        res_b = SchedulingResult(assignments={}, failures={})
        res_s = SchedulingResult(assignments={}, failures={})
        batched._commit_bind_batch(binds, res_b)
        for pod, node in binds:
            serial._commit_bind(pod, node, res_s)
        assert res_b.assignments == res_s.assignments
        assert set(batched.bound) == set(serial.bound)
        for name in serial.bound:
            b, s = batched.bound[name], serial.bound[name]
            assert (b.node, b.quota, b.non_preemptible, b.priority) == \
                (s.node, s.quota, s.non_preemptible, s.priority)
            np.testing.assert_array_equal(b.requests, s.requests)
        for qname in ("q", "q2"):
            np.testing.assert_array_equal(
                batched.quota_tree.nodes[qname].used,
                serial.quota_tree.nodes[qname].used)
            np.testing.assert_array_equal(
                batched.quota_tree.nodes[qname].non_preemptible_used,
                serial.quota_tree.nodes[qname].non_preemptible_used)
        assert set(batched.pending) == set(serial.pending) == set()

    def test_bind_batch_fn_called_once_per_round(self):
        calls = []
        sched = _scheduler(quota=True, batch_solver_threshold=1,
                           bind_batch_fn=lambda b: calls.append(b),
                           bind_fn=lambda p, n: calls.append("PER-POD"))
        _feed_nodes(sched)
        for i in range(6):
            sched.enqueue(_pod(300 + i, f"p{i}", quota="q"))
        result = sched.schedule_round()
        assert len(result.assignments) == 6
        assert len(calls) == 1 and "PER-POD" not in calls
        assert sorted(calls[0]) == sorted(result.assignments.items())

    def test_round_path_unchanged_binds(self):
        """End-to-end: two identical schedulers, one round each — the
        (now batched) Bind phase decides and charges exactly what the
        round always did (covered against the whole existing suite; the
        explicit pairing here guards the batch-vs-loop seam)."""
        a = _scheduler(quota=True, batch_solver_threshold=1)
        b = _scheduler(quota=True, batch_solver_threshold=1)
        for sched in (a, b):
            _feed_nodes(sched)
            for i in range(10):
                sched.enqueue(_pod(500 + i, f"p{i}",
                                   quota=("q" if i % 2 else None)))
        ra, rb = a.schedule_round(), b.schedule_round()
        assert ra.assignments == rb.assignments
        np.testing.assert_array_equal(a.quota_tree.nodes["q"].used,
                                      b.quota_tree.nodes["q"].used)


# ---------------------------------------------------------------------------
# quality tenants in the tenant-axis program
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def kit_off():
    from koordinator_tpu.scheduler.solver_kit import SolverKit

    return SolverKit(mesh="off")


def _front(kit, modes, batch_tenant_axis):
    from koordinator_tpu.scheduler.tenancy import (
        TenantScheduler,
        TenantSpec,
    )

    front = TenantScheduler(solver_kit=kit, cycle_pod_budget=1 << 20,
                            batch_tenant_axis=batch_tenant_axis,
                            pipeline=batch_tenant_axis)
    for name, mode in modes.items():
        front.add_tenant(
            TenantSpec(name=name, weight=1.0, node_capacity=16),
            batch_solver_threshold=1, quality_mode=mode)
    return front


def _seed_front(front, pods_per_tenant=8, base=0):
    for ti, tenant in enumerate(front.tenants()):
        _feed_nodes(tenant.scheduler, n=10, seed=31 + ti)
        for j in range(pods_per_tenant):
            tenant.scheduler.enqueue(
                _pod(base * 10_000 + ti * 1_000 + j, f"p{base}-{j}"))


def _binds(results):
    return {name: dict(r.assignments) for name, r in results.items()}


class TestQualityTenantAxis:
    def test_lp_tenants_join_batched_cycle(self, kit_off):
        """The PR 13 gap, closed: an all-lp fleet runs the BATCHED
        cycle (one vmapped lp_pack_assign dispatch), bit-identical to
        serial per-tenant execution."""
        modes = {"a": "lp", "b": "lp", "c": "lp"}
        serial = _front(kit_off, modes, batch_tenant_axis=False)
        batched = _front(kit_off, modes, batch_tenant_axis=True)
        for front in (serial, batched):
            _seed_front(front, base=1)
        r_ser = serial.schedule_cycle()
        r_bat = batched.schedule_cycle()
        assert batched.last_mode == "batched"
        for t in batched.tenants():
            assert t.scheduler.last_solve_path == "quality_lp_batched"
        for t in serial.tenants():
            assert t.scheduler.last_solve_path == "quality_lp"
        assert _binds(r_ser) == _binds(r_bat)

    def test_mixed_fleet_partitions_both_programs(self, kit_off):
        """Plain and lp tenants share one batched cycle: each group
        dispatches through ITS program, nobody falls back to the
        serialized pipeline, and every tenant's binds match serial."""
        modes = {"a": "off", "b": "lp", "c": "off", "d": "lp"}
        serial = _front(kit_off, modes, batch_tenant_axis=False)
        batched = _front(kit_off, modes, batch_tenant_axis=True)
        for front in (serial, batched):
            _seed_front(front, base=2)
        r_ser = serial.schedule_cycle()
        r_bat = batched.schedule_cycle()
        assert batched.last_mode == "batched"
        paths = {t.name: t.scheduler.last_solve_path
                 for t in batched.tenants()}
        assert paths == {"a": "tenant_batched",
                         "b": "quality_lp_batched",
                         "c": "tenant_batched",
                         "d": "quality_lp_batched"}
        assert _binds(r_ser) == _binds(r_bat)

    def test_auto_mode_unescalated_joins_plain_program(self, kit_off):
        """auto tenants whose latch is DOWN are plain-group members —
        they keep the select+pass1 program until slack escalates."""
        modes = {"a": "auto", "b": "auto"}
        batched = _front(kit_off, modes, batch_tenant_axis=True)
        _seed_front(batched, base=3)
        batched.schedule_cycle()
        assert batched.last_mode == "batched"
        for t in batched.tenants():
            assert t.scheduler.last_solve_path in (
                "tenant_batched", "quality_lp_batched")
            # the latch decides the group; unescalated == plain
            if not t.scheduler._quality_escalate:
                assert t.scheduler.last_solve_path == "tenant_batched"
