"""Gate-default parity audit against the reference's featuregate tables.

Round-3 shipped ``AuditEvents: True`` while the reference defaults it
false (pkg/features/koordlet_features.go:215); this test makes that class
of drift impossible by diffing EVERY default in ``features.py`` against
the reference's three Go tables, parsed straight from the source:

- pkg/features/koordlet_features.go:214-242      -> KOORDLET_GATES
- pkg/koordlet/runtimehooks/config.go:108-117    -> RUNTIMEHOOK_GATES
- pkg/features/features.go + scheduler_features.go -> SCHEDULER_GATES
  (the union; overlapping names carry identical defaults in both)

Skips when the reference checkout is absent (other machines/CI).
"""

import os
import re

import pytest

from koordinator_tpu.features import (
    KOORDLET_GATES,
    RUNTIMEHOOK_GATES,
    SCHEDULER_GATES,
)

REF = "/root/reference"

GO_DEFAULT_RE = re.compile(
    r"^\s*(\w+):\s*\{Default:\s*(true|false)\b", re.MULTILINE
)


def parse_go_defaults(*paths):
    out = {}
    for path in paths:
        with open(path) as f:
            src = f.read()
        for name, default in GO_DEFAULT_RE.findall(src):
            val = default == "true"
            if name in out and out[name] != val:
                raise AssertionError(
                    f"reference tables disagree on {name}: {out[name]} vs {val}"
                )
            out[name] = val
    return out


pytestmark = pytest.mark.skipif(
    not os.path.isdir(os.path.join(REF, "pkg", "features")),
    reason="reference checkout not available",
)


def assert_parity(gates, expected, *, what):
    ours = gates.known()
    mismatched = {
        name: (ours[name], expected[name])
        for name in set(ours) & set(expected)
        if ours[name] != expected[name]
    }
    assert not mismatched, (
        f"{what} defaults diverge from the reference "
        f"(ours, reference): {mismatched}"
    )
    missing = set(expected) - set(ours)
    assert not missing, f"{what} gates missing from our registry: {missing}"


def test_koordlet_gate_defaults_match_reference():
    expected = parse_go_defaults(
        os.path.join(REF, "pkg", "features", "koordlet_features.go")
    )
    assert_parity(KOORDLET_GATES, expected, what="koordlet")


def test_runtimehook_gate_defaults_match_reference():
    expected = parse_go_defaults(
        os.path.join(REF, "pkg", "koordlet", "runtimehooks", "config.go")
    )
    assert_parity(RUNTIMEHOOK_GATES, expected, what="runtimehooks")


def test_scheduler_manager_gate_defaults_match_reference():
    expected = parse_go_defaults(
        os.path.join(REF, "pkg", "features", "features.go"),
        os.path.join(REF, "pkg", "features", "scheduler_features.go"),
    )
    assert_parity(SCHEDULER_GATES, expected, what="scheduler/manager")
