"""NodeTopology reporter + kubelet stub parsing."""

import json
import os

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet.kubelet_stub import KubeletStub, parse_pod_list
from koordinator_tpu.koordlet.nodetopo import NodeTopologyReporter
from koordinator_tpu.koordlet.system.config import make_test_config


def make_sysfs_topology(cfg, n_cpus=4, n_numa=2, mem_kb_per_node=1000000):
    base = os.path.join(cfg.sys_root, "devices", "system", "cpu")
    os.makedirs(base, exist_ok=True)
    with open(os.path.join(base, "online"), "w") as f:
        f.write(f"0-{n_cpus - 1}")
    for cpu in range(n_cpus):
        topo = os.path.join(base, f"cpu{cpu}", "topology")
        os.makedirs(topo, exist_ok=True)
        with open(os.path.join(topo, "core_id"), "w") as f:
            f.write(str(cpu // 2))
        with open(os.path.join(topo, "physical_package_id"), "w") as f:
            f.write("0")
        node = cpu % n_numa
        os.makedirs(os.path.join(base, f"cpu{cpu}", f"node{node}"), exist_ok=True)
    for node in range(n_numa):
        nd = os.path.join(cfg.sys_root, "devices", "system", "node", f"node{node}")
        os.makedirs(nd, exist_ok=True)
        with open(os.path.join(nd, "meminfo"), "w") as f:
            f.write(f"Node {node} MemTotal: {mem_kb_per_node} kB\n")


class TestNodeTopologyReporter:
    def test_zones_and_annotations(self, tmp_path):
        cfg = make_test_config(tmp_path)
        make_sysfs_topology(cfg)
        reporter = NodeTopologyReporter(
            cfg, kubelet_reserved_cpus=(0,), cpu_manager_policy="static")
        topo = reporter.report()
        assert len(topo.zones) == 2
        assert topo.zones[0].cpu_milli == 2000
        assert topo.zones[0].memory_bytes == 1000000 * 1024
        ann = topo.to_annotations()
        detail = json.loads(ann["node.koordinator.sh/cpu-topology"])["detail"]
        assert len(detail) == 4
        assert ann["node.koordinator.sh/reserved-cpus"] == "0"
        assert "static" in ann["kubelet.koordinator.sh/cpu-manager-policy"]


KUBELET_PODS = {
    "items": [
        {
            "metadata": {"uid": "u1", "name": "web", "namespace": "prod",
                         "labels": {ext.LABEL_POD_QOS: "LS"}},
            "spec": {
                "priority": 9500,
                "containers": [{"name": "c1", "resources": {
                    "requests": {"cpu": "2", "memory": "4Gi"},
                    "limits": {"cpu": "2500m", "memory": "4Gi"}}}],
            },
            "status": {"phase": "Running", "qosClass": "Burstable",
                       "containerStatuses": [
                           {"name": "c1",
                            "containerID": "containerd://abc123"}]},
        },
    ]
}


class TestKubeletStub:
    def test_parse_pods(self):
        pods = parse_pod_list(KUBELET_PODS)
        assert len(pods) == 1
        pod = pods[0]
        assert pod.uid == "u1" and pod.qos_class == QoSClass.LS
        assert pod.kube_qos == "burstable"
        assert pod.requests["cpu"] == 2000       # "2" cores -> milli
        assert pod.limits["cpu"] == 2500         # "2500m" stays milli
        assert pod.requests["memory"] == 4 << 30
        assert pod.containers[0].container_id == "abc123"

    def test_stub_fetch(self):
        stub = KubeletStub(lambda path: json.dumps(
            KUBELET_PODS if path == "/pods"
            else {"kubeletconfig": {"cpuManagerPolicy": "static"}}))
        assert len(stub.get_all_pods()) == 1
        assert stub.get_kubelet_configz()["cpuManagerPolicy"] == "static"


class TestHttpsKubeletClient:
    """The HTTPS+token transport behind the stub (kubelet_stub.go:40):
    a real TLS server fixture with a self-signed cert and bearer-token
    auth, exactly the surface a kubelet presents."""

    @pytest.fixture(scope="class")
    def tls_server(self, tmp_path_factory):
        import http.server
        import ssl
        import subprocess
        import threading

        certdir = tmp_path_factory.mktemp("kubelet-certs")
        cert = str(certdir / "kubelet.crt")
        key = str(certdir / "kubelet.key")
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "1", "-subj",
             "/CN=127.0.0.1", "-addext",
             "subjectAltName=IP:127.0.0.1"],
            check=True, capture_output=True)

        pod_list = {"items": [{
            "metadata": {"uid": "tls-u1", "name": "tls-pod",
                         "namespace": "default"},
            "spec": {"containers": [{"resources": {
                "requests": {"cpu": "500m", "memory": "1Gi"}}}]},
            "status": {"phase": "Running", "qosClass": "Burstable"},
        }]}

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                if self.headers.get("Authorization") != "Bearer sekrit":
                    self.send_response(401)
                    self.end_headers()
                    return
                if self.path.rstrip("/") == "/pods":
                    body = json.dumps(pod_list).encode()
                elif self.path == "/configz":
                    body = json.dumps({"kubeletconfig": {
                        "cpuManagerPolicy": "static"}}).encode()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert, key)
        server.socket = ctx.wrap_socket(server.socket, server_side=True)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        yield server.server_address[1], cert
        server.shutdown()
        server.server_close()

    def test_pods_and_configz_over_tls_with_token(self, tls_server, tmp_path):
        port, cert = tls_server
        token_file = tmp_path / "token"
        token_file.write_text("sekrit\n")
        stub = KubeletStub.connect(
            "127.0.0.1", port, ca_file=cert,
            token_file=str(token_file))
        pods = stub.get_all_pods()
        assert [p.uid for p in pods] == ["tls-u1"]
        assert pods[0].requests == {"cpu": 500, "memory": 1 << 30}
        assert stub.get_kubelet_configz()["cpuManagerPolicy"] == "static"

    def test_bad_token_is_an_error(self, tls_server):
        port, cert = tls_server
        stub = KubeletStub.connect(
            "127.0.0.1", port, ca_file=cert, token="wrong")
        with pytest.raises(OSError, match="code 401"):
            stub.get_all_pods()

    def test_insecure_skip_verify(self, tls_server):
        port, _ = tls_server
        stub = KubeletStub.connect(
            "127.0.0.1", port, insecure_skip_verify=True, token="sekrit")
        assert [p.uid for p in stub.get_all_pods()] == ["tls-u1"]

    def test_untrusted_cert_refused_when_verifying(self, tls_server):
        port, _ = tls_server
        stub = KubeletStub.connect("127.0.0.1", port, token="sekrit")
        with pytest.raises(OSError):
            stub.get_all_pods()
