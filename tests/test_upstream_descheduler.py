"""Upstream-port descheduler plugins (descheduler/upstream.py) vs the
sigs.k8s.io/descheduler semantics the reference registers
(pkg/descheduler/framework/plugins/kubernetes/plugin.go:60-132)."""

import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.descheduler.framework import (
    Descheduler,
    Evictor,
    EvictorFilter,
    PodInfo,
    Profile,
)
from koordinator_tpu.descheduler.upstream import (
    HighNodeUtilization,
    NodeInfo,
    PodLifeTime,
    RemoveDuplicates,
    RemoveFailedPods,
    RemovePodsHavingTooManyRestarts,
    RemovePodsViolatingInterPodAntiAffinity,
    RemovePodsViolatingNodeAffinity,
    RemovePodsViolatingNodeTaints,
    RemovePodsViolatingTopologySpreadConstraint,
    pod_fits_node_affinity,
    tolerates,
)

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def run(plugins, pods, balance=False):
    profile = Profile(
        name="t",
        deschedule_plugins=[] if balance else plugins,
        balance_plugins=plugins if balance else [],
        evictor_filter=EvictorFilter(),
        evictor=Evictor(),
    )
    d = Descheduler([profile], pods_fn=lambda: pods, interval_seconds=0)
    d.run_once()
    return [uid for uid, _ in profile.evictor.evicted]


def test_pod_lifetime():
    pods = [
        PodInfo(uid="old", name="o", namespace="d", node="n0", created=0.0),
        PodInfo(uid="new", name="n", namespace="d", node="n0", created=900.0),
        PodInfo(uid="done", name="s", namespace="d", node="n0", created=0.0,
                phase="Succeeded"),
    ]
    plugin = PodLifeTime(max_seconds=600, states=["Running"],
                         clock=lambda: 1000.0)
    assert run([plugin], pods) == ["old"]


def test_remove_failed_pods_reasons_and_lifetime():
    pods = [
        PodInfo(uid="oom", name="a", namespace="d", node="n0", phase="Failed",
                reason="OOMKilled", created=0.0),
        PodInfo(uid="young", name="b", namespace="d", node="n0",
                phase="Failed", reason="OOMKilled", created=990.0),
        PodInfo(uid="other", name="c", namespace="d", node="n0",
                phase="Failed", reason="Evicted", created=0.0),
        PodInfo(uid="live", name="d", namespace="d", node="n0",
                phase="Running", created=0.0),
    ]
    plugin = RemoveFailedPods(reasons=["OOMKilled"],
                              min_pod_lifetime_seconds=60,
                              clock=lambda: 1000.0)
    assert run([plugin], pods) == ["oom"]


def test_too_many_restarts():
    pods = [
        PodInfo(uid="flappy", name="a", namespace="d", node="n0",
                restart_count=12),
        PodInfo(uid="stable", name="b", namespace="d", node="n0",
                restart_count=1),
    ]
    assert run([RemovePodsHavingTooManyRestarts(10)], pods) == ["flappy"]


def test_remove_duplicates_keeps_oldest_per_node():
    pods = [
        PodInfo(uid="a1", name="a1", namespace="d", node="n0",
                owner="ReplicaSet/web", images=("img",), created=1.0),
        PodInfo(uid="a2", name="a2", namespace="d", node="n0",
                owner="ReplicaSet/web", images=("img",), created=2.0),
        PodInfo(uid="a3", name="a3", namespace="d", node="n1",
                owner="ReplicaSet/web", images=("img",), created=3.0),
        PodInfo(uid="ds", name="ds", namespace="d", node="n0",
                owner="DaemonSet/logs", images=("img",), created=0.0),
    ]
    plugin = RemoveDuplicates(exclude_owner_kinds=["DaemonSet"])
    assert run([plugin], pods, balance=True) == ["a2"]


def test_node_affinity_matching_and_plugin():
    node_gpu = NodeInfo("gpu", labels={"pool": "gpu"})
    node_cpu = NodeInfo("cpu", labels={"pool": "cpu"})
    pod = PodInfo(uid="p", name="p", namespace="d", node="cpu",
                  required_affinity=((("pool", "In", ("gpu",)),),))
    assert pod_fits_node_affinity(pod, node_gpu)
    assert not pod_fits_node_affinity(pod, node_cpu)
    plugin = RemovePodsViolatingNodeAffinity(
        nodes_fn=lambda: [node_gpu, node_cpu])
    ok = PodInfo(uid="ok", name="ok", namespace="d", node="gpu",
                 required_affinity=((("pool", "In", ("gpu",)),),))
    assert run([plugin], [pod, ok]) == ["p"]


def test_node_taints_and_tolerations():
    taint = ("dedicated", "ml", "NoSchedule")
    assert tolerates(
        PodInfo(uid="x", name="x", namespace="d", node="n",
                tolerations=(("dedicated", "Equal", "ml", "NoSchedule"),)),
        taint)
    assert tolerates(
        PodInfo(uid="x", name="x", namespace="d", node="n",
                tolerations=(("", "Exists", "", ""),)),
        taint)
    nodes = [NodeInfo("n0", taints=(taint,)), NodeInfo("n1")]
    pods = [
        PodInfo(uid="intoler", name="a", namespace="d", node="n0"),
        PodInfo(uid="toler", name="b", namespace="d", node="n0",
                tolerations=(("dedicated", "Exists", "", "NoSchedule"),)),
        PodInfo(uid="elsewhere", name="c", namespace="d", node="n1"),
    ]
    plugin = RemovePodsViolatingNodeTaints(nodes_fn=lambda: nodes)
    assert run([plugin], pods) == ["intoler"]


def test_inter_pod_anti_affinity():
    pods = [
        PodInfo(uid="guard", name="g", namespace="d", node="n0",
                labels={"app": "guard"},
                anti_affinity=(({"app": "noisy"}, "hostname"),)),
        PodInfo(uid="noisy", name="n", namespace="d", node="n0",
                labels={"app": "noisy"}),
        PodInfo(uid="far", name="f", namespace="d", node="n1",
                labels={"app": "noisy"}),
    ]
    plugin = RemovePodsViolatingInterPodAntiAffinity()
    assert run([plugin], pods) == ["noisy"]


def test_topology_spread_constraint():
    nodes = [NodeInfo("n0", labels={"zone": "a"}),
             NodeInfo("n1", labels={"zone": "b"})]
    constraint = (("zone", 1, {"app": "web"}),)
    pods = (
        [PodInfo(uid=f"a{i}", name=f"a{i}", namespace="d", node="n0",
                 labels={"app": "web"}, spread_constraints=constraint,
                 created=float(i)) for i in range(4)]
        + [PodInfo(uid="b0", name="b0", namespace="d", node="n1",
                   labels={"app": "web"}, spread_constraints=constraint,
                   created=0.0)]
    )
    plugin = RemovePodsViolatingTopologySpreadConstraint(
        nodes_fn=lambda: nodes)
    # zone a has 4, zone b has 1, maxSkew 1 -> shed 2 newest from zone a
    assert sorted(run([plugin], pods, balance=True)) == ["a2", "a3"]


def test_high_node_utilization_compacts_cold_nodes():
    alloc = np.zeros((2, R), np.int32)
    alloc[:, CPU], alloc[:, MEM] = 10_000, 100_000
    requested = np.zeros_like(alloc)
    requested[0, CPU], requested[0, MEM] = 1_000, 5_000     # 10% / 5%
    requested[1, CPU], requested[1, MEM] = 8_000, 70_000    # 80% / 70%
    thresholds = np.full(R, -1, np.int32)
    thresholds[CPU], thresholds[MEM] = 20, 20
    plugin = HighNodeUtilization(
        state_fn=lambda: (requested, alloc, np.ones(2, bool), ["n0", "n1"]),
        thresholds=thresholds,
    )
    assert plugin.underutilized_nodes() == ["n0"]
    pods = [
        PodInfo(uid="cold", name="a", namespace="d", node="n0"),
        PodInfo(uid="hot", name="b", namespace="d", node="n1"),
    ]
    assert run([plugin], pods, balance=True) == ["cold"]
