"""HTTP/JSON gateway + TCP framed transport (SURVEY §5 comm backend: the
externally-speakable boundary — any language's HTTP client can drive the
sidecar; the framed RPC also listens on TCP for cross-host control)."""

import json
import urllib.error
import urllib.request

import numpy as np

from koordinator_tpu.ha import InMemoryLeaseStore, LeaseService
from koordinator_tpu.transport.channel import RpcClient, RpcServer
from koordinator_tpu.transport.http_gateway import HttpGateway
from koordinator_tpu.transport.wire import PROTOCOL_VERSION, FrameType

from tests.test_scheduler import mk_scheduler, node, pod


def _req(port, path, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=10) as resp:
        return resp.status, json.loads(resp.read().decode())


class TestHttpGateway:
    def test_health_version_and_solve(self):
        sched, binds = mk_scheduler([node("n1"), node("n2")])
        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            assert _req(gw.port, "/healthz") == (200, {"ok": True})
            assert _req(gw.port, "/version") == (
                200, {"protocol": PROTOCOL_VERSION})
            sched.enqueue(pod("p1", cpu=4_000))
            status, doc = _req(gw.port, "/v1/solve", "POST", {})
            assert status == 200
            assert doc["assignments"]["p1"] in ("n1", "n2")
            assert len(binds) == 1
        finally:
            gw.stop()

    def test_hooks_route(self):
        from koordinator_tpu.runtimeproxy import (
            Dispatcher,
            HookResponse,
            HookType,
        )

        class Server:
            def handle(self, hook, request):
                return HookResponse(annotations={"seen": "yes"})

        dispatcher = Dispatcher()
        dispatcher.register(Server(), [HookType.PRE_RUN_POD_SANDBOX])
        gw = HttpGateway(dispatcher=dispatcher)
        gw.start()
        try:
            status, doc = _req(
                gw.port, "/v1/hooks/PreRunPodSandbox", "POST",
                {"pod_meta": {"name": "p"}, "labels": {}})
            assert status == 200
            assert doc["annotations"] == {"seen": "yes"}
            try:
                _req(gw.port, "/v1/hooks/NoSuchHook", "POST", {})
                raise AssertionError("unknown hook must 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            gw.stop()

    def test_lease_cas_over_http(self):
        store = InMemoryLeaseStore()
        gw = HttpGateway(lease_store=store)
        gw.start()
        try:
            status, doc = _req(gw.port, "/v1/leases/sched")
            assert status == 200 and doc["holder"] == ""
            status, doc = _req(
                gw.port, "/v1/leases/sched", "PUT",
                {"expect_holder": "", "holder": "a",
                 "duration_seconds": 5.0, "acquire_time": 1.0,
                 "renew_time": 1.0, "transitions": 1})
            assert status == 200 and doc["ok"]
            # CAS conflict -> 409
            try:
                _req(gw.port, "/v1/leases/sched", "PUT",
                     {"expect_holder": "x", "holder": "b"})
                raise AssertionError("stale CAS must 409")
            except urllib.error.HTTPError as e:
                assert e.code == 409
            assert store.get("sched").holder == "a"
        finally:
            gw.stop()

    def test_unattached_routes_501(self):
        gw = HttpGateway()
        gw.start()
        try:
            try:
                _req(gw.port, "/v1/solve", "POST", {})
                raise AssertionError("must 501 without a scheduler")
            except urllib.error.HTTPError as e:
                assert e.code == 501
        finally:
            gw.stop()


class TestTcpTransport:
    def test_framed_rpc_over_tcp(self):
        server = RpcServer("tcp://127.0.0.1:0")
        svc = LeaseService()
        svc.attach(server)
        server.start()
        try:
            addr = server.address
            assert addr.startswith("tcp://127.0.0.1:")
            client = RpcClient(addr)
            client.connect()
            _, doc, _ = client.call(FrameType.LEASE_GET, {"name": "x"})
            assert doc["holder"] == ""
            client.close()
        finally:
            server.stop()
