"""HTTP/JSON gateway + TCP framed transport (SURVEY §5 comm backend: the
externally-speakable boundary — any language's HTTP client can drive the
sidecar; the framed RPC also listens on TCP for cross-host control)."""

import json
import urllib.error
import urllib.request

import numpy as np

from koordinator_tpu.ha import InMemoryLeaseStore, LeaseService
from koordinator_tpu.transport.channel import RpcClient, RpcServer
from koordinator_tpu.transport.http_gateway import HttpGateway
from koordinator_tpu.transport.wire import PROTOCOL_VERSION, FrameType

from tests.test_scheduler import mk_scheduler, node, pod


def _req(port, path, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=10) as resp:
        return resp.status, json.loads(resp.read().decode())


class TestHttpGateway:
    def test_health_version_and_solve(self):
        sched, binds = mk_scheduler([node("n1"), node("n2")])
        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            assert _req(gw.port, "/healthz") == (200, {"ok": True})
            assert _req(gw.port, "/version") == (
                200, {"protocol": PROTOCOL_VERSION})
            sched.enqueue(pod("p1", cpu=4_000))
            status, doc = _req(gw.port, "/v1/solve", "POST", {})
            assert status == 200
            assert doc["assignments"]["p1"] in ("n1", "n2")
            assert len(binds) == 1
        finally:
            gw.stop()

    def test_hooks_route(self):
        from koordinator_tpu.runtimeproxy import (
            Dispatcher,
            HookResponse,
            HookType,
        )

        class Server:
            def handle(self, hook, request):
                return HookResponse(annotations={"seen": "yes"})

        dispatcher = Dispatcher()
        dispatcher.register(Server(), [HookType.PRE_RUN_POD_SANDBOX])
        gw = HttpGateway(dispatcher=dispatcher)
        gw.start()
        try:
            status, doc = _req(
                gw.port, "/v1/hooks/PreRunPodSandbox", "POST",
                {"pod_meta": {"name": "p"}, "labels": {}})
            assert status == 200
            assert doc["annotations"] == {"seen": "yes"}
            try:
                _req(gw.port, "/v1/hooks/NoSuchHook", "POST", {})
                raise AssertionError("unknown hook must 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            gw.stop()

    def test_lease_cas_over_http(self):
        store = InMemoryLeaseStore()
        gw = HttpGateway(lease_store=store)
        gw.start()
        try:
            status, doc = _req(gw.port, "/v1/leases/sched")
            assert status == 200 and doc["holder"] == ""
            status, doc = _req(
                gw.port, "/v1/leases/sched", "PUT",
                {"expect_holder": "", "holder": "a",
                 "duration_seconds": 5.0, "acquire_time": 1.0,
                 "renew_time": 1.0, "transitions": 1})
            assert status == 200 and doc["ok"]
            # CAS conflict -> 409
            try:
                _req(gw.port, "/v1/leases/sched", "PUT",
                     {"expect_holder": "x", "holder": "b"})
                raise AssertionError("stale CAS must 409")
            except urllib.error.HTTPError as e:
                assert e.code == 409
            assert store.get("sched").holder == "a"
        finally:
            gw.stop()

    def test_unattached_routes_501(self):
        gw = HttpGateway()
        gw.start()
        try:
            try:
                _req(gw.port, "/v1/solve", "POST", {})
                raise AssertionError("must 501 without a scheduler")
            except urllib.error.HTTPError as e:
                assert e.code == 501
        finally:
            gw.stop()


class TestTcpTransport:
    def test_framed_rpc_over_tcp(self):
        server = RpcServer("tcp://127.0.0.1:0")
        svc = LeaseService()
        svc.attach(server)
        server.start()
        try:
            addr = server.address
            assert addr.startswith("tcp://127.0.0.1:")
            client = RpcClient(addr)
            client.connect()
            _, doc, _ = client.call(FrameType.LEASE_GET, {"name": "x"})
            assert doc["holder"] == ""
            client.close()
        finally:
            server.stop()


class TestPodResourcesProxy:
    """PodResourcesProxy (states_pod_resources.go List enrichment): the
    kubelet pod-resources listing gains the koord-allocated devices that
    device plugins never reported."""

    def _states(self, annotations):
        from koordinator_tpu.api.qos import QoSClass
        from koordinator_tpu.koordlet.statesinformer import (
            PodMeta,
            StatesInformer,
        )

        states = StatesInformer()
        states.set_pods([PodMeta(
            uid="u1", name="p1", namespace="default",
            qos_class=QoSClass.LS, kube_qos="burstable",
            annotations=annotations)])
        return states

    def test_list_merges_annotation_devices(self):
        from koordinator_tpu.api import extension as ext
        from koordinator_tpu.koordlet.pod_resources import PodResourcesProxy

        ann = {}
        ext.set_device_allocations(ann, {
            "gpu": [{"minor": 0, "resources": {"core": 100}},
                    {"minor": 2, "resources": {"core": 100}}],
            "rdma": [{"minor": 1, "extension": {"virtual_functions": [
                {"bus_id": "0000:3b:02.1"}]}}],
        })
        upstream = {"pod_resources": [{
            "name": "p1", "namespace": "default",
            "containers": [{"name": "main", "devices": [
                {"resource_name": "cpu", "device_ids": []}]}],
        }]}
        proxy = PodResourcesProxy(self._states(ann), lambda: upstream)
        out = proxy.list()
        devices = out["pod_resources"][0]["containers"][0]["devices"]
        names = [d["resource_name"] for d in devices]
        assert names == sorted(names)
        by_name = {d["resource_name"]: d["device_ids"] for d in devices}
        assert by_name["nvidia.com/gpu"] == ["0", "2"]
        # VF bus ids win over the device minor
        assert by_name["koordinator.sh/rdma"] == ["0000:3b:02.1"]

    def test_pod_missing_upstream_still_reported(self):
        from koordinator_tpu.api import extension as ext
        from koordinator_tpu.koordlet.pod_resources import PodResourcesProxy

        ann = {}
        ext.set_device_allocations(ann, {"gpu": [{"minor": 1}]})
        proxy = PodResourcesProxy(self._states(ann), lambda: {})
        out = proxy.list()
        assert out["pod_resources"][0]["name"] == "p1"
        devs = out["pod_resources"][0]["containers"][0]["devices"]
        assert devs == [{"resource_name": "nvidia.com/gpu",
                         "device_ids": ["1"]}]

    def test_served_on_gateway(self):
        from koordinator_tpu.api import extension as ext
        from koordinator_tpu.koordlet.pod_resources import PodResourcesProxy

        ann = {}
        ext.set_device_allocations(ann, {"gpu": [{"minor": 3}]})
        gw = HttpGateway(
            pod_resources=PodResourcesProxy(self._states(ann), lambda: {}))
        gw.start()
        try:
            status, doc = _req(gw.port, "/v1/podresources")
            assert status == 200
            assert doc["pod_resources"][0]["containers"][0]["devices"][0][
                "device_ids"] == ["3"]
        finally:
            gw.stop()

    def test_repeated_list_does_not_duplicate(self):
        from koordinator_tpu.api import extension as ext
        from koordinator_tpu.koordlet.pod_resources import PodResourcesProxy

        ann = {}
        ext.set_device_allocations(ann, {"gpu": [{"minor": 0}]})
        upstream = {"pod_resources": [{
            "name": "p1", "namespace": "default",
            "containers": [{"name": "main", "devices": []}],
        }], "extra_field": 7}
        proxy = PodResourcesProxy(self._states(ann), lambda: upstream)
        first = proxy.list()
        second = proxy.list()
        devs = second["pod_resources"][0]["containers"][0]["devices"]
        assert len(devs) == 1, "cached upstream dict was mutated"
        # the upstream's own structure is untouched
        assert upstream["pod_resources"][0]["containers"][0]["devices"] == []
        # extra top-level upstream fields pass through
        assert first["extra_field"] == 7


def test_gateway_survives_garbage_requests():
    """The HTTP surface is as reachable as the framed socket: raw
    garbage, lying Content-Length, malformed JSON bodies, and unknown
    routes must cost only that request — the server keeps answering
    /healthz afterwards."""
    import socket

    sched, _ = mk_scheduler([node("n1")])
    # a lease store attaches a body-PARSING route (PUT /v1/leases/...)
    # for the malformed-JSON probe below
    gw = HttpGateway(scheduler=sched, lease_store=InMemoryLeaseStore())
    gw.start()
    try:
        blobs = [
            b"\x00" * 64,
            b"NOT-HTTP AT ALL\r\n\r\n",
            b"POST /v1/solve HTTP/1.1\r\nContent-Length: 10\r\n\r\nnot json!!",
            b"POST /v1/state HTTP/1.1\r\nContent-Length: 999999\r\n\r\nshort",
            b"GET /v1/%00%ff HTTP/1.1\r\n\r\n",
        ]
        for blob in blobs:
            s = socket.create_connection(("127.0.0.1", gw.port), timeout=5)
            s.settimeout(5)
            try:
                s.sendall(blob)
                try:
                    while s.recv(4096):
                        pass
                except OSError:
                    pass
            finally:
                s.close()
            assert _req(gw.port, "/healthz") == (200, {"ok": True})
        # malformed JSON through the normal client path on a
        # body-PARSING route: an error status, not a hang or crash
        # (/v1/solve ignores its body by design, so it is not the probe)
        status, doc = _req_raw_body(gw.port, "/v1/leases/x", b"{broken",
                                    method="PUT")
        assert status in (400, 500), status
        assert _req(gw.port, "/healthz") == (200, {"ok": True})
    finally:
        gw.stop()


def _req_raw_body(port, path, body: bytes, method: str = "POST"):
    import http.client
    import json as _json

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request(method, path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, _json.loads(raw)
        except ValueError:
            return resp.status, {}
    finally:
        conn.close()


class TestDebugLatencyRoute:
    """Surface parity for /debug/latency (ISSUE 20): the gateway route
    serves the same shared builder as DebugService, including its typed
    400/501 errors."""

    def test_latency_table_then_typed_errors(self):
        from koordinator_tpu import journey

        ledger_was = journey.LEDGER.enabled
        journey.LEDGER.set_enabled(True)
        journey.LEDGER.reset_for_tests()
        sched, _binds = mk_scheduler([node("n1")])
        sched.enqueue(pod("p1", cpu=2_000))
        sched.schedule_round()
        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            status, doc = _req(gw.port, "/debug/latency")
            assert status == 200
            assert doc["enabled"] is True
            assert doc["stages"][0] == "e2e"
            assert any(r["stage"] == "e2e" and r["count"] >= 1
                       for r in doc["series"])

            # unknown tenant filter: typed 400 with the recorded set
            try:
                _req(gw.port, "/debug/latency?tenant=absent")
                raise AssertionError("unknown tenant did not 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert "unknown tenant" in json.loads(
                    e.read().decode())["error"]

            # kill switch thrown: typed 501, not an empty 200
            journey.LEDGER.set_enabled(False)
            try:
                _req(gw.port, "/debug/latency")
                raise AssertionError("disabled ledger did not 501")
            except urllib.error.HTTPError as e:
                assert e.code == 501
        finally:
            journey.LEDGER.set_enabled(ledger_was)
            journey.LEDGER.reset_for_tests()
            gw.stop()
