"""ManagerSyncBinding + ColocationLoop unit coverage (the §3.2 manager
leg; the full three-binary flow lives in test_deployment_sim.py).

Pins the two restart/re-registration behaviors the r5 review caught:
a bootstrap snapshot must restore the colocation formula's usage inputs
(sys_usage/hp_usage ride the merged node_upsert arrays), and a wholesale
node re-upsert must reset the diff-suppression state so the batch
capacity it wiped gets re-pushed.
"""

import numpy as np

from koordinator_tpu.api.resources import ResourceDim, resource_vector
from koordinator_tpu.manager.colocation_loop import (
    ColocationLoop,
    ManagerSyncBinding,
)
from koordinator_tpu.manager.noderesource_controller import (
    NodeResourceController,
)
from koordinator_tpu.transport import StateSyncService


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _service_with_node(clock):
    service = StateSyncService()
    service.upsert_node("n0", resource_vector(cpu=16_000, memory=16_384))
    service.update_node_usage(
        "n0",
        resource_vector(cpu=2_000, memory=4_096),
        sys_usage=resource_vector(cpu=500, memory=512),
        hp_usage=resource_vector(cpu=3_000, memory=2_048))
    return service


def _loop(service, clock):
    binding = ManagerSyncBinding(clock=clock)
    service.attach_binding(binding)
    pushes = []

    def push(name, allocatable):
        service.update_node_allocatable(name, allocatable)
        pushes.append((name, np.asarray(allocatable).copy()))

    controller = NodeResourceController(clock=clock)
    return ColocationLoop(controller, binding, push), binding, pushes


def test_bootstrap_replay_restores_formula_inputs():
    """A manager that attaches AFTER the koordlet's report still sees
    sys/hp usage (they ride the merged node_upsert replay): its first
    reconcile must subtract HP.Used instead of over-advertising."""
    clock = FakeClock()
    service = _service_with_node(clock)
    loop, binding, pushes = _loop(service, clock)
    # attach_binding replays nothing retroactively; replay the snapshot
    # by hand the way a bootstrap does
    doc, arrays = service._snapshot()
    from koordinator_tpu.transport.deltasync import (
        StateSyncClient,
        _unpack_event_arrays,
    )

    for entry in doc["events"]:
        from koordinator_tpu.transport.deltasync import _dispatch_event

        _dispatch_event(binding, entry, _unpack_event_arrays(entry, arrays))

    with binding.lock:
        view = binding.nodes["n0"]
        assert view.hp_usage is not None and view.sys_usage is not None
        assert int(view.hp_usage[ResourceDim.CPU]) == 3_000

    assert loop.tick() == 1
    name, alloc = pushes[-1]
    batch = int(alloc[ResourceDim.BATCH_CPU])
    assert 0 < batch < 16_000
    # with HP forgotten the formula would yield ~3,000m more batch
    with binding.lock:
        binding.nodes["n0"].hp_usage = np.zeros_like(
            binding.nodes["n0"].hp_usage)
    loop.tick()
    _, alloc_nohp = pushes[-1]
    assert int(alloc_nohp[ResourceDim.BATCH_CPU]) - batch >= 2_500


def test_reupsert_resets_diff_suppression_and_repushes():
    """node_upsert replaces the stored doc wholesale (wiping batch dims
    from the scheduler's view); the manager must re-push even though its
    own computed value did not change."""
    clock = FakeClock()
    service = _service_with_node(clock)
    loop, binding, pushes = _loop(service, clock)
    # live path: the binding saw the node via attach_binding? no —
    # attach happened after; re-send the node and usage live
    service.upsert_node("n0", resource_vector(cpu=16_000, memory=16_384))
    service.update_node_usage(
        "n0", resource_vector(cpu=2_000, memory=4_096),
        sys_usage=resource_vector(cpu=500, memory=512),
        hp_usage=resource_vector(cpu=3_000, memory=2_048))
    from koordinator_tpu import metrics

    patches_before = metrics.colocation_patches_total.value()
    assert loop.tick() == 1
    assert metrics.colocation_patches_total.value() == patches_before + 1
    first = pushes[-1][1]
    assert int(first[ResourceDim.BATCH_CPU]) > 0
    # steady state: same inputs, no new push
    assert loop.tick() == 0

    # the koordlet re-registers the node (restart): batch dims wiped
    service.upsert_node("n0", resource_vector(cpu=16_000, memory=16_384),
                        usage=resource_vector(cpu=2_000, memory=4_096))
    assert loop.tick() == 1, "re-upsert must defeat diff suppression"
    again = pushes[-1][1]
    assert int(again[ResourceDim.BATCH_CPU]) == int(
        first[ResourceDim.BATCH_CPU])

    # node removal drops both view and record
    service.remove_node("n0")
    assert loop.tick() == 0
    with binding.lock:
        assert "n0" not in binding.nodes
        assert "n0" not in binding.records


def test_stale_metrics_zero_batch_over_the_loop():
    """Degrade mode (noderesource_controller._degraded): when a node's
    usage report goes stale past degradeTimeMinutes, the loop must push
    a ZEROING patch — leaving the last batch capacity advertised on a
    node whose metrics went dark is the over-commit the degrade path
    exists to prevent."""
    clock = FakeClock()
    service = _service_with_node(clock)
    loop, binding, pushes = _loop(service, clock)
    service.upsert_node("n0", resource_vector(cpu=16_000, memory=16_384))
    service.update_node_usage(
        "n0", resource_vector(cpu=2_000, memory=4_096),
        sys_usage=resource_vector(cpu=500, memory=512),
        hp_usage=resource_vector(cpu=3_000, memory=2_048))
    assert loop.tick() == 1
    assert int(pushes[-1][1][ResourceDim.BATCH_CPU]) > 0

    # collectors go dark: 16 minutes pass with no usage refresh
    clock.t += 16 * 60
    assert loop.tick() == 1, "degrade must emit a zeroing patch"
    degraded = pushes[-1][1]
    assert int(degraded[ResourceDim.BATCH_CPU]) == 0
    assert int(degraded[ResourceDim.BATCH_MEMORY]) == 0
    assert int(degraded[ResourceDim.MID_CPU]) == 0
    # base capacity dims are untouched
    assert int(degraded[ResourceDim.CPU]) == 16_000
    # a fresh report recovers the capacity
    service.update_node_usage(
        "n0", resource_vector(cpu=2_000, memory=4_096),
        sys_usage=resource_vector(cpu=500, memory=512),
        hp_usage=resource_vector(cpu=3_000, memory=2_048))
    assert loop.tick() == 1
    assert int(pushes[-1][1][ResourceDim.BATCH_CPU]) > 0


def test_manager_sidecar_reconnects_after_scheduler_restart(tmp_path):
    """The colocation loop must survive a sidecar restart: the manager's
    reconnecting client re-dials + re-bootstraps on the next tick (a
    bare RpcClient would leave the watch dead and batch allocatable
    permanently stale — r5 review finding)."""
    import time

    from koordinator_tpu.cmd.binaries import (
        main_koord_manager,
        main_koord_scheduler,
    )

    sock = str(tmp_path / "reconnect.sock")

    def boot_scheduler():
        asm = main_koord_scheduler([
            "--node-capacity", "8", "--listen-socket", sock,
            "--disable-leader-election"])
        asm.state_sync.upsert_node(
            "n0", resource_vector(cpu=16_000, memory=16_384))
        asm.state_sync.update_node_usage(
            "n0", resource_vector(cpu=2_000, memory=4_096),
            sys_usage=resource_vector(cpu=500, memory=512),
            hp_usage=resource_vector(cpu=3_000, memory=2_048))
        return asm

    sched = boot_scheduler()
    manager_asm = None
    try:
        manager_asm = main_koord_manager(
            ["--scheduler-sidecar-addr", sock])
        manager = manager_asm.component
        # lazy dial: the first tick bootstraps the watch AND reconciles
        deadline = time.monotonic() + 10
        pushed = 0
        while pushed == 0 and time.monotonic() < deadline:
            pushed = manager.colocation_loop.tick()
            time.sleep(0.05)
        assert pushed == 1

        # sidecar dies; ticks must not crash, failures are counted
        sched.stop()
        time.sleep(0.1)
        manager.colocation_loop.tick()
        assert (manager.colocation_loop.connect_failures
                + manager.colocation_loop.push_failures) >= 1

        # a fresh sidecar comes up on the same socket: the next tick
        # re-dials, re-bootstraps (full snapshot: the new service's rv
        # restarted), and pushes batch capacity to the NEW scheduler
        sched = boot_scheduler()
        deadline = time.monotonic() + 10
        pushed = 0
        while pushed == 0 and time.monotonic() < deadline:
            pushed = manager.colocation_loop.tick()
            time.sleep(0.1)
        assert pushed == 1, "loop never recovered after sidecar restart"
        stored = sched.state_sync.nodes["n0"]["arrays"]
        assert int(stored["allocatable"][ResourceDim.BATCH_CPU]) > 0
    finally:
        if manager_asm is not None:
            manager_asm.component.stop()
        sched.stop()


def test_manager_boots_before_scheduler(tmp_path):
    """Deploy order must not matter: a manager assembled while the
    scheduler sidecar is still down ticks with counted failures instead
    of crashing, then picks up the loop when the sidecar appears."""
    import time

    from koordinator_tpu.cmd.binaries import (
        main_koord_manager,
        main_koord_scheduler,
    )

    sock = str(tmp_path / "order.sock")
    manager_asm = main_koord_manager(["--scheduler-sidecar-addr", sock])
    manager = manager_asm.component
    sched = None
    try:
        assert manager.colocation_loop.tick() == 0
        assert manager.colocation_loop.connect_failures == 1

        sched = main_koord_scheduler([
            "--node-capacity", "8", "--listen-socket", sock,
            "--disable-leader-election"])
        sched.state_sync.upsert_node(
            "n0", resource_vector(cpu=16_000, memory=16_384))
        sched.state_sync.update_node_usage(
            "n0", resource_vector(cpu=2_000, memory=4_096),
            sys_usage=resource_vector(cpu=500, memory=512),
            hp_usage=resource_vector(cpu=3_000, memory=2_048))
        deadline = time.monotonic() + 10
        pushed = 0
        while pushed == 0 and time.monotonic() < deadline:
            pushed = manager.colocation_loop.tick()
            time.sleep(0.05)
        assert pushed == 1
        stored = sched.state_sync.nodes["n0"]["arrays"]
        assert int(stored["allocatable"][ResourceDim.BATCH_CPU]) > 0
    finally:
        manager_asm.component.stop()
        if sched is not None:
            sched.stop()


def test_wire_fed_hp_request_aggregates_feed_calculate_policies():
    """maxUsageRequest/request policies on wire-fed records: without the
    hp_request/hp_max_used_req aggregates on the node_usage report the
    policy inputs were silently 0 and batch capacity over-advertised by
    the whole HP request footprint."""
    from koordinator_tpu.manager.sloconfig import ColocationConfig

    clock = FakeClock()
    config = ColocationConfig(enable=True,
                              cpu_calculate_policy="maxUsageRequest",
                              memory_calculate_policy="request")

    def run(with_aggregates: bool):
        service = StateSyncService()
        service.upsert_node("n0", resource_vector(cpu=16_000, memory=16_384))
        kw = {}
        if with_aggregates:
            kw = dict(
                hp_request=resource_vector(cpu=8_000, memory=9_000),
                hp_max_used_req=resource_vector(cpu=9_000, memory=10_000))
        service.update_node_usage(
            "n0", resource_vector(cpu=2_000, memory=4_096),
            sys_usage=resource_vector(cpu=500, memory=512),
            hp_usage=resource_vector(cpu=3_000, memory=2_048), **kw)
        binding = ManagerSyncBinding(clock=clock)
        service.attach_binding(binding)
        # re-send live (attach_binding has no retroactive replay)
        service.update_node_usage(
            "n0", resource_vector(cpu=2_000, memory=4_096),
            sys_usage=resource_vector(cpu=500, memory=512),
            hp_usage=resource_vector(cpu=3_000, memory=2_048), **kw)
        pushes = []
        loop = ColocationLoop(NodeResourceController(config, clock=clock),
                              binding,
                              lambda name, alloc: pushes.append(alloc))
        # the node view needs allocatable: replay the upsert live too
        service.upsert_node("n0", resource_vector(cpu=16_000, memory=16_384))
        service.update_node_usage(
            "n0", resource_vector(cpu=2_000, memory=4_096),
            sys_usage=resource_vector(cpu=500, memory=512),
            hp_usage=resource_vector(cpu=3_000, memory=2_048), **kw)
        assert loop.tick() == 1
        return pushes[-1]

    with_agg = run(True)
    without = run(False)
    # maxUsageRequest (cpu): 9,000m of per-pod max(request, usage) must be
    # carved out instead of 0 — the with-aggregates push advertises less
    assert (int(without[ResourceDim.BATCH_CPU])
            - int(with_agg[ResourceDim.BATCH_CPU])) >= 8_000
    # request (memory): the 9,000 MiB HP request footprint likewise
    assert (int(without[ResourceDim.BATCH_MEMORY])
            - int(with_agg[ResourceDim.BATCH_MEMORY])) >= 8_000


def test_bootstrap_replay_preserves_report_time_for_degrade():
    """A manager that bootstraps AFTER the koordlet's last report must
    date the usage by the REPORT timestamp riding the merged doc, not by
    apply time: a stale node is then zeroed on the first reconcile
    instead of getting a fresh degrade window per restart."""
    clock = FakeClock(t=1_000.0)
    service = StateSyncService()
    service.upsert_node("n0", resource_vector(cpu=16_000, memory=16_384))
    service.update_node_usage(
        "n0", resource_vector(cpu=2_000, memory=4_096),
        sys_usage=resource_vector(cpu=500, memory=512),
        hp_usage=resource_vector(cpu=3_000, memory=2_048),
        report_time=1_000.0)

    # 20 minutes later (past degradeTimeMinutes=15) a fresh manager
    # attaches and replays the bootstrap snapshot
    clock.t = 1_000.0 + 20 * 60
    binding = ManagerSyncBinding(clock=clock)
    doc, arrays = service._snapshot()
    from koordinator_tpu.transport.deltasync import (
        _dispatch_event,
        _unpack_event_arrays,
    )

    for entry in doc["events"]:
        _dispatch_event(binding, entry, _unpack_event_arrays(entry, arrays))
    with binding.lock:
        assert binding.nodes["n0"].usage_time == 1_000.0

    pushes = []
    loop = ColocationLoop(NodeResourceController(clock=clock), binding,
                          lambda name, alloc: pushes.append(alloc))
    assert loop.tick() == 1, "stale node must push a zeroing patch"
    zeroed = pushes[-1]
    assert int(zeroed[ResourceDim.BATCH_CPU]) == 0
    assert int(zeroed[ResourceDim.BATCH_MEMORY]) == 0

    # a FRESH report (new report_time) recovers capacity
    service.attach_binding(binding)
    service.update_node_usage(
        "n0", resource_vector(cpu=2_000, memory=4_096),
        sys_usage=resource_vector(cpu=500, memory=512),
        hp_usage=resource_vector(cpu=3_000, memory=2_048),
        report_time=clock.t)
    assert loop.tick() == 1
    assert int(pushes[-1][ResourceDim.BATCH_CPU]) > 0
