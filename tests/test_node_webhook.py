"""Node admission webhooks (manager/node_webhook.py).

Mirrors the reference's resource_amplification_test.go behaviors: raw
allocatable saved on first amplified update, amplified capacity written at
admission, kubelet changes refresh the raw baseline, feature-off cleans
the annotation; plus the slo-config conflict check from slo_plugin_test.go
and the validating-side rejection of malformed amplification annotations.
"""

import json

from koordinator_tpu.api import extension as ext
from koordinator_tpu.manager.node_webhook import (
    NodeMutatingWebhook,
    NodeValidatingWebhook,
)

AMP = ext.ANNOTATION_NODE_AMPLIFICATION
RAW = ext.ANNOTATION_NODE_RAW_ALLOCATABLE


def node(cpu=4000, memory=8192, ratios=None, annotations=None, labels=None):
    ann = dict(annotations or {})
    if ratios is not None:
        ann[AMP] = json.dumps(ratios)
    return {
        "name": "n1", "labels": labels or {}, "annotations": ann,
        "allocatable": {"cpu": cpu, "memory": memory},
    }


class TestAmplificationMutating:
    def test_amplifies_and_saves_raw_on_first_update(self):
        wh = NodeMutatingWebhook()
        n = node(cpu=4000, memory=8192, ratios={"cpu": 2.0})
        assert wh.mutate(n, old_node=node()) == []
        assert n["allocatable"]["cpu"] == 8000
        assert n["allocatable"]["memory"] == 8192  # no memory ratio
        raw = json.loads(n["annotations"][RAW])
        assert raw == {"cpu": 4000, "memory": 8192}

    def test_reamplify_uses_saved_raw_not_amplified(self):
        wh = NodeMutatingWebhook()
        n = node(cpu=4000, ratios={"cpu": 2.0})
        wh.mutate(n, old_node=node())
        # a second admission with unchanged kubelet values must NOT
        # compound: 4000*2, not 8000*2
        n2 = dict(n, allocatable=dict(n["allocatable"]))
        old = dict(n, allocatable=dict(n["allocatable"]))
        wh.mutate(n2, old_node=old)
        assert n2["allocatable"]["cpu"] == 8000

    def test_kubelet_change_refreshes_raw(self):
        wh = NodeMutatingWebhook()
        n = node(cpu=4000, ratios={"cpu": 2.0})
        wh.mutate(n, old_node=node())
        # kubelet reduces allocatable (reserved resources changed)
        n3 = dict(n, allocatable={"cpu": 3000, "memory": 8192})
        wh.mutate(n3, old_node=n)
        assert json.loads(n3["annotations"][RAW])["cpu"] == 3000
        assert n3["allocatable"]["cpu"] == 6000

    def test_feature_off_restores_raw_and_cleans_annotation(self):
        wh = NodeMutatingWebhook()
        n = node(cpu=4000, ratios={"cpu": 2.0})
        wh.mutate(n, old_node=node())
        assert RAW in n["annotations"]
        assert n["allocatable"]["cpu"] == 8000
        del n["annotations"][AMP]
        wh.mutate(n, old_node=None)
        assert RAW not in n["annotations"]
        # kubelet's baseline comes back — amplified capacity must not
        # outlive the feature
        assert n["allocatable"]["cpu"] == 4000

    def test_ratio_at_most_one_is_skipped(self):
        wh = NodeMutatingWebhook()
        n = node(cpu=4000, ratios={"cpu": 1.0})
        assert wh.mutate(n, old_node=node()) == []
        assert n["allocatable"]["cpu"] == 4000

    def test_create_is_untouched(self):
        wh = NodeMutatingWebhook()
        n = node(cpu=4000, ratios={"cpu": 2.0})
        assert wh.mutate(n, operation="CREATE") == []
        assert n["allocatable"]["cpu"] == 4000

    def test_bad_annotation_errors(self):
        wh = NodeMutatingWebhook()
        n = node(annotations={AMP: "not json"})
        errs = wh.mutate(n, old_node=node())
        assert errs and "NodeResourceAmplification" in errs[0]


class TestValidating:
    def test_bad_amplification_rejected(self):
        wh = NodeValidatingWebhook()
        for bad in ("not json", json.dumps({"cpu": 0.5}),
                    json.dumps({"cpu": "two"}), json.dumps([2])):
            errs = wh.validate(node(annotations={AMP: bad}))
            assert errs, bad
        assert wh.validate(node(ratios={"cpu": 1.5})) == []

    def test_slo_config_conflict_rejected(self):
        config = {
            "colocation-config": json.dumps({
                "nodeStrategies": [
                    {"name": "a", "nodeSelector":
                        {"matchLabels": {"pool": "x"}}},
                    {"name": "b", "nodeSelector":
                        {"matchLabels": {"pool": "x", "zone": "1"}}},
                ],
            }),
        }
        wh = NodeValidatingWebhook(config_data_fn=lambda: config)
        bad = node(labels={"pool": "x", "zone": "1"})
        errs = wh.validate(bad, old_node=node(labels={}))
        assert errs and "conflicting node strategies" in errs[0]
        # a node matching one strategy is fine
        ok = node(labels={"pool": "x"})
        assert wh.validate(ok, old_node=node(labels={})) == []
        # unchanged labels skip the check entirely
        assert wh.validate(bad, old_node=bad) == []
