import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig, greedy_assign, score_pods
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def mk_nodes(*cpu_mem):
    alloc = np.zeros((len(cpu_mem), R), np.int32)
    for i, (c, m) in enumerate(cpu_mem):
        alloc[i, CPU], alloc[i, MEM] = c, m
    return alloc


def mk_pods(*cpu_mem, priority=None):
    req = np.zeros((len(cpu_mem), R), np.int32)
    for i, (c, m) in enumerate(cpu_mem):
        req[i, CPU], req[i, MEM] = c, m
    prio = np.asarray(priority, np.int32) if priority is not None else None
    return req, prio


def plain_config():
    """Config with thresholds/estimator defaults off, for pure packing tests."""
    cfg = ScoringConfig.default()
    return cfg.replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32),
        estimator_factors=jnp.full(R, 100, jnp.int32),
    )


def test_score_pods_prefers_emptier_node():
    alloc = mk_nodes((10_000, 32_768), (10_000, 32_768))
    requested = np.zeros((2, R), np.int32)
    requested[0, CPU] = 8_000  # node 0 heavily requested
    usage = np.zeros((2, R), np.int32)
    usage[0, CPU] = 7_000
    state = ClusterState.from_arrays(alloc, requested=requested, usage=usage)
    req, _ = mk_pods((1_000, 1_024))
    pods = PodBatch.build(req, node_capacity=state.capacity)
    scores, feasible = jax.jit(score_pods)(state, pods, plain_config())
    s = np.asarray(scores)[0]
    f = np.asarray(feasible)[0]
    assert f[0] and f[1]
    assert s[1] > s[0]


def test_score_pods_filters_full_and_invalid_nodes():
    alloc = mk_nodes((2_000, 4_096), (10_000, 32_768))
    requested = np.zeros((2, R), np.int32)
    requested[0, CPU] = 1_500
    state = ClusterState.from_arrays(alloc, requested=requested)
    req, _ = mk_pods((1_000, 1_024))
    pods = PodBatch.build(req, node_capacity=state.capacity)
    _, feasible = score_pods(state, pods, plain_config())
    f = np.asarray(feasible)[0]
    assert not f[0]          # only 500 mcpu free
    assert f[1]
    assert not f[2:].any()   # padded nodes are invalid


def test_greedy_assign_capacity_feedback():
    # Two pods that each fit either node but not together on one.
    alloc = mk_nodes((1_000, 4_096), (1_000, 4_096))
    state = ClusterState.from_arrays(alloc)
    req, _ = mk_pods((700, 1_024), (700, 1_024))
    pods = PodBatch.build(req, node_capacity=state.capacity)
    assignments, new_state, _ = jax.jit(greedy_assign)(state, pods, plain_config())
    a = np.asarray(assignments)[:2]
    assert set(a.tolist()) == {0, 1}
    assert np.asarray(new_state.node_requested)[:2, CPU].tolist() == [700, 700]


def test_greedy_assign_priority_order():
    # One good (empty) node, one loaded node: higher-priority pod should get
    # first pick even though it comes later in the batch.
    alloc = mk_nodes((10_000, 32_768), (10_000, 32_768))
    usage = np.zeros((2, R), np.int32)
    usage[0, CPU] = 6_000
    state = ClusterState.from_arrays(alloc, usage=usage)
    req, prio = mk_pods((9_000, 1_024), (9_000, 1_024), priority=[5500, 9500])
    pods = PodBatch.build(req, priority=prio, node_capacity=state.capacity)
    assignments, _, _ = greedy_assign(state, pods, plain_config())
    a = np.asarray(assignments)
    assert a[1] == 1  # prod pod got the emptier node
    assert a[0] == 0


def test_greedy_assign_unschedulable():
    alloc = mk_nodes((1_000, 1_024))
    state = ClusterState.from_arrays(alloc)
    req, _ = mk_pods((2_000, 512), (500, 512))
    pods = PodBatch.build(req, node_capacity=state.capacity)
    assignments, _, _ = greedy_assign(state, pods, plain_config())
    a = np.asarray(assignments)
    assert a[0] == -1
    assert a[1] == 0
    assert a[2:].tolist() == [-1] * (len(a) - 2)  # padded pods unassigned


def test_greedy_assign_respects_feasibility_mask():
    alloc = mk_nodes((10_000, 32_768), (10_000, 32_768))
    state = ClusterState.from_arrays(alloc)
    req, _ = mk_pods((1_000, 1_024))
    feasible = np.zeros((1, state.capacity), bool)
    feasible[0, 1] = True  # only node 1 allowed (e.g. nodeSelector)
    pods = PodBatch.build(req, feasible=feasible, node_capacity=state.capacity)
    assignments, _, _ = greedy_assign(state, pods, plain_config())
    assert int(assignments[0]) == 1


def test_greedy_assign_threshold_feedback():
    # LoadAware thresholds must apply to estimated usage accumulated during the
    # batch, not just the starting snapshot (assign-cache semantics).
    alloc = mk_nodes((1_000, 100_000))
    usage = np.zeros((1, R), np.int32)
    usage[0, CPU] = 400
    state = ClusterState.from_arrays(alloc, usage=usage)
    cfg = plain_config().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(65),
    )
    req, _ = mk_pods((200, 16), (200, 16))
    pods = PodBatch.build(req, node_capacity=state.capacity)
    assignments, _, _ = greedy_assign(state, pods, cfg)
    a = np.asarray(assignments)[:2]
    # First pod: 600/1000 = 60 <= 65 ok. Second: 800/1000 = 80 > 65 rejected.
    assert a[0] == 0
    assert a[1] == -1


def test_aggregated_thresholds_replace_instantaneous():
    # When aggregated (percentile) thresholds are configured they are checked
    # INSTEAD of the instantaneous ones (load_aware.go Filter either/or).
    alloc = mk_nodes((1_000, 100_000))
    usage = np.zeros((1, R), np.int32)
    usage[0, CPU] = 900          # instantaneous spike: 90%
    agg = np.zeros((1, R), np.int32)
    agg[0, CPU] = 300            # p95 usage: 30%
    state = ClusterState.from_arrays(alloc, usage=usage, agg_usage=agg)
    req, _ = mk_pods((50, 16))
    pods = PodBatch.build(req, node_capacity=state.capacity)

    base = plain_config()
    inst_only = base.replace(
        usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(65))
    _, feas = score_pods(state, pods, inst_only)
    assert not bool(np.asarray(feas)[0, 0])  # 95% > 65 -> rejected

    both = inst_only.replace(
        agg_usage_thresholds=jnp.zeros(R, jnp.int32).at[CPU].set(65))
    _, feas = score_pods(state, pods, both)
    assert bool(np.asarray(feas)[0, 0])  # agg policy replaces inst: 35% <= 65


def test_greedy_assign_deterministic():
    rng = np.random.default_rng(7)
    alloc = np.zeros((16, R), np.int32)
    alloc[:, CPU] = rng.integers(4_000, 16_000, 16)
    alloc[:, MEM] = rng.integers(8_192, 65_536, 16)
    state = ClusterState.from_arrays(alloc)
    req = np.zeros((32, R), np.int32)
    req[:, CPU] = rng.integers(100, 2_000, 32)
    req[:, MEM] = rng.integers(128, 4_096, 32)
    prio = rng.integers(3000, 9999, 32).astype(np.int32)
    pods = PodBatch.build(req, priority=prio, node_capacity=state.capacity)
    cfg = plain_config()
    a1, _, _ = greedy_assign(state, pods, cfg)
    a2, _, _ = greedy_assign(state, pods, cfg)
    assert np.array_equal(np.asarray(a1), np.asarray(a2))
