"""Randomized invariants of reservation accounting.

test_reservation.py pins the reference scenarios; this sweeps random
reservation sets and allocation sequences:

  (ledger)   take + spill == request exactly; allocated never exceeds
             reserved; remaining never negative; no-reservation charges
             spill entirely and leave the set untouched
  (once)     an allocate-once row is consumed whole on first use
  (nominate) the nominated reservation fits, sits on the pod's chosen
             node, and has the smallest total remainder among the
             eligible rows (best-fit, recomputed independently)
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import prop_seeds

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
from koordinator_tpu.ops.reservation import (
    ReservationSet,
    allocate_from_reservation,
    nominate_reservation,
)

R = NUM_RESOURCE_DIMS


def _random_set(rng: np.random.Generator, n_nodes: int):
    v = int(rng.integers(1, 6))
    reserved = rng.integers(0, 8_000, (v, R)).astype(np.int32)
    allocated = (reserved * rng.uniform(0, 1, (v, R))).astype(np.int32)
    node_idx = rng.integers(-1, n_nodes, v).astype(np.int32)
    once = (rng.random(v) < 0.3)
    return ReservationSet.build(reserved, node_idx, allocated=allocated,
                                allocate_once=once)


@pytest.mark.parametrize("seed", prop_seeds(24))
def test_allocation_ledger(seed):
    rng = np.random.default_rng(seed)
    rsv = _random_set(rng, n_nodes=4)

    for _ in range(12):
        use_none = rng.random() < 0.2
        r_idx = -1 if use_none else int(rng.integers(0, rsv.capacity))
        request = rng.integers(0, 5_000, R).astype(np.int32)
        before = np.asarray(rsv.allocated).copy()
        rem_before = np.asarray(rsv.remaining).copy()

        rsv2, spill = allocate_from_reservation(
            rsv, jnp.int32(r_idx), jnp.asarray(request))
        spill = np.asarray(spill)
        after = np.asarray(rsv2.allocated)

        if r_idx < 0:
            assert (spill == request).all(), f"seed {seed}"
            assert (after == before).all(), f"seed {seed}: set mutated"
        else:
            take = np.minimum(request, rem_before[r_idx])
            # (ledger) exact split
            assert (take + spill == request).all(), f"seed {seed}"
            if bool(np.asarray(rsv.allocate_once)[r_idx]) and (
                    np.asarray(rsv.valid)[r_idx]
                    and np.asarray(rsv.node_idx)[r_idx] >= 0):
                # (once) consumed whole
                assert (after[r_idx]
                        == np.asarray(rsv.reserved)[r_idx]).all(), (
                    f"seed {seed}: allocate-once not consumed whole")
            else:
                assert (after[r_idx] == before[r_idx] + take).all()
            # untouched other rows
            mask = np.ones(rsv.capacity, bool)
            mask[r_idx] = False
            assert (after[mask] == before[mask]).all()
        # (ledger) remaining never negative, zero off active rows
        rem = np.asarray(rsv2.remaining)
        assert (rem >= 0).all(), f"seed {seed}: negative remainder"
        inactive = ~(np.asarray(rsv2.valid)
                     & (np.asarray(rsv2.node_idx) >= 0))
        assert (rem[inactive] == 0).all()
        rsv = rsv2


@pytest.mark.parametrize("seed", prop_seeds(24))
def test_nominate_best_fit(seed):
    rng = np.random.default_rng(100 + seed)
    n_nodes, n_pods = 4, int(rng.integers(1, 8))
    rsv = _random_set(rng, n_nodes)
    fits = rng.random((n_pods, rsv.capacity)) < 0.5
    node = rng.integers(-1, n_nodes, n_pods).astype(np.int32)

    out = np.asarray(nominate_reservation(
        jnp.asarray(fits), rsv, jnp.asarray(node)))

    node_idx = np.asarray(rsv.node_idx)
    total_rem = np.asarray(rsv.remaining).sum(axis=1)
    for p in range(n_pods):
        eligible = (fits[p] & (node_idx == node[p])
                    & (node[p] >= 0))
        if not eligible.any():
            assert out[p] == -1, f"seed {seed}: pod {p} got {out[p]}"
            continue
        r = out[p]
        assert eligible[r], f"seed {seed}: nominated ineligible row"
        assert total_rem[r] == total_rem[eligible].min(), (
            f"seed {seed}: not best-fit ({total_rem[r]} vs "
            f"{total_rem[eligible].min()})")
