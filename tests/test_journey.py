"""Pod-journey ledger (ISSUE 20): sketch algebra, e2e recording flow,
fleet merge, wire threading of arrival_ts, debug surfaces, and — the
load-bearing guarantee — bit-identity of scheduling decisions and quota
charges with the ledger on vs off.

The sketch tests pin the DDSketch contract the fleet aggregation leans
on: merge is associative + commutative with the empty sketch as
identity AND byte-deterministic (``to_doc`` of equal sketches is equal
JSON), and every quantile stays within the declared <=1% relative
error across six decades of latencies at once — a fixed-bucket
histogram cannot do that, which is why the ledger exists.
"""

import json
import os
import time

import numpy as np
import pytest

from koordinator_tpu import journey
from koordinator_tpu.api.resources import resource_vector
from koordinator_tpu.journey import (
    DDSketch,
    JourneyLedger,
    RELATIVE_ACCURACY,
    merge_snapshot_rows,
)
from koordinator_tpu.scheduler.scheduler import Scheduler
from koordinator_tpu.scheduler.services import (
    DebugApiError,
    debug_latency_body,
)
from koordinator_tpu.scheduler.snapshot import ClusterSnapshot, PodSpec
from koordinator_tpu.transport.deltasync import (
    SchedulerBinding,
    StateSyncService,
)


def canon(sk: DDSketch) -> str:
    # "sum" is the one doc field whose low bits depend on float
    # accumulation ORDER, not on which samples were seen — byte
    # determinism is claimed (and asserted) for everything else.
    doc = sk.to_doc()
    doc.pop("sum", None)
    return json.dumps(doc, sort_keys=True)


def sketch_of(values) -> DDSketch:
    sk = DDSketch()
    sk.insert_many(values)
    return sk


@pytest.fixture(autouse=True)
def _fresh_ledger():
    journey.LEDGER.set_enabled(True)
    journey.LEDGER.reset_for_tests()
    yield
    journey.LEDGER.set_enabled(True)
    journey.LEDGER.reset_for_tests()


class TestSketchAlgebra:
    def test_merge_commutative(self):
        a = sketch_of([0.001, 0.5, 3.0, 0.02])
        b = sketch_of([1e-4, 7.0, 0.3])
        ab = a.copy().merge(b)
        ba = b.copy().merge(a)
        assert canon(ab) == canon(ba)

    def test_merge_associative(self):
        a = sketch_of([0.001, 0.5])
        b = sketch_of([0.02, 90.0])
        c = sketch_of([5e-4, 0.25, 1.5])
        left = a.copy().merge(b).merge(c)           # (a+b)+c
        bc = b.copy().merge(c)
        right = a.copy().merge(bc)                  # a+(b+c)
        assert canon(left) == canon(right)

    def test_empty_sketch_is_merge_identity(self):
        a = sketch_of([0.004, 0.2, 12.0])
        before = canon(a)
        assert canon(a.copy().merge(DDSketch())) == before
        assert canon(DDSketch().merge(a)) == before
        assert DDSketch().merge(DDSketch()).count == 0
        assert DDSketch().quantile(0.99) is None

    def test_merge_equals_sketch_of_concatenation(self):
        """Merge is LOSS-FREE: merging two sketches gives exactly the
        sketch of the concatenated samples (bucket-wise add)."""
        rng = np.random.RandomState(7)
        xs = rng.lognormal(-4, 2, 500)
        ys = rng.lognormal(-2, 1, 300)
        merged = sketch_of(xs).merge(sketch_of(ys))
        whole = sketch_of(np.concatenate([xs, ys]))
        assert canon(merged) == canon(whole)
        assert merged.to_doc()["sum"] == pytest.approx(
            whole.to_doc()["sum"])

    def test_relative_error_bound_across_six_decades(self):
        """Property test: quantiles stay within the declared relative
        accuracy from 100us to 100s — six decades in ONE sketch."""
        rng = np.random.RandomState(20)
        # uniform in log-space across [1e-4, 1e2)
        values = 10.0 ** rng.uniform(-4, 2, 20_000)
        sk = DDSketch()
        sk.insert_batch(values)
        hi = np.sort(values)
        for q in (0.01, 0.1, 0.5, 0.9, 0.99, 0.999):
            est = sk.quantile(q)
            true = float(hi[int(q * (len(hi) - 1))])
            rel = abs(est - true) / true
            assert rel <= RELATIVE_ACCURACY, (q, est, true, rel)

    def test_vectorized_insert_matches_scalar_inserts(self):
        rng = np.random.RandomState(3)
        values = rng.lognormal(-3, 2, 2_000)
        batched = DDSketch()
        batched.insert_batch(values)
        scalar = sketch_of(values)
        assert canon(batched) == canon(scalar)
        assert batched.to_doc()["sum"] == pytest.approx(
            scalar.to_doc()["sum"])

    def test_to_doc_roundtrip_is_byte_deterministic(self):
        sk = sketch_of([0.002, 0.4, 0.0, 25.0, 3e-4])
        doc = sk.to_doc()
        wire = json.dumps(doc, sort_keys=True)
        back = DDSketch.from_doc(json.loads(wire))
        assert json.dumps(back.to_doc(), sort_keys=True) == wire
        # bucket keys serialize in sorted order — equal sketches give
        # equal BYTES without a canonicalization pass
        assert list(doc["buckets"]) == sorted(doc["buckets"],
                                              key=lambda k: int(k))

    def test_zero_and_negative_values_land_in_zero_bucket(self):
        sk = sketch_of([0.0, -1.0, 5e-10])
        assert sk.zero_count == 3 and sk.count == 3
        assert sk.quantile(0.5) == 0.0


class TestLedger:
    def _pods(self, n, qos=0):
        return [PodSpec(name=f"p{i}", requests=np.zeros(4, np.int32),
                        qos=qos) for i in range(n)]

    def test_record_batch_populates_all_stages(self):
        led = JourneyLedger()
        pods = self._pods(4)
        arrived = time.time() - 0.005
        for p in pods:
            led.note_enqueue(p.name, arrival_ts=arrived)
        t = time.perf_counter()
        led.record_bind_batch("a", pods, round_start_perf=t,
                              commit_perf=t + 0.001, ack_perf=t + 0.002)
        stages = {r["stage"] for r in led.report()["series"]}
        assert stages == set(journey.STAGES)
        e2e = [r for r in led.report("a")["series"]
               if r["stage"] == "e2e"][0]
        assert e2e["count"] == 4 and e2e["p99_s"] > 0

    def test_no_arrival_stamp_skips_ingest_stage(self):
        led = JourneyLedger()
        pods = self._pods(2)
        for p in pods:
            led.note_enqueue(p.name)
        t = time.perf_counter()
        led.record_bind_batch("a", pods, round_start_perf=t,
                              commit_perf=t)
        stages = {r["stage"] for r in led.report()["series"]}
        assert "ingest" not in stages and "e2e" in stages

    def test_qos_classes_get_separate_series(self):
        led = JourneyLedger()
        pods = self._pods(2, qos=0) + [
            PodSpec(name="be", requests=np.zeros(4, np.int32), qos=3)]
        for p in pods:
            led.note_enqueue(p.name)
        t = time.perf_counter()
        led.record_bind_batch("a", pods, round_start_perf=t,
                              commit_perf=t)
        qos_seen = {(r["qos"], r["stage"])
                    for r in led.report()["series"]}
        assert (0, "e2e") in qos_seen and (3, "e2e") in qos_seen

    def test_forget_drops_stamps_and_unstamped_pods_are_skipped(self):
        led = JourneyLedger()
        led.note_enqueue("gone")
        led.forget("gone")
        t = time.perf_counter()
        led.record_bind_batch("a", self._pods(1),
                              round_start_perf=t, commit_perf=t)
        assert led.report()["series"] == []
        assert led.pending_count() == 0

    def test_disabled_ledger_records_nothing_and_clears(self):
        led = JourneyLedger()
        led.note_enqueue("p0")
        led.set_enabled(False)
        assert led.pending_count() == 0
        led.note_enqueue("p1")
        t = time.perf_counter()
        led.record_bind_batch("a", self._pods(2),
                              round_start_perf=t, commit_perf=t)
        assert led.report()["series"] == []

    def test_jsonl_snapshot_merges_to_fleet_table(self, tmp_path):
        """Two 'processes' flush JSONL; the merged table equals the
        single-process table over the union of their samples."""
        t = time.perf_counter()
        led1, led2 = JourneyLedger(), JourneyLedger()
        for led, names in ((led1, ("a0", "a1")), (led2, ("b0",))):
            pods = [PodSpec(name=n, requests=np.zeros(4, np.int32))
                    for n in names]
            for p in pods:
                led.note_enqueue(p.name)
            led.record_bind_batch("t0", pods, round_start_perf=t,
                                  commit_perf=t)
        p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
        assert led1.write_jsonl(p1) > 0
        assert led2.write_jsonl(p2) > 0
        rows = []
        for path in (p1, p2):
            with open(path) as fh:
                rows.extend(json.loads(line) for line in fh)
        merged = merge_snapshot_rows(rows)
        e2e = merged[("t0", 0, "e2e")]
        assert e2e.count == 3


class TestLatencyReport:
    def test_cli_merges_files_into_one_table(self, tmp_path, capsys):
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import latency_report

        led = JourneyLedger()
        pods = [PodSpec(name=f"x{i}", requests=np.zeros(4, np.int32))
                for i in range(3)]
        for p in pods:
            led.note_enqueue(p.name)
        t = time.perf_counter()
        led.record_bind_batch("ten", pods, round_start_perf=t,
                              commit_perf=t + 0.001)
        path = str(tmp_path / "one.jsonl")
        led.write_jsonl(path)
        assert latency_report.main([path, path]) == 0   # self-merge: 2x
        out = capsys.readouterr().out
        assert "ten" in out and "e2e" in out
        table = latency_report.journey_table(
            latency_report.read_rows([path, path]))
        e2e = [r for r in table["series"] if r["stage"] == "e2e"][0]
        assert e2e["count"] == 6 and e2e["p99_s"] is not None

    def test_empty_inputs_exit_2(self, tmp_path, capsys):
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import latency_report

        empty = tmp_path / "empty.jsonl"
        empty.write_text("\nnot json\n{\"unrelated\": 1}\n")
        assert latency_report.main([str(empty)]) == 2


def _assemble():
    snap = ClusterSnapshot(capacity=8)
    sched = Scheduler(snap)
    svc = StateSyncService()
    svc.attach_binding(SchedulerBinding(sched))
    svc.upsert_node("n1", np.asarray(
        resource_vector(cpu=64_000, memory=262_144), np.int32))
    return sched, svc


class TestWireThreading:
    def test_arrival_ts_survives_deltasync_into_podspec(self):
        sched, svc = _assemble()
        stamp = time.time() - 0.25
        svc.add_pod("p1", np.asarray(
            resource_vector(cpu=1_000, memory=1_024), np.int32),
            arrival_ts=stamp)
        assert sched.pending["p1"].arrival_ts == pytest.approx(stamp)

    def test_stampless_pod_add_defaults_to_zero(self):
        sched, svc = _assemble()
        svc.add_pod("p1", np.asarray(
            resource_vector(cpu=1_000, memory=1_024), np.int32))
        assert sched.pending["p1"].arrival_ts == 0.0
        # and no arrival_ts key pollutes the stored doc (sparse column:
        # absent means absent)
        assert "arrival_ts" not in svc.pods["p1"]["doc"]

    def test_non_numeric_arrival_ts_rejected_by_push_validation(self):
        from koordinator_tpu.transport.wire import WireSchemaError

        _sched, svc = _assemble()
        before_rv = svc.rv
        with pytest.raises(WireSchemaError, match="arrival_ts"):
            svc._handle_state_push(
                {"kind": "pod_add", "name": "bad", "priority": 0,
                 "arrival_ts": "yesterday"},
                {"requests": np.asarray(
                    resource_vector(cpu=1_000, memory=1_024), np.int32)})
        assert svc.rv == before_rv  # rejected push commits nothing

    def test_bound_pod_lands_in_ledger_via_real_round(self):
        sched, svc = _assemble()
        svc.add_pod("p1", np.asarray(
            resource_vector(cpu=1_000, memory=1_024), np.int32),
            arrival_ts=time.time() - 0.01)
        res = sched.schedule_round()
        assert res.assignments == {"p1": "n1"}
        series = journey.LEDGER.report()["series"]
        stages = {r["stage"] for r in series}
        assert {"e2e", "ingest", "queue_wait", "solve",
                "commit"} <= stages


class TestBitIdentity:
    """THE acceptance criterion: KOORD_JOURNEY=0 must not change one
    scheduling decision or quota charge."""

    def _run(self, enabled: bool):
        journey.LEDGER.set_enabled(enabled)
        journey.LEDGER.reset_for_tests()
        from koordinator_tpu.api.resources import (
            NUM_RESOURCE_DIMS,
            ResourceDim,
        )
        from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree

        mx = np.full(NUM_RESOURCE_DIMS, UNBOUNDED, np.int64)
        mx[ResourceDim.CPU] = 8_000
        tree = QuotaTree(np.asarray(
            resource_vector(cpu=32_000, memory=131_072), np.int64))
        tree.add("team", min=np.zeros(NUM_RESOURCE_DIMS, np.int64),
                 max=mx)
        snap = ClusterSnapshot(capacity=16)
        sched = Scheduler(snap, quota_tree=tree)
        svc = StateSyncService()
        svc.attach_binding(SchedulerBinding(sched))
        svc.upsert_node("n1", np.asarray(
            resource_vector(cpu=16_000, memory=65_536), np.int32))
        svc.upsert_node("n2", np.asarray(
            resource_vector(cpu=4_000, memory=8_192), np.int32))
        for i in range(12):
            svc.add_pod(
                f"p{i}", np.asarray(resource_vector(
                    cpu=1_000 + 100 * (i % 3), memory=1_024), np.int32),
                priority=i % 4, quota="team", qos=i % 3,
                arrival_ts=time.time())
        assignments = {}
        for _ in range(3):
            assignments.update(sched.schedule_round().assignments)
        used = np.asarray(tree.nodes["team"].used).tolist()
        return assignments, used

    def test_decisions_and_quota_charges_identical_on_vs_off(self):
        on_assign, on_used = self._run(True)
        off_assign, off_used = self._run(False)
        assert on_assign == off_assign
        assert on_used == off_used
        assert on_assign, "round placed nothing — vacuous comparison"


class TestDebugSurface:
    def test_body_reports_recorded_series(self):
        sched, svc = _assemble()
        svc.add_pod("p1", np.asarray(
            resource_vector(cpu=1_000, memory=1_024), np.int32))
        sched.schedule_round()
        body = debug_latency_body(sched, {})
        assert body["enabled"] is True
        assert body["stages"] == list(journey.STAGES)
        assert any(r["stage"] == "e2e" for r in body["series"])

    def test_unknown_tenant_is_typed_400(self):
        sched, _svc = _assemble()
        with pytest.raises(DebugApiError) as ei:
            debug_latency_body(sched, {"tenant": "absent"})
        assert ei.value.status == 400

    def test_disabled_ledger_is_typed_501(self):
        sched, _svc = _assemble()
        journey.LEDGER.set_enabled(False)
        with pytest.raises(DebugApiError) as ei:
            debug_latency_body(sched, {})
        assert ei.value.status == 501

    def test_debug_service_serves_the_shared_builder(self):
        from koordinator_tpu.scheduler.services import DebugService

        sched, svc = _assemble()
        svc.add_pod("p1", np.asarray(
            resource_vector(cpu=1_000, memory=1_024), np.int32))
        sched.schedule_round()
        dbg = DebugService(sched)
        status, body = dbg.handle("/debug/latency", {})
        assert status == 200 and body["enabled"] is True
        status, body = dbg.handle("/debug/latency", {"tenant": "nope"})
        assert status == 400 and "error" in body


class TestSloIntegration:
    def test_pod_e2e_p99_spec_ships_over_the_journey_gauge(self):
        """The ledger is a first-class SloMonitor window source: the
        shipped gauge SLO burns from the sketch-backed e2e p99 gauge,
        sliced to the {q=0.99, stage=e2e} series."""
        from koordinator_tpu.slo_monitor import KIND_GAUGE, default_specs

        spec = {s.name: s for s in default_specs()}["pod_e2e_p99"]
        assert spec.kind == KIND_GAUGE
        assert spec.metric == "koord_scheduler_pod_journey_latency_seconds"
        assert dict(spec.label_filter) == {"q": "0.99", "stage": "e2e"}
        assert spec.threshold == pytest.approx(0.2)
        # any tenant's e2e-p99 series counts; other stages never do
        assert spec.matches_labels(
            {"tenant": "t0", "qos": "1", "stage": "e2e", "q": "0.99"})
        assert not spec.matches_labels(
            {"tenant": "t0", "qos": "1", "stage": "solve", "q": "0.99"})
