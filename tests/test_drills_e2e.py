"""Drill e2e: every catalog scenario runs GREEN across a seed window.

Each case stands up the full socket stack (scheduler replicas + lease
service + manager + koordlet-style feeders) under seeded churn at
``time_scale`` compression, injects the scenario's adversarial event,
and asserts the machine-checkable verdict: never-overcommit, post-heal
reconvergence, gang atomicity, bounded RTO/degraded time, no thread/fd
leak, SLO burn within budget (koordinator_tpu/drills/verdict.py).

Marked ``chaos`` AND ``slow``: tier-1's ``-m "not slow"`` keeps it out
of CI; run it with ``pytest -m chaos`` or sweep seed windows with
``SOAK_DRILLS=1 tools/soak.sh`` (the failing seed is printed for exact
replay via ``KOORD_DRILL_SEED_BASE``).
"""

import os

import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.slow]


def drill_seeds():
    """Seed window, env-steerable exactly like chaos_seeds — the soak
    harness sweeps fresh windows and prints the base on failure."""
    base = int(os.environ.get("KOORD_DRILL_SEED_BASE", "0"))
    count = int(os.environ.get("KOORD_DRILL_SEED_COUNT", "0") or 0) or 3
    return list(range(base, base + count))


SCENARIO_NAMES = ("leader_failover", "manager_restart", "rack_storm",
                  "quota_reorg", "tenant_sever", "warm_restart")


@pytest.mark.parametrize("seed", drill_seeds())
@pytest.mark.parametrize("scenario", SCENARIO_NAMES)
def test_drill_scenario_is_green(scenario, seed, tmp_path):
    from koordinator_tpu.drills import run_drill

    verdict = run_drill(scenario, seed, str(tmp_path), time_scale=6.0)
    assert verdict.green, (
        f"replay: run_drill({scenario!r}, seed={seed})\n"
        + verdict.render())
