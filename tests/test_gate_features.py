"""Round-3 gate completions: every SURVEY §2.10 koordlet gate now has a
real implementation behind it — AllocatableEvict strategies, Libpfm4
gating the CPI path, AuditEvents(+HTTPHandler), PerCPUMetric,
HugePageReport, HamiCoreVGPUMonitor."""

import json
import os

import pytest

from koordinator_tpu.api import crds
from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.features import KOORDLET_GATES
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.statesinformer import NodeInfo, PodMeta, StatesInformer
from koordinator_tpu.koordlet.system.config import make_test_config


@pytest.fixture
def cfg(tmp_path):
    return make_test_config(tmp_path)


def gate(name):
    class _Ctx:
        def __enter__(self):
            self.old = KOORDLET_GATES.enabled(name)
            KOORDLET_GATES.set(name, True)

        def __exit__(self, *a):
            KOORDLET_GATES.set(name, self.old)
    return _Ctx()


def be_pod(uid, batch_cpu=0, batch_mem=0, priority=0):
    return PodMeta(
        uid=uid, name=uid, namespace="default", qos_class=QoSClass.BE,
        kube_qos="besteffort", priority=priority,
        requests={ext.RESOURCE_BATCH_CPU: batch_cpu,
                  ext.RESOURCE_BATCH_MEMORY: batch_mem},
        phase="Running",
    )


class TestAllocatableEvict:
    def _ctx(self, cfg, pods, batch_cpu_alloc):
        from koordinator_tpu.koordlet.qosmanager.framework import (
            StrategyContext,
        )
        from koordinator_tpu.koordlet.resourceexecutor import (
            ResourceUpdateExecutor,
        )

        states = StatesInformer()
        states.set_node(NodeInfo(name="n1", allocatable={
            ext.RESOURCE_BATCH_CPU: batch_cpu_alloc}))
        states.set_pods(pods)
        slo = crds.NodeSLO(
            resource_used_threshold_with_be=crds.ResourceThresholdStrategy(
                enable=True,
                cpu_evict_by_allocatable_threshold_percent=100,
                cpu_evict_by_allocatable_lower_percent=80,
            ))
        states.set_node_slo(slo)
        return StrategyContext(states, mc.MetricCache(),
                               ResourceUpdateExecutor(cfg), cfg)

    def test_evicts_when_requests_exceed_shrunken_allocatable(self, cfg):
        from koordinator_tpu.koordlet.qosmanager.evict import (
            AllocatableEvict,
        )
        from koordinator_tpu.koordlet.qosmanager.framework import Evictor

        killed = []
        pods = [be_pod("low", batch_cpu=3000, priority=1),
                be_pod("high", batch_cpu=3000, priority=10)]
        # allocatable shrank to 4000 but 6000 is requested (150% > 100%)
        ctx = self._ctx(cfg, pods, batch_cpu_alloc=4000)
        evictor = Evictor(ctx, lambda pod, reason: killed.append(pod.uid))
        strat = AllocatableEvict(ctx, evictor, resource="cpu")
        with gate("CPUAllocatableEvict"):
            assert strat.enabled()
            strat.update()
        # target = 80% of 4000 = 3200: evicting "low" (3000) brings
        # requests to 3000 <= 3200 — the higher-priority pod survives
        assert killed == ["low"]

    def test_quiet_when_requests_fit(self, cfg):
        from koordinator_tpu.koordlet.qosmanager.evict import (
            AllocatableEvict,
        )
        from koordinator_tpu.koordlet.qosmanager.framework import Evictor

        killed = []
        ctx = self._ctx(cfg, [be_pod("a", batch_cpu=3000)],
                        batch_cpu_alloc=4000)
        strat = AllocatableEvict(
            ctx, Evictor(ctx, lambda p, r: killed.append(p.uid)),
            resource="cpu")
        with gate("CPUAllocatableEvict"):
            strat.update()
        assert killed == []


class TestPerCPUMetric:
    def test_percpu_series_behind_gate(self, cfg):
        from koordinator_tpu.koordlet.metricsadvisor import (
            NodeResourceCollector,
            _Deps,
        )

        t = [100.0]
        deps = _Deps(StatesInformer(), mc.MetricCache(), cfg,
                     lambda: t[0])
        col = NodeResourceCollector(deps)

        def write_stat(total, cpu0, cpu1):
            os.makedirs(cfg.proc_root, exist_ok=True)
            with open(cfg.proc_path("stat"), "w") as f:
                f.write(f"cpu {total} 0 0 1000 0 0 0 0\n"
                        f"cpu0 {cpu0} 0 0 500 0 0 0 0\n"
                        f"cpu1 {cpu1} 0 0 500 0 0 0 0\n")
            with open(cfg.proc_path("meminfo"), "w") as f:
                f.write("MemTotal: 1000 kB\nMemAvailable: 500 kB\n"
                        "Cached: 100 kB\nBuffers: 0 kB\nMemFree: 400 kB\n")

        with gate("PerCPUMetric"):
            write_stat(0, 0, 0)
            col.collect()
            t[0] = 101.0
            write_stat(200, 150, 50)   # 1s later: cpu0 1.5 cores, cpu1 0.5
            col.collect()
        r0 = deps.cache.query(mc.NODE_PERCPU_USAGE, {"cpu": "0"}, end=200.0)
        r1 = deps.cache.query(mc.NODE_PERCPU_USAGE, {"cpu": "1"}, end=200.0)
        assert r0.latest() == pytest.approx(1.5)
        assert r1.latest() == pytest.approx(0.5)

    def test_no_series_without_gate(self, cfg):
        from koordinator_tpu.koordlet.metricsadvisor import (
            NodeResourceCollector,
            _Deps,
        )

        deps = _Deps(StatesInformer(), mc.MetricCache(), cfg, lambda: 1.0)
        col = NodeResourceCollector(deps)
        os.makedirs(cfg.proc_root, exist_ok=True)
        with open(cfg.proc_path("stat"), "w") as f:
            f.write("cpu 0 0 0 0 0 0 0 0\ncpu0 0 0 0 0 0 0 0 0\n")
        with open(cfg.proc_path("meminfo"), "w") as f:
            f.write("MemTotal: 1000 kB\nMemAvailable: 500 kB\n"
                    "Cached: 0 kB\nBuffers: 0 kB\nMemFree: 500 kB\n")
        col.collect()
        assert deps.cache.query(
            mc.NODE_PERCPU_USAGE, {"cpu": "0"}, end=10.0).latest() == 0.0


class TestHugePageReport:
    def test_zone_hugepages_in_annotation_behind_gate(self, cfg):
        from koordinator_tpu.koordlet.nodetopo import NodeTopologyReporter

        # one fake NUMA node with cpu + hugepage sysfs
        node_dir = cfg.sys_path("devices", "system", "node", "node0")
        os.makedirs(os.path.join(node_dir, "hugepages",
                                 "hugepages-2048kB"), exist_ok=True)
        with open(os.path.join(node_dir, "hugepages", "hugepages-2048kB",
                               "nr_hugepages"), "w") as f:
            f.write("128\n")
        cpu_dir = cfg.sys_path("devices", "system", "cpu", "cpu0")
        os.makedirs(os.path.join(cpu_dir, "topology"), exist_ok=True)
        for fn, val in (("core_id", "0"), ("physical_package_id", "0")):
            with open(os.path.join(cpu_dir, "topology", fn), "w") as f:
                f.write(val)
        os.makedirs(os.path.join(node_dir, "cpu0"), exist_ok=True)

        reporter = NodeTopologyReporter(cfg)
        topo = reporter.report()
        assert all(not z.hugepages for z in topo.zones)   # gate off
        with gate("HugePageReport"):
            topo = reporter.report()
        zones = {z.name: z for z in topo.zones}
        assert zones["node0"].hugepages == {"2048kB": 128}
        ann = topo.to_annotations()
        assert json.loads(ann["node.koordinator.sh/hugepages"]) == {
            "node0": {"2048kB": 128}}


class TestHamiVGPUMonitor:
    def test_samples_behind_gate(self, cfg):
        from koordinator_tpu.koordlet.devices import HamiVGPUCollector
        from koordinator_tpu.koordlet.metricsadvisor import _Deps

        root = os.path.join(cfg.var_run_root, "hami-vgpu-metrics")
        os.makedirs(root, exist_ok=True)
        with open(os.path.join(root, "dev0-pod1.json"), "w") as f:
            json.dump({"uuid": "GPU-0", "podUID": "p1",
                       "coreUtilPct": 42.5,
                       "memoryUsedBytes": 1 << 30}, f)
        deps = _Deps(StatesInformer(), mc.MetricCache(), cfg, lambda: 50.0)
        col = HamiVGPUCollector(deps)
        assert not col.enabled()          # gate off
        with gate("HamiCoreVGPUMonitor"):
            assert col.enabled()
            col.collect()
        labels = {"uuid": "GPU-0", "pod_uid": "p1"}
        assert deps.cache.query(
            mc.HAMI_VGPU_CORE_USAGE, labels, end=100.0).latest() == 42.5
        assert deps.cache.query(
            mc.HAMI_VGPU_MEM_USED, labels, end=100.0).latest() == float(1 << 30)


class TestAuditGates:
    def test_daemon_auditor_gated(self, tmp_path, cfg):
        from koordinator_tpu.koordlet.daemon import Daemon

        d = Daemon(cfg=cfg, audit_dir=str(tmp_path / "a1"))
        assert d.auditor is None          # AuditEvents off by default
        d.stop()
        with gate("AuditEvents"):
            d = Daemon(cfg=cfg, audit_dir=str(tmp_path / "a2"))
            assert d.auditor is not None
            d.stop()

    def test_audit_http_handler(self, tmp_path):
        import urllib.request

        from koordinator_tpu.koordlet.audit import Auditor
        from koordinator_tpu.transport.http_gateway import HttpGateway

        auditor = Auditor(str(tmp_path / "audit"), clock=lambda: 7.0)
        auditor.log("eviction", "evict", "pod-1", {"reason": "pressure"})
        gw = HttpGateway(auditor=auditor)
        gw.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{gw.port}/v1/audit?size=10",
                    timeout=10) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["events"][0]["target"] == "pod-1"
            assert doc["events"][0]["reason"] == "pressure"
        finally:
            gw.stop()

    def test_cpi_requires_libpfm4_gate(self, cfg):
        from koordinator_tpu import native
        from koordinator_tpu.koordlet.metricsadvisor import (
            CPICollector,
            _Deps,
        )

        if not native.ensure_built():
            pytest.skip("native lib unavailable")
        deps = _Deps(StatesInformer(), mc.MetricCache(), cfg, lambda: 1.0)
        col = CPICollector(deps)
        with gate("CPICollector"):
            assert not col.enabled()      # Libpfm4 still off
            with gate("Libpfm4"):
                assert col.enabled()
