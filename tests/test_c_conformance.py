"""Cross-language conformance: a compiled C client drives the full wire
protocol against a live Python sidecar.

Closes the round-3 gap "nothing non-Python has ever spoken any of it":
the BASELINE north star is the reference's Go plugins calling into this
framework as a sidecar (frameworkext/interface.go:70, the api.proto:148
contract role), and until a peer with no Python and no numpy completes
HELLO negotiation -> snapshot decode -> state push -> delta watch ->
solve -> lease CAS, that seam is untested.  The client is
native/conformance_client.c; it hand-encodes frames, the JSON documents,
and the little-endian int32 array section.
"""

import json
import os
import subprocess

import jax.numpy as jnp
import pytest

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, resource_vector
from koordinator_tpu.ha import LeaseService
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
from koordinator_tpu.transport import (
    RpcClient,
    RpcServer,
    StateSyncClient,
    StateSyncService,
)
from koordinator_tpu.transport.deltasync import SchedulerBinding
from koordinator_tpu.transport.services import SolveService

R = NUM_RESOURCE_DIMS
SRC = os.path.join(os.path.dirname(__file__), "..", "native",
                   "conformance_client.c")


@pytest.fixture(scope="module")
def client_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("cbin") / "conformance_client")
    try:
        proc = subprocess.run(
            ["gcc", "-O2", "-Wall", "-Werror", "-o", out, SRC],
            capture_output=True, text=True)
    except FileNotFoundError:
        pytest.skip("no C toolchain on this machine")
    if proc.returncode != 0:
        pytest.fail(f"C client failed to compile:\n{proc.stderr}")
    return out


def mk_scheduler():
    cfg = ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32))
    return Scheduler(ClusterSnapshot(capacity=16), config=cfg)


def test_c_client_full_protocol(client_bin):
    server = RpcServer("tcp://127.0.0.1:0")
    service = StateSyncService()
    service.attach(server)
    # state that predates the C client: it must arrive via SNAPSHOT
    service.upsert_node("py-node", resource_vector(cpu=8_000, memory=32_768))
    service.add_pod("py-pod", resource_vector(cpu=1_000, memory=1_024))

    sched = mk_scheduler()
    SolveService(sched).attach(server)
    LeaseService().attach(server)
    server.start()

    # the solver's own feed: a Python sync client over the same socket,
    # exactly the production wiring — the C client's pushed state must
    # reach the scheduler through the commit->broadcast->binding path
    sync = StateSyncClient(SchedulerBinding(sched))
    feed = RpcClient(server.address, on_push=sync.on_push)
    feed.connect()
    try:
        assert sync.bootstrap(feed) == 2

        proc = subprocess.run(
            [client_bin, "127.0.0.1", server.address.rsplit(":", 1)[1],
             str(R)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, (
            f"C client failed (stderr):\n{proc.stderr}\n"
            f"stdout:\n{proc.stdout}")
        result = json.loads(proc.stdout)

        # protocol negotiation: the v1 HELLO was rejected, v3 accepted
        assert result["skew_rejected"] is True
        # snapshot: both pre-existing events, rv consistent, arrays sane
        assert result["snapshot_events"] == 2
        assert result["snapshot_rv"] == 2
        assert result["snapshot_arrays_ok"] is True
        # state pushes committed in order and came back as DELTA pushes
        assert result["node_rv"] == 3 and result["pod_rv"] == 4
        assert result["deltas_seen"] >= 1
        # the solve saw C-originated state: c-pod landed on c-node
        # (its node_selector only matches the label the C client set)
        assert result["c_pod_node"] == "c-node"
        assert "py-pod" in result["assignments"]
        # lease CAS semantics held
        assert result["lease_acquired"] is True
        assert result["stale_cas_refused"] is True

        # and the Python-side scheduler really holds the C state
        assert "c-pod" not in sched.pending
    finally:
        feed.close()
        server.stop()
