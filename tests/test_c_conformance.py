"""Cross-language conformance: a compiled C client drives the full wire
protocol against a live Python sidecar.

Closes the round-3 gap "nothing non-Python has ever spoken any of it":
the BASELINE north star is the reference's Go plugins calling into this
framework as a sidecar (frameworkext/interface.go:70, the api.proto:148
contract role), and until a peer with no Python and no numpy completes
HELLO negotiation -> snapshot decode -> state push -> delta watch ->
solve -> lease CAS, that seam is untested.  The client is
native/conformance_client.c; it hand-encodes frames, the JSON documents,
and the little-endian int32 array section.
"""

import json
import os
import subprocess

import pytest

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, resource_vector

R = NUM_RESOURCE_DIMS
SRC = os.path.join(os.path.dirname(__file__), "..", "native",
                   "conformance_client.c")


@pytest.fixture(scope="module")
def client_bin(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("cbin") / "conformance_client")
    try:
        proc = subprocess.run(
            ["gcc", "-O2", "-Wall", "-Werror", "-o", out, SRC],
            capture_output=True, text=True)
    except FileNotFoundError:
        pytest.skip("no C toolchain on this machine")
    if proc.returncode != 0:
        pytest.fail(f"C client failed to compile:\n{proc.stderr}")
    return out


def test_c_client_full_protocol(client_bin):
    """The C peer drives the SHIPPED binary: ``koord-scheduler
    --listen-socket tcp://...`` assembles the whole sidecar (solve +
    state-sync + lease frames, in-process binding), so this is the
    deployment artifact speaking the protocol, not a test harness."""
    from koordinator_tpu.cmd.binaries import main_koord_scheduler

    asm = main_koord_scheduler([
        "--node-capacity", "16",
        "--listen-socket", "tcp://127.0.0.1:0",
        "--disable-leader-election",
    ])
    sched = asm.component
    # state that predates the C client: it must arrive via SNAPSHOT
    asm.state_sync.upsert_node("py-node",
                               resource_vector(cpu=8_000, memory=32_768))
    asm.state_sync.add_pod("py-pod", resource_vector(cpu=1_000, memory=1_024))

    try:
        proc = subprocess.run(
            [client_bin, "127.0.0.1",
             asm.server.address.rsplit(":", 1)[1], str(R)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, (
            f"C client failed (stderr):\n{proc.stderr}\n"
            f"stdout:\n{proc.stdout}")
        result = json.loads(proc.stdout)

        # protocol negotiation: the v1 HELLO was rejected, v3 accepted
        assert result["skew_rejected"] is True
        # snapshot: both pre-existing events, rv consistent, arrays sane
        assert result["snapshot_events"] == 2
        assert result["snapshot_rv"] == 2
        assert result["snapshot_arrays_ok"] is True
        # state pushes committed in order and came back as DELTA pushes
        assert result["node_rv"] == 3 and result["pod_rv"] == 4
        assert result["deltas_seen"] >= 1
        # the solve saw C-originated state: c-pod landed on c-node
        # (its node_selector only matches the label the C client set)
        assert result["c_pod_node"] == "c-node"
        assert "py-pod" in result["assignments"]
        # lease CAS semantics held
        assert result["lease_acquired"] is True
        assert result["stale_cas_refused"] is True

        # and the binary's scheduler really holds the C state
        assert "c-pod" not in sched.pending
    finally:
        asm.stop()


def test_c_client_drives_runtime_hooks(client_bin, tmp_path):
    """The runtime boundary spoken by a non-Python peer: the C client
    plays the CRI-proxy role against the koordlet BINARY's hook server
    (--runtime-hook-server-addr), asserting GroupIdentity's BE bvt
    resolution, BatchResource's kernel-limit math, and that an unknown
    hook errors without killing the connection — the other half of the
    docs/runtime_boundary.md bespoke-frames contract."""
    from koordinator_tpu.cmd.binaries import main_koordlet

    asm = main_koordlet([
        "--cgroup-root-dir", str(tmp_path / "cg"),
        "--proc-root-dir", str(tmp_path / "proc"),
        "--runtime-hook-server-addr", "tcp://127.0.0.1:0",
    ])
    try:
        port = asm.component.hook_server.address.rsplit(":", 1)[1]
        proc = subprocess.run(
            [client_bin, "--hooks", "127.0.0.1", port],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, (
            f"C hooks client failed (stderr):\n{proc.stderr}\n"
            f"stdout:\n{proc.stdout}")
        result = json.loads(proc.stdout)
        assert result == {"bvt_ok": True, "limits_ok": True,
                          "unknown_rejected": True, "survived": True}
    finally:
        asm.component.stop()
