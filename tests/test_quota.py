import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig, greedy_assign
from koordinator_tpu.quota import (
    QuotaDeviceState,
    QuotaTree,
    charge_quota,
    quota_admission_mask,
)
from koordinator_tpu.quota.tree import UNBOUNDED, hamilton_deltas
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM = ResourceDim.CPU, ResourceDim.MEMORY


def vec(cpu=0, mem=0, fill=0):
    v = np.full(R, fill, dtype=np.int64)
    v[CPU], v[MEM] = cpu, mem
    return v


def unbounded(cpu=None, mem=None):
    v = np.full(R, UNBOUNDED, dtype=np.int64)
    if cpu is not None:
        v[CPU] = cpu
    if mem is not None:
        v[MEM] = mem
    return v


# -- Hamilton apportionment -------------------------------------------------


def test_hamilton_exact_split():
    assert hamilton_deltas(100, 4, [1, 3], ["a", "b"]) == [25, 75]


def test_hamilton_residual_largest_remainder():
    # 100 over weights 1,1,1: base 33 each, residual 1 -> largest remainder
    # (all equal) -> name asc tie-break gives "a" the extra.
    assert hamilton_deltas(100, 3, [1, 1, 1], ["a", "b", "c"]) == [34, 33, 33]
    # remainders 2/3,2/3,2/3 after base... verify conservation always:
    for pool, ws in ((7, [2, 3, 5]), (11, [1, 7, 3]), (1, [9, 9])):
        d = hamilton_deltas(pool, sum(ws), ws, [str(i) for i in range(len(ws))])
        assert sum(d) == pool


def test_hamilton_zero_weight_gets_nothing():
    assert hamilton_deltas(10, 5, [5, 0], ["a", "b"]) == [10, 0]


def test_hamilton_huge_values_exact():
    # the reference needs 128-bit here; python ints are exact
    pool = 2**40
    ws = [2**35, 2**35 + 1]
    d = hamilton_deltas(pool, sum(ws), ws, ["a", "b"])
    assert sum(d) == pool


# -- redistribution ---------------------------------------------------------


def test_redistribution_min_then_fair_share():
    t = QuotaTree(vec(100))
    t.add("a", min=vec(10), max=unbounded(cpu=1000))
    t.add("b", min=vec(20), max=unbounded(cpu=1000))
    # equal shared weights
    t.nodes["a"].shared_weight = vec(1)
    t.nodes["b"].shared_weight = vec(1)
    t.set_request("a", vec(60))
    t.set_request("b", vec(60))
    t.refresh_runtime()
    # start at min (10, 20), pool 70 split 35/35 -> 45/55, both < request
    assert t.runtime_of("a")[CPU] == 45
    assert t.runtime_of("b")[CPU] == 55


def test_redistribution_saturation_waterfill():
    t = QuotaTree(vec(100))
    t.add("a", min=vec(0), max=unbounded(cpu=1000))
    t.add("b", min=vec(0), max=unbounded(cpu=1000))
    t.nodes["a"].shared_weight = vec(1)
    t.nodes["b"].shared_weight = vec(1)
    t.set_request("a", vec(30))
    t.set_request("b", vec(200))
    t.refresh_runtime()
    # round 1: 50/50, a saturates at 30 returning 20; round 2: b gets 70
    assert t.runtime_of("a")[CPU] == 30
    assert t.runtime_of("b")[CPU] == 70


def test_redistribution_no_lent_keeps_min():
    t = QuotaTree(vec(100))
    t.add("a", min=vec(40), max=unbounded(cpu=1000), allow_lent=False)
    t.add("b", min=vec(0), max=unbounded(cpu=1000))
    t.nodes["a"].shared_weight = vec(1)
    t.nodes["b"].shared_weight = vec(1)
    t.set_request("a", vec(5))     # requests less than min but won't lend
    t.set_request("b", vec(500))
    t.refresh_runtime()
    assert t.runtime_of("a")[CPU] == 40   # keeps its min
    assert t.runtime_of("b")[CPU] == 60


def test_redistribution_guarantee_overrides_min():
    t = QuotaTree(vec(100))
    t.add("a", min=vec(10), max=unbounded(cpu=1000), guarantee=vec(30))
    t.add("b", min=vec(0), max=unbounded(cpu=1000))
    t.nodes["a"].shared_weight = vec(1)
    t.nodes["b"].shared_weight = vec(1)
    t.set_request("a", vec(100))
    t.set_request("b", vec(100))
    t.refresh_runtime()
    # a starts at guarantee 30, pool 70 split 35/35 -> a=65, b=35
    assert t.runtime_of("a")[CPU] == 65
    assert t.runtime_of("b")[CPU] == 35


def test_redistribution_request_capped_by_max():
    t = QuotaTree(vec(100))
    t.add("a", min=vec(0), max=unbounded(cpu=25))
    t.add("b", min=vec(0), max=unbounded(cpu=1000))
    t.nodes["a"].shared_weight = vec(1)
    t.nodes["b"].shared_weight = vec(1)
    t.set_request("a", vec(80))   # limited to max 25
    t.set_request("b", vec(80))
    t.refresh_runtime()
    assert t.runtime_of("a")[CPU] == 25
    assert t.runtime_of("b")[CPU] == 75


def test_hierarchical_redistribution():
    t = QuotaTree(vec(100))
    t.add("parent", min=vec(0), max=unbounded(cpu=1000))
    t.add("other", min=vec(0), max=unbounded(cpu=1000))
    t.add("c1", min=vec(0), max=unbounded(cpu=1000), parent="parent")
    t.add("c2", min=vec(0), max=unbounded(cpu=1000), parent="parent")
    for n in t.nodes.values():
        n.shared_weight = vec(1)
    t.set_request("c1", vec(40))
    t.set_request("c2", vec(40))
    t.set_request("other", vec(20))
    t.refresh_runtime()
    # parent aggregates 80, other 20; exactly satisfiable
    assert t.runtime_of("parent")[CPU] == 80
    assert t.runtime_of("other")[CPU] == 20
    assert t.runtime_of("c1")[CPU] == 40
    assert t.runtime_of("c2")[CPU] == 40


# -- device admission -------------------------------------------------------


def build_device(tree, **kw):
    state, index = QuotaDeviceState.from_tree(tree, **kw)
    return state, index


def test_admission_basic_and_parent_chain():
    t = QuotaTree(vec(100, 1000))
    t.add("team", min=vec(0), max=unbounded(cpu=50, mem=500))
    t.add("app", min=vec(0), max=unbounded(cpu=40, mem=400), parent="team")
    t.add("app2", min=vec(0), max=unbounded(cpu=40, mem=400), parent="team")
    t.set_request("app", vec(40, 400))
    t.set_request("app2", vec(40, 400))
    t.refresh_runtime()
    # team aggregates 80 capped at max 50 -> runtime 50, split 25/25 to apps
    assert t.runtime_of("team")[CPU] == 50
    assert t.runtime_of("app")[CPU] == 25
    t.set_used("team", vec(45, 0))   # team nearly exhausted on cpu
    t.set_used("app", vec(10, 0))
    qs, idx = build_device(t)

    req = np.zeros((2, R), np.int32)
    req[0, CPU] = 4   # team headroom 5 left: fits
    req[1, CPU] = 6   # exceeds team (parent) headroom 5, fits app's own 15
    qid = np.full(2, idx["app"], np.int32)
    mask = np.asarray(
        quota_admission_mask(qs, jnp.asarray(req), jnp.asarray(qid))
    )
    assert mask.tolist() == [True, False]

    # without parent checking the second pod is admitted (app headroom 30)
    mask2 = np.asarray(
        quota_admission_mask(
            qs, jnp.asarray(req), jnp.asarray(qid), check_parents=False
        )
    )
    assert mask2.tolist() == [True, True]


def test_admission_no_quota_pod_always_admitted():
    t = QuotaTree(vec(10))
    t.add("q", min=vec(0), max=unbounded(cpu=1))
    t.refresh_runtime()
    qs, _ = build_device(t)
    req = np.zeros((1, R), np.int32)
    req[0, CPU] = 999
    mask = quota_admission_mask(
        qs, jnp.asarray(req), jnp.asarray(np.array([-1], np.int32))
    )
    assert bool(mask[0])


def test_admission_unbounded_dims_unchecked():
    t = QuotaTree(vec(100, 1000))
    t.add("q", min=vec(0), max=unbounded(cpu=50))  # memory unbounded
    t.set_request("q", vec(50, 0))
    t.refresh_runtime()
    qs, idx = build_device(t)
    req = np.zeros((1, R), np.int32)
    req[0, CPU] = 10
    req[0, MEM] = 10**6  # huge but unchecked dim
    mask = quota_admission_mask(
        qs, jnp.asarray(req), jnp.asarray(np.array([idx["q"]], np.int32))
    )
    assert bool(mask[0])


def test_admission_non_preemptible_checks_min():
    t = QuotaTree(vec(100))
    t.add("q", min=vec(10), max=unbounded(cpu=50))
    t.set_request("q", vec(50))
    t.refresh_runtime()
    t.set_used("q", vec(0), non_preemptible=vec(8))
    qs, idx = build_device(t)
    req = np.zeros((2, R), np.int32)
    req[0, CPU] = 2    # 8+2 <= min 10
    req[1, CPU] = 3    # 8+3 > min 10
    qid = np.full(2, idx["q"], np.int32)
    np_flag = jnp.asarray(np.array([True, True]))
    mask = np.asarray(
        quota_admission_mask(qs, jnp.asarray(req), jnp.asarray(qid), np_flag)
    )
    assert mask.tolist() == [True, False]


def test_charge_quota_feedback():
    t = QuotaTree(vec(100))
    t.add("team", min=vec(0), max=unbounded(cpu=50))
    t.add("app", min=vec(0), max=unbounded(cpu=50), parent="team")
    t.set_request("app", vec(50))
    t.refresh_runtime()
    qs, idx = build_device(t)
    req = np.zeros(R, np.int32)
    req[CPU] = 30
    qs2 = charge_quota(qs, jnp.asarray(req), jnp.asarray(idx["app"]))
    # both app and team headroom drop by 30
    assert int(qs2.headroom[idx["app"], CPU]) == int(qs.headroom[idx["app"], CPU]) - 30
    assert int(qs2.headroom[idx["team"], CPU]) == int(qs.headroom[idx["team"], CPU]) - 30
    # uncharge restores
    qs3 = charge_quota(qs2, jnp.asarray(req), jnp.asarray(idx["app"]), sign=-1)
    assert np.array_equal(np.asarray(qs3.headroom), np.asarray(qs.headroom))


def test_admission_stale_quota_id_rejected():
    # a quota_id pointing at a padded/invalid row must reject, not admit
    t = QuotaTree(vec(10))
    t.add("q", min=vec(0), max=unbounded(cpu=5))
    t.refresh_runtime()
    qs, _ = build_device(t)
    req = np.zeros((1, R), np.int32)
    req[0, CPU] = 1
    stale = qs.capacity - 1  # padded row
    mask = quota_admission_mask(
        qs, jnp.asarray(req), jnp.asarray(np.array([stale], np.int32))
    )
    assert not bool(mask[0])


def test_admission_checked_dims_follow_pods_quota():
    # ancestor leaves CPU unbounded but is over-used; the pod's own quota
    # declares CPU, so the reference still checks CPU at the ancestor.
    t = QuotaTree(vec(100))
    t.add("team", min=vec(0), max=np.full(R, UNBOUNDED, np.int64))  # no caps
    t.add("app", min=vec(0), max=unbounded(cpu=40), parent="team")
    t.set_request("app", vec(40))
    t.refresh_runtime()
    # runtime caps at aggregated requests: team runtime == app runtime == 40
    t.set_used("team", vec(36))
    qs, idx = build_device(t)
    req = np.zeros((1, R), np.int32)
    req[0, CPU] = 3  # app headroom 40, team headroom 40-36=4 -> fits
    ok = quota_admission_mask(
        qs, jnp.asarray(req), jnp.asarray(np.array([idx["app"]], np.int32))
    )
    assert bool(ok[0])
    t.set_used("team", vec(39))  # team headroom 1 on its unbounded dim
    qs2, _ = build_device(t)
    ok2 = quota_admission_mask(
        qs2, jnp.asarray(req), jnp.asarray(np.array([idx["app"]], np.int32))
    )
    assert not bool(ok2[0])  # CPU is in app's max -> checked at team too


def test_charge_quota_non_preemptible_updates_min_headroom():
    t = QuotaTree(vec(100))
    t.add("q", min=vec(10), max=unbounded(cpu=50))
    t.set_request("q", vec(50))
    t.refresh_runtime()
    qs, idx = build_device(t)
    req = np.zeros(R, np.int32)
    req[CPU] = 8
    qs2 = charge_quota(qs, jnp.asarray(req), jnp.asarray(idx["q"]),
                       non_preemptible=True)
    assert int(qs2.min_headroom[idx["q"], CPU]) == 2
    # a second 8-core non-preemptible pod must now fail the min check
    mask = quota_admission_mask(
        qs2, jnp.asarray(req[None, :]), jnp.asarray(np.array([idx["q"]], np.int32)),
        jnp.asarray(np.array([True])),
    )
    assert not bool(mask[0])


# -- greedy integration -----------------------------------------------------


def test_greedy_assign_respects_quota():
    alloc = np.zeros((2, R), np.int32)
    alloc[:, CPU] = 10_000
    alloc[:, MEM] = 65_536
    state = ClusterState.from_arrays(alloc)

    t = QuotaTree(vec(20_000, 131_072))
    t.add("q", min=vec(0), max=unbounded(cpu=1_500, mem=131_072))
    t.set_request("q", vec(2_000, 2_048))
    t.refresh_runtime()
    qs, idx = build_device(t)

    req = np.zeros((2, R), np.int32)
    req[:, CPU] = 1_000
    req[:, MEM] = 1_024
    pods = PodBatch.build(
        req,
        quota_id=np.full(2, idx["q"], np.int32),
        node_capacity=state.capacity,
    )
    cfg = ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32),
    )
    a, _, qs2 = jax.jit(greedy_assign)(state, pods, cfg, qs)
    a = np.asarray(a)[:2]
    # quota runtime = 1500 cpu: only one 1000m pod admitted
    assert sorted(a.tolist())[0] == -1
    assert sorted(a.tolist())[1] >= 0
    assert int(qs2.headroom[idx["q"], CPU]) == 500
