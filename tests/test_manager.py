import jax.numpy as jnp
import numpy as np

from koordinator_tpu.manager.noderesource import (
    POLICY_MAX_USAGE_REQUEST,
    POLICY_REQUEST,
    POLICY_USAGE,
    ColocationStrategy,
    batch_allocatable,
    cpu_normalization,
    mid_allocatable,
    node_safety_margin,
)


def arr(*v):
    return jnp.asarray(np.array(v, np.int32))


def test_safety_margin():
    s = ColocationStrategy.default()  # cpu reclaim 60 -> margin 40%
    mc, mm = node_safety_margin(arr(10_000), arr(65_536), s)
    assert int(mc[0]) == 4_000
    assert int(mm[0]) == 65_536 * 35 // 100


def test_batch_by_usage_formula():
    # batch = cap - margin - max(sysUsed, reserved) - hpUsed
    s = ColocationStrategy.default()
    bc, bm = batch_allocatable(
        capacity_cpu=arr(10_000), capacity_mem=arr(100_000),
        system_used_cpu=arr(500), system_used_mem=arr(2_000),
        reserved_cpu=arr(300), reserved_mem=arr(3_000),
        hp_used_cpu=arr(2_000), hp_used_mem=arr(20_000),
        hp_req_cpu=arr(4_000), hp_req_mem=arr(40_000),
        hp_max_used_req_cpu=arr(4_500), hp_max_used_req_mem=arr(45_000),
        strategy=s,
    )
    # cpu: 10000 - 4000 - max(500,300) - 2000 = 3500
    assert int(bc[0]) == 3_500
    # mem: 100000 - 35000 - max(2000,3000) - 20000 = 42000
    assert int(bm[0]) == 42_000


def test_batch_policies_and_threshold():
    s = ColocationStrategy.default().replace(
        cpu_calculate_policy=jnp.int32(POLICY_MAX_USAGE_REQUEST),
        memory_calculate_policy=jnp.int32(POLICY_REQUEST),
        batch_cpu_threshold_pct=jnp.int32(20),
    )
    bc, bm = batch_allocatable(
        capacity_cpu=arr(10_000), capacity_mem=arr(100_000),
        system_used_cpu=arr(500), system_used_mem=arr(2_000),
        reserved_cpu=arr(300), reserved_mem=arr(3_000),
        hp_used_cpu=arr(2_000), hp_used_mem=arr(20_000),
        hp_req_cpu=arr(4_000), hp_req_mem=arr(40_000),
        hp_max_used_req_cpu=arr(4_500), hp_max_used_req_mem=arr(45_000),
        strategy=s,
    )
    # cpu byMaxUsageRequest: 10000-4000-500-4500 = 1000, threshold cap 2000
    assert int(bc[0]) == 1_000
    # mem byRequest: 100000-35000-3000-40000 = 22000
    assert int(bm[0]) == 22_000

    s2 = s.replace(batch_cpu_threshold_pct=jnp.int32(5))
    bc2, _ = batch_allocatable(
        capacity_cpu=arr(10_000), capacity_mem=arr(100_000),
        system_used_cpu=arr(500), system_used_mem=arr(2_000),
        reserved_cpu=arr(300), reserved_mem=arr(3_000),
        hp_used_cpu=arr(2_000), hp_used_mem=arr(20_000),
        hp_req_cpu=arr(4_000), hp_req_mem=arr(40_000),
        hp_max_used_req_cpu=arr(4_500), hp_max_used_req_mem=arr(45_000),
        strategy=s2,
    )
    assert int(bc2[0]) == 500  # capped at 5% of capacity


def test_batch_clamps_negative_to_zero():
    s = ColocationStrategy.default()
    bc, _ = batch_allocatable(
        capacity_cpu=arr(1_000), capacity_mem=arr(1_000),
        system_used_cpu=arr(900), system_used_mem=arr(0),
        reserved_cpu=arr(0), reserved_mem=arr(0),
        hp_used_cpu=arr(900), hp_used_mem=arr(0),
        hp_req_cpu=arr(0), hp_req_mem=arr(0),
        hp_max_used_req_cpu=arr(0), hp_max_used_req_mem=arr(0),
        strategy=s,
    )
    assert int(bc[0]) == 0


def test_mid_allocatable():
    s = ColocationStrategy.default().replace(
        mid_cpu_threshold_pct=jnp.int32(10),
        mid_unallocated_pct=jnp.int32(50),
    )
    mc, mm = mid_allocatable(
        capacity_cpu=arr(10_000), capacity_mem=arr(100_000),
        prod_reclaimable_cpu=arr(800), prod_reclaimable_mem=arr(5_000),
        node_unused_cpu=arr(600), node_unused_mem=arr(50_000),
        unallocated_cpu=arr(400), unallocated_mem=arr(10_000),
        strategy=s,
    )
    # cpu: min(min(800, 600) + 400*50%, 10000*10%) = min(800, 1000) = 800
    assert int(mc[0]) == 800
    # mem: min(min(5000,50000) + 10000*50%, 100000*10%) = min(10000,10000)
    assert int(mm[0]) == 10_000


def test_mid_negative_reclaimable_clamped():
    s = ColocationStrategy.default()
    mc, _ = mid_allocatable(
        capacity_cpu=arr(10_000), capacity_mem=arr(100_000),
        prod_reclaimable_cpu=arr(-500), prod_reclaimable_mem=arr(0),
        node_unused_cpu=arr(600), node_unused_mem=arr(0),
        unallocated_cpu=arr(0), unallocated_mem=arr(0),
        strategy=s,
    )
    assert int(mc[0]) == 0


def test_cpu_normalization_and_vectorization():
    ratio = arr(120, 80, 100)
    out = cpu_normalization(arr(10_000, 10_000, 10_000), ratio)
    assert np.asarray(out).tolist() == [12_000, 8_000, 10_000]


def test_amplification_no_int32_overflow_above_100pct():
    from koordinator_tpu.manager.noderesource import amplify_capacity
    from koordinator_tpu.state.cluster_state import MAX_QUANTITY

    out = amplify_capacity(arr(10_000_000), arr(150))
    assert int(out[0]) == 15_000_000  # would wrap negative with naive *150
    # results are clamped at MAX_QUANTITY to preserve the int32 invariant
    assert int(amplify_capacity(arr(20_000_000), arr(150))[0]) == MAX_QUANTITY
    assert int(amplify_capacity(arr(MAX_QUANTITY), arr(101))[0]) == MAX_QUANTITY
