"""Versioned component config (cmd/component_config.py) vs the
reference's KubeSchedulerConfiguration loading with per-plugin args,
defaulting, and validation (apis/config/types.go:31-396, v1/ defaulting,
validation/)."""

import textwrap

import numpy as np
import pytest

from koordinator_tpu.api.resources import ResourceDim
from koordinator_tpu.cmd.binaries import main_koord_scheduler
from koordinator_tpu.cmd.component_config import (
    ComponentConfigError,
    load_scheduler_config,
)

FULL = textwrap.dedent("""
    apiVersion: kubescheduler.config.k8s.io/v1
    kind: KubeSchedulerConfiguration
    profiles:
    - schedulerName: koord-scheduler
      pluginConfig:
      - name: LoadAwareScheduling
        args:
          resourceWeights: {cpu: 2, memory: 1}
          dominantResourceWeight: 1
          usageThresholds: {cpu: 70, memory: 90}
          aggregated:
            usageThresholds: {cpu: 60}
          estimatedScalingFactors: {cpu: 80}
      - name: NodeResourcesFitPlus
        args:
          resources:
            cpu: {weight: 3, type: MostAllocated}
            memory: {weight: 1, type: LeastAllocated}
      - name: ScarceResourceAvoidance
        args: {resources: [gpu], weight: 2}
      - name: Coscheduling
        args: {defaultTimeout: 300s, enablePreemption: true}
""")


def write(tmp_path, content):
    path = tmp_path / "sched-config.yaml"
    path.write_text(content)
    return str(path)


def test_full_profile_loads_with_defaulting(tmp_path):
    out = load_scheduler_config(write(tmp_path, FULL))
    scoring = out.scoring
    w = np.asarray(scoring.loadaware_resource_weights)
    assert w[ResourceDim.CPU] == 2 and w[ResourceDim.MEMORY] == 1
    assert int(scoring.loadaware_dominant_weight) == 1
    thr = np.asarray(scoring.usage_thresholds)
    assert thr[ResourceDim.CPU] == 70 and thr[ResourceDim.MEMORY] == 90
    agg = np.asarray(scoring.agg_usage_thresholds)
    assert agg[ResourceDim.CPU] == 60 and agg[ResourceDim.MEMORY] == 0
    factors = np.asarray(scoring.estimator_factors)
    # given value applies; unspecified memory keeps the reference default
    assert factors[ResourceDim.CPU] == 80
    assert factors[ResourceDim.MEMORY] == 70
    fp_w = np.asarray(scoring.fitplus_resource_weights)
    assert fp_w[ResourceDim.CPU] == 3 and fp_w[ResourceDim.MEMORY] == 1
    most = np.asarray(scoring.fitplus_most_allocated)
    assert bool(most[ResourceDim.CPU]) and not bool(most[ResourceDim.MEMORY])
    scarce = np.asarray(scoring.scarce_dims)
    assert bool(scarce[ResourceDim.GPU])
    assert int(scoring.scarce_plugin_weight) == 2
    assert out.gang_default_timeout_sec == 300.0
    assert out.enable_preemption is True


def test_empty_plugin_config_is_pure_defaults(tmp_path):
    out = load_scheduler_config(write(tmp_path, textwrap.dedent("""
        kind: KubeSchedulerConfiguration
        profiles:
        - schedulerName: koord-scheduler
    """)))
    from koordinator_tpu.ops.assignment import ScoringConfig

    defaults = ScoringConfig.default()
    assert np.array_equal(np.asarray(out.scoring.usage_thresholds),
                          np.asarray(defaults.usage_thresholds))
    assert out.gang_default_timeout_sec == 600.0
    assert out.enable_preemption is None


@pytest.mark.parametrize("snippet,match", [
    ("- name: Typo\n        args: {}", "unknown pluginConfig"),
    ("- name: LoadAwareScheduling\n        args: {usageThreshold: {}}",
     "unknown args"),
    ("- name: LoadAwareScheduling\n"
     "        args: {usageThresholds: {cpu: 150}}", "outside"),
    ("- name: LoadAwareScheduling\n"
     "        args: {usageThresholds: {floppy: 10}}", "unknown resource"),
    ("- name: NodeResourcesFitPlus\n"
     "        args: {resources: {cpu: {type: BalancedAllocation}}}",
     "unsupported scoring strategy"),
    ("- name: Coscheduling\n"
     "        args: {defaultTimeout: soon}", "bad duration"),
])
def test_validation_is_loud(tmp_path, snippet, match):
    content = textwrap.dedent("""
        kind: KubeSchedulerConfiguration
        profiles:
        - schedulerName: koord-scheduler
          pluginConfig:
    """) + "      " + snippet + "\n"
    with pytest.raises(ComponentConfigError, match=match):
        load_scheduler_config(write(tmp_path, content))


def test_missing_profile_is_an_error(tmp_path):
    with pytest.raises(ComponentConfigError, match="no profile"):
        load_scheduler_config(write(tmp_path, textwrap.dedent("""
            kind: KubeSchedulerConfiguration
            profiles:
            - schedulerName: other-scheduler
        """)))


def test_preemption_from_config_requires_an_evictor(tmp_path):
    with pytest.raises(SystemExit, match="no eviction transport"):
        main_koord_scheduler([
            "--config", write(tmp_path, FULL),
            "--disable-leader-election",
        ])


def test_binary_wires_config_file(tmp_path):
    evictions = []
    asm = main_koord_scheduler([
        "--config", write(tmp_path, FULL),
        "--disable-leader-election",
    ], preempt_fn=lambda victim, preemptor: evictions.append(victim))
    try:
        sched = asm.component
        thr = np.asarray(sched.config.usage_thresholds)
        assert thr[ResourceDim.CPU] == 70
        assert sched.gang_default_timeout_sec == 300.0
        assert sched.enable_preemption is True
        # a gang with no explicit WaitTime inherits the config default
        from koordinator_tpu.scheduler.scheduler import GangRecord

        sched.register_gang(GangRecord(name="g", min_member=2))
        assert sched.gangs["g"].wait_time_sec == 300.0
    finally:
        asm.stop()


class TestDeschedulerConfig:
    FULL = textwrap.dedent("""
        kind: DeschedulerConfiguration
        profiles:
        - name: koord-descheduler
          plugins:
            deschedule:
              enabled: [PodLifeTime, RemovePodsHavingTooManyRestarts]
          pluginConfig:
          - name: LowNodeLoad
            args:
              lowThresholds: {cpu: 40, memory: 50}
              highThresholds: {cpu: 70, memory: 85}
              useDeviationThresholds: true
              anomalyCondition: {consecutiveAbnormalities: 5}
          - name: PodLifeTime
            args: {maxPodLifeTimeSeconds: 3600}
          - name: RemovePodsHavingTooManyRestarts
            args: {podRestartThreshold: 7}
          - name: MigrationController
            args:
              maxMigratingPerNode: 4
              maxMigratingPerWorkload: "10%"
          - name: DefaultEvictor
            args:
              priorityThreshold: 8000
              evictLocalStoragePods: true
              maxNoOfPodsToEvictPerNode: 5
    """)

    def test_full_profile(self, tmp_path):
        from koordinator_tpu.cmd.descheduler_config import (
            load_descheduler_config,
        )

        path = tmp_path / "desched.yaml"
        path.write_text(self.FULL)
        out = load_descheduler_config(str(path))
        low = np.asarray(out.lownodeload.low_thresholds)
        high = np.asarray(out.lownodeload.high_thresholds)
        assert low[ResourceDim.CPU] == 40 and high[ResourceDim.MEMORY] == 85
        # unconfigured resources stay unchecked (-1), not defaulted
        assert low[ResourceDim.GPU] == -1
        assert bool(out.lownodeload.use_deviation)
        assert int(out.lownodeload.anomaly_rounds) == 5
        assert out.pod_lifetime_max_seconds == 3600
        assert out.pod_restart_threshold == 7
        assert out.migration_limits.max_migrating_per_node == 4
        assert out.migration_limits.max_migrating_per_workload == "10%"
        assert out.priority_threshold == 8000
        assert out.evict_local_storage_pods is True
        assert out.max_evictions_per_round == 5
        assert out.deschedule_enabled == [
            "PodLifeTime", "RemovePodsHavingTooManyRestarts"]

    def test_binary_wires_descheduler_config(self, tmp_path):
        from koordinator_tpu.cmd.binaries import main_koord_descheduler
        from koordinator_tpu.descheduler.framework import PodInfo

        path = tmp_path / "desched.yaml"
        path.write_text(self.FULL)
        pods = [PodInfo(uid="old", name="old", namespace="d", node="n1",
                        created=0.0)]
        asm = main_koord_descheduler(
            ["--config", str(path), "--disable-leader-election",
             "--descheduling-interval-seconds", "0"],
            pods_fn=lambda: pods)
        profile = asm.component.profiles[0]
        # config-enabled plugins assembled with config args
        names = {type(p).__name__ for p in profile.deschedule_plugins}
        assert "PodLifeTime" in names
        assert profile.evictor_filter.priority_threshold == 8000
        assert profile.max_evictions_per_round == 5
        # PodLifeTime got the 3600s limit: the ancient pod is descheduled
        assert asm.component.run_once()["default"] >= 1

    def test_cli_flag_overrides_config(self, tmp_path):
        from koordinator_tpu.cmd.binaries import main_koord_descheduler

        path = tmp_path / "desched.yaml"
        path.write_text(self.FULL)
        asm = main_koord_descheduler(
            ["--config", str(path), "--priority-threshold", "100",
             "--disable-leader-election"])
        assert asm.component.profiles[0].evictor_filter \
                  .priority_threshold == 100

    def test_validation_is_loud(self, tmp_path):
        from koordinator_tpu.cmd.component_config import (
            ComponentConfigError,
        )
        from koordinator_tpu.cmd.descheduler_config import (
            load_descheduler_config,
        )

        path = tmp_path / "bad.yaml"
        path.write_text(textwrap.dedent("""
            kind: DeschedulerConfiguration
            profiles:
            - name: koord-descheduler
              pluginConfig:
              - name: LowNodeLoad
                args: {lowThresholds: {cpu: 400}}
        """))
        with pytest.raises(ComponentConfigError, match="outside"):
            load_descheduler_config(str(path))
        path.write_text(textwrap.dedent("""
            kind: DeschedulerConfiguration
            profiles:
            - name: koord-descheduler
              pluginConfig:
              - name: MigrationController
                args: {maxMigratingPerWorkload: "150%"}
        """))
        with pytest.raises(ComponentConfigError, match="outside"):
            load_descheduler_config(str(path))
