"""Versioned component config (cmd/component_config.py) vs the
reference's KubeSchedulerConfiguration loading with per-plugin args,
defaulting, and validation (apis/config/types.go:31-396, v1/ defaulting,
validation/)."""

import textwrap

import numpy as np
import pytest

from koordinator_tpu.api.resources import ResourceDim
from koordinator_tpu.cmd.binaries import main_koord_scheduler
from koordinator_tpu.cmd.component_config import (
    ComponentConfigError,
    load_scheduler_config,
)

FULL = textwrap.dedent("""
    apiVersion: kubescheduler.config.k8s.io/v1
    kind: KubeSchedulerConfiguration
    profiles:
    - schedulerName: koord-scheduler
      pluginConfig:
      - name: LoadAwareScheduling
        args:
          resourceWeights: {cpu: 2, memory: 1}
          dominantResourceWeight: 1
          usageThresholds: {cpu: 70, memory: 90}
          aggregated:
            usageThresholds: {cpu: 60}
          estimatedScalingFactors: {cpu: 80}
      - name: NodeResourcesFitPlus
        args:
          resources:
            cpu: {weight: 3, type: MostAllocated}
            memory: {weight: 1, type: LeastAllocated}
      - name: ScarceResourceAvoidance
        args: {resources: [gpu], weight: 2}
      - name: Coscheduling
        args: {defaultTimeout: 300s, enablePreemption: true}
""")


def write(tmp_path, content):
    path = tmp_path / "sched-config.yaml"
    path.write_text(content)
    return str(path)


def test_full_profile_loads_with_defaulting(tmp_path):
    out = load_scheduler_config(write(tmp_path, FULL))
    scoring = out.scoring
    w = np.asarray(scoring.loadaware_resource_weights)
    assert w[ResourceDim.CPU] == 2 and w[ResourceDim.MEMORY] == 1
    assert int(scoring.loadaware_dominant_weight) == 1
    thr = np.asarray(scoring.usage_thresholds)
    assert thr[ResourceDim.CPU] == 70 and thr[ResourceDim.MEMORY] == 90
    agg = np.asarray(scoring.agg_usage_thresholds)
    assert agg[ResourceDim.CPU] == 60 and agg[ResourceDim.MEMORY] == 0
    factors = np.asarray(scoring.estimator_factors)
    # given value applies; unspecified memory keeps the reference default
    assert factors[ResourceDim.CPU] == 80
    assert factors[ResourceDim.MEMORY] == 70
    fp_w = np.asarray(scoring.fitplus_resource_weights)
    assert fp_w[ResourceDim.CPU] == 3 and fp_w[ResourceDim.MEMORY] == 1
    most = np.asarray(scoring.fitplus_most_allocated)
    assert bool(most[ResourceDim.CPU]) and not bool(most[ResourceDim.MEMORY])
    scarce = np.asarray(scoring.scarce_dims)
    assert bool(scarce[ResourceDim.GPU])
    assert int(scoring.scarce_plugin_weight) == 2
    assert out.gang_default_timeout_sec == 300.0
    assert out.enable_preemption is True


def test_empty_plugin_config_is_pure_defaults(tmp_path):
    out = load_scheduler_config(write(tmp_path, textwrap.dedent("""
        kind: KubeSchedulerConfiguration
        profiles:
        - schedulerName: koord-scheduler
    """)))
    from koordinator_tpu.ops.assignment import ScoringConfig

    defaults = ScoringConfig.default()
    assert np.array_equal(np.asarray(out.scoring.usage_thresholds),
                          np.asarray(defaults.usage_thresholds))
    assert out.gang_default_timeout_sec == 600.0
    assert out.enable_preemption is None


@pytest.mark.parametrize("snippet,match", [
    ("- name: Typo\n        args: {}", "unknown pluginConfig"),
    ("- name: LoadAwareScheduling\n        args: {usageThreshold: {}}",
     "unknown args"),
    ("- name: LoadAwareScheduling\n"
     "        args: {usageThresholds: {cpu: 150}}", "outside"),
    ("- name: LoadAwareScheduling\n"
     "        args: {usageThresholds: {floppy: 10}}", "unknown resource"),
    ("- name: NodeResourcesFitPlus\n"
     "        args: {resources: {cpu: {type: BalancedAllocation}}}",
     "unsupported scoring strategy"),
    ("- name: Coscheduling\n"
     "        args: {defaultTimeout: soon}", "bad duration"),
])
def test_validation_is_loud(tmp_path, snippet, match):
    content = textwrap.dedent("""
        kind: KubeSchedulerConfiguration
        profiles:
        - schedulerName: koord-scheduler
          pluginConfig:
    """) + "      " + snippet + "\n"
    with pytest.raises(ComponentConfigError, match=match):
        load_scheduler_config(write(tmp_path, content))


def test_missing_profile_is_an_error(tmp_path):
    with pytest.raises(ComponentConfigError, match="no profile"):
        load_scheduler_config(write(tmp_path, textwrap.dedent("""
            kind: KubeSchedulerConfiguration
            profiles:
            - schedulerName: other-scheduler
        """)))


def test_preemption_from_config_requires_an_evictor(tmp_path):
    with pytest.raises(SystemExit, match="no eviction transport"):
        main_koord_scheduler([
            "--config", write(tmp_path, FULL),
            "--disable-leader-election",
        ])


def test_binary_wires_config_file(tmp_path):
    evictions = []
    asm = main_koord_scheduler([
        "--config", write(tmp_path, FULL),
        "--disable-leader-election",
    ], preempt_fn=lambda victim, preemptor: evictions.append(victim))
    try:
        sched = asm.component
        thr = np.asarray(sched.config.usage_thresholds)
        assert thr[ResourceDim.CPU] == 70
        assert sched.gang_default_timeout_sec == 300.0
        assert sched.enable_preemption is True
        # a gang with no explicit WaitTime inherits the config default
        from koordinator_tpu.scheduler.scheduler import GangRecord

        sched.register_gang(GangRecord(name="g", min_member=2))
        assert sched.gangs["g"].wait_time_sec == 300.0
    finally:
        asm.stop()
