"""Multi-host distributed solve: two real processes under jax.distributed
jointly form one ("pods", "nodes") mesh (the DCN path, SURVEY §2.11 —
"across hosts DCN via jax.distributed") and run the sharded batch solve;
every host must reach the same assignments as a single-process solve.

Each worker gets 4 virtual CPU devices (xla_force_host_platform_device
_count), so the 2-process global mesh has 8 — the same mesh shape the
single-process parity tests (test_mesh.py) use.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    coordinator, pid = sys.argv[1], int(sys.argv[2])
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=2, process_id=pid)
    assert jax.device_count() == 8 and jax.local_device_count() == 4

    import numpy as np
    from jax.experimental import multihost_utils
    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
    from koordinator_tpu.ops.assignment import ScoringConfig
    from koordinator_tpu.ops.batch_assign import batch_assign
    from koordinator_tpu.parallel.mesh import (
        shard_cluster_state, shard_pod_batch, solver_mesh)
    from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

    R = NUM_RESOURCE_DIMS
    rng = np.random.default_rng(42)       # identical data on both hosts
    n_nodes, n_pods = 256, 512
    alloc = np.zeros((n_nodes, R), np.int32)
    alloc[:, ResourceDim.CPU] = rng.integers(8_000, 64_000, n_nodes)
    alloc[:, ResourceDim.MEMORY] = rng.integers(16_384, 262_144, n_nodes)
    usage = (alloc * rng.random((n_nodes, R)) * 0.4).astype(np.int32)
    state = ClusterState.from_arrays(alloc, usage=usage, capacity=n_nodes)
    req = np.zeros((n_pods, R), np.int32)
    req[:, ResourceDim.CPU] = rng.integers(100, 2_000, n_pods)
    req[:, ResourceDim.MEMORY] = rng.integers(128, 4_096, n_pods)
    pods = PodBatch.build(
        req, priority=rng.integers(3000, 9999, n_pods).astype(np.int32),
        node_capacity=n_nodes, capacity=n_pods)
    cfg = ScoringConfig.default()

    # single-device reference on host-local data
    ref, _, _ = batch_assign(state, pods, cfg)
    ref = np.asarray(ref)

    # the distributed solve: global mesh across both processes
    mesh = solver_mesh(pods_axis=2)
    assert mesh.devices.size == 8
    gstate = shard_cluster_state(state, mesh)
    gpods = shard_pod_batch(pods, mesh)
    with mesh:
        out, _, _ = batch_assign(gstate, gpods, cfg)
    got = np.asarray(multihost_utils.process_allgather(out, tiled=True))

    np.testing.assert_array_equal(got, ref)
    print(f"OK process {pid}: {int((got >= 0).sum())} assigned")
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_solve_matches_single(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    coordinator = f"127.0.0.1:{_free_port()}"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=repo_root)
        for pid in range(2)
    ]
    outs = []
    for proc in procs:
        try:
            out, _ = proc.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("distributed workers timed out")
        outs.append(out)
    for pid, (proc, out) in enumerate(zip(procs, outs)):
        assert proc.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"OK process {pid}" in out
