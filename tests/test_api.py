import numpy as np

from koordinator_tpu.api.priority import (
    PriorityClass,
    priority_band_tensor,
    priority_class_of,
)
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.resources import (
    NUM_RESOURCE_DIMS,
    ResourceDim,
    resource_vector,
    stack_vectors,
)


def test_qos_parse():
    assert QoSClass.parse("LS") is QoSClass.LS
    assert QoSClass.parse("lse") is QoSClass.LSE
    assert QoSClass.parse("") is QoSClass.NONE
    assert QoSClass.parse("bogus") is QoSClass.NONE
    assert QoSClass.BE.is_best_effort
    assert QoSClass.LSR.is_latency_sensitive
    assert not QoSClass.BE.is_latency_sensitive


def test_priority_bands_scalar():
    assert priority_class_of(9500) is PriorityClass.PROD
    assert priority_class_of(9000) is PriorityClass.PROD
    assert priority_class_of(9999) is PriorityClass.PROD
    assert priority_class_of(7500) is PriorityClass.MID
    assert priority_class_of(5500) is PriorityClass.BATCH
    assert priority_class_of(3000) is PriorityClass.FREE
    assert priority_class_of(0) is PriorityClass.NONE
    assert priority_class_of(8000) is PriorityClass.NONE


def test_priority_bands_tensor_matches_scalar():
    import jax.numpy as jnp

    vals = np.array([9500, 7000, 5999, 3500, 123, 8000, 9999], dtype=np.int32)
    bands = priority_band_tensor(jnp.asarray(vals))
    expect = [int(priority_class_of(int(v))) for v in vals]
    assert list(np.asarray(bands)) == expect


def test_resource_vector():
    v = resource_vector({"cpu": 4000, "memory": 8192})
    assert v[ResourceDim.CPU] == 4000
    assert v[ResourceDim.MEMORY] == 8192
    assert v.sum() == 12192

    v2 = resource_vector(cpu=1000, gpu=2000)
    assert v2[ResourceDim.GPU] == 2000

    m = stack_vectors([v, v2], capacity=8)
    assert m.shape == (8, NUM_RESOURCE_DIMS)
    assert m[1, ResourceDim.CPU] == 1000
    assert (m[2:] == 0).all()
