"""SLO burn-rate engine (ISSUE 5): windows, alerts, /debug/slo, e2e.

Covers the spec kinds' window math against hand-fed registries, the
fire/clear hysteresis state machine, both /debug/slo surfaces, and the
acceptance flow: a fault-injected slow solve (transport/faults.py
``solve_delay``) drives the scheduler past the latency SLO — the fast
burn fires within its window bound, /debug/slo names the offending SLO,
a flight-recorder dump triggers, and the alert clears after recovery.
"""

import json
import urllib.request

import pytest

from koordinator_tpu import metrics
from koordinator_tpu.api.resources import resource_vector
from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec
from koordinator_tpu.slo_monitor import (
    KIND_GAUGE,
    KIND_LATENCY,
    KIND_RATIO,
    BurnWindow,
    SloMonitor,
    SloSpec,
    default_specs,
)


class FakeClock:
    def __init__(self, t=10_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def make_monitor(specs, registry, clock, **kw):
    return SloMonitor(specs=specs, registries=(registry,), clock=clock,
                      **kw)


def latency_spec(**kw):
    defaults = dict(
        name="lat", description="p99 latency", kind=KIND_LATENCY,
        metric="t_lat_seconds", threshold=0.2, objective=0.01,
        fast=BurnWindow(window_s=60.0, fire_burn=14.4),
        slow=BurnWindow(window_s=600.0, fire_burn=1.0))
    defaults.update(kw)
    return SloSpec(**defaults)


class TestWindowMath:
    def test_latency_bad_fraction_from_bucket_deltas(self):
        reg = metrics.Registry("t")
        h = reg.histogram("lat_seconds", buckets=(0.1, 0.25, 1.0))
        clock = FakeClock()
        mon = make_monitor([latency_spec()], reg, clock)
        for _ in range(100):
            h.observe(0.01)        # pre-window history
        mon.sample_once()          # baseline cumulative counts
        for _ in range(90):
            h.observe(0.05)        # good
        for _ in range(10):
            h.observe(0.9)         # bad (> 0.2)
        clock.tick(10.0)
        report = mon.tick()
        fast = report["slos"][0]["windows"]["fast"]
        assert not fast["no_data"]
        # windowed DELTA: only the 100 observations between the two
        # samples count, not the pre-baseline history
        assert fast["events"] == 100.0
        # 10 observations above the 0.2 threshold, plus the interpolated
        # 0.1-0.25-bucket share above 0.2 (zero here: that bucket is empty)
        assert fast["bad_fraction"] == pytest.approx(0.10)
        assert fast["burn_rate"] == pytest.approx(10.0)
        assert fast["p99_s"] > 0.2

    def test_latency_threshold_interpolates_inside_a_bucket(self):
        reg = metrics.Registry("t")
        h = reg.histogram("lat_seconds", buckets=(0.1, 0.3, 1.0))
        clock = FakeClock()
        mon = make_monitor([latency_spec()], reg, clock)
        h.observe(0.15)       # seed the series, then baseline
        mon.sample_once()
        for _ in range(100):
            h.observe(0.15)   # all land in the (0.1, 0.3] bucket
        clock.tick(10.0)
        fast = mon.tick()["slos"][0]["windows"]["fast"]
        # threshold 0.2 bisects the bucket: half the mass counts bad
        assert fast["bad_fraction"] == pytest.approx(0.5)

    def test_latency_aggregates_across_label_sets(self):
        reg = metrics.Registry("t")
        # the threshold (0.2) is an exact bucket bound, so the bad
        # fraction needs no interpolation: exactly the Bind observation
        h = reg.histogram("lat_seconds", buckets=(0.1, 0.2, 1.0))
        clock = FakeClock()
        mon = make_monitor([latency_spec()], reg, clock)
        h.observe(0.05, labels={"phase": "Solve"})   # seed both series
        h.observe(0.9, labels={"phase": "Bind"})
        mon.sample_once()
        h.observe(0.05, labels={"phase": "Solve"})
        h.observe(0.9, labels={"phase": "Bind"})
        clock.tick(5.0)
        fast = mon.tick()["slos"][0]["windows"]["fast"]
        assert fast["events"] == 2.0
        assert fast["bad_fraction"] == pytest.approx(0.5)

    def test_threshold_at_last_bound_still_counts_inf_observations_bad(self):
        """A threshold at/above the last finite bucket bound must not
        bless +Inf-bucket observations: a 5s solve cannot satisfy a 1s
        SLO just because the buckets stop at 1s (review finding)."""
        reg = metrics.Registry("t")
        h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
        clock = FakeClock()
        mon = make_monitor([latency_spec(threshold=1.0)], reg, clock)
        h.observe(0.05)
        mon.sample_once()
        h.observe(0.05)    # provably good
        h.observe(5.0)     # +Inf bucket: unprovable -> bad
        clock.tick(5.0)
        fast = mon.tick()["slos"][0]["windows"]["fast"]
        assert fast["events"] == 2.0
        assert fast["bad_fraction"] == pytest.approx(0.5)

    def test_single_sample_is_no_data_not_zero_burn_confidence(self):
        reg = metrics.Registry("t")
        h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
        h.observe(0.9)
        clock = FakeClock()
        mon = make_monitor([latency_spec()], reg, clock)
        report = mon.tick()   # exactly one sample: no delta computable
        fast = report["slos"][0]["windows"]["fast"]
        assert fast["no_data"] is True
        assert fast["burn_rate"] == 0.0
        assert report["breached"] == []

    def test_gauge_time_above_threshold(self):
        reg = metrics.Registry("t")
        g = reg.gauge("staleness_seconds")
        clock = FakeClock()
        spec = SloSpec(
            name="stale", description="d", kind=KIND_GAUGE,
            metric="t_staleness_seconds", threshold=30.0, objective=0.05,
            fast=BurnWindow(window_s=100.0, fire_burn=14.4),
            slow=BurnWindow(window_s=1000.0, fire_burn=1.0))
        mon = make_monitor([spec], reg, clock)
        for value in (1.0, 1.0, 45.0, 50.0):   # 2 of 4 samples above
            g.set(value)
            mon.sample_once()
            clock.tick(10.0)
        fast = mon.evaluate()["slos"][0]["windows"]["fast"]
        assert fast["bad_fraction"] == pytest.approx(0.5)
        assert fast["burn_rate"] == pytest.approx(10.0)

    def test_ratio_counter_over_denominator(self):
        reg = metrics.Registry("t")
        shed = reg.counter("sheds_total")
        rounds = reg.counter("rounds_total")
        clock = FakeClock()
        spec = SloSpec(
            name="shed", description="d", kind=KIND_RATIO,
            metric="t_sheds_total", denominator="t_rounds_total",
            objective=0.01,
            fast=BurnWindow(window_s=100.0, fire_burn=14.4),
            slow=BurnWindow(window_s=1000.0, fire_burn=1.0))
        mon = make_monitor([spec], reg, clock)
        shed.inc(0)
        rounds.inc(0)
        mon.sample_once()
        rounds.inc(50)
        shed.inc(2)
        clock.tick(10.0)
        fast = mon.tick()["slos"][0]["windows"]["fast"]
        assert fast["bad_fraction"] == pytest.approx(0.04)
        assert fast["burn_rate"] == pytest.approx(4.0)
        assert fast["denominator"] == 50.0

    def test_ratio_zero_denominator_is_no_data(self):
        reg = metrics.Registry("t")
        reg.counter("sheds_total").inc(0)
        reg.counter("rounds_total").inc(0)
        clock = FakeClock()
        spec = SloSpec(
            name="shed", description="d", kind=KIND_RATIO,
            metric="t_sheds_total", denominator="t_rounds_total",
            objective=0.01)
        mon = make_monitor([spec], reg, clock)
        mon.sample_once()
        clock.tick(5.0)
        fast = mon.tick()["slos"][0]["windows"]["fast"]
        assert fast["no_data"] is True


class TestAlertStateMachine:
    def _burning_monitor(self, clock):
        """A latency monitor plus the knob to make it burn: observing
        bad values then ticking.  The series is seeded before the
        baseline sample (windowed deltas need two samples)."""
        reg = metrics.Registry("t")
        h = reg.histogram("lat_seconds", buckets=(0.1, 0.25, 1.0))
        mon = make_monitor([latency_spec()], reg, clock)
        h.observe(0.01)
        return mon, h

    def test_fire_clear_hysteresis(self):
        clock = FakeClock()
        fired = []
        mon, h = self._burning_monitor(clock)
        mon.on_breach = lambda spec, doc: fired.append(spec.name)
        mon.sample_once()
        for _ in range(10):
            h.observe(0.9)
        clock.tick(5.0)
        report = mon.tick()
        assert report["breached"] == ["lat"]
        assert fired == ["lat"]
        assert metrics.slo_breached.value({"slo": "lat"}) == 1.0
        assert metrics.slo_alerts_total.value(
            {"slo": "lat", "phase": "fire"}) == 1.0
        # still burning next tick: no re-fire (one alert per breach)
        clock.tick(5.0)
        mon.tick()
        assert metrics.slo_alerts_total.value(
            {"slo": "lat", "phase": "fire"}) == 1.0
        assert fired == ["lat"]
        # recovery: good observations, the window slides past the bad
        for _ in range(30):
            h.observe(0.01)
            clock.tick(5.0)
            report = mon.tick()
        assert report["breached"] == []
        assert metrics.slo_breached.value({"slo": "lat"}) == 0.0
        assert metrics.slo_alerts_total.value(
            {"slo": "lat", "phase": "clear"}) == 1.0
        state = report["slos"][0]
        assert state["breaches_total"] == 1
        assert state["peak_burn"]["fast"] >= 14.4

    def test_burn_below_fire_threshold_never_alerts(self):
        clock = FakeClock()
        mon, h = self._burning_monitor(clock)
        mon.sample_once()
        # 5% bad of a 1% budget = burn 5 — over budget but under the
        # 14.4 page threshold
        for _ in range(95):
            h.observe(0.01)
        for _ in range(5):
            h.observe(0.9)
        clock.tick(5.0)
        report = mon.tick()
        fast = report["slos"][0]["windows"]["fast"]
        assert fast["burn_rate"] == pytest.approx(5.0)
        assert report["breached"] == []

    def test_on_breach_exception_never_kills_evaluation(self):
        clock = FakeClock()
        mon, h = self._burning_monitor(clock)

        def boom(spec, doc):
            raise RuntimeError("observer bug")

        mon.on_breach = boom
        mon.sample_once()
        for _ in range(10):
            h.observe(0.9)
        clock.tick(5.0)
        report = mon.tick()   # must not raise
        assert report["breached"] == ["lat"]

    def test_peak_burn_and_gauges_per_window(self):
        clock = FakeClock()
        mon, h = self._burning_monitor(clock)
        mon.sample_once()
        for _ in range(10):
            h.observe(0.9)
        clock.tick(5.0)
        mon.tick()
        assert metrics.slo_burn_rate.value(
            {"slo": "lat", "window": "fast"}) == pytest.approx(100.0)
        assert metrics.slo_burn_rate.value(
            {"slo": "lat", "window": "slow"}) == pytest.approx(100.0)


class TestDefaultSpecsAndSampling:
    def test_default_specs_reference_registered_metrics(self):
        known = set()
        for reg in metrics.ALL_REGISTRIES:
            for full, m in reg.items():
                known.add(full)
                if isinstance(m, metrics.Histogram):
                    known.add(f"{full}_count")
        for spec in default_specs():
            base = (spec.metric[: -len("_count")]
                    if spec.metric.endswith("_count") else spec.metric)
            assert spec.metric in known or base in known, spec.metric
            if spec.denominator:
                assert spec.denominator in known, spec.denominator

    def test_sample_once_covers_counters_gauges_histograms(self):
        reg = metrics.Registry("s")
        reg.counter("c_total").inc(3, {"a": "b"})
        reg.gauge("g").set(7.0)
        reg.histogram("h_seconds", buckets=(0.1, 1.0)).observe(0.05)
        clock = FakeClock()
        mon = make_monitor([], reg, clock)
        appended = mon.sample_once()
        assert appended >= 1 + 1 + (2 + 2)
        assert mon.cache.query("s_c_total", {"a": "b"}).latest() == 3.0
        assert mon.cache.query("s_g").latest() == 7.0
        assert mon.cache.query(
            "s_h_seconds_bucket", {"le": "0.1"}).latest() == 1.0
        assert mon.cache.query("s_h_seconds_count").latest() == 1.0

    def test_background_sampler_start_stop(self):
        reg = metrics.Registry("bg")
        reg.gauge("g").set(1.0)
        mon = SloMonitor(specs=[], registries=(reg,),
                         sample_interval_s=0.01)
        mon.start()
        try:
            import time

            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if not mon.cache.query("bg_g").empty:
                    break
                time.sleep(0.01)
            assert not mon.cache.query("bg_g").empty
        finally:
            mon.stop()
        assert mon._thread is None


# ---- the acceptance flow ---------------------------------------------------


def make_sched(**kw):
    snap = ClusterSnapshot(capacity=8)
    snap.upsert_node(NodeSpec(
        name="n0",
        allocatable=resource_vector(cpu=1_000_000, memory=1_000_000)))
    return Scheduler(snap, **kw)


class TestEndToEndBreach:
    def test_slow_solve_breach_fires_dumps_and_recovers(self):
        """Acceptance: a fault-injected slow solve drives the scheduler
        past the latency SLO; the fast-burn window fires within its
        bound, /debug/slo names the offending SLO, the flight recorder
        dumps, and the alert clears after recovery (hysteresis)."""
        from koordinator_tpu.scheduler.services import DebugService
        from koordinator_tpu.transport.faults import (
            FaultConfig,
            FaultInjector,
        )

        inj = FaultInjector(seed=3, config=FaultConfig(
            solve_delay_p=1.0, solve_delay_ms=60.0))
        sched = make_sched(faults=inj)
        clock = FakeClock()
        spec = latency_spec(
            metric="koord_scheduler_scheduling_duration_seconds",
            threshold=0.05,
            fast=BurnWindow(window_s=30.0, fire_burn=14.4),
            slow=BurnWindow(window_s=300.0, fire_burn=1.0))
        mon = SloMonitor(
            specs=[spec], clock=clock,
            on_breach=lambda s, d: sched.flight_recorder.dump_now(
                f"slo:{s.name}"))
        sched.slo_monitor = mon
        service = DebugService(sched)

        dumps_before = metrics.round_flight_dumps.value(
            labels={"reason": "slo:lat"})
        mon.sample_once()
        first_bad_at = clock.t
        seq = 0
        fired_at = None
        for _ in range(4):
            sched.enqueue(PodSpec(
                name=f"p{seq}",
                requests=resource_vector(cpu=100, memory=64)))
            seq += 1
            sched.schedule_round()
            assert inj.injected["solve_delay"] >= 1
            clock.tick(2.0)
            report = mon.tick()
            if report["breached"]:
                fired_at = clock.t
                break
        # the fast-burn alert fired, and within the fast window bound
        assert fired_at is not None, "fast burn never fired"
        assert fired_at - first_bad_at <= spec.fast.window_s

        # /debug/slo (DebugService surface) reports the breach by name
        status, body = service.handle("/debug/slo")
        assert status == 200
        assert body["breached"] == ["lat"]
        [slo] = body["slos"]
        assert slo["name"] == "lat" and slo["breached"]
        assert slo["windows"]["fast"]["burn_rate"] >= 14.4

        # the breach dumped the latest round's flight record
        assert metrics.round_flight_dumps.value(
            labels={"reason": "slo:lat"}) == dumps_before + 1
        assert metrics.slo_alerts_total.value(
            {"slo": "lat", "phase": "fire"}) == 1.0

        # recovery: heal the injector, run fast rounds until the fast
        # window slides past the slow ones — hysteresis clears
        inj.heal()
        cleared = False
        for _ in range(30):
            sched.enqueue(PodSpec(
                name=f"p{seq}",
                requests=resource_vector(cpu=100, memory=64)))
            seq += 1
            sched.schedule_round()
            clock.tick(2.0)
            report = mon.tick()
            if not report["breached"]:
                cleared = True
                break
        assert cleared, "alert never cleared after recovery"
        assert metrics.slo_alerts_total.value(
            {"slo": "lat", "phase": "clear"}) == 1.0
        status, body = service.handle("/debug/slo")
        assert body["breached"] == []
        # the breach history survives the clear
        assert body["slos"][0]["breaches_total"] == 1
        assert body["slos"][0]["peak_burn"]["fast"] >= 14.4

    def test_debug_slo_over_http_gateway(self):
        from koordinator_tpu.transport.http_gateway import HttpGateway

        sched = make_sched()
        clock = FakeClock()
        sched.slo_monitor = SloMonitor(specs=default_specs(), clock=clock)
        gw = HttpGateway(scheduler=sched)
        gw.start()
        try:
            base = f"http://127.0.0.1:{gw.port}"
            sched.enqueue(PodSpec(
                name="p0", requests=resource_vector(cpu=100, memory=64)))
            sched.schedule_round()
            clock.tick(5.0)
            with urllib.request.urlopen(base + "/debug/slo",
                                        timeout=5) as r:
                assert r.status == 200
                body = json.loads(r.read())
            names = {s["name"] for s in body["slos"]}
            assert "scheduling_latency_p99" in names
            assert body["breached"] == []
            # the profiler endpoint ships dark: 403 until armed
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    base + "/debug/profile?seconds=0.01", timeout=5)
            assert ei.value.code == 403
        finally:
            gw.stop()

    def test_debug_slo_without_monitor_is_501(self):
        from koordinator_tpu.scheduler.services import DebugService

        sched = make_sched()
        status, body = DebugService(sched).handle("/debug/slo")
        assert status == 501
        assert "SLO" in body["error"] or "slo" in body["error"].lower()
