"""ScheduleExplanation persistence + workload auditor
(scheduler/explanation.py) vs frameworkext/schedule_diagnosis.go:44-108 and
frameworkext/workloadauditor/workload_auditor.go."""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, resource_vector
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.scheduler import ClusterSnapshot, NodeSpec, PodSpec, Scheduler
from koordinator_tpu.scheduler.diagnosis import PodDiagnosis
from koordinator_tpu.scheduler.explanation import (
    ExplanationStore,
    WorkloadAuditor,
)

R = NUM_RESOURCE_DIMS


def diag(**kw):
    defaults = dict(total_nodes=4, feasible_nodes=0,
                    insufficient_resources=4, usage_over_threshold=0,
                    affinity_mismatch=0, quota_rejected=False, invalid=0)
    defaults.update(kw)
    return PodDiagnosis(**defaults)


def test_async_record_drain_and_delete():
    store = ExplanationStore(clock=lambda: 42.0)
    store.record("p1", diag())
    assert store.get("p1") is None          # queued, not yet written
    assert store.drain() == 1
    exp = store.get("p1")
    assert exp.pod_name == "p1" and exp.update_time == 42.0
    assert "4 insufficient resources" in exp.reasons[0]
    store.delete("p1")
    assert store.get("p1") is None


def test_blocking_mode_writes_through():
    store = ExplanationStore(blocking=True)
    store.record("p1", diag())
    assert store.get("p1") is not None


def test_queue_bound_drops_instead_of_blocking():
    store = ExplanationStore(queue_size=2)
    for i in range(5):
        store.record(f"p{i}", diag())
    assert store.dropped == 3
    assert store.drain() == 2


def test_capacity_evicts_oldest():
    store = ExplanationStore(capacity=2, blocking=True)
    for i in range(3):
        store.record(f"p{i}", diag())
    assert store.get("p0") is None
    assert store.get("p1") is not None and store.get("p2") is not None


def test_preemption_nomination_lands_on_cr():
    store = ExplanationStore(blocking=True)
    store.record("p1", diag(preempt_node="n3", preempt_victims=["v1", "v2"]))
    exp = store.get("p1")
    assert "n3" in exp.node_offers
    assert "preempting [v1, v2]" in exp.node_offers["n3"]


def test_auditor_rings_and_transitions():
    t = [0.0]
    a = WorkloadAuditor(ring_size=4, clock=lambda: t[0])
    a.record_attempt("gang-a")
    a.record_attempt("gang-a")
    assert a.attempts("gang-a") == 2
    a.record_gating("p", True)
    a.record_gating("p", True)    # no transition -> no event
    a.record_gating("p", False)
    assert [e.record_type for e in a.events("p")] == ["Gated", "Gated"]
    assert [e.message for e in a.events("p")] == ["gated", "ungated"]
    for i in range(10):
        a.record("gang-a", "ScheduleFailed", f"m{i}")
    assert len(a.events("gang-a")) == 4   # ring bound
    a.delete("gang-a")
    assert a.attempts("gang-a") == 0 and a.events("gang-a") == []


def test_disabled_auditor_records_nothing():
    a = WorkloadAuditor(enabled=False)
    a.record_attempt("x")
    a.record("x", "ScheduleFailed")
    assert a.attempts("x") == 0 and a.events("x") == []


def test_scheduler_persists_and_clears_explanations():
    snap = ClusterSnapshot(capacity=16)
    snap.upsert_node(NodeSpec(
        name="n1", allocatable=resource_vector(cpu=4_000, memory=8_192),
        usage=np.zeros(R, np.int32)))
    cfg = ScoringConfig.default().replace(
        usage_thresholds=jnp.zeros(R, jnp.int32),
        estimator_defaults=jnp.zeros(R, jnp.int32))
    store = ExplanationStore(blocking=True)
    auditor = WorkloadAuditor()
    sched = Scheduler(snap, config=cfg, explanations=store, auditor=auditor)

    sched.enqueue(PodSpec(name="big",
                          requests=resource_vector(cpu=99_000, memory=1_024)))
    res = sched.schedule_round()
    assert "big" in res.failures
    exp = store.get("big")
    assert exp is not None and "available" in exp.reasons[0]
    assert auditor.attempts("big") == 1
    assert auditor.events("big")[-1].record_type == "ScheduleFailed"

    # shrink the pod and reschedule: explanation clears, success recorded
    sched.pending.pop("big")
    sched.enqueue(PodSpec(name="big",
                          requests=resource_vector(cpu=1_000, memory=1_024)))
    res = sched.schedule_round()
    assert res.assignments == {"big": "n1"}
    assert store.get("big") is None
    assert auditor.events("big")[-1].record_type == "ScheduleSuccess"


def test_delete_purges_queued_entry_too():
    # a bind between record() and drain() must not resurrect the failure
    store = ExplanationStore()
    store.record("p1", diag())
    store.delete("p1")       # bound before the worker drained
    assert store.drain() == 0
    assert store.get("p1") is None
