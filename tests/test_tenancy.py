"""Multi-tenant round pipeline (ISSUE 11): the tenancy subsystem's
acceptance suite.

The contracts under test:

- **pipeline bit-identity**: two rounds per tenant driven through the
  pipelined host/device split (tenant B's solve dispatched before
  tenant A's commit) produce the SAME binds and the SAME quota charges
  as the serial single-tenant-at-a-time path — including the
  incremental dirty path (cycle 2 re-scores only the delta) and the
  8-way sharded mesh;
- **tenant-axis batching**: the one-dispatch ``vmap``-batched
  select+pass1 program is bit-identical per tenant to the serial
  solves;
- **degraded isolation**: tenant A's stale sync feed suspends ONLY A's
  BE admission — B keeps binding BE pods through the same cycle;
- **weighted fairness**: under sustained overload from a loadgen
  multi-tenant trace, admitted shares converge to weight fractions
  (deficit round robin);
- **surfaces**: /debug/tenants parity across DebugService and the HTTP
  gateway, per-half tenant-stamped flight records, per-tenant SLO
  label filtering.

Compile budget: every front shares ONE SolverKit per mesh flavor
(module fixtures), shapes are tiny, and the pipelined/serial pairs
replay identical seeded inputs.
"""

import dataclasses
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import loadgen  # noqa: E402  (tools/loadgen.py; no JAX at module scope)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def kit_off():
    """One single-device SolverKit shared by every unsharded front in
    this module (T tenants already share one kit per front; the tests
    extend the sharing across fronts so the module compiles each
    program once)."""
    from koordinator_tpu.scheduler.solver_kit import SolverKit

    return SolverKit(mesh="off")


def _quota_tree(cpu_max: int = 60_000):
    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
    from koordinator_tpu.quota.tree import UNBOUNDED, QuotaTree

    total = np.zeros(NUM_RESOURCE_DIMS, np.int64)
    total[0] = 200_000
    tree = QuotaTree(total)
    mx = np.full(NUM_RESOURCE_DIMS, UNBOUNDED, np.int64)
    mx[0] = cpu_max
    tree.add("q", min=np.zeros(NUM_RESOURCE_DIMS, np.int64), max=mx)
    return tree


def _make_front(kit=None, tenants=("a", "b"), weights=None, quotas=False,
                **front_kw):
    from koordinator_tpu.scheduler.tenancy import TenantScheduler, TenantSpec

    front_kw.setdefault("cycle_pod_budget", 1 << 20)
    front = TenantScheduler(solver_kit=kit, **front_kw)
    for i, name in enumerate(tenants):
        front.add_tenant(
            TenantSpec(name=name,
                       weight=(weights[i] if weights else 1.0),
                       node_capacity=16),
            batch_solver_threshold=1,
            quota_tree=_quota_tree() if quotas else None)
    return front


def _feed_nodes(scheduler, n=10, seed=3, batch_cpu=0):
    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.scheduler.snapshot import NodeSpec

    rng = np.random.default_rng(seed)
    for i in range(n):
        scheduler.snapshot.upsert_node(NodeSpec(
            name=f"n{i}",
            allocatable=resource_vector(
                cpu=int(rng.integers(8_000, 32_000)),
                memory=int(rng.integers(16_384, 65_536)),
                **({"batch_cpu": batch_cpu} if batch_cpu else {})),
            usage=resource_vector(cpu=int(rng.integers(0, 2_000)),
                                  memory=int(rng.integers(0, 4_096)))))


def _pod(seed, name, quota=None):
    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.scheduler.snapshot import PodSpec

    rng = np.random.default_rng(seed)
    return PodSpec(
        name=name,
        requests=resource_vector(cpu=int(rng.integers(200, 3_000)),
                                 memory=int(rng.integers(256, 8_192))),
        priority=int(rng.integers(3_000, 9_999)),
        quota=quota)


def _seed_tenants(front, pods_per_tenant=6, base=0, quota=None):
    for ti, tenant in enumerate(front.tenants()):
        _feed_nodes(tenant.scheduler, seed=11 + ti)
        for j in range(pods_per_tenant):
            tenant.scheduler.enqueue(_pod(
                base * 10_000 + ti * 1_000 + j,
                f"p{base}-{j}", quota=quota))


def _delta_tenants(front, base):
    """A small steady-state delta per tenant: three new pods + one
    node's usage refresh (keeps the dirty fraction under the
    incremental threshold next cycle)."""
    from koordinator_tpu.api.resources import resource_vector

    for ti, tenant in enumerate(front.tenants()):
        sched = tenant.scheduler
        for j in range(3):
            sched.enqueue(_pod(base * 10_000 + ti * 1_000 + 500 + j,
                               f"p{base}-d{j}",
                               quota=("q" if sched.quota_tree else None)))
        spec = sched.snapshot.node_specs["n1"]
        sched.snapshot.upsert_node(dataclasses.replace(
            spec, usage=resource_vector(cpu=700 + 13 * ti, memory=2_048)))


def _binds(results):
    return {name: dict(r.assignments) for name, r in results.items()}


def _quota_used(front):
    out = {}
    for t in front.tenants():
        tree = t.scheduler.quota_tree
        if tree is not None:
            out[t.name] = np.asarray(tree.nodes["q"].used).tolist()
    return out


def _assert_no_overcommit(front):
    for t in front.tenants():
        st = t.scheduler.snapshot.state
        ok = (np.asarray(st.node_requested)
              <= np.asarray(st.node_allocatable)).all(axis=-1)
        assert ok[np.asarray(st.node_valid)].all(), \
            f"tenant {t.name} overcommitted"


# ---------------------------------------------------------------------------
# pipeline bit-identity
# ---------------------------------------------------------------------------


class TestPipelineBitIdentity:
    def test_two_round_overlap_matches_serial_incl_incremental(self, kit_off):
        """Two cycles, two tenants, quota-charged: the pipelined cycle
        (B's device solve dispatched before A's host commit) must bind
        the same pods to the same nodes and charge the same quota as
        serial single-tenant-at-a-time rounds — and cycle 2 must
        actually take the incremental dirty path."""
        serial = _make_front(kit_off, quotas=True, pipeline=False,
                             batch_tenant_axis=False)
        piped = _make_front(kit_off, quotas=True, pipeline=True,
                            batch_tenant_axis=False)
        for front in (serial, piped):
            _seed_tenants(front, base=1, quota="q")
            # small cluster: bind deltas are a large node FRACTION;
            # force the incremental path so cycle 2 exercises the merge
            for t in front.tenants():
                t.scheduler.incremental_dirty_threshold = 1.0
        r_ser1 = serial.schedule_cycle()
        r_pip1 = piped.schedule_cycle()
        assert serial.last_mode == "serial"
        assert piped.last_mode == "pipelined"
        assert _binds(r_ser1) == _binds(r_pip1)
        assert _quota_used(serial) == _quota_used(piped)

        _delta_tenants(serial, base=2)
        _delta_tenants(piped, base=2)
        r_ser2 = serial.schedule_cycle()
        r_pip2 = piped.schedule_cycle()
        assert _binds(r_ser2) == _binds(r_pip2)
        assert _quota_used(serial) == _quota_used(piped)
        _assert_no_overcommit(piped)
        # the steady-state delta actually rode the incremental path
        for t in piped.tenants():
            assert t.scheduler.last_solve_path == "incremental", \
                t.scheduler.last_solve_path

    def test_pipelined_matches_serial_on_sharded_mesh(self):
        """The same two-cycle pipelined-vs-serial identity with every
        tenant's solve on the 8-way nodes-axis mesh (shard_min_nodes=0
        engages sharding at the 16-row test capacity)."""
        from koordinator_tpu.scheduler.solver_kit import SolverKit

        kit_mesh = SolverKit(mesh="auto", shard_min_nodes=0)
        assert kit_mesh.shards == 8    # the virtual 8-device platform
        serial = _make_front(kit_mesh, pipeline=False,
                             batch_tenant_axis=False)
        piped = _make_front(kit_mesh, pipeline=True,
                            batch_tenant_axis=False)
        for front in (serial, piped):
            _seed_tenants(front, base=3)
            for t in front.tenants():
                t.scheduler.incremental_dirty_threshold = 1.0
                assert t.scheduler.snapshot.solver_sharding_active
        assert _binds(serial.schedule_cycle()) == \
            _binds(piped.schedule_cycle())
        _delta_tenants(serial, base=4)
        _delta_tenants(piped, base=4)
        assert _binds(serial.schedule_cycle()) == \
            _binds(piped.schedule_cycle())
        _assert_no_overcommit(piped)
        for t in piped.tenants():
            assert t.scheduler.last_solve_path == "incremental"


class TestTenantAxisBatch:
    def test_batched_cycle_matches_serial_per_tenant(self, kit_off):
        """The ONE vmapped tenant-axis program (stacked (T, N, R)
        states, broadcast config) binds exactly what per-tenant serial
        solves bind, quota charges included."""
        serial = _make_front(kit_off, quotas=True, pipeline=False,
                             batch_tenant_axis=False)
        batched = _make_front(kit_off, quotas=True,
                              batch_tenant_axis=True)
        for front in (serial, batched):
            _seed_tenants(front, pods_per_tenant=8, base=5, quota="q")
        r_ser = serial.schedule_cycle()
        r_bat = batched.schedule_cycle()
        assert batched.last_mode == "batched"
        for t in batched.tenants():
            assert t.scheduler.last_solve_path == "tenant_batched"
        assert _binds(r_ser) == _binds(r_bat)
        assert _quota_used(serial) == _quota_used(batched)
        _assert_no_overcommit(batched)

    def test_misaligned_cycle_falls_back_to_pipelined(self, kit_off):
        """A gang in one tenant's round breaks shape alignment: the
        cycle falls back to the pipelined per-tenant dispatch and still
        schedules everything."""
        from koordinator_tpu.scheduler.scheduler import GangRecord

        front = _make_front(kit_off, batch_tenant_axis=True)
        _seed_tenants(front, pods_per_tenant=4, base=6)
        sched_a = front.tenant("a").scheduler
        sched_a.register_gang(GangRecord(name="g1", min_member=2))
        for j in range(2):
            pod = _pod(66_000 + j, f"g1-{j}")
            pod.gang = "g1"
            sched_a.enqueue(pod)
        results = front.schedule_cycle()
        assert front.last_mode == "pipelined"
        assert len(results) == 2
        assert any("g1-" in p for p in results["a"].assignments)


# ---------------------------------------------------------------------------
# isolation + fairness
# ---------------------------------------------------------------------------


class TestDegradedIsolation:
    def test_one_stale_tenant_suspends_only_its_own_be_admission(
            self, kit_off):
        """Tenant A's sync feed stalls past the staleness threshold;
        the same cycle must flip ONLY A into degraded mode: A's BE pod
        is suspended (held pending), B's BE pod binds."""
        from koordinator_tpu.api.qos import QoSClass
        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.scheduler.snapshot import PodSpec
        from koordinator_tpu.scheduler.tenancy import (
            TenantScheduler,
            TenantSpec,
        )

        now = [100.0]
        front = TenantScheduler(solver_kit=kit_off,
                                batch_tenant_axis=False)
        for name in ("a", "b"):
            front.add_tenant(
                TenantSpec(name=name, node_capacity=16),
                batch_solver_threshold=1,
                staleness_threshold_sec=5.0,
                clock=lambda: now[0])
            _feed_nodes(front.tenant(name).scheduler, batch_cpu=8_000,
                        seed=21)
        # A's feed last spoke long ago; B's is fresh
        front.tenant("a").scheduler.snapshot.mark_sync(10.0)
        front.tenant("b").scheduler.snapshot.mark_sync(99.5)
        for name in ("a", "b"):
            front.tenant(name).scheduler.enqueue(PodSpec(
                name="be-pod",
                requests=resource_vector(batch_cpu=500),
                qos=int(QoSClass.BE)))
        results = front.schedule_cycle()
        a, b = front.tenant("a").scheduler, front.tenant("b").scheduler
        assert a.degraded and not b.degraded
        assert a.last_suspended == 1
        assert "be-pod" in a.pending            # held, not failed
        assert "be-pod" in results["b"].assignments
        # isolation the other way too: A recovering exits degraded
        # without touching B
        a.snapshot.mark_sync(now[0])
        front.schedule_cycle()
        assert not a.degraded and not b.degraded


class TestWeightedFairness:
    def test_admission_shares_converge_under_loadgen_overload(
            self, kit_off):
        """Sustained overload from a 3-tenant loadgen trace: admitted
        shares must converge to the weight fractions (1:1:2)."""
        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec

        cfg = dataclasses.replace(
            loadgen.LoadGenConfig(seed=9), tenants=3, duration_s=120.0,
            arrival_rate=3.0, gang_rate=0.0, node_flap_rate=0.0,
            quota_churn_rate=0.0, pod_lifetime_s=1e9, quotas=0)
        events = loadgen.generate_trace(cfg)
        by_tenant = {name: [] for name in cfg.tenant_names()}
        for e in events:
            if e.kind == loadgen.POD_ADD:
                by_tenant[e.payload["tenant"]].append(e)
        assert all(len(v) > 200 for v in by_tenant.values())

        front = _make_front(kit_off, tenants=cfg.tenant_names(),
                            weights=(1.0, 1.0, 2.0),
                            batch_tenant_axis=False,
                            cycle_pod_budget=32)
        for name, adds in by_tenant.items():
            sched = front.tenant(name).scheduler
            # a fat node wall so admission (not capacity) is the bound
            for i in range(4):
                sched.snapshot.upsert_node(NodeSpec(
                    name=f"n{i}", allocatable=resource_vector(
                        cpu=10_000_000, memory=10_000_000)))
            for e in adds:
                sched.enqueue(PodSpec(
                    name=e.name,
                    requests=resource_vector(cpu=e.payload["cpu"],
                                             memory=e.payload["memory"]),
                    priority=int(e.payload["priority"])))
        for _ in range(10):
            front.schedule_cycle()
        admitted = {t.name: t.admitted_total for t in front.tenants()}
        total = sum(admitted.values())
        assert total > 0
        shares = {k: v / total for k, v in admitted.items()}
        assert shares["t0"] == pytest.approx(0.25, abs=0.03)
        assert shares["t1"] == pytest.approx(0.25, abs=0.03)
        assert shares["t2"] == pytest.approx(0.50, abs=0.03)
        # overload persisted: the budget, not the backlog, was binding
        assert all(len(t.scheduler.pending) > 0 for t in front.tenants())
        # and the report serves the same observables
        report = front.tenants_report()
        t2 = next(d for d in report["tenants"] if d["name"] == "t2")
        assert t2["share_target"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# surfaces
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_debug_tenants_parity_across_both_surfaces(self, kit_off):
        """/debug/tenants serves the SAME body through the DebugService
        and the HTTP gateway (shared debug_tenants_body builder), and a
        single-tenant scheduler answers a typed 501 on both."""
        import json
        import urllib.request

        from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
        from koordinator_tpu.scheduler.services import DebugService
        from koordinator_tpu.transport.http_gateway import HttpGateway

        front = _make_front(kit_off, batch_tenant_axis=False)
        _seed_tenants(front, pods_per_tenant=2, base=7)
        front.schedule_cycle()
        service = DebugService(front.tenant("a").scheduler)
        status, body = service.handle("/debug/tenants")
        assert status == 200
        assert {d["name"] for d in body["tenants"]} == {"a", "b"}
        assert body["cycle"]["mode"] == "pipelined"

        gateway = HttpGateway(scheduler=front.tenant("b").scheduler)
        gateway.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{gateway.port}/debug/tenants"
            ) as resp:
                gw_body = json.loads(resp.read())
        finally:
            gateway.stop()
        assert gw_body == body

        lone = Scheduler(ClusterSnapshot(capacity=16), mesh="off",
                         solver_kit=kit_off)
        assert DebugService(lone).handle("/debug/tenants")[0] == 501

    def test_flight_records_stamp_tenant_and_half(self, kit_off):
        """A pipelined cycle leaves one solve-half and one commit-half
        record per tenant, tenant-stamped; serial schedule_round keeps
        half='round'."""
        front = _make_front(kit_off, batch_tenant_axis=False)
        _seed_tenants(front, pods_per_tenant=2, base=8)
        front.schedule_cycle()
        for t in front.tenants():
            halves = [(r.tenant, r.half)
                      for r in t.scheduler.flight_recorder.records]
            assert (t.name, "solve") in halves
            assert (t.name, "commit") in halves
        # /debug/rounds carries the stamps
        from koordinator_tpu.scheduler.services import debug_rounds_body

        doc = debug_rounds_body(front.tenant("a").scheduler, 8)
        assert {r["half"] for r in doc["rounds"]} == {"solve", "commit"}
        assert {r["tenant"] for r in doc["rounds"]} == {"a"}

    def test_scheduling_latency_carries_tenant_label(self, kit_off):
        from koordinator_tpu import metrics

        front = _make_front(kit_off, batch_tenant_axis=False)
        _seed_tenants(front, pods_per_tenant=2, base=9)
        front.schedule_cycle()
        label_sets = [dict(labels) for labels, *_ in
                      metrics.scheduling_latency.state()]
        tenants = {ls.get("tenant") for ls in label_sets
                   if "tenant" in ls}
        assert {"a", "b"} <= tenants
        # per-tenant enqueue/admission counters too
        assert metrics.pods_enqueued_total.value(
            labels={"tenant": "a"}) > 0
        assert metrics.tenant_admitted.value(labels={"tenant": "a"}) > 0

    def test_tenant_slo_spec_slices_by_label(self):
        """The per-tenant p99 SLO only counts its own tenant's
        observations: tenant A's slow solves must not burn tenant B's
        budget."""
        from koordinator_tpu import metrics as m
        from koordinator_tpu.slo_monitor import SloMonitor, tenant_slo_specs

        class FakeClock:
            def __init__(self):
                self.t = 1_000.0

            def __call__(self):
                return self.t

        reg = m.Registry("t11")
        h = reg.histogram("scheduling_duration_seconds",
                          buckets=(0.1, 0.2, 1.0))
        clock = FakeClock()
        specs = tenant_slo_specs(["a", "b"], latency_threshold_s=0.2)
        specs = [dataclasses.replace(
            s, metric="t11_scheduling_duration_seconds") for s in specs]
        mon = SloMonitor(specs=specs, registries=(reg,), clock=clock)
        h.observe(0.9, labels={"phase": "Solve", "tenant": "a"})
        h.observe(0.05, labels={"phase": "Solve", "tenant": "b"})
        mon.sample_once()
        h.observe(0.9, labels={"phase": "Solve", "tenant": "a"})
        h.observe(0.05, labels={"phase": "Solve", "tenant": "b"})
        clock.t += 10.0
        report = mon.tick()
        by_name = {d["name"]: d for d in report["slos"]}
        assert by_name["tenant_a_latency_p99"]["windows"]["fast"][
            "bad_fraction"] == pytest.approx(1.0)
        assert by_name["tenant_b_latency_p99"]["windows"]["fast"][
            "bad_fraction"] == pytest.approx(0.0)


class TestSharedSolverKit:
    def test_tenants_share_one_jit_cache(self, kit_off):
        """T tenants on one front reuse the SAME instrumented jit
        entries — the multiplexing that keeps N clusters from compiling
        N copies of the solver."""
        front = _make_front(kit_off, batch_tenant_axis=False)
        a = front.tenant("a").scheduler
        b = front.tenant("b").scheduler
        assert a.kit is b.kit is kit_off
        assert a._pass1 is b._pass1
        assert a._solve is b._solve

    def test_standalone_scheduler_builds_its_own_kit(self):
        from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler

        s1 = Scheduler(ClusterSnapshot(capacity=16), mesh="off")
        s2 = Scheduler(ClusterSnapshot(capacity=16), mesh="off")
        assert s1.kit is not s2.kit     # the pre-tenancy default
