"""Shared seeded problem builders for solver test suites.

One canonical builder for the (ClusterState, PodBatch) problems that the
candidate-selection and Pallas suites both exercise, so a scoring-field
change lands in one place.  (`__graft_entry__._build_problem` stays
self-contained by design — the driver runs it without the test tree.)
"""

import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

R = NUM_RESOURCE_DIMS
CPU, MEM, GPU = ResourceDim.CPU, ResourceDim.MEMORY, ResourceDim.GPU


def build_problem(n_nodes=64, n_pods=128, seed=0, classes=3,
                  invalid_tail=0, with_gpu=True, factored=True,
                  pad_pods_pow2=True):
    """Seeded random scheduling problem.

    ``factored`` attaches a selector-class mask (the factored feasibility
    form); ``invalid_tail`` zeroes + invalidates the last nodes;
    ``pad_pods_pow2`` pads the pod batch capacity to a power of two
    (PodBatch.build's natural padding behavior in the suites).
    """
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    alloc = np.zeros((n_nodes, R), np.int32)
    alloc[:, CPU] = rng.integers(8_000, 64_000, n_nodes)
    alloc[:, MEM] = rng.integers(16_384, 262_144, n_nodes)
    if with_gpu:
        alloc[:, GPU] = rng.integers(0, 2, n_nodes) * 8_000
    usage = (alloc * rng.random((n_nodes, R)) * 0.6).astype(np.int32)
    requested = (alloc * rng.random((n_nodes, R)) * 0.5).astype(np.int32)
    node_class = rng.integers(0, classes, n_nodes).astype(np.int32)
    if invalid_tail:
        alloc[-invalid_tail:] = 0
    state = ClusterState.from_arrays(
        alloc, requested=requested, usage=usage, capacity=n_nodes,
        node_class=node_class)
    if invalid_tail:
        valid = np.ones(n_nodes, bool)
        valid[-invalid_tail:] = False
        state = state.replace(node_valid=jnp.asarray(valid))

    req = np.zeros((n_pods, R), np.int32)
    req[:, CPU] = rng.integers(100, 4_000, n_pods)
    req[:, MEM] = rng.integers(128, 8_192, n_pods)
    if with_gpu:
        req[rng.random(n_pods) < 0.2, GPU] = 1_000
    kw = {}
    if factored:
        sel = rng.random((n_pods, 8)) < 0.7
        sel[:, :classes] |= rng.random((n_pods, classes)) < 0.5
        kw = dict(selector_mask=sel, class_capacity=8)
    cap = (1 << (n_pods - 1).bit_length()) if pad_pods_pow2 else n_pods
    pods = PodBatch.build(
        req, priority=rng.integers(3000, 9999, n_pods).astype(np.int32),
        node_capacity=n_nodes, capacity=cap, **kw)
    return state, pods


def candidate_recall(exact_nodes, exact_keys, got_nodes):
    """Fraction of each pod's true (feasible, key >= 0) top-k candidates
    found by a method's candidate sets."""
    hits = total = 0
    for p in range(exact_nodes.shape[0]):
        want = set(np.asarray(exact_nodes)[p][
            np.asarray(exact_keys)[p] >= 0].tolist())
        if not want:
            continue
        got = set(np.asarray(got_nodes)[p].tolist())
        hits += len(want & got)
        total += len(want)
    return hits / max(total, 1)
