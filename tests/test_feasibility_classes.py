"""Factored feasibility: label/taint equivalence classes.

The dense (P, N) mask is replaced by a (P, C) selector mask over node
equivalence classes plus a node→class map (ClusterState.node_class); these
tests pin the factored path to the dense oracle.
"""

import numpy as np

from koordinator_tpu.scheduler import ClusterSnapshot, NodeSpec, PodSpec

from tests.test_scheduler import mk_scheduler, node, pod


def node_l(name, labels=None, taints=None, cpu=16_000):
    n = node(name, cpu=cpu, labels=labels)
    n.taints = taints or {}
    return n


class TestClassRegistry:
    def test_nodes_share_classes(self):
        snap = ClusterSnapshot(capacity=16)
        for i in range(6):
            snap.upsert_node(node_l(f"a{i}", labels={"pool": "a"}))
        for i in range(6):
            snap.upsert_node(node_l(f"b{i}", labels={"pool": "b"}))
        snap.flush()
        assert len(snap._class_sigs) == 2
        nc = np.asarray(snap.state.node_class)
        rows_a = [snap.node_index[f"a{i}"] for i in range(6)]
        rows_b = [snap.node_index[f"b{i}"] for i in range(6)]
        assert len({nc[r] for r in rows_a}) == 1
        assert len({nc[r] for r in rows_b}) == 1
        assert nc[rows_a[0]] != nc[rows_b[0]]

    def test_selector_row_matches_dense_oracle(self):
        snap = ClusterSnapshot(capacity=16)
        snap.upsert_node(node_l("plain"))
        snap.upsert_node(node_l("gpu", labels={"accel": "gpu"}))
        snap.upsert_node(node_l("tainted", taints={"dedicated": "batch"}))
        snap.flush()
        cases = [
            PodSpec("any", requests=pod("x").requests),
            PodSpec("want-gpu", requests=pod("x").requests,
                    node_selector={"accel": "gpu"}),
            PodSpec("tolerates", requests=pod("x").requests,
                    tolerations={"dedicated": "batch"}),
        ]
        nc = np.asarray(snap.state.node_class)
        for p in cases:
            dense = snap.feasibility_row(p)
            sel = snap.selector_row_for(p)
            factored = sel[nc] & np.asarray(snap.state.node_valid)
            assert (factored == dense).all(), p.name

    def test_taint_blocks_untolerating_pod(self):
        snap = ClusterSnapshot(capacity=16)
        snap.upsert_node(node_l("t", taints={"dedicated": "batch"}))
        snap.flush()
        p = PodSpec("p", requests=pod("x").requests)
        assert not snap.selector_row_for(p).any()
        tol = PodSpec("q", requests=pod("x").requests,
                      tolerations={"dedicated": "batch"})
        row = snap.selector_row_for(tol)
        assert row[np.asarray(snap.state.node_class)[snap.node_index["t"]]]


class TestSchedulerFactoredPath:
    def test_selector_routing(self):
        sched, _ = mk_scheduler([
            node_l("cpu-1", labels={"pool": "cpu"}),
            node_l("gpu-1", labels={"pool": "gpu"}),
        ])
        sched.enqueue(pod("wants-gpu", node_selector={"pool": "gpu"}))
        sched.enqueue(pod("wants-cpu", node_selector={"pool": "cpu"}))
        res = sched.schedule_round()
        assert res.assignments == {
            "wants-gpu": "gpu-1", "wants-cpu": "cpu-1",
        }
        # factored batch: no dense mask was built
        assert sched.last_result is res

    def test_unmatched_selector_diagnosed(self):
        sched, _ = mk_scheduler([node_l("n1", labels={"pool": "a"})])
        sched.enqueue(pod("p", node_selector={"pool": "zzz"}))
        res = sched.schedule_round()
        assert res.failures["p"].affinity_mismatch == 1

    def test_taint_respected_via_scheduler(self):
        sched, _ = mk_scheduler([
            node_l("general"),
            node_l("batch-only", taints={"dedicated": "batch"}),
        ])
        sched.enqueue(pod("plain"))
        sched.enqueue(pod("batchy", tolerations={"dedicated": "batch"},
                          node_selector={}))
        res = sched.schedule_round()
        assert res.assignments["plain"] == "general"
        assert res.assignments["batchy"] in {"general", "batch-only"}

    def test_hinted_pod_falls_back_dense(self):
        from koordinator_tpu.scheduler.hints import PodHint, SchedulingHints

        sched, _ = mk_scheduler([node_l("n1"), node_l("n2")])
        hints = SchedulingHints(sched.snapshot)
        sched.hints = hints
        hints.set_hint("p", PodHint(excluded_nodes={"n1"}))
        sched.enqueue(pod("p"))
        res = sched.schedule_round()
        assert res.assignments == {"p": "n2"}

    def test_class_added_after_batch_is_safe(self):
        # a node class registered between rounds grows class_capacity;
        # earlier batches' masks stay consistent (clip + re-build per round)
        sched, _ = mk_scheduler([node_l("n1", labels={"pool": "a"})])
        sched.enqueue(pod("p1", node_selector={"pool": "a"}))
        assert sched.schedule_round().assignments == {"p1": "n1"}
        for i in range(12):  # force class growth past the initial capacity
            sched.snapshot.upsert_node(
                node_l(f"x{i}", labels={"pool": f"x{i}"})
            )
        sched.enqueue(pod("p2", node_selector={"pool": "x5"}))
        assert sched.schedule_round().assignments == {"p2": "x5"}
