"""Micro-benchmarks mirroring the reference's ``go test -bench`` harnesses.

The reference ships benchmark harnesses without recorded results
(BASELINE.md); its baseline procedure is "run the reference's harnesses
on our hardware".  This is the TPU-native rebuild of each scenario at
the reference's shapes — where the reference benches one plugin call on
one node, the rebuilt kernel is *batched over every node*, so the honest
comparison unit here is whole-cluster rounds/sec alongside the derived
per-node-call time.

Scenarios (reference file:line):
- numa_filter:       nodenumaresource/plugin_benchmark_test.go:79,190
                     (Filter_CPUBind + PreFilter_LargeCluster)
- numa_take_cpus:    nodenumaresource/cpu_accumulator_test.go:655,706
- deviceshare_filter: deviceshare/plugin_benchmark_test.go:143-145
                     (1024 nodes x 8 GPUs)
- reservation_fit:   reservation/plugin_benchmark_test.go:37 +
                     transformer_benchmark_test.go:42 (restore+fit)
- diagnosis_dump:    frameworkext/schedule_diagnosis_test.go:230,331
- webhook_profile:   webhook/pod/mutating/cluster_colocation_profile_
                     test.go:1868 (profile matching + mutation)

Prints ONE JSON line {"metric": "micro", ...scenario fields...}.  Device
kernels use bench.py's chained-loop methodology (tunnel-safe); the two
host-path scenarios (diagnosis, webhook) are plain wall clock.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from bench import K_ITERS, _median_readback_seconds

N_NODES = 1_024


def _time_kernel(fn, args, iters: int = K_ITERS, n: int = 3) -> float:
    """Seconds per iteration of a scalar-returning jitted chained loop.

    The accumulator feeds back into each call as ``salt``; scenario
    bodies must mix ``salt & 1`` (a genuinely data-dependent 0/1) into
    their inputs — ``& 0`` would constant-fold and let XLA hoist the
    kernel out of the loop, timing one execution instead of ``iters``.
    """

    def chained(*a):
        def body(i, acc):
            return acc + fn(*a, salt=acc)

        return jax.lax.fori_loop(0, iters, body, jnp.int32(0))

    def rtt_fn(*a):
        return a[0].ravel()[0].astype(jnp.int32) * 0

    rtt, _ = _median_readback_seconds(jax.jit(rtt_fn), args, n=n)
    total, _ = _median_readback_seconds(jax.jit(chained), args, n=n)
    return max((total - rtt) / iters, 1e-9)


def bench_numa_filter() -> dict:
    """Batched cpuset Filter over 1,024 nodes x 128 cpus (the LargeCluster
    variant; the reference filters one node per call)."""
    from koordinator_tpu.ops.numa import CPUTopology, cpuset_fit_batched

    topo = CPUTopology.uniform(sockets=2, numa_per_socket=2,
                               cores_per_numa=16, threads_per_core=2)
    topos = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (N_NODES,) + x.shape), topo)
    rng = np.random.default_rng(3)
    refs = jnp.asarray(
        rng.integers(0, 2, (N_NODES, topo.capacity)).astype(np.int32))
    max_ref = jnp.ones(N_NODES, jnp.int32)

    def fn(refs, salt):
        fits = cpuset_fit_batched(
            topos, refs + (salt & 1), max_ref, jnp.int32(16),
            full_pcpus=True)
        return fits.sum().astype(jnp.int32)

    per = _time_kernel(fn, (refs,))
    return {
        "numa_filter_rounds_per_sec_1024n": round(1 / per, 1),
        "numa_filter_ns_per_node_call": round(per / N_NODES * 1e9, 1),
    }


def bench_numa_take_cpus() -> dict:
    """cpuset accumulator take on one 128-cpu node (FullPCPUs,
    most-allocated — cpu_accumulator_test.go's hot case)."""
    from koordinator_tpu.ops.numa import (
        BIND_FULL_PCPUS,
        STRATEGY_MOST_ALLOCATED,
        CPUTopology,
        take_cpus,
    )

    topo = CPUTopology.uniform(sockets=2, numa_per_socket=2,
                               cores_per_numa=16, threads_per_core=2)
    rng = np.random.default_rng(4)
    refs = jnp.asarray(rng.integers(0, 2, topo.capacity).astype(np.int32))

    def fn(refs, salt):
        sel, ok = take_cpus(topo, refs + (salt & 1), jnp.int32(1),
                            jnp.int32(16), bind_policy=BIND_FULL_PCPUS,
                            strategy=STRATEGY_MOST_ALLOCATED)
        return sel.sum().astype(jnp.int32) + ok.astype(jnp.int32)

    per = _time_kernel(fn, (refs,))
    return {"numa_take_cpus_us_per_call_128c": round(per * 1e6, 1)}


def bench_deviceshare_filter() -> dict:
    """Device Filter+Score over 1,024 nodes x 8 GPUs (plugin_benchmark_
    test.go:143's LargeCluster shape, batched instead of per-node)."""
    from koordinator_tpu.ops.deviceshare import (
        DeviceState,
        device_fit,
        device_score,
    )

    dev = DeviceState.build(
        [[{"core": 100, "memory": 80 << 10} for _ in range(8)]
         for _ in range(N_NODES)])
    rng = np.random.default_rng(5)
    used = (np.asarray(dev.total)
            * rng.integers(0, 2, dev.total.shape)).astype(np.int32)
    free = jnp.asarray(np.asarray(dev.total) - used)

    def fn(free, salt):
        d = dev.replace(free=free + (salt & 1))
        fits = device_fit(d, jnp.int32(2), jnp.int32(100),
                          jnp.int32(40 << 10))
        score = device_score(d, jnp.int32(2), jnp.int32(100),
                             jnp.int32(40 << 10))
        return fits.sum().astype(jnp.int32) + (score.sum() & 1)

    per = _time_kernel(fn, (free,))
    return {
        "deviceshare_filter_score_rounds_per_sec_1024n_8gpu": round(
            1 / per, 1),
        "deviceshare_ns_per_node_call": round(per / N_NODES * 1e9, 1),
    }


def bench_reservation_fit() -> dict:
    """Restore+fit: 1,000 pods x 512 reservations over 1,024 nodes
    (transformer_benchmark_test.go restores per node; here one batched
    matrix does every (pod, reservation) pair)."""
    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
    from koordinator_tpu.ops.reservation import (
        ReservationSet,
        reservation_fit,
    )

    rng = np.random.default_rng(6)
    r = NUM_RESOURCE_DIMS
    n_rsv, n_pods = 512, 1_000
    reserved = np.zeros((n_rsv, r), np.int32)
    reserved[:, 0] = rng.integers(1_000, 8_000, n_rsv)
    reserved[:, 1] = rng.integers(1_024, 16_384, n_rsv)
    rsv = ReservationSet.build(
        reserved, rng.integers(0, N_NODES, n_rsv).astype(np.int32))
    node_free = jnp.asarray(
        rng.integers(0, 16_000, (N_NODES, r)).astype(np.int32))
    requests = np.zeros((n_pods, r), np.int32)
    requests[:, 0] = rng.integers(500, 4_000, n_pods)
    requests = jnp.asarray(requests)
    match = jnp.asarray(rng.random((n_pods, rsv.capacity)) < 0.25)

    def fn(node_free, salt):
        fits = reservation_fit(rsv, node_free + (salt & 1), requests, match)
        return fits.sum().astype(jnp.int32)

    per = _time_kernel(fn, (node_free,))
    return {
        "reservation_fit_rounds_per_sec_1000p_512v": round(1 / per, 1),
        "reservation_fit_ns_per_pod": round(per / n_pods * 1e9, 1),
    }


def bench_diagnosis_dump() -> dict:
    """Failure-reason dump for 512 unschedulable pods over 10,240 nodes
    (schedule_diagnosis_test.go:230 serializes per-pod diagnoses)."""
    from __graft_entry__ import _build_problem
    from koordinator_tpu.scheduler.diagnosis import explain_pod

    state, pods, cfg = _build_problem(10_240, 512, seed=10)
    explain_pod(state, pods, cfg, 0)  # warm the jitted pieces
    t0 = time.perf_counter()
    msgs = [explain_pod(state, pods, cfg, i).message() for i in range(512)]
    dt = time.perf_counter() - t0
    assert all(msgs)
    return {"diagnosis_dump_pods_per_sec_10240n": round(512 / dt, 1)}


def bench_webhook_profile() -> dict:
    """Profile matching + mutation: 64 selective profiles x 2,000 pods
    (cluster_colocation_profile_test.go:1868 benches one admission)."""
    from koordinator_tpu.api import crds
    from koordinator_tpu.manager.webhook import PodMutatingWebhook

    profiles = [
        crds.ClusterColocationProfile(
            name=f"p{i}", pod_selector={"tier": f"t{i}"}, qos_class="BE",
            koordinator_priority=5000 + i)
        for i in range(64)
    ]
    hook = PodMutatingWebhook(profiles)
    pods = [
        {"metadata": {"name": f"pod-{j}", "namespace": "default",
                      "labels": {"tier": f"t{j % 96}"}},
         "spec": {"containers": [{"name": "m", "resources": {
             "requests": {"cpu": "500m", "memory": "1Gi"}}}]}}
        for j in range(2_000)
    ]
    import copy

    from koordinator_tpu.api import extension as ext

    hook.mutate(copy.deepcopy(pods[0]))  # warm without touching pods[0]
    t0 = time.perf_counter()
    for p in pods:
        hook.mutate(p)
    dt = time.perf_counter() - t0
    matched = sum(
        1 for p in pods
        if ext.LABEL_POD_QOS in p["metadata"].get("labels", {}))
    assert matched  # 2/3 of pods hit a profile
    return {"webhook_admissions_per_sec_64profiles": round(2_000 / dt, 1)}


def main() -> None:
    out: dict = {"metric": "micro"}
    for fn in (bench_numa_filter, bench_numa_take_cpus,
               bench_deviceshare_filter, bench_reservation_fit,
               bench_diagnosis_dump, bench_webhook_profile):
        try:
            out.update(fn())
        except Exception as e:  # one broken scenario must not cost the rest
            out[f"{fn.__name__}_error"] = repr(e)[:200]
    print(json.dumps(out))


if __name__ == "__main__":
    import os

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    main()
