/* Non-Python conformance client for the framed wire protocol (v3).
 *
 * Proves the sidecar boundary is language-neutral — the role the
 * reference assigns to its versioned proto contract
 * (apis/runtime/v1alpha1/api.proto:148) and the frameworkext plugin
 * seam (pkg/scheduler/frameworkext/interface.go:70): a peer with no
 * Python, no numpy, and no shared code completes the full protocol:
 *
 *   1. HELLO with a stale protocol number  -> ERROR (skew rejected)
 *   2. HELLO {last_rv:-1, proto:3}         -> SNAPSHOT (+ array section)
 *   3. STATE_PUSH node_upsert / pod_add    -> {rv} (arrays encoded here,
 *      little-endian int32, manifest JSON written by hand)
 *   4. DELTA pushes (request_id 0) observed for our own events
 *   5. SOLVE_REQUEST                       -> SOLVE_RESPONSE assignments
 *   6. LEASE_GET / LEASE_UPDATE CAS        -> acquire ok, bad CAS refused
 *
 * Output: one JSON result line on stdout; exit 0 iff every step held.
 * The matching harness is tests/test_c_conformance.py.
 *
 * Wire format (transport/wire.py):
 *   header  <u16 magic=0x4B54><u8 ver=1><u8 type><u32 req_id><u32 len>
 *   payload <u32 json_len><json utf-8><raw array section>
 * JSON parsing here is a deliberately small scanner (find key, read
 * scalar / balanced object) — enough for the compact single-level
 * documents the server emits, with no third-party dependency.
 */

#include <arpa/inet.h>
#include <netdb.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#define MAGIC 0x4B54
#define WIRE_VERSION 1
#define PROTO 3

enum {
    F_HELLO = 1, F_SNAPSHOT = 2, F_DELTA = 3, F_ACK = 4, F_ERROR = 5,
    F_SOLVE_REQUEST = 6, F_SOLVE_RESPONSE = 7,
    F_HOOK_REQUEST = 8, F_HOOK_RESPONSE = 9, F_PING = 10,
    F_LEASE_GET = 11, F_LEASE_UPDATE = 12, F_STATE_PUSH = 13,
};

static int R_VEC = 10; /* resource vector length; argv[3] overrides */

static int die(const char *msg) {
    fprintf(stderr, "conformance_client: FAIL: %s\n", msg);
    exit(1);
}

/* ---- socket helpers ---------------------------------------------------- */

static int g_sock = -1;

static void send_all(const void *buf, size_t n) {
    const char *p = buf;
    while (n > 0) {
        ssize_t w = send(g_sock, p, n, 0);
        if (w <= 0) die("send failed");
        p += w;
        n -= (size_t)w;
    }
}

static void recv_all(void *buf, size_t n) {
    char *p = buf;
    while (n > 0) {
        ssize_t r = recv(g_sock, p, n, 0);
        if (r <= 0) die("recv failed (peer closed or timeout)");
        p += r;
        n -= (size_t)r;
    }
}

/* ---- frame encode/decode ---------------------------------------------- */

struct frame {
    uint8_t type;
    uint32_t req_id;
    uint32_t len;     /* payload length */
    char *payload;    /* malloc'd; json starts at payload+4 */
    uint32_t json_len;
    char *json;       /* NUL-terminated copy of the json document */
};

/* payload = u32 json_len | json | arrays; header packed little-endian */
static void send_frame(uint8_t type, uint32_t req_id, const char *json,
                       const void *arrays, uint32_t arrays_len) {
    uint32_t jlen = (uint32_t)strlen(json);
    uint32_t plen = 4 + jlen + arrays_len;
    unsigned char header[12];
    header[0] = MAGIC & 0xff;
    header[1] = MAGIC >> 8;
    header[2] = WIRE_VERSION;
    header[3] = type;
    memcpy(header + 4, &req_id, 4);   /* host is little-endian (x86) */
    memcpy(header + 8, &plen, 4);
    send_all(header, 12);
    send_all(&jlen, 4);
    send_all(json, jlen);
    if (arrays_len) send_all(arrays, arrays_len);
}

static void read_one_frame(struct frame *f) {
    unsigned char header[12];
    recv_all(header, 12);
    uint16_t magic = (uint16_t)(header[0] | (header[1] << 8));
    if (magic != MAGIC) die("bad frame magic");
    if (header[2] != WIRE_VERSION) die("bad wire version");
    f->type = header[3];
    memcpy(&f->req_id, header + 4, 4);
    memcpy(&f->len, header + 8, 4);
    if (f->len > (64u << 20)) die("oversized frame");
    f->payload = malloc(f->len + 1);
    if (!f->payload) die("oom");
    recv_all(f->payload, f->len);
    if (f->len < 4) die("short payload");
    memcpy(&f->json_len, f->payload, 4);
    /* f->len >= 4 here; subtract to avoid unsigned wrap in 4+json_len */
    if (f->json_len > f->len - 4) die("json_len exceeds payload");
    f->json = malloc(f->json_len + 1);
    if (!f->json) die("oom");
    memcpy(f->json, f->payload + 4, f->json_len);
    f->json[f->json_len] = 0;
}

static void free_frame(struct frame *f) {
    free(f->payload);
    free(f->json);
    f->payload = f->json = NULL;
}

/* Read frames until one answers req_id; pushes (req_id 0) are counted
 * per-type in push_counts and their rv (if any) recorded. */
static int g_push_counts[16];
static long g_last_push_rv = -1;

static long json_find_long(const char *doc, const char *key, long dflt);

static void await_reply(uint32_t req_id, struct frame *out) {
    for (;;) {
        read_one_frame(out);
        if (out->req_id == req_id) return;
        if (out->req_id == 0) {
            if (out->type < 16) g_push_counts[out->type]++;
            long rv = json_find_long(out->json, "rv", -1);
            if (rv > g_last_push_rv) g_last_push_rv = rv;
        }
        free_frame(out);
    }
}

/* ---- minimal JSON scanning -------------------------------------------- */

/* Find `"key":` at any nesting level (documents here never repeat key
 * names at different depths in conflicting ways) and return a pointer
 * just past the colon, or NULL. */
static const char *json_value_of(const char *doc, const char *key) {
    char pat[128];
    snprintf(pat, sizeof pat, "\"%s\":", key);
    const char *p = strstr(doc, pat);
    return p ? p + strlen(pat) : NULL;
}

static long json_find_long(const char *doc, const char *key, long dflt) {
    const char *p = json_value_of(doc, key);
    if (!p) return dflt;
    return strtol(p, NULL, 10);
}

static int json_find_bool(const char *doc, const char *key, int dflt) {
    const char *p = json_value_of(doc, key);
    if (!p) return dflt;
    return strncmp(p, "true", 4) == 0;
}

/* Copy the balanced {...} object that starts at the value of `key`. */
static char *json_find_object(const char *doc, const char *key) {
    const char *p = json_value_of(doc, key);
    if (!p || *p != '{') return NULL;
    int depth = 0;
    const char *q = p;
    int in_str = 0;
    for (; *q; q++) {
        if (in_str) {
            if (*q == '\\' && q[1]) q++;
            else if (*q == '"') in_str = 0;
            continue;
        }
        if (*q == '"') in_str = 1;
        else if (*q == '{') depth++;
        else if (*q == '}' && --depth == 0) { q++; break; }
    }
    size_t n = (size_t)(q - p);
    char *out = malloc(n + 1);
    if (!out) die("oom");
    memcpy(out, p, n);
    out[n] = 0;
    return out;
}

/* Copy the string value of `key` ("key":"value"), or NULL. */
static char *json_find_string(const char *doc, const char *key) {
    const char *p = json_value_of(doc, key);
    if (!p || *p != '"') return NULL;
    p++;
    const char *q = p;
    while (*q && *q != '"') {
        if (*q == '\\' && q[1]) q++;
        q++;
    }
    size_t n = (size_t)(q - p);
    char *out = malloc(n + 1);
    if (!out) die("oom");
    memcpy(out, p, n);
    out[n] = 0;
    return out;
}

/* Count `"kind":"..."` occurrences (events in a snapshot/delta doc). */
static int count_occurrences(const char *doc, const char *needle) {
    int n = 0;
    for (const char *p = doc; (p = strstr(p, needle)); p += strlen(needle))
        n++;
    return n;
}

/* Validate every __arrays__ manifest entry fits the raw section. */
static int arrays_manifest_ok(const struct frame *f) {
    const char *doc = f->json;
    uint32_t raw_len = f->len - 4 - f->json_len;
    const char *p = json_value_of(doc, "__arrays__");
    if (!p) return 1; /* no arrays: trivially consistent */
    while ((p = strstr(p, "\"offset\":"))) {
        long off = strtol(p + 9, NULL, 10);
        const char *nb = strstr(p, "\"nbytes\":");
        if (!nb) return 0;
        long nbytes = strtol(nb + 9, NULL, 10);
        if (off < 0 || nbytes < 0 || (uint32_t)(off + nbytes) > raw_len)
            return 0;
        p = nb + 9;
    }
    return 1;
}

/* ---- steps ------------------------------------------------------------- */

static uint32_t g_req_id = 1;

/* ---- runtime-hook conformance (--hooks mode) ---------------------------
 *
 * Drives the runtime boundary the way a non-Python CRI proxy would
 * (docs/runtime_boundary.md; the reference's api.proto:148 hook RPCs):
 * HOOK_REQUEST frames against the koordlet's hook server, asserting the
 * GroupIdentity bvt resolution and BatchResource kernel-limit math, and
 * that an unknown hook name errors WITHOUT killing the connection. */
static int run_hooks_mode(void) {
    struct frame f;

    /* A. PreRunPodSandbox for a BE pod: GroupIdentity resolves the
     * best-effort bvt value from the default NodeSLO */
    const char *sandbox =
        "{\"hook\":\"PreRunPodSandbox\","
        "\"pod_meta\":{\"uid\":\"u-c\",\"name\":\"c-be\","
        "\"namespace\":\"default\"},"
        "\"labels\":{\"koordinator.sh/qosClass\":\"BE\"},"
        "\"cgroup_parent\":\"kubepods/besteffort/podu-c\"}";
    send_frame(F_HOOK_REQUEST, g_req_id, sandbox, NULL, 0);
    await_reply(g_req_id++, &f);
    if (f.type != F_HOOK_RESPONSE) die("expected HOOK_RESPONSE (sandbox)");
    char *bvt = json_find_string(f.json, "cpu.bvt_warp_ns");
    int bvt_ok = bvt && strcmp(bvt, "-1") == 0;
    free(bvt);
    free_frame(&f);

    /* B. PreCreateContainer with batch requests: BatchResource derives
     * the kernel limits (cfs quota/shares from batch-cpu milli-cores,
     * memory.limit from batch-memory bytes) */
    const char *create =
        "{\"hook\":\"PreCreateContainer\","
        "\"pod_meta\":{\"uid\":\"u-c\",\"name\":\"c-be\","
        "\"namespace\":\"default\"},"
        "\"container_meta\":{\"name\":\"main\",\"id\":\"cc1\"},"
        "\"labels\":{\"koordinator.sh/qosClass\":\"BE\"},"
        "\"cgroup_parent\":\"kubepods/besteffort/podu-c\","
        "\"resources\":{\"kubernetes.io/batch-cpu\":2000,"
        "\"kubernetes.io/batch-memory\":1073741824}}";
    send_frame(F_HOOK_REQUEST, g_req_id, create, NULL, 0);
    await_reply(g_req_id++, &f);
    if (f.type != F_HOOK_RESPONSE) die("expected HOOK_RESPONSE (create)");
    char *quota = json_find_string(f.json, "cpu.cfs_quota");
    char *shares = json_find_string(f.json, "cpu.shares");
    char *memlim = json_find_string(f.json, "memory.limit");
    int limits_ok = quota && strcmp(quota, "200000") == 0
        && shares && strcmp(shares, "2048") == 0
        && memlim && strcmp(memlim, "1073741824") == 0;
    free(quota);
    free(shares);
    free(memlim);
    free_frame(&f);

    /* C. unknown hook name -> ERROR frame, connection survives */
    send_frame(F_HOOK_REQUEST, g_req_id, "{\"hook\":\"NoSuchHook\"}",
               NULL, 0);
    await_reply(g_req_id++, &f);
    int unknown_rejected = (f.type == F_ERROR);
    free_frame(&f);

    /* D. the rejection did not poison the connection */
    send_frame(F_HOOK_REQUEST, g_req_id, sandbox, NULL, 0);
    await_reply(g_req_id++, &f);
    int survived = (f.type == F_HOOK_RESPONSE);
    free_frame(&f);

    printf("{\"bvt_ok\":%s,\"limits_ok\":%s,\"unknown_rejected\":%s,"
           "\"survived\":%s}\n",
           bvt_ok ? "true" : "false", limits_ok ? "true" : "false",
           unknown_rejected ? "true" : "false",
           survived ? "true" : "false");
    return (bvt_ok && limits_ok && unknown_rejected && survived) ? 0 : 1;
}

static void connect_to(const char *host, const char *port) {
    struct addrinfo hints = {0}, *res;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host, port, &hints, &res) != 0 || !res)
        die("resolve failed");
    g_sock = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (g_sock < 0 || connect(g_sock, res->ai_addr, res->ai_addrlen) != 0)
        die("connect failed");
    freeaddrinfo(res);
    struct timeval tv = {30, 0};
    setsockopt(g_sock, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
}

int main(int argc, char **argv) {
    if (argc >= 2 && strcmp(argv[1], "--hooks") == 0) {
        if (argc != 4)
            die("usage: conformance_client --hooks HOST PORT");
        connect_to(argv[2], argv[3]);
        return run_hooks_mode();
    }
    if (argc != 3 && argc != 4)
        die("usage: conformance_client HOST PORT [RESOURCE_DIMS]");
    if (argc == 4) R_VEC = atoi(argv[3]);
    if (R_VEC < 2 || R_VEC > 64) die("bad RESOURCE_DIMS");

    connect_to(argv[1], argv[2]);

    struct frame f;

    /* 1. protocol-skew rejection: HELLO with an old protocol number */
    send_frame(F_HELLO, g_req_id, "{\"last_rv\":-1,\"proto\":1}", NULL, 0);
    await_reply(g_req_id++, &f);
    int skew_rejected = (f.type == F_ERROR);
    free_frame(&f);

    /* 2. real HELLO -> SNAPSHOT (the connection survives the ERROR) */
    char hello[64];
    snprintf(hello, sizeof hello, "{\"last_rv\":-1,\"proto\":%d}", PROTO);
    send_frame(F_HELLO, g_req_id, hello, NULL, 0);
    await_reply(g_req_id++, &f);
    if (f.type != F_SNAPSHOT) die("expected SNAPSHOT after HELLO");
    long snapshot_rv = json_find_long(f.json, "rv", -1);
    int snapshot_events = count_occurrences(f.json, "\"kind\":");
    int snapshot_arrays_ok = arrays_manifest_ok(&f);
    free_frame(&f);
    if (snapshot_rv < 0) die("snapshot carried no rv");

    /* 3. push OUR node + pod into the sidecar: the Go-plugin feed
     * direction.  Arrays are hand-encoded little-endian int32 rows. */
    size_t vec_bytes = (size_t)R_VEC * sizeof(int32_t);
    int32_t *both = calloc(2 * (size_t)R_VEC, sizeof(int32_t));
    if (!both) die("oom");
    both[0] = 16000;  /* cpu millicores */
    both[1] = 65536;  /* memory MiB */
    char doc[512];
    snprintf(doc, sizeof doc,
             "{\"kind\":\"node_upsert\",\"name\":\"c-node\","
             "\"labels\":{\"made-in\":\"c\"},"
             "\"__arrays__\":["
             "{\"key\":\"allocatable\",\"dtype\":\"<i4\",\"shape\":[%d],"
             "\"offset\":0,\"nbytes\":%zu},"
             "{\"key\":\"usage\",\"dtype\":\"<i4\",\"shape\":[%d],"
             "\"offset\":%zu,\"nbytes\":%zu}]}",
             R_VEC, vec_bytes, R_VEC, vec_bytes, vec_bytes);
    send_frame(F_STATE_PUSH, g_req_id, doc, both, 2 * vec_bytes);
    await_reply(g_req_id++, &f);
    if (f.type != F_ACK) die("node state-push not acked");
    long node_rv = json_find_long(f.json, "rv", -1);
    free_frame(&f);

    int32_t *req_vec = calloc((size_t)R_VEC, sizeof(int32_t));
    if (!req_vec) die("oom");
    req_vec[0] = 2000;
    req_vec[1] = 4096;
    snprintf(doc, sizeof doc,
             "{\"kind\":\"pod_add\",\"name\":\"c-pod\",\"priority\":7,"
             "\"node_selector\":{\"made-in\":\"c\"},"
             "\"__arrays__\":[{\"key\":\"requests\",\"dtype\":\"<i4\","
             "\"shape\":[%d],\"offset\":0,\"nbytes\":%zu}]}",
             R_VEC, vec_bytes);
    send_frame(F_STATE_PUSH, g_req_id, doc, req_vec, vec_bytes);
    await_reply(g_req_id++, &f);
    if (f.type != F_ACK) die("pod state-push not acked");
    long pod_rv = json_find_long(f.json, "rv", -1);
    free_frame(&f);
    if (!(node_rv > snapshot_rv && pod_rv > node_rv))
        die("state-push rvs not monotonic");

    /* 4. our own events come back as rv-ordered DELTA pushes */
    while (g_last_push_rv < pod_rv) {
        read_one_frame(&f);
        if (f.req_id == 0) {
            if (f.type < 16) g_push_counts[f.type]++;
            long rv = json_find_long(f.json, "rv", -1);
            if (rv > g_last_push_rv) g_last_push_rv = rv;
        }
        free_frame(&f);
    }
    int deltas_seen = g_push_counts[F_DELTA];

    /* 5. drive scheduling rounds; our pod must land on our node.
     * Our DELTA arriving back on THIS connection does not mean the
     * sidecar's own solver feed (a separate sync client) has applied it
     * yet, so retry the solve until c-pod appears — the same
     * eventual-consistency polling a real plugin does against informer
     * lag. */
    char *assignments = NULL;
    char c_pod_node[64] = "";
    long round_pods = -1;
    for (int attempt = 0; attempt < 100 && !c_pod_node[0]; attempt++) {
        free(assignments);
        send_frame(F_SOLVE_REQUEST, g_req_id, "{}", NULL, 0);
        await_reply(g_req_id++, &f);
        if (f.type != F_SOLVE_RESPONSE) die("expected SOLVE_RESPONSE");
        assignments = json_find_object(f.json, "assignments");
        if (!assignments) die("solve response had no assignments object");
        round_pods = json_find_long(f.json, "round_pods", -1);
        free_frame(&f);
        const char *cpod = strstr(assignments, "\"c-pod\":\"");
        if (cpod) {
            cpod += strlen("\"c-pod\":\"");
            size_t i = 0;
            while (cpod[i] && cpod[i] != '"' && i < sizeof c_pod_node - 1) {
                c_pod_node[i] = cpod[i];
                i++;
            }
            c_pod_node[i] = 0;
        } else {
            usleep(100 * 1000);
        }
    }

    /* 6. lease CAS: read, acquire from empty, then a stale CAS must
     * be refused (the leader-election safety property) */
    send_frame(F_LEASE_GET, g_req_id, "{\"name\":\"conformance\"}", NULL, 0);
    await_reply(g_req_id++, &f);
    if (f.type != F_ACK) die("lease get failed");
    free_frame(&f);

    snprintf(doc, sizeof doc,
             "{\"name\":\"conformance\",\"expect_holder\":\"\","
             "\"holder\":\"c-client\",\"duration_seconds\":15.0,"
             "\"acquire_time\":1.0,\"renew_time\":1.0,\"transitions\":0}");
    send_frame(F_LEASE_UPDATE, g_req_id, doc, NULL, 0);
    await_reply(g_req_id++, &f);
    int lease_acquired = (f.type == F_ACK) &&
        json_find_bool(f.json, "ok", 0);
    free_frame(&f);

    snprintf(doc, sizeof doc,
             "{\"name\":\"conformance\",\"expect_holder\":\"someone-else\","
             "\"holder\":\"thief\",\"duration_seconds\":15.0,"
             "\"acquire_time\":2.0,\"renew_time\":2.0,\"transitions\":1}");
    send_frame(F_LEASE_UPDATE, g_req_id, doc, NULL, 0);
    await_reply(g_req_id++, &f);
    int stale_cas_refused = (f.type == F_ACK) &&
        !json_find_bool(f.json, "ok", 1);
    free_frame(&f);

    printf("{\"skew_rejected\":%s,\"snapshot_rv\":%ld,"
           "\"snapshot_events\":%d,\"snapshot_arrays_ok\":%s,"
           "\"node_rv\":%ld,\"pod_rv\":%ld,\"deltas_seen\":%d,"
           "\"assignments\":%s,\"c_pod_node\":\"%s\",\"round_pods\":%ld,"
           "\"lease_acquired\":%s,\"stale_cas_refused\":%s}\n",
           skew_rejected ? "true" : "false", snapshot_rv, snapshot_events,
           snapshot_arrays_ok ? "true" : "false", node_rv, pod_rv,
           deltas_seen, assignments, c_pod_node, round_pods,
           lease_acquired ? "true" : "false",
           stale_cas_refused ? "true" : "false");
    free(assignments);
    close(g_sock);

    if (!skew_rejected) die("old protocol was not rejected");
    if (!snapshot_arrays_ok) die("snapshot array manifest inconsistent");
    if (!lease_acquired) die("lease CAS acquire failed");
    if (!stale_cas_refused) die("stale lease CAS was not refused");
    if (!c_pod_node[0]) die("c-pod was not assigned to any node");
    return 0;
}
