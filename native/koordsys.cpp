// libkoordsys: the agent's native fast path.
//
// The reference's only native code is cgo bindings — NVML for GPU metrics and
// libpfm4 for perf counters (pkg/koordlet/util/perf_group/
// perf_group_linux.go:39-40, collector_gpu_linux.go). This library provides
// the TPU-rebuild equivalents:
//
//   * ks_batch_read: one C pass reading hundreds of small cgroup/procfs files
//     (the per-pod collector hot loop; Python open/read per file costs ~10x).
//   * ks_cpi_*: perf_event_open cycles+instructions counters per cgroup, the
//     CPI collector's data source (libpfm's role in the reference). Uses the
//     raw syscall — no libpfm dependency.
//   * ks_watch_*: inotify directory watching for the PLEG (the reference's
//     pleg.go is fsnotify-driven, pkg/koordlet/pleg/pleg.go:81): pod/container
//     cgroup dirs appearing or vanishing gate the Python scan-diff, so quiet
//     ticks cost no tree walk.
//
// Everything degrades gracefully: callers treat any negative return as
// "unsupported here" and fall back to the Python path.

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#ifdef __linux__
#include <dirent.h>
#include <fcntl.h>
#include <poll.h>
#include <sys/inotify.h>
#include <sys/ioctl.h>
#include <sys/syscall.h>
#include <unistd.h>
#include <linux/perf_event.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// Batched small-file read.
//
// paths:   n NUL-terminated file paths
// buf:     n rows of stride bytes each; row i receives file i's content,
//          NUL-terminated and truncated to stride-1
// sizes:   out, per-file byte count or -errno
// returns: number of files read successfully
// ---------------------------------------------------------------------------
int ks_batch_read(const char **paths, int n, char *buf, int stride,
                  long *sizes) {
#ifndef __linux__
    (void)paths; (void)n; (void)buf; (void)stride; (void)sizes;
    return -1;
#else
    int ok = 0;
    for (int i = 0; i < n; i++) {
        char *row = buf + (size_t)i * stride;
        row[0] = '\0';
        int fd = open(paths[i], O_RDONLY | O_CLOEXEC);
        if (fd < 0) {
            sizes[i] = -errno;
            continue;
        }
        ssize_t total = 0;
        for (;;) {
            ssize_t got = read(fd, row + total, stride - 1 - total);
            if (got < 0) {
                if (errno == EINTR) continue;
                total = -errno;
                break;
            }
            if (got == 0 || total + got >= stride - 1) {
                total += got;
                break;
            }
            total += got;
        }
        close(fd);
        if (total >= 0) {
            row[total < stride - 1 ? total : stride - 1] = '\0';
            sizes[i] = total;
            ok++;
        } else {
            sizes[i] = total;
        }
    }
    return ok;
#endif
}

// ---------------------------------------------------------------------------
// Cgroup CPI counters via perf_event_open.
//
// A handle owns, per online CPU, a cycles counter with an instructions
// counter in the same event group (PERF_FLAG_PID_CGROUP scoping). Reads
// return the summed deltas since open.
// ---------------------------------------------------------------------------

#define KS_MAX_HANDLES 256
#define KS_MAX_CPUS 512

struct ks_cpi_handle {
    int used;
    int n_cpus;
    int cycles_fd[KS_MAX_CPUS];
    int instructions_fd[KS_MAX_CPUS];
};

static ks_cpi_handle g_handles[KS_MAX_HANDLES];

#ifdef __linux__
static long perf_open(struct perf_event_attr *attr, int pid, int cpu,
                      int group_fd, unsigned long flags) {
    return syscall(SYS_perf_event_open, attr, pid, cpu, group_fd, flags);
}
#endif

// Open counters for a cgroup (perf_cgroup path under the perf_event mount,
// e.g. "/sys/fs/cgroup/perf_event/kubepods/pod1"). Returns handle id >= 0 or
// -errno. n_cpus = number of online CPUs to instrument.
int ks_cpi_open(const char *cgroup_dir, int n_cpus) {
#ifndef __linux__
    (void)cgroup_dir; (void)n_cpus;
    return -38;  // -ENOSYS
#else
    if (n_cpus <= 0 || n_cpus > KS_MAX_CPUS) return -EINVAL;
    int slot = -1;
    for (int i = 0; i < KS_MAX_HANDLES; i++) {
        if (!g_handles[i].used) { slot = i; break; }
    }
    if (slot < 0) return -EMFILE;

    int cgroup_fd = open(cgroup_dir, O_RDONLY | O_DIRECTORY | O_CLOEXEC);
    if (cgroup_fd < 0) return -errno;

    ks_cpi_handle *h = &g_handles[slot];
    memset(h, 0, sizeof(*h));
    h->n_cpus = n_cpus;

    struct perf_event_attr attr;
    int opened = 0;
    for (int cpu = 0; cpu < n_cpus; cpu++) {
        memset(&attr, 0, sizeof(attr));
        attr.size = sizeof(attr);
        attr.type = PERF_TYPE_HARDWARE;
        attr.config = PERF_COUNT_HW_CPU_CYCLES;
        attr.disabled = 1;
        attr.inherit = 1;
        attr.exclude_kernel = 0;
        long cfd = perf_open(&attr, cgroup_fd, cpu, -1, PERF_FLAG_PID_CGROUP);
        if (cfd < 0) { h->cycles_fd[cpu] = -1; h->instructions_fd[cpu] = -1; continue; }

        memset(&attr, 0, sizeof(attr));
        attr.size = sizeof(attr);
        attr.type = PERF_TYPE_HARDWARE;
        attr.config = PERF_COUNT_HW_INSTRUCTIONS;
        attr.disabled = 0;
        attr.inherit = 1;
        long ifd = perf_open(&attr, cgroup_fd, cpu, (int)cfd, PERF_FLAG_PID_CGROUP);
        if (ifd < 0) { close((int)cfd); h->cycles_fd[cpu] = -1; h->instructions_fd[cpu] = -1; continue; }

        h->cycles_fd[cpu] = (int)cfd;
        h->instructions_fd[cpu] = (int)ifd;
        ioctl((int)cfd, PERF_EVENT_IOC_ENABLE, 0);
        opened++;
    }
    close(cgroup_fd);
    if (opened == 0) return -EACCES;  // perf unavailable (permissions/kernel)
    h->used = 1;
    return slot;
#endif
}

// Sum counters across CPUs. Returns 0 or -errno.
int ks_cpi_read(int handle, unsigned long long *cycles,
                unsigned long long *instructions) {
#ifndef __linux__
    (void)handle; (void)cycles; (void)instructions;
    return -38;
#else
    if (handle < 0 || handle >= KS_MAX_HANDLES || !g_handles[handle].used)
        return -EBADF;
    ks_cpi_handle *h = &g_handles[handle];
    unsigned long long c_total = 0, i_total = 0;
    for (int cpu = 0; cpu < h->n_cpus; cpu++) {
        unsigned long long v;
        if (h->cycles_fd[cpu] >= 0 &&
            read(h->cycles_fd[cpu], &v, sizeof(v)) == sizeof(v))
            c_total += v;
        if (h->instructions_fd[cpu] >= 0 &&
            read(h->instructions_fd[cpu], &v, sizeof(v)) == sizeof(v))
            i_total += v;
    }
    *cycles = c_total;
    *instructions = i_total;
    return 0;
#endif
}

void ks_cpi_close(int handle) {
#ifdef __linux__
    if (handle < 0 || handle >= KS_MAX_HANDLES || !g_handles[handle].used)
        return;
    ks_cpi_handle *h = &g_handles[handle];
    for (int cpu = 0; cpu < h->n_cpus; cpu++) {
        if (h->cycles_fd[cpu] >= 0) close(h->cycles_fd[cpu]);
        if (h->instructions_fd[cpu] >= 0) close(h->instructions_fd[cpu]);
    }
    h->used = 0;
#else
    (void)handle;
#endif
}

// ---------------------------------------------------------------------------
// Inotify directory watching (PLEG fast path).
//
// ks_watch_open  -> inotify fd (or -errno)
// ks_watch_add   -> watch descriptor for one directory (or -errno); watches
//                   dir create/delete/move — the pod/container lifecycle
//                   signals the reference's fsnotify PLEG consumes
// ks_watch_poll  -> serialize pending events into out as lines
//                   "<wd> <C|D> <name>\n" (C = appeared, D = vanished);
//                   returns bytes written, 0 on timeout, or -errno; a
//                   "-1 C *" line means events were lost (kernel queue or
//                   out-buffer overflow) — rescan everything
// ks_watch_close — cleanup (per-dir watches drop with their dirs)
// ---------------------------------------------------------------------------

int ks_watch_open(void) {
#ifndef __linux__
    return -38;  // -ENOSYS
#else
    int fd = inotify_init1(IN_NONBLOCK | IN_CLOEXEC);
    return fd < 0 ? -errno : fd;
#endif
}

int ks_watch_add(int fd, const char *path) {
#ifndef __linux__
    (void)fd; (void)path;
    return -38;
#else
    int wd = inotify_add_watch(
        fd, path,
        IN_CREATE | IN_DELETE | IN_MOVED_FROM | IN_MOVED_TO | IN_ONLYDIR);
    return wd < 0 ? -errno : wd;
#endif
}

int ks_watch_poll(int fd, int timeout_ms, char *out, int cap) {
#ifndef __linux__
    (void)fd; (void)timeout_ms; (void)out; (void)cap;
    return -38;
#else
    struct pollfd pfd = {fd, POLLIN, 0};
    int pr = poll(&pfd, 1, timeout_ms);
    if (pr < 0) return -errno;
    if (pr == 0) return 0;
    char buf[16384];
    ssize_t got = read(fd, buf, sizeof(buf));
    if (got < 0) return errno == EAGAIN ? 0 : -errno;
    // reserve room for the synthetic overflow marker: events read() has
    // already consumed from the fd but that don't fit in `out` must still
    // be signaled, or callers would silently miss real create/deletes
    const char overflow_line[] = "-1 C *\n";
    const int marker = (int)sizeof(overflow_line) - 1;
    if (cap <= marker) return -EINVAL;
    const int soft_cap = cap - marker;
    int used = 0;
    int truncated = 0;
    ssize_t off = 0;
    while (off + (ssize_t)sizeof(struct inotify_event) <= got) {
        struct inotify_event *ev = (struct inotify_event *)(buf + off);
        off += sizeof(struct inotify_event) + ev->len;
        if (ev->mask & IN_Q_OVERFLOW) {
            truncated = 1;   // kernel queue overflowed: marker below
            continue;
        }
        if (ev->len == 0) continue;
        char kind = 0;
        if (ev->mask & (IN_CREATE | IN_MOVED_TO)) kind = 'C';
        else if (ev->mask & (IN_DELETE | IN_MOVED_FROM)) kind = 'D';
        else continue;
        int need = snprintf(NULL, 0, "%d %c %s\n", ev->wd, kind, ev->name);
        if (used + need >= soft_cap) {   // out full: marker signals the rest
            truncated = 1;
            break;
        }
        used += snprintf(out + used, soft_cap - used, "%d %c %s\n",
                         ev->wd, kind, ev->name);
    }
    if (truncated) {
        memcpy(out + used, overflow_line, marker);
        used += marker;
    }
    return used;
#endif
}

void ks_watch_close(int fd) {
#ifdef __linux__
    if (fd >= 0) close(fd);
#else
    (void)fd;
#endif
}

// Library self-check (Python binding probes this at load).
int ks_version(void) { return 2; }

}  // extern "C"
