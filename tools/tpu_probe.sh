#!/bin/bash
# Auto-capture prober: the axon tunnel flaps for hours at a time
# (PERF_NOTES tunnel log, rounds 2-4).  Poll it with a cheap kernel and,
# the moment it answers, capture the round's hardware record — bench.py
# headline, bench_stages.py stage split, bench_micro.py scenarios — into
# probe_results/.  Single-instance via pidfile; exits after one full
# nonzero capture (the CAPTURED marker) so it never burns the chip in a
# loop.  Lives in the repo because the /tmp copies of rounds 2-3 were
# lost between sessions.
set -u
PIDFILE=/tmp/tpu_probe.pid
if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
    exit 0
fi
echo $$ > "$PIDFILE"
# clean up on ANY exit: a stale pidfile whose PID gets recycled by an
# unrelated process would silently block every future probe run
trap 'rm -f "$PIDFILE"' EXIT
OUT=/root/repo/probe_results
mkdir -p "$OUT"
# a CAPTURED marker older than 6h is from a previous round/session —
# expire it so the new round can capture its own record (bench.py's
# promotion only accepts captures <12h old)
if [ -f "$OUT/CAPTURED" ]; then
    if [ -n "$(find "$OUT/CAPTURED" -mmin +360 2>/dev/null)" ]; then
        rm -f "$OUT/CAPTURED"
    else
        exit 0
    fi
fi

while true; do
    # bench._device_alive classifies HOW the probe failed
    # (no_devices_enumerated / probe_kernel_hung / transfer_stall /
    # probe_error) so probe.log records a diagnosis per ROADMAP item 1,
    # not four rounds of undifferentiated "tunnel down"
    # the probe also reports the device count (ISSUE 10): a sharded
    # capture on a multichip window must be distinguishable from the
    # single-chip tunnel in the published perf trajectory — printed by
    # the SAME process (jax is already initialized there; a second
    # python would burn up to 2 min of the capture window re-acquiring
    # the runtime)
    kind=$(timeout 200 python -c 'import sys
sys.path.insert(0, "/root/repo")
from bench import _device_alive
ok, kind, err = _device_alive(150.0)
if ok:
    import jax
    # full 2-D mesh provenance in the capture window (ISSUE 14): the
    # axis split this window would solve on, printed by the SAME
    # process (jax is already warm here)
    from koordinator_tpu.parallel import mesh as pmesh
    m = pmesh.resolve_solver_mesh("auto")
    ax = pmesh.mesh_axes(m) or {"pods": 1, "nodes": 1}
    print(f"ok {len(jax.devices())} {ax['pods']}x{ax['nodes']}")
else:
    print(kind)' 2>/dev/null | tail -1)
    [ -z "$kind" ] && kind=probe_process_hung
    mesh_shape=unknown
    case "$kind" in
        ok\ *) rest=${kind#ok }; ndev=${rest%% *}
               case "$rest" in *\ *) mesh_shape=${rest#* };; esac
               kind=ok;;
        *) ndev=unknown;;
    esac
    if [ "$kind" = "ok" ]; then
        ts=$(date +%Y%m%d_%H%M%S)
        echo "$(date -Is) tunnel up (n_devices=${ndev}," \
            "mesh=${mesh_shape}), capturing" >> "$OUT/probe.log"
        # NO_PROBE_PROMOTION: this run must produce a FRESH measurement
        # or a zero that keeps the hunt alive — a promoted old capture
        # here would satisfy the nonzero grep below and end the hunt
        # without any new hardware evidence
        KOORD_BENCH_PROBE_TRIES=1 KOORD_BENCH_NO_PROBE_PROMOTION=1 \
            timeout 3600 python /root/repo/bench.py \
            > "$OUT/bench_$ts.json" 2> "$OUT/bench_$ts.err"
        timeout 1800 python /root/repo/bench_stages.py \
            > "$OUT/stages_$ts.jsonl" 2> "$OUT/stages_$ts.err"
        # publish the staged capture IMMEDIATELY (ISSUE 9 satellite):
        # the per-stage device walls become a provenance-stamped
        # published_*.json the moment they exist, instead of waiting
        # for the next official bench round to promote them
        timeout 120 python /root/repo/bench.py --publish-staged \
            >> "$OUT/probe.log" 2>&1 || true
        timeout 1200 python /root/repo/bench_micro.py \
            > "$OUT/micro_$ts.json" 2> "$OUT/micro_$ts.err"
        # approx_max_k recall on the backend where it is actually
        # approximate (VERDICT r4 next #6): candidate recall + at-shape
        # assigned_frac for approx/chunked/exact, drives method="auto"
        timeout 1800 python /root/repo/bench_recall.py \
            > "$OUT/recall_$ts.json" 2> "$OUT/recall_$ts.err"
        echo "$(date -Is) capture done" >> "$OUT/probe.log"
        # a nonzero headline ends the hunt; a zero record (tunnel died
        # mid-capture) keeps probing for the next window
        if [ -s "$OUT/bench_$ts.json" ] && \
           ! grep -q '"value": 0.0' "$OUT/bench_$ts.json"; then
            touch "$OUT/CAPTURED"
            exit 0
        fi
    else
        echo "$(date -Is) tunnel down ($kind)" >> "$OUT/probe.log"
    fi
    sleep 240
done
