#!/usr/bin/env python
"""Static dashboard drift check (ISSUE 5 satellite).

Every metric name referenced by a PromQL ``expr`` in
``dashboards/*.json`` must be a series the registries in
``koordinator_tpu/metrics.py`` actually register (histograms expand to
their ``_bucket``/``_sum``/``_count`` series).  A renamed or deleted
instrument otherwise leaves a silently-empty dashboard panel — drift an
operator only notices during an incident.

Usage:
    python tools/check_dashboards.py                  # shipped dashboards
    python tools/check_dashboards.py path/to/dash.json ...

Exit 0 = clean; exit 1 lists every unregistered reference.  Also
invoked by tools/soak.sh (a soak against drifted dashboards is wasted
evidence) and by tests/test_metrics.py (positive + negative).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

#: metric-name shapes our registries can produce (see metrics.Registry
#: prefixes); anything else inside an expr is PromQL syntax, not a metric
METRIC_RE = re.compile(r"\b(koord_[a-z0-9_]+|koordlet_[a-z0-9_]+)\b")

#: floor on total references checked across the shipped dashboards: a
#: regex or schema rot that silently matched nothing would otherwise
#: turn the check into a rubber stamp
MIN_REFERENCES = 10


def known_series() -> set[str]:
    """Every series name the component registries expose (histogram
    sub-series included)."""
    from koordinator_tpu import metrics as m

    names: set[str] = set()
    for reg in m.ALL_REGISTRIES:
        for full, metric in reg.items():
            names.add(full)
            if isinstance(metric, m.Histogram):
                names.update({f"{full}_bucket", f"{full}_sum",
                              f"{full}_count"})
    return names


def check_file(path: str, known: set[str]) -> tuple[list[str], int]:
    """(errors, references_checked) for one dashboard JSON."""
    errors: list[str] = []
    checked = 0
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable dashboard JSON: {e}"], 0
    for panel in doc.get("panels", []):
        title = panel.get("title", "?")
        for target in panel.get("targets", []):
            expr = target.get("expr", "")
            for name in METRIC_RE.findall(expr):
                checked += 1
                if name not in known:
                    errors.append(
                        f"{path}: panel {title!r} references "
                        f"unregistered metric {name!r}")
    return errors, checked


def check_dashboards(paths: list[str] | None = None,
                     known: set[str] | None = None) -> tuple[list[str], int]:
    """(errors, total references checked) over the given dashboards
    (default: the repo's dashboards/*.json)."""
    if paths is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..", "dashboards")
        paths = sorted(glob.glob(os.path.join(root, "*.json")))
        if not paths:
            return ["no dashboards found under dashboards/"], 0
    known = known if known is not None else known_series()
    errors: list[str] = []
    checked = 0
    for path in paths:
        errs, n = check_file(path, known)
        errors.extend(errs)
        checked += n
    return errors, checked


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    paths = argv or None
    errors, checked = check_dashboards(paths)
    if paths is None and checked < MIN_REFERENCES:
        errors.append(
            f"only {checked} metric references found across the shipped "
            f"dashboards (< {MIN_REFERENCES}): the extractor regex or "
            "dashboard schema drifted and the check is no longer "
            "checking anything")
    if errors:
        for err in errors:
            print(err, file=sys.stderr)
        return 1
    print(f"dashboards OK: {checked} metric references, all registered")
    return 0


if __name__ == "__main__":
    # runnable from anywhere: the repo root (koordinator_tpu's parent)
    # must be importable
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."))
    raise SystemExit(main())
