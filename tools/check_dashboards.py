#!/usr/bin/env python
"""Static dashboard drift check — thin CLI shim.

The implementation moved into the koordlint framework
(``tools/koordlint/analyzers/dashboard_drift.py``, the fifth analyzer);
this entry point stays so existing wiring keeps working unchanged:

    python tools/check_dashboards.py                  # shipped dashboards
    python tools/check_dashboards.py path/to/dash.json ...

Exit 0 = clean; exit 1 lists every unregistered reference.  Also invoked
by tools/soak.sh (which now ALSO runs the full ``python -m
tools.koordlint`` suite first) and by tests/test_metrics.py
(positive + negative).
"""

from __future__ import annotations

import os
import sys

# runnable from anywhere AND importable via spec_from_file_location:
# the repo root (tools/' parent) must be on sys.path before the
# koordlint import below
_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
if os.path.abspath(_ROOT) not in (os.path.abspath(p) for p in sys.path):
    sys.path.insert(0, _ROOT)

from tools.koordlint.analyzers.dashboard_drift import (  # noqa: E402
    METRIC_RE,
    MIN_REFERENCES,
    check_dashboards,
    check_file,
    known_series,
)

__all__ = ["METRIC_RE", "MIN_REFERENCES", "check_dashboards", "check_file",
           "known_series", "main"]


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    paths = argv or None
    errors, checked = check_dashboards(paths,
                                       root=os.path.abspath(_ROOT))
    if errors:
        for err in errors:
            print(err, file=sys.stderr)
        return 1
    print(f"dashboards OK: {checked} metric references, all registered")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
