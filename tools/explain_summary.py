#!/usr/bin/env python
"""Explainability-surface smoke: drive a fresh scheduler, read back
``/debug/explain`` live, and tally top unschedulable reasons.

Assembles the scheduler binary (HTTP gateway + explain accounting),
runs a synthetic workload engineered so pods fail for a KNOWN mix of
reasons (resource fit, usage threshold, affinity, elastic quota), then
queries the gateway exactly as an operator would and prints an
end-of-run top-unschedulable-reasons summary.

FAILS (exit 1) if any pod ends the run pending with zero recorded
reasons — an unexplained pending pod means the reject-reason accounting
lost a pod, which is the regression this smoke exists to catch.
``tools/soak.sh`` runs it at the end of every soak (SOAK_EXPLAIN=0
disables); the numbers describe THIS driver's synthetic run, not the
soak's pytest windows (those run in their own interpreters).

    python tools/explain_summary.py --rounds 3
    python tools/explain_summary.py --json      # raw per-pod bodies
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="explain_summary")
    parser.add_argument("--rounds", type=int, default=3)
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    import numpy as np

    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.cmd.binaries import main_koord_scheduler
    from koordinator_tpu.quota.tree import QuotaTree
    from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec

    asm = main_koord_scheduler(
        ["--disable-leader-election", "--http-port", "0"])
    sched = asm.component
    try:
        # a small cluster where every reject reason has a home: n0 fits
        # everything, n1 is CPU-starved, n2 memory-starved, n3 sits over
        # the LoadAware usage threshold, n4 carries a label no pod
        # tolerates by default
        sched.snapshot.upsert_node(NodeSpec(
            name="n0", allocatable=resource_vector(cpu=64_000,
                                                   memory=65_536)))
        sched.snapshot.upsert_node(NodeSpec(
            name="n1", allocatable=resource_vector(cpu=500,
                                                   memory=65_536)))
        sched.snapshot.upsert_node(NodeSpec(
            name="n2", allocatable=resource_vector(cpu=64_000,
                                                   memory=128)))
        sched.snapshot.upsert_node(NodeSpec(
            name="n3", allocatable=resource_vector(cpu=10_000,
                                                   memory=65_536),
            usage=resource_vector(cpu=9_500)))
        sched.snapshot.upsert_node(NodeSpec(
            name="n4", allocatable=resource_vector(cpu=64_000,
                                                   memory=65_536),
            taints={"reserved": "special"}))
        # elastic quota with no headroom: quota-blocked pods
        total = np.asarray(resource_vector(cpu=1, memory=1), np.int64)
        tree = QuotaTree(total_resource=total)
        tree.add("starved", min=np.zeros_like(total),
                 max=np.asarray(resource_vector(cpu=1, memory=1),
                                np.int64))
        tree.refresh_runtime()
        sched.quota_tree = tree

        # fits nowhere but n0... which the giant pod then saturates
        sched.enqueue(PodSpec(name="giant",
                              requests=resource_vector(cpu=60_000,
                                                       memory=60_000)))
        sched.enqueue(PodSpec(name="crowded-out",
                              requests=resource_vector(cpu=8_000,
                                                       memory=8_000)))
        sched.enqueue(PodSpec(name="quota-blocked", quota="starved",
                              requests=resource_vector(cpu=1_000,
                                                       memory=512)))
        for _ in range(max(args.rounds, 1)):
            sched.schedule_round()

        port = asm.gateway.port
        pending = [name for name in sched.pending]
        unexplained: list[str] = []
        tally: dict[str, int] = {}
        bodies: dict[str, dict] = {}
        for name in pending:
            # candidates=0: this loop polls every pending pod and only
            # needs the retained reason counts, not the per-pod score
            # decomposition (which runs a score pass under the round
            # lock)
            url = (f"http://127.0.0.1:{port}/debug/explain/"
                   + urllib.parse.quote(name, safe="") + "?candidates=0")
            body = None
            # generous timeout + one retry: the first request pays the
            # on-demand candidate decomposition's cold jit compile, and
            # a transport timeout must not masquerade as the
            # zero-recorded-reasons regression this smoke exists to
            # catch (tools/explain_dump.py documents the same hazard)
            for attempt in range(2):
                try:
                    with urllib.request.urlopen(url, timeout=60) as resp:
                        body = json.loads(resp.read())
                    break
                except urllib.error.HTTPError as e:
                    unexplained.append(f"{name}: HTTP {e.code}")
                    break
                except Exception as e:  # noqa: BLE001 — transport
                    if attempt == 1:
                        unexplained.append(f"{name}: unreachable: {e}")
            if body is None:
                continue
            bodies[name] = body
            exp = body.get("explanation") or {}
            reasons = {k: v for k, v in (exp.get("reasons") or {}).items()
                       if v > 0}
            if not reasons:
                unexplained.append(
                    f"{name}: pending with zero recorded reasons")
                continue
            top = exp.get("top_reason") or max(
                reasons.items(), key=lambda kv: (kv[1], kv[0]))[0]
            tally[top] = tally.get(top, 0) + 1

        if args.json:
            print(json.dumps(bodies, indent=2, default=str))
        print("== top unschedulable reasons (/debug/explain, fresh "
              "synthetic drive — not a readback of the soak windows)")
        for reason, count in sorted(tally.items(),
                                    key=lambda kv: (-kv[1], kv[0])):
            print(f"  {reason:<22} {count} pod(s)")
        if not pending:
            print("  (no pods pending)")
        if unexplained:
            for line in unexplained:
                print(f"ERROR: {line}", file=sys.stderr)
            return 1
        return 0
    finally:
        asm.stop()


if __name__ == "__main__":
    raise SystemExit(main())
