#!/usr/bin/env python
"""Pretty-print a JSONL trace export as per-trace timelines.

The JSONL comes from the tracing module's JsonlExporter — one span per
line — typically enabled with ``KOORD_TRACE_JSONL=<path>`` on any
binary (or ``SOAK_TRACE=1 tools/soak.sh``).

Usage:
    tools/trace_dump.py trace.jsonl                  # every trace
    tools/trace_dump.py trace.jsonl --pod p0         # traces whose
                                                     # spans mention pod
    tools/trace_dump.py trace.jsonl --trace <id>     # one trace
    tools/trace_dump.py trace.jsonl --slowest-round  # the slowest
                                                     # scheduler.round
                                                     # span's flight
                                                     # record fields
    tools/trace_dump.py trace.jsonl --perfetto out.json
                                                     # Chrome trace-event
                                                     # export (open in
                                                     # ui.perfetto.dev)

The ``--perfetto`` export also understands per-cycle timeline docs
(the ``/debug/timeline`` cycle bodies, one JSON object per line mixed
into or instead of the span lines): each timeline segment becomes a
complete event on a per-tenant track under a "timeline" process, and
the cycle's device-idle intervals become an async track so the idle
gaps the critical-path solver attributed are visible as spans, not
inferred from whitespace.

Output per trace: spans sorted by start time, indented by parentage,
with offset-from-trace-start and duration, e.g.

    trace 9ac4... (pod-e2e)
      +0.000ms   1.2ms scheduler  scheduler.enqueue  pod=pod-e2e
      +4.1ms    80.0ms scheduler  scheduler.round    path=incremental
      ...

Dependency-free stdlib; malformed lines are skipped with a count.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def load_docs(path: str) -> tuple[list[dict], list[dict], int]:
    """Split a JSONL export into (spans, timeline cycle docs, bad).

    Span docs carry ``trace_id`` (the JsonlExporter's shape); timeline
    cycle docs carry ``segments`` (the ``/debug/timeline`` body's
    per-cycle shape) — both can ride the same file.
    """
    spans, cycles, bad = [], [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(doc, dict) and doc.get("trace_id"):
                spans.append(doc)
            elif isinstance(doc, dict) and isinstance(
                    doc.get("segments"), list):
                cycles.append(doc)
            else:
                bad += 1
    return spans, cycles, bad


def load_spans(path: str) -> tuple[list[dict], int]:
    spans, cycles, bad = load_docs(path)
    return spans, bad + len(cycles)


def group_traces(spans: list[dict]) -> dict[str, list[dict]]:
    traces: dict[str, list[dict]] = defaultdict(list)
    for span in spans:
        traces[span["trace_id"]].append(span)
    for trace in traces.values():
        trace.sort(key=lambda s: (s.get("start_time") or 0.0))
    return traces


def _depth(span: dict, by_id: dict[str, dict]) -> int:
    depth, seen = 0, set()
    cur = span
    while cur.get("parent_id") and cur["parent_id"] in by_id:
        if cur["span_id"] in seen:   # defensive: cyclic/garbage input
            break
        seen.add(cur["span_id"])
        cur = by_id[cur["parent_id"]]
        depth += 1
    return depth


def _fmt_attrs(attrs: dict, limit: int = 5) -> str:
    items = [f"{k}={v}" for k, v in list(attrs.items())[:limit]
             if v is not None]
    return " ".join(items)


def pod_of(trace: list[dict]) -> str | None:
    for span in trace:
        pod = (span.get("attributes") or {}).get("pod")
        if pod:
            return pod
    return None


def print_trace(trace_id: str, trace: list[dict], out=sys.stdout) -> None:
    by_id = {s["span_id"]: s for s in trace}
    t0 = min(s.get("start_time") or 0.0 for s in trace)
    pod = pod_of(trace)
    header = f"trace {trace_id}" + (f" (pod {pod})" if pod else "")
    print(header, file=out)
    for span in trace:
        offset_ms = ((span.get("start_time") or t0) - t0) * 1000.0
        dur_ms = (span.get("duration_s") or 0.0) * 1000.0
        indent = "  " * (_depth(span, by_id) + 1)
        status = "" if span.get("status") == "ok" else " [ERROR]"
        print(f"{indent}+{offset_ms:9.3f}ms {dur_ms:9.3f}ms "
              f"{span.get('service') or '-':<12} {span['name']}{status}  "
              f"{_fmt_attrs(span.get('attributes') or {})}", file=out)


def perfetto_events(spans: list[dict],
                    cycles: list[dict]) -> list[dict]:
    """Build Chrome trace-event objects (the JSON Array Format that
    Perfetto/chrome://tracing load) from span and timeline-cycle docs.

    Track layout: one process (pid) per emitting service, one thread
    (tid) per tenant within it ("" renders as "main"); timeline cycle
    docs get their own "timeline" process with the same per-tenant
    thread split, plus an async device-idle track per cycle so the
    attributed idle gaps show as spans.  Timestamps are the source
    docs' own clocks in microseconds — spans use wall time, timeline
    docs the monotonic perf counter — which Perfetto renders fine
    because tracks are only compared within a process.
    """
    events: list[dict] = []
    pids: dict[str, int] = {}
    tids: dict[tuple[str, str], int] = {}

    def pid_of(service: str) -> int:
        if service not in pids:
            pids[service] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[service], "tid": 0,
                           "args": {"name": service}})
        return pids[service]

    def tid_of(service: str, tenant: str) -> int:
        key = (service, tenant)
        if key not in tids:
            tids[key] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid_of(service), "tid": tids[key],
                           "args": {"name": tenant or "main"}})
        return tids[key]

    for span in spans:
        attrs = span.get("attributes") or {}
        service = span.get("service") or "unknown"
        tenant = str(attrs.get("tenant") or "")
        events.append({
            "ph": "X", "name": span.get("name") or "span",
            "cat": service,
            "pid": pid_of(service), "tid": tid_of(service, tenant),
            "ts": (span.get("start_time") or 0.0) * 1e6,
            "dur": max((span.get("duration_s") or 0.0) * 1e6, 1.0),
            "args": {"trace_id": span.get("trace_id"),
                     **{k: v for k, v in attrs.items()
                        if v is not None}},
        })
    for doc in cycles:
        t0 = float(doc.get("start") or 0.0)
        cycle = doc.get("cycle")
        for seg in doc.get("segments") or []:
            tenant = str(seg.get("tenant") or "")
            events.append({
                "ph": "X",
                "name": seg.get("name") or seg.get("cause") or "segment",
                "cat": seg.get("cause") or "segment",
                "pid": pid_of("timeline"),
                "tid": tid_of("timeline", tenant),
                "ts": (t0 + float(seg.get("start") or 0.0)) * 1e6,
                "dur": max((float(seg.get("end") or 0.0)
                            - float(seg.get("start") or 0.0)) * 1e6, 1.0),
                "args": {"cycle": cycle, "cause": seg.get("cause")},
            })
        for i, (i0, i1) in enumerate(doc.get("device_idle") or []):
            ident = f"idle-{cycle}-{i}"
            common = {"cat": "device_idle", "name": "device_idle",
                      "pid": pid_of("timeline"), "id": ident,
                      "args": {"cycle": cycle}}
            events.append({"ph": "b", "ts": (t0 + float(i0)) * 1e6,
                           **common})
            events.append({"ph": "e", "ts": (t0 + float(i1)) * 1e6,
                           **common})
    return events


def export_perfetto(spans: list[dict], cycles: list[dict],
                    out_path: str) -> int:
    events = perfetto_events(spans, cycles)
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return len(events)


def print_slowest_round(spans: list[dict], out=sys.stdout) -> int:
    rounds = [s for s in spans if s.get("name") == "scheduler.round"]
    if not rounds:
        print("no scheduler.round spans in the export", file=out)
        return 1
    slowest = max(rounds, key=lambda s: s.get("duration_s") or 0.0)
    attrs = slowest.get("attributes") or {}
    print(f"slowest round: trace {slowest['trace_id']} "
          f"({(slowest.get('duration_s') or 0) * 1000:.3f}ms)", file=out)
    for key in ("round", "solver", "solve_path", "pods", "placed",
                "failed", "suspended", "degraded", "staleness_s",
                "dirty_node_frac", "dirty_pod_frac", "solve_wall_s",
                "solve_device_s"):
        if key in attrs:
            print(f"  {key:>16}: {attrs[key]}", file=out)
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="pretty-print a JSONL trace export")
    parser.add_argument("path", help="JSONL file from the JsonlExporter")
    parser.add_argument("--pod", help="only traces mentioning this pod")
    parser.add_argument("--trace", help="only this trace id")
    parser.add_argument("--slowest-round", action="store_true",
                        help="print the slowest scheduler.round span's "
                             "flight-record fields and exit")
    parser.add_argument("--perfetto", metavar="OUT",
                        help="write a Chrome trace-event JSON file "
                             "(open in ui.perfetto.dev) instead of "
                             "pretty-printing; timeline cycle docs in "
                             "the input become per-tenant tracks with "
                             "an async device-idle track")
    args = parser.parse_args(argv)
    spans, cycles, bad = load_docs(args.path)
    if bad:
        print(f"({bad} malformed lines skipped)", file=sys.stderr)
    if args.perfetto:
        if not spans and not cycles:
            print("no spans or timeline cycles to export",
                  file=sys.stderr)
            return 1
        n = export_perfetto(spans, cycles, args.perfetto)
        print(f"wrote {n} trace events ({len(spans)} spans, "
              f"{len(cycles)} timeline cycles) to {args.perfetto}",
              file=sys.stderr)
        return 0
    if args.slowest_round:
        return print_slowest_round(spans)
    traces = group_traces(spans)
    shown = 0
    for trace_id, trace in sorted(
            traces.items(),
            key=lambda kv: min(s.get("start_time") or 0.0
                               for s in kv[1])):
        if args.trace and trace_id != args.trace:
            continue
        if args.pod and pod_of(trace) != args.pod:
            continue
        print_trace(trace_id, trace)
        shown += 1
    if not shown:
        print("no matching traces", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
