#!/bin/bash
# Reproducible randomized soak over the property suites (VERDICT r4 #8:
# the ~1,700-run campaign that closed round 4 was run by hand and was
# unreproducible).  Sweeps FRESH seed windows through every randomized
# invariant suite via the conftest prop_seeds knobs and prints one JSON
# tally line; CI keeps the cheap default seeds untouched.
#
# Usage:  tools/soak.sh            # 10 windows of the suites' default
#                                  # seed counts, bases 1000,2000,...
#         SOAK_WINDOWS=40 SOAK_COUNT=8 tools/soak.sh   # 40 windows x 8
#                                  # seeds per suite (~40*8*25 runs)
# Knobs:  SOAK_WINDOWS (default 10)  number of seed windows
#         SOAK_COUNT   (default 0)   seeds per suite per window
#                                    (0 = each suite's CI default count)
#         SOAK_BASE0   (default 1000) first window's seed base
#         SOAK_STRIDE  (default 1000) distance between window bases
#         SOAK_OUT     (default soak_results) output directory
#         SOAK_TRACE   (default 0)    1 = enable the JSONL trace
#                                     exporter (KOORD_TRACE_JSONL) for
#                                     every window and print the slowest
#                                     round's flight record at the end
#                                     (tools/trace_dump.py
#                                     --slowest-round)
#         SOAK_SLO     (default 1)    1 = end the run with the SLO
#                                     surface smoke: tools/slo_summary
#                                     drives a fresh scheduler+gateway
#                                     and prints per-SLO worst burn +
#                                     breach count from its live
#                                     /debug/slo (proves the SLO
#                                     machinery end to end; the pytest
#                                     windows run in their own
#                                     interpreters, so this is not a
#                                     readback of the soak itself)
#         SOAK_EXPLAIN (default 1)    1 = end the run with the
#                                     explainability smoke:
#                                     tools/explain_summary.py drives a
#                                     fresh scheduler+gateway with pods
#                                     failing for a known reason mix,
#                                     prints the top-unschedulable-
#                                     reasons tally from live
#                                     /debug/explain, and FAILS the
#                                     soak if any pod ends pending with
#                                     zero recorded reasons
#         SOAK_LOADGEN (default 0)    1 = end the run with the steady-
#                                     state smoke: tools/soak_report.py
#                                     replays a seeded churn trace
#                                     (loadgen) against a live
#                                     scheduler+manager+feeder over
#                                     real sockets, prints the
#                                     per-series trend verdict table
#                                     joined to flight records + SLO
#                                     breaches, and FAILS the soak on a
#                                     leak/drift (red) verdict; the
#                                     injected-thread-leak self-test
#                                     runs too (must come back red),
#                                     plus a 4-tenant multi-cluster
#                                     smoke whose per-tenant verdict
#                                     section must come back green
#         SOAK_QUALITY (default 0)    1 = end the run with the solve-
#                                     quality smoke: one loadgen soak
#                                     with --quality-mode auto (the
#                                     LP-relaxation packing engine
#                                     escalating on capacity slack);
#                                     the verdict must stay GREEN and
#                                     quality_rounds_total must be
#                                     nonzero — both enforced by
#                                     soak_report's exit status
#         SOAK_FORECAST (default 0)   1 = end the run with the
#                                     reactive-vs-predictive A/B smoke
#                                     (tools/soak_report.py --forecast):
#                                     both arms replay ONE seeded
#                                     diurnal trace (forecast/ab.py),
#                                     the per-arm scorecard prints
#                                     (SLO-breach minutes, reactive
#                                     evictions, pre-staged
#                                     migrations, forecast error), and
#                                     the soak FAILS unless the
#                                     predictive arm is no worse on
#                                     breaches and evictions and
#                                     pre-staged at least one
#                                     migration
#         SOAK_BENCH_DIFF (default 0) 1 = end the run with the perf
#                                     regression sentinel: a fresh
#                                     bench_stages --smoke capture is
#                                     diffed per-stage against the
#                                     committed baseline
#                                     (tools/baselines/
#                                     bench_stages_smoke.jsonl) via
#                                     tools/bench_diff.py and the soak
#                                     FAILS on any stage regressing
#                                     beyond SOAK_BENCH_DIFF_TOLERANCE
#                                     (default 1.0 = 100%: the
#                                     committed baseline was captured
#                                     on different hardware, so the
#                                     default only catches
#                                     order-of-magnitude rot; tighten
#                                     it when soaking on the baseline
#                                     machine)
#         SOAK_CHAOS   (default 0)    1 = also sweep the chaos
#                                     fault-injection suite (tests/
#                                     test_chaos.py, `chaos` marker)
#                                     across the same seed windows via
#                                     KOORD_CHAOS_SEED_BASE/_COUNT; a
#                                     failing window prints its seed
#                                     base so the exact fault schedule
#                                     replays with
#                                     KOORD_CHAOS_SEED_BASE=<base>
#         SOAK_DRILLS  (default 0)    1 = also sweep the adversarial
#                                     failure drills (tests/
#                                     test_drills_e2e.py, every catalog
#                                     scenario x the window's seeds) via
#                                     KOORD_DRILL_SEED_BASE/_COUNT; a
#                                     failing window prints its seed
#                                     base so the exact drill replays
#                                     with KOORD_DRILL_SEED_BASE=<base>,
#                                     and the run ends with the drill
#                                     verdict table (tools/
#                                     soak_report.py --drills: per-
#                                     scenario checks + measured RTO,
#                                     exit 0 iff all GREEN)
set -u
cd "$(dirname "$0")/.."

WINDOWS=${SOAK_WINDOWS:-10}
COUNT=${SOAK_COUNT:-0}
BASE0=${SOAK_BASE0:-1000}
STRIDE=${SOAK_STRIDE:-1000}
OUT=${SOAK_OUT:-soak_results}
CHAOS=${SOAK_CHAOS:-0}
DRILLS=${SOAK_DRILLS:-0}
LOADGEN=${SOAK_LOADGEN:-0}
QUALITY=${SOAK_QUALITY:-0}
FORECAST=${SOAK_FORECAST:-0}
TRACE=${SOAK_TRACE:-0}
SLO=${SOAK_SLO:-1}
EXPLAIN=${SOAK_EXPLAIN:-1}
BENCH_DIFF=${SOAK_BENCH_DIFF:-0}
BENCH_DIFF_TOLERANCE=${SOAK_BENCH_DIFF_TOLERANCE:-1.0}
BENCH_BASELINE=${SOAK_BENCH_BASELINE:-tools/baselines/bench_stages_smoke.jsonl}
mkdir -p "$OUT"
ts=$(date +%Y%m%d_%H%M%S)
log="$OUT/soak_$ts.log"

# static-analysis gate first: a soak over a tree with known invariant
# violations (jit host syncs, donation hazards, lock races, drifted
# debug surfaces) produces evidence nobody should trust.  Exits the
# soak's tally as a failure, never silently.
total_passed=0
total_failed=0
failures=""
echo "== koordlint static-analysis suite" \
    "(python -m tools.koordlint --format json)" | tee -a "$log"
if python -m tools.koordlint --format json >> "$log" 2>&1; then
    total_passed=$((total_passed + 1))
else
    total_failed=$((total_failed + 1))
    failures="$failures;koordlint: unsuppressed findings (see log -"
    failures="$failures run python -m tools.koordlint)"
fi

# dashboard drift gate (also a koordlint analyzer; the standalone shim
# stays for precise per-dashboard CLI output in the log)
echo "== dashboard drift check (tools/check_dashboards.py)" | tee -a "$log"
if python tools/check_dashboards.py >> "$log" 2>&1; then
    total_passed=$((total_passed + 1))
else
    total_failed=$((total_failed + 1))
    failures="$failures;dashboard drift: tools/check_dashboards.py failed"
    failures="$failures (see log)"
fi
trace_jsonl=""
if [ "$TRACE" = "1" ]; then
    trace_jsonl="$OUT/trace_$ts.jsonl"
    export KOORD_TRACE_JSONL="$trace_jsonl"
    echo "== tracing to $trace_jsonl" | tee -a "$log"
fi

SUITES="tests/test_deviceshare_properties.py \
tests/test_gang_properties.py \
tests/test_incremental_solve.py \
tests/test_lownodeload_properties.py \
tests/test_network_topology_properties.py \
tests/test_numa_properties.py \
tests/test_preemption_properties.py \
tests/test_quota_properties.py \
tests/test_replay_parity.py \
tests/test_reservation_properties.py \
tests/test_scheduler_accounting.py"

for ((w = 0; w < WINDOWS; w++)); do
    base=$((BASE0 + w * STRIDE))
    echo "== window $((w + 1))/$WINDOWS seed base $base" | tee -a "$log"
    KOORD_PROP_SEED_BASE=$base KOORD_PROP_SEED_COUNT=$COUNT \
        python -m pytest $SUITES -q --tb=line >> "$log" 2>&1
    rc=$?
    p=$(tail -40 "$log" | grep -oE "[0-9]+ passed" | tail -1 | grep -oE "[0-9]+")
    f=$(tail -40 "$log" | grep -oE "[0-9]+ failed" | tail -1 | grep -oE "[0-9]+")
    total_passed=$((total_passed + ${p:-0}))
    total_failed=$((total_failed + ${f:-0}))
    # a window that crashes without printing 'N failed' (collection
    # error, ImportError, OOM kill) must not count as green: trust
    # pytest's exit code over the summary grep.  Crash notes APPEND —
    # a later window's FAILED grep must not erase them.
    if [ "$rc" -ne 0 ] && [ "${f:-0}" -eq 0 ]; then
        total_failed=$((total_failed + 1))
        failures="$failures;window base=$base: pytest rc=$rc with no "
        failures="${failures}parsed failure count (crash — see log)"
    fi
    if [ "${f:-0}" -gt 0 ]; then
        failures="$failures;$(grep "^FAILED" "$log" | sort -u \
            | tr '\n' ';')"
    fi

    if [ "$CHAOS" = "1" ]; then
        echo "== chaos window $((w + 1))/$WINDOWS seed base $base" \
            | tee -a "$log"
        KOORD_CHAOS_SEED_BASE=$base KOORD_CHAOS_SEED_COUNT=$COUNT \
            python -m pytest tests/test_chaos.py -m chaos -q --tb=line \
            >> "$log" 2>&1
        crc=$?
        cp=$(tail -40 "$log" | grep -oE "[0-9]+ passed" | tail -1 \
            | grep -oE "[0-9]+")
        cf=$(tail -40 "$log" | grep -oE "[0-9]+ failed" | tail -1 \
            | grep -oE "[0-9]+")
        total_passed=$((total_passed + ${cp:-0}))
        if [ "$crc" -ne 0 ]; then
            total_failed=$((total_failed + ${cf:-1}))
            # the seed base IS the replay handle: rerun the exact fault
            # schedule with KOORD_CHAOS_SEED_BASE=<base>
            echo "CHAOS FAILURE at seed base $base — replay with" \
                "KOORD_CHAOS_SEED_BASE=$base python -m pytest" \
                "tests/test_chaos.py -m chaos" | tee -a "$log"
            failures="$failures;chaos seed base=$base rc=$crc:"
            failures="$failures $(grep '^FAILED' "$log" | sort -u \
                | tr '\n' ';')"
        fi
    fi

    if [ "$DRILLS" = "1" ]; then
        echo "== drill window $((w + 1))/$WINDOWS seed base $base" \
            | tee -a "$log"
        KOORD_DRILL_SEED_BASE=$base KOORD_DRILL_SEED_COUNT=$COUNT \
            python -m pytest tests/test_drills_e2e.py -m chaos -q \
            --tb=line >> "$log" 2>&1
        drc=$?
        dp=$(tail -40 "$log" | grep -oE "[0-9]+ passed" | tail -1 \
            | grep -oE "[0-9]+")
        df=$(tail -40 "$log" | grep -oE "[0-9]+ failed" | tail -1 \
            | grep -oE "[0-9]+")
        total_passed=$((total_passed + ${dp:-0}))
        if [ "$drc" -ne 0 ]; then
            total_failed=$((total_failed + ${df:-1}))
            # the seed base IS the replay handle: rerun the exact drill
            # (churn trace + storm schedule) with
            # KOORD_DRILL_SEED_BASE=<base>
            echo "DRILL FAILURE at seed base $base — replay with" \
                "KOORD_DRILL_SEED_BASE=$base python -m pytest" \
                "tests/test_drills_e2e.py -m chaos" | tee -a "$log"
            failures="$failures;drill seed base=$base rc=$drc:"
            failures="$failures $(grep '^FAILED' "$log" | sort -u \
                | tr '\n' ';')"
        fi
    fi
done

if [ "$DRILLS" = "1" ]; then
    # drill verdict table BEFORE the tally so its verdict counts in the
    # JSON: every catalog scenario runs once at the report seed and the
    # per-scenario check + RTO table prints; exit 0 iff all GREEN
    echo "== drill verdict table (soak_report --drills)" | tee -a "$log"
    if python tools/soak_report.py --drills >> "$log" 2>&1; then
        grep -E "^(== drills|-- |   |VERDICT)" "$log" | tail -12
        total_passed=$((total_passed + 1))
    else
        tail -16 "$log"
        total_failed=$((total_failed + 1))
        failures="$failures;drills: RED scenario verdict or harness"
        failures="$failures failure (see log)"
    fi
fi

if [ "$EXPLAIN" = "1" ]; then
    # explainability smoke BEFORE the tally so its verdict counts in the
    # JSON: top-unschedulable-reasons summary from a live
    # /debug/explain, failing if any pod ends pending with zero
    # recorded reasons (an unexplained pending pod = the reject-reason
    # accounting lost a pod)
    echo "== explainability smoke (tools/explain_summary.py)" | tee -a "$log"
    if python tools/explain_summary.py >> "$log" 2>&1; then
        tail -8 "$log"
        total_passed=$((total_passed + 1))
    else
        tail -8 "$log"
        total_failed=$((total_failed + 1))
        failures="$failures;explain smoke: pending pod with zero recorded"
        failures="$failures reasons or surface failure (see log)"
    fi
fi

if [ "$LOADGEN" = "1" ]; then
    # steady-state smoke BEFORE the tally so its verdict counts in the
    # JSON: a seeded churn soak must come back GREEN (no leak/drift, no
    # live SLO breach, bounded backlog), and the deliberate thread-leak
    # self-test must come back RED (a leak detector that can't catch a
    # planted leak proves nothing)
    echo "== steady-state smoke (tools/soak_report.py)" | tee -a "$log"
    if python tools/soak_report.py >> "$log" 2>&1; then
        grep -E "^(== steady|VERDICT|-- )" "$log" | tail -8
        total_passed=$((total_passed + 1))
    else
        tail -12 "$log"
        total_failed=$((total_failed + 1))
        failures="$failures;steady-state smoke: red verdict or harness"
        failures="$failures failure (see log)"
    fi
    echo "== injected-leak self-test (soak_report --inject-leak thread)" \
        | tee -a "$log"
    if python tools/soak_report.py --inject-leak thread >> "$log" 2>&1; then
        tail -2 "$log"
        total_passed=$((total_passed + 1))
    else
        tail -6 "$log"
        total_failed=$((total_failed + 1))
        failures="$failures;leak self-test: injected thread leak was NOT"
        failures="$failures caught (see log)"
    fi
    # multi-tenant smoke (ISSUE 11): four simulated clusters on one
    # TenantScheduler mesh — one churn process + socket stack + sync
    # binding per tenant; the verdict's per-tenant section must be
    # populated and GREEN (no tenant degraded)
    echo "== multi-tenant steady-state smoke (soak_report --tenants 4)" \
        | tee -a "$log"
    if python tools/soak_report.py --tenants 4 --duration 60 --nodes 16 \
            >> "$log" 2>&1; then
        grep -E "^(-- tenants|   t[0-9]|VERDICT)" "$log" | tail -7
        total_passed=$((total_passed + 1))
    else
        tail -12 "$log"
        total_failed=$((total_failed + 1))
        failures="$failures;multi-tenant smoke: red verdict or harness"
        failures="$failures failure (see log)"
    fi
fi

if [ "$QUALITY" = "1" ]; then
    # solve-quality smoke BEFORE the tally so its verdict counts in the
    # JSON: a churn soak with --quality-mode auto must come back GREEN
    # AND must have escalated at least one round onto the LP packing
    # path (soak_report exits nonzero on quality_rounds_total == 0)
    echo "== solve-quality smoke (soak_report --quality-mode auto)" \
        | tee -a "$log"
    if python tools/soak_report.py --quality-mode auto >> "$log" 2>&1; then
        grep -E "^(-- quality|VERDICT)" "$log" | tail -2
        total_passed=$((total_passed + 1))
    else
        tail -12 "$log"
        total_failed=$((total_failed + 1))
        failures="$failures;quality smoke: red verdict or zero quality"
        failures="$failures rounds (see log)"
    fi
fi

if [ "$FORECAST" = "1" ]; then
    # forecast A/B smoke BEFORE the tally so its verdict counts in the
    # JSON: the reactive and predictive arms replay one seeded diurnal
    # trace; the predictive arm must be no worse on SLO-breach minutes
    # and reactive evictions AND must have pre-staged at least one
    # reservation-first migration (both enforced by soak_report's exit)
    echo "== forecast A/B smoke (soak_report --forecast)" | tee -a "$log"
    if python tools/soak_report.py --forecast >> "$log" 2>&1; then
        grep -E "^(== forecast|-- forecast|   |VERDICT)" "$log" | tail -9
        total_passed=$((total_passed + 1))
    else
        tail -12 "$log"
        total_failed=$((total_failed + 1))
        failures="$failures;forecast A/B: predictive arm worse than"
        failures="$failures reactive or zero prestaged migrations (see log)"
    fi
fi

if [ "$BENCH_DIFF" = "1" ]; then
    # perf regression sentinel BEFORE the tally so its verdict counts
    # in the JSON: capture bench_stages --smoke fresh and diff every
    # stage against the committed baseline; any stage beyond the
    # tolerance (or missing/errored) fails the soak
    bench_capture="$OUT/bench_stages_$ts.jsonl"
    echo "== perf regression sentinel (bench_stages --smoke vs" \
        "$BENCH_BASELINE, tolerance $BENCH_DIFF_TOLERANCE)" | tee -a "$log"
    if python bench_stages.py --smoke > "$bench_capture" 2>> "$log" \
            && python tools/bench_diff.py "$BENCH_BASELINE" \
                "$bench_capture" --tolerance "$BENCH_DIFF_TOLERANCE" \
                >> "$log" 2>&1; then
        grep -E "bench_diff:" "$log" | tail -1
        total_passed=$((total_passed + 1))
    else
        grep -E "\"verdict\": \"(regressed|missing|errored)\"|bench_diff:" \
            "$log" | tail -6
        total_failed=$((total_failed + 1))
        failures="$failures;bench_diff: stage regression vs committed"
        failures="$failures baseline (see log and $bench_capture)"
    fi
fi

# the tally is built by python so failure text (quotes, backslashes in
# assert messages) can never produce invalid JSON
json="$OUT/soak_$ts.json"
SOAK_TALLY_FAILURES="$failures" python - "$WINDOWS" "$COUNT" "$BASE0" \
        "$STRIDE" "$total_passed" "$total_failed" "$log" <<'PYEOF' \
    | tee "$json"
import json
import os
import sys

w, c, b, s, p, f, log = sys.argv[1:8]
print(json.dumps({
    "windows": int(w),
    "seeds_per_suite_per_window": (int(c) or "suite-default"),
    "base0": int(b), "stride": int(s),
    "total_passed": int(p), "total_failed": int(f),
    "failures": os.environ.get("SOAK_TALLY_FAILURES", "").strip(";"),
    "log": log,
}))
PYEOF

if [ "$TRACE" = "1" ] && [ -s "$trace_jsonl" ]; then
    echo "== slowest round ($trace_jsonl)" | tee -a "$log"
    python tools/trace_dump.py "$trace_jsonl" --slowest-round \
        | tee -a "$log"
fi
if [ "$SLO" = "1" ]; then
    # SLO surface smoke from a live /debug/slo (fresh synthetic drive
    # over the gateway — not a readback of the pytest windows above):
    # per-SLO worst burn rate + breach count
    python tools/slo_summary.py | tee -a "$log" \
        || echo "WARNING: slo_summary failed (see log)" | tee -a "$log"
fi
[ "$total_failed" -eq 0 ]
