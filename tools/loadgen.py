#!/usr/bin/env python
"""Churn load generator: a deterministic, seeded, trace-driven arrival
process for the steady-state observatory (ISSUE 9 / ROADMAP item 3).

Everything before this proved the control plane round-at-a-time;
production scale is a CONTINUOUS arrival process.  This module turns a
seed into a reproducible churn trace — Poisson pod arrivals with
diurnal rate modulation, exponential pod lifetimes, gang bursts,
quota-tree churn, node flaps — and replays it against a real scheduler
sidecar + manager + koordlet-style feeder over real sockets, reusing
the chaos soak's socket scaffolding and replay-seed discipline
(tests/test_chaos.py): the SAME seed always produces the SAME trace,
so a failing soak replays exactly.

Trace format (JSONL, one event per line, ascending virtual time)::

    {"t": 12.375, "kind": "pod_add",  "name": "p-42", "cpu": 1000,
     "memory": 1024, "qos": 0, "priority": 1000, "gang": null,
     "quota": "team-a"}
    {"t": 13.000, "kind": "pod_del",  "name": "p-17"}
    {"t": 30.125, "kind": "gang_burst", "gang": "g-3", "size": 8, ...}
    {"t": 45.500, "kind": "node_down", "name": "n-210"}
    {"t": 75.500, "kind": "node_up",   "name": "n-210"}
    {"t": 90.250, "kind": "quota_update", "quota": "team-b",
     "scale": 0.5}

``t`` is VIRTUAL seconds from soak start; the harness replays at
``time_scale``x wall compression (a 30-minute trace drives a 3-minute
wall soak at time_scale=10 without changing the event sequence).

Arrival shapes follow "A Predictive Autoscaler for Elastic Batch Jobs"
(PAPERS.md): elastic-batch pods arrive in a thinned inhomogeneous
Poisson process whose rate swings sinusoidally (the diurnal curve),
punctuated by gang bursts (tightly-coupled jobs arrive all at once)
and served with exponential lifetimes.

No JAX at module scope (marker-audit): the harness imports the
scheduler stack inside methods, so tier-1 smoke tests import this
module for trace math without paying a backend init.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import random
import sys
import threading
import time
from typing import Iterable, Optional

POD_ADD = "pod_add"
POD_DEL = "pod_del"
GANG_BURST = "gang_burst"
NODE_DOWN = "node_down"
NODE_UP = "node_up"
QUOTA_UPDATE = "quota_update"

EVENT_KINDS = (POD_ADD, POD_DEL, GANG_BURST, NODE_DOWN, NODE_UP,
               QUOTA_UPDATE)


@dataclasses.dataclass(frozen=True)
class Event:
    """One trace event (JSON-able; ``payload`` carries kind-specific
    fields)."""

    t: float
    kind: str
    name: str = ""
    payload: dict = dataclasses.field(default_factory=dict)

    def to_doc(self) -> dict:
        return {"t": self.t, "kind": self.kind, "name": self.name,
                **self.payload}

    @classmethod
    def from_doc(cls, doc: dict) -> "Event":
        doc = dict(doc)
        return cls(t=float(doc.pop("t")), kind=str(doc.pop("kind")),
                   name=str(doc.pop("name", "")), payload=doc)


@dataclasses.dataclass(frozen=True)
class LoadGenConfig:
    """One soak's knobs — everything the seed expands from."""

    seed: int = 0
    duration_s: float = 1800.0      # virtual seconds of churn
    nodes: int = 10_000
    node_cpu_milli: int = 16_000
    node_memory_mib: int = 65_536
    #: midline pod arrival rate (pods per virtual second)
    arrival_rate: float = 8.0
    #: diurnal modulation: rate(t) = arrival_rate * (1 + amp*sin(2πt/T))
    diurnal_amplitude: float = 0.5
    diurnal_period_s: float = 600.0
    #: exponential service lifetime (virtual seconds) after which the
    #: submitter deletes the pod whether it bound or not
    pod_lifetime_s: float = 240.0
    #: fraction of arrivals that are BE/batch-dim pods
    be_fraction: float = 0.25
    #: gang bursts: Poisson at this rate, each a gang of [lo, hi] pods
    gang_rate: float = 0.02
    gang_size: tuple[int, int] = (4, 16)
    #: node flaps: Poisson at this rate; a flapped node is DOWN for
    #: outage_s then comes back empty
    node_flap_rate: float = 0.01
    node_outage_s: float = 60.0
    #: quota churn: every interval one quota's max rescales within
    #: [squeeze, relax] of its base
    quotas: int = 4
    quota_churn_rate: float = 0.05
    quota_scale_range: tuple[float, float] = (0.4, 1.5)
    pod_cpu_milli: tuple[int, int] = (250, 2_000)
    pod_memory_mib: tuple[int, int] = (128, 2_048)
    #: multi-tenant traces (ISSUE 11): >1 emits one INDEPENDENT churn
    #: process per tenant — per-tenant seeds derive from the master
    #: seed (tenant_seed), every event carries a ``tenant`` field, and
    #: the harness replays each tenant's stream against its own cluster
    #: on a shared TenantScheduler mesh
    tenants: int = 1
    #: weighted-fair admission weights, one per tenant (short tuples
    #: pad with 1.0) — drives the TenantScheduler's DRR shares
    tenant_weights: tuple = ()

    def quota_names(self) -> list[str]:
        return [f"lg-quota-{i}" for i in range(self.quotas)]

    def tenant_names(self) -> list[str]:
        return [f"t{i}" for i in range(max(self.tenants, 1))]

    def tenant_weight(self, i: int) -> float:
        if i < len(self.tenant_weights):
            return float(self.tenant_weights[i])
        return 1.0


def tenant_seed(master_seed: int, tenant_index: int) -> int:
    """Per-tenant seed derived deterministically from the master seed:
    the SAME (master seed, tenant) pair always yields the same
    sub-trace, and tenant t's sub-trace is byte-identical to a
    single-tenant trace generated directly from this seed (asserted in
    tests/test_loadgen.py)."""
    return (master_seed * 1_000_003 + 7_919 * (tenant_index + 1)) \
        & 0x7FFFFFFF


def generate_trace(cfg: LoadGenConfig) -> list[Event]:
    """Expand a config (seed included) into the full sorted event list.

    Deterministic by construction: one ``random.Random(seed)`` drives
    every draw in a fixed order, so the same (seed, knobs) pair always
    yields the same byte-identical trace — the replay-seed discipline
    the chaos soak established.
    """
    if cfg.tenants > 1:
        return _generate_multi_tenant(cfg)
    rng = random.Random(cfg.seed)
    events: list[Event] = []
    pod_seq = 0
    gang_seq = 0

    def pod_payload(gang: str | None = None) -> dict:
        be = rng.random() < cfg.be_fraction
        return {
            "cpu": rng.randint(*cfg.pod_cpu_milli),
            "memory": rng.randint(*cfg.pod_memory_mib),
            "qos": 4 if be else 0,          # QoSClass.BE == 4
            "be": be,
            "priority": 0 if be else 1000,
            "gang": gang,
            "quota": rng.choice(cfg.quota_names()) if cfg.quotas else None,
        }

    def add_pod(t: float, gang: str | None = None) -> None:
        nonlocal pod_seq
        name = f"lg-p{pod_seq}"
        pod_seq += 1
        events.append(Event(t, POD_ADD, name, pod_payload(gang)))
        dead = t + rng.expovariate(1.0 / cfg.pod_lifetime_s)
        if dead < cfg.duration_s:
            events.append(Event(dead, POD_DEL, name))

    # -- pod arrivals: inhomogeneous Poisson by thinning ---------------------
    peak_rate = cfg.arrival_rate * (1.0 + abs(cfg.diurnal_amplitude))
    t = 0.0
    while peak_rate > 0:
        t += rng.expovariate(peak_rate)
        if t >= cfg.duration_s:
            break
        rate_t = cfg.arrival_rate * (
            1.0 + cfg.diurnal_amplitude
            * math.sin(2.0 * math.pi * t / cfg.diurnal_period_s))
        if rng.random() * peak_rate <= max(rate_t, 0.0):
            add_pod(t)

    # -- gang bursts ---------------------------------------------------------
    t = 0.0
    while cfg.gang_rate > 0:
        t += rng.expovariate(cfg.gang_rate)
        if t >= cfg.duration_s:
            break
        gang = f"lg-g{gang_seq}"
        gang_seq += 1
        size = rng.randint(*cfg.gang_size)
        events.append(Event(t, GANG_BURST, gang, {"size": size}))
        for _ in range(size):
            add_pod(t, gang=gang)

    # -- node flaps ----------------------------------------------------------
    t = 0.0
    down_until: dict[str, float] = {}
    while cfg.node_flap_rate > 0 and cfg.nodes > 0:
        t += rng.expovariate(cfg.node_flap_rate)
        if t >= cfg.duration_s:
            break
        node = f"lg-n{rng.randrange(cfg.nodes)}"
        if down_until.get(node, -1.0) >= t:
            continue                        # already down; skip this flap
        up_at = t + cfg.node_outage_s
        down_until[node] = up_at
        events.append(Event(t, NODE_DOWN, node))
        if up_at < cfg.duration_s:
            events.append(Event(up_at, NODE_UP, node))

    # -- quota churn ---------------------------------------------------------
    t = 0.0
    while cfg.quota_churn_rate > 0 and cfg.quotas > 0:
        t += rng.expovariate(cfg.quota_churn_rate)
        if t >= cfg.duration_s:
            break
        lo, hi = cfg.quota_scale_range
        events.append(Event(t, QUOTA_UPDATE, rng.choice(cfg.quota_names()),
                            {"scale": round(rng.uniform(lo, hi), 3)}))

    events.sort(key=lambda e: (e.t, e.kind, e.name))
    return events


def _generate_multi_tenant(cfg: LoadGenConfig) -> list[Event]:
    """One independent churn process per tenant, stamped and merged.

    Each tenant's sub-trace is ``generate_trace`` of the SAME knobs
    under its derived seed (so single-tenant determinism tests transfer
    verbatim); the merged stream sorts by (t, tenant, kind, name) for a
    stable, reproducible interleaving."""
    import dataclasses as _dc

    merged: list[Event] = []
    for i, name in enumerate(cfg.tenant_names()):
        sub = _dc.replace(cfg, seed=tenant_seed(cfg.seed, i), tenants=1)
        for e in generate_trace(sub):
            merged.append(Event(e.t, e.kind, e.name,
                                {**e.payload, "tenant": name}))
    merged.sort(key=lambda e: (e.t, e.payload.get("tenant", ""),
                               e.kind, e.name))
    return merged


def write_trace(events: Iterable[Event], path: str) -> None:
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e.to_doc()) + "\n")


def read_trace(path: str) -> list[Event]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(Event.from_doc(json.loads(line)))
    return out


def trace_stats(events: list[Event]) -> dict:
    counts: dict[str, int] = {}
    tenants: dict[str, int] = {}
    for e in events:
        counts[e.kind] = counts.get(e.kind, 0) + 1
        tenant = e.payload.get("tenant")
        if tenant is not None:
            tenants[tenant] = tenants.get(tenant, 0) + 1
    span = events[-1].t - events[0].t if len(events) > 1 else 0.0
    stats = {"events": len(events), "span_s": round(span, 3),
             "counts": counts,
             "arrival_rate": (round(counts.get(POD_ADD, 0) / span, 3)
                              if span > 0 else 0.0)}
    if tenants:
        stats["tenants"] = dict(sorted(tenants.items()))
    return stats


# ---------------------------------------------------------------------------
# Replay harness: scheduler sidecar + manager + feeder over real sockets
# ---------------------------------------------------------------------------

class SteadyStateHarness:
    """Drives a churn trace against the assembled control plane and
    watches it with the full observatory: SLO burn rates, self-telemetry
    sampling, and the long-horizon trend engine — all over ONE shared
    MetricCache with the two-tier downsampling horizon so a multi-hour
    soak stays memory-bounded.

    Socket scaffolding mirrors tests/test_chaos.py: an RpcServer on a
    unix socket hosts StateSyncService (+SchedulerBinding) and
    SolveService; a feeder client pushes node/pod events; a manager-side
    StateSyncClient + ColocationLoop watches and pushes batch
    allocatable back; a solver client drives rounds on a cadence.

    Leak injection (the harness must be able to catch itself lying):

    - ``inject_thread_leak`` — a toy service "handles" each cycle by
      spawning a thread that parks forever (released at close), the
      classic forgotten-worker leak; caught via koord_process_threads.
    - ``inject_queue_leak`` — pod deletions are dropped and solve
      rounds stop, so the admission queue only ever grows; caught via
      koord_scheduler_pending_pods.
    """

    def __init__(self, cfg: LoadGenConfig, workdir: str,
                 time_scale: float = 10.0,
                 solve_interval_s: float = 5.0,
                 sample_interval_s: float = 0.15,
                 trend_scale: float = 1.0,
                 slo_latency_threshold_s: float = 0.2,
                 warmup_fraction: float = 0.3,
                 inject_thread_leak: bool = False,
                 inject_queue_leak: bool = False,
                 quality_mode: str = "off",
                 quality_slack_threshold: float = 0.3):
        self.cfg = cfg
        self.workdir = workdir
        self.time_scale = time_scale
        self.solve_interval_s = solve_interval_s      # virtual seconds
        #: WALL seconds: trend fits run over real timestamps, and the
        #: sampler runs on its own thread so a blocking solve can't
        #: starve the observatory (the replay loop is single-threaded)
        self.sample_interval_s = sample_interval_s
        self.trend_scale = trend_scale
        #: the paper's p99 bar is 0.2; CPU smoke runs pass a looser one
        #: because their early rounds pay jit compilation in-line
        self.slo_latency_threshold_s = slo_latency_threshold_s
        #: the verdict's trend window opens after this fraction of the
        #: soak: the first rounds pay jit compilation and allocator
        #: warmup — real, one-time growth that a slope fit would read
        #: as a leak.  A true leak keeps leaking in the steady window.
        self.warmup_fraction = warmup_fraction
        self.steady_started_at: float | None = None
        self.inject_thread_leak = inject_thread_leak
        self.inject_queue_leak = inject_queue_leak
        #: solve-quality mode threaded into every scheduler the harness
        #: assembles (SOAK_QUALITY soaks run with "auto")
        self.quality_mode = quality_mode
        self.quality_slack_threshold = quality_slack_threshold
        self._leak_release = threading.Event()
        self._leaked_threads: list[threading.Thread] = []
        self._closers: list = []
        self.rounds = 0
        self.events_applied = 0
        self.push_errors = 0
        self.run_started_at: float | None = None
        self.scheduler = None
        self.monitor = None
        self.trend = None
        self.telemetry = None
        #: multi-tenant assembly (cfg.tenants > 1): the TenantScheduler
        #: front-end; per-tenant cluster stacks live in the maps below
        self.front = None
        self._feeders: dict = {}          # tenant -> feeder client
        self._tenant_sched: dict = {}     # tenant -> Scheduler
        self._quota_base: dict = {}       # (tenant, quota) -> base max
        self._colocations: list = []      # one ColocationLoop per cluster

    # -- assembly ------------------------------------------------------------

    def _build_quota_tree(self, tenant: str):
        import numpy as np

        from koordinator_tpu.api.resources import (
            NUM_RESOURCE_DIMS,
            resource_vector,
        )
        from koordinator_tpu.quota.tree import QuotaTree

        cfg = self.cfg
        total = resource_vector(
            cpu=cfg.node_cpu_milli * max(cfg.nodes, 1),
            memory=cfg.node_memory_mib * max(cfg.nodes, 1))
        quota_tree = QuotaTree(np.asarray(total, np.int64))
        for name in cfg.quota_names():
            qmax = (np.asarray(total, np.int64) * 2)
            quota_tree.add(name, min=np.zeros(NUM_RESOURCE_DIMS, np.int64),
                           max=qmax)
            self._quota_base[(tenant, name)] = qmax.copy()
        return quota_tree

    def _start_cluster(self, tenant: str, scheduler, index: int):
        """One cluster's socket stack: an RpcServer hosting a
        StateSyncService bound to THIS tenant's scheduler (the
        per-tenant sync binding — tenant isolation is structural: only
        this feed can make this tenant stale), a feeder client, and a
        manager-side watch + colocation loop.  Returns the server so
        the caller can mount the (shared) SolveService on cluster 0."""
        import numpy as np

        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient
        from koordinator_tpu.manager.colocation_loop import (
            ColocationLoop,
            ManagerSyncBinding,
        )
        from koordinator_tpu.manager.noderesource_controller import (
            NodeResourceController,
        )
        from koordinator_tpu.transport import (
            RpcServer,
            StateSyncClient,
            StateSyncService,
        )
        from koordinator_tpu.transport.deltasync import SchedulerBinding
        from koordinator_tpu.transport.retry import RetryPolicy

        cfg = self.cfg
        FrameType = self._FrameType
        sock = f"{self.workdir}/loadgen-{tenant}.sock"
        server = RpcServer(sock, service="scheduler")
        sync = StateSyncService(retention=8192)
        sync.attach(server)
        sync.attach_binding(SchedulerBinding(scheduler))
        server.start()
        self._closers.append(server.stop)

        retry = RetryPolicy(initial_backoff_s=0.05, max_backoff_s=0.5)
        feeder = ReconnectingSidecarClient(sock, retry_policy=retry,
                                           timeout=30.0)
        self._closers.append(feeder.close)
        self._feeders[tenant] = feeder
        self._tenant_sched[tenant] = scheduler

        binding = ManagerSyncBinding()
        mgr_sync = StateSyncClient(binding)

        def bootstrap_watch(client):
            mgr_sync.bind_client(client)
            mgr_sync.bootstrap(client)

        mgr_client = ReconnectingSidecarClient(
            sock, on_push=mgr_sync.on_push, on_connect=bootstrap_watch,
            retry_policy=retry, timeout=30.0)
        self._closers.append(mgr_client.close)

        def push_allocatable(name, allocatable,
                             _client=mgr_client):
            _client.call(
                FrameType.STATE_PUSH,
                {"kind": "node_allocatable", "name": name},
                {"allocatable": np.asarray(allocatable, np.int32)})

        self._colocations.append(ColocationLoop(
            NodeResourceController(), binding, push_allocatable,
            ensure_fn=mgr_client.ensure))

        # register the fleet directly on the sync service (the
        # informer-replay path the real binaries take at startup)
        alloc = np.asarray(resource_vector(
            cpu=cfg.node_cpu_milli, memory=cfg.node_memory_mib), np.int32)
        for i in range(cfg.nodes):
            sync.upsert_node(f"lg-n{i}", alloc)
        self._node_alloc = alloc
        if index == 0:
            self._server = server
            self._sync = sync
            self.feeder = feeder
            self.mgr_client = mgr_client
            self.mgr_sync = mgr_sync
        return server, sock

    def start(self) -> None:
        import numpy as np

        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient
        from koordinator_tpu.koordlet.metriccache import MetricCache
        from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
        from koordinator_tpu.selftelemetry import SelfTelemetry
        from koordinator_tpu.slo_monitor import (
            SloMonitor,
            default_specs,
            tenant_slo_specs,
        )
        from koordinator_tpu.transport.retry import RetryPolicy
        from koordinator_tpu.transport.services import SolveService
        from koordinator_tpu.transport.wire import FrameType
        from koordinator_tpu.trend import TrendEngine, default_trend_specs

        self._np = np
        self._resource_vector = resource_vector
        self._FrameType = FrameType

        cfg = self.cfg
        names = cfg.tenant_names()
        capacity = max(16, 1 << (cfg.nodes - 1).bit_length())
        # staleness is wall-clock: at time_scale compression the sync
        # feed beats every solve_interval/time_scale wall seconds, so
        # 8 beats of silence is a real stall, not compression artifact
        staleness = max(30.0, 8 * self.solve_interval_s / self.time_scale)
        if cfg.tenants > 1:
            from koordinator_tpu.scheduler.tenancy import (
                TenantScheduler,
                TenantSpec,
            )

            # the soak's budget is deliberately generous: the soak
            # proves steady state, the fairness tests prove sharing
            self.front = TenantScheduler(cycle_pod_budget=65_536)
            solve_target = self.front
            for i, name in enumerate(names):
                tenant = self.front.add_tenant(
                    TenantSpec(name=name, weight=cfg.tenant_weight(i),
                               node_capacity=capacity),
                    quota_tree=self._build_quota_tree(name),
                    staleness_threshold_sec=staleness,
                    quality_mode=self.quality_mode,
                    quality_slack_threshold=self.quality_slack_threshold)
                self._start_cluster(name, tenant.scheduler, i)
            self.scheduler = self.front.primary
        else:
            quota_tree = self._build_quota_tree(names[0])
            self.scheduler = Scheduler(
                ClusterSnapshot(capacity=capacity), quota_tree=quota_tree,
                staleness_threshold_sec=staleness,
                quality_mode=self.quality_mode,
                quality_slack_threshold=self.quality_slack_threshold)
            solve_target = self.scheduler
            self._start_cluster(names[0], self.scheduler, 0)
        sock0 = f"{self.workdir}/loadgen-{names[0]}.sock"
        SolveService(solve_target).attach(self._server)
        retry = RetryPolicy(initial_backoff_s=0.05, max_backoff_s=0.5)
        self.solver = ReconnectingSidecarClient(sock0, retry_policy=retry,
                                                timeout=240.0)
        self._closers.append(self.solver.close)

        # -- the observatory: one cache feeds SLO burn rates AND trends,
        # with the cold downsampling tier bounding an hours-long run
        cache = MetricCache(
            capacity_per_series=4096,
            retention_sec=max(4 * 3600.0, cfg.duration_s * 2),
            downsample_after_sec=600.0,
            downsample_resolution_sec=10.0)
        self.telemetry = SelfTelemetry("loadgen-harness")
        specs = default_specs(
            latency_threshold_s=self.slo_latency_threshold_s)
        if cfg.tenants > 1:
            # per-tenant p99 specs slice the shared latency histogram
            # by its {tenant=...} label (slo_monitor.tenant_slo_specs)
            specs = specs + tenant_slo_specs(
                names, latency_threshold_s=self.slo_latency_threshold_s)
        self.monitor = SloMonitor(
            specs=specs,
            cache=cache,
            sample_interval_s=self.sample_interval_s,
            on_breach=lambda spec, doc:
                self.scheduler.flight_recorder.dump_now(f"slo:{spec.name}"),
            pre_sample=[self.telemetry.sample])
        self.scheduler.slo_monitor = self.monitor
        self.trend = TrendEngine(cache,
                                 specs=default_trend_specs(
                                     scale=self.trend_scale),
                                 window_s=max(cfg.duration_s, 600.0))
        self.scheduler.trend_engine = self.trend
        if self.front is not None:
            self.front.slo_monitor = self.monitor
            self.front.trend_engine = self.trend

        # -- warm the solve path before the trend window opens (jit
        # compilation is one-time cost, not a trend): one warm pod per
        # tenant, one cycle, removal.  In quality mode the warm round
        # is forced onto the LP path too — auto's latch would otherwise
        # leave the quality program to compile mid-run, where its
        # (much larger) one-time cost reads as a latency breach and an
        # RSS step to the trend engine
        if self.quality_mode != "off":
            for sched in (self._tenant_sched.values()
                          if self._tenant_sched else [self.scheduler]):
                sched.arm_quality_escalation()
        for name in names:
            self._feeders[name].call(
                FrameType.STATE_PUSH,
                {"kind": "pod_add", "name": "lg-warm",
                 "priority": 1000},
                {"requests": np.asarray(resource_vector(
                    cpu=100, memory=64), np.int32)})
        self.solver.call(FrameType.SOLVE_REQUEST, {}, deadline_ms=240_000)
        for name in names:
            self._feeders[name].call(
                FrameType.STATE_PUSH,
                {"kind": "pod_remove", "name": "lg-warm"})
        for colocation in self._colocations:
            colocation.tick()
        self.colocation = self._colocations[0]

    # -- event application ---------------------------------------------------

    def _apply(self, event: Event) -> None:
        np = self._np
        rv = self._resource_vector
        FrameType = self._FrameType
        p = event.payload
        # tenant routing: every event lands on ITS tenant's feeder /
        # scheduler / quota tree (single-tenant traces carry no tenant
        # field and route to the only cluster)
        tenant = p.get("tenant", self.cfg.tenant_names()[0])
        feeder = self._feeders.get(tenant, self.feeder)
        scheduler = self._tenant_sched.get(tenant, self.scheduler)
        try:
            if event.kind == POD_ADD:
                if p.get("be"):
                    req = rv(batch_cpu=p["cpu"], batch_memory=p["memory"])
                else:
                    req = rv(cpu=p["cpu"], memory=p["memory"])
                doc = {"kind": "pod_add", "name": event.name,
                       "qos": int(p.get("qos", 0)),
                       "priority": int(p.get("priority", 0)),
                       # journey-ledger ingest stamp: rides the push as a
                       # sparse extras column so /debug/latency can split
                       # the feeder->enqueue hop out of e2e (ISSUE 20)
                       "arrival_ts": time.time()}
                if p.get("gang"):
                    doc["gang"] = p["gang"]
                if p.get("quota"):
                    doc["quota"] = p["quota"]
                feeder.call(FrameType.STATE_PUSH, doc,
                            {"requests": np.asarray(req, np.int32)})
            elif event.kind == POD_DEL:
                if self.inject_queue_leak:
                    return          # the leak: completions never arrive
                feeder.call(FrameType.STATE_PUSH,
                            {"kind": "pod_remove",
                             "name": event.name})
            elif event.kind == NODE_DOWN:
                feeder.call(FrameType.STATE_PUSH,
                            {"kind": "node_remove",
                             "name": event.name})
            elif event.kind == NODE_UP:
                feeder.call(
                    FrameType.STATE_PUSH,
                    {"kind": "node_upsert", "name": event.name},
                    {"allocatable": self._node_alloc})
            elif event.kind == GANG_BURST:
                # PodGroup CRs don't ride the node-state wire: register
                # the gang in-process before its members' pod_adds apply
                # (events sort gang_burst < pod_add at equal t)
                from koordinator_tpu.scheduler.scheduler import GangRecord

                scheduler.register_gang(GangRecord(
                    name=event.name, min_member=int(p["size"])))
            elif event.kind == QUOTA_UPDATE:
                # quota specs don't ride the wire (they are CRs, not
                # node state): churn them in-process under the round
                # lock, the webhook-update path's equivalent
                tree = scheduler.quota_tree
                base = self._quota_base.get((tenant, event.name))
                if tree is not None and base is not None:
                    with scheduler.lock:
                        node = tree.nodes.get(event.name)
                        if node is not None:
                            node.max = (base.astype(np.float64)
                                        * float(p.get("scale", 1.0))
                                        ).astype(np.int64)
            # GANG_BURST itself is a marker; its pods ride as POD_ADDs
            self.events_applied += 1
        except Exception:  # noqa: BLE001 — count-and-continue, the way
            self.push_errors += 1          # the real binaries ride out
            #                                a wedged peer tick

    def _solve_tick(self) -> None:
        try:
            self.solver.call(self._FrameType.SOLVE_REQUEST, {},
                             deadline_ms=240_000)
            self.rounds += 1
        except Exception:  # noqa: BLE001
            self.push_errors += 1
        for colocation in self._colocations:
            try:
                colocation.tick()
            except Exception:  # noqa: BLE001
                self.push_errors += 1
        self._maybe_leak_thread()

    def _maybe_leak_thread(self) -> None:
        """The injected leak: one forgotten worker per cycle, parked on
        the release event so close() can reap them all."""
        if self.inject_thread_leak:
            t = threading.Thread(target=self._leak_release.wait,
                                 daemon=True)
            t.start()
            self._leaked_threads.append(t)

    # -- replay --------------------------------------------------------------

    def run(self, events: list[Event],
            progress=None) -> dict:
        """Replay the trace at ``time_scale``x wall compression; solve
        rounds and observatory samples interleave on their own virtual
        cadences.  Returns the soak verdict document
        (:meth:`verdict`)."""
        start_wall = time.monotonic()
        self.run_started_at = time.time()
        warmup_vt = self.cfg.duration_s * self.warmup_fraction
        next_solve = 0.0
        i = 0
        vt_end = max(self.cfg.duration_s,
                     events[-1].t if events else 0.0)
        # sampling runs on the monitor's own wall-cadence thread: the
        # replay loop blocks on solves, and a starved sampler would
        # leave the trend window with too few points for any verdict
        self.monitor.start()
        try:
            while True:
                vt = (time.monotonic() - start_wall) * self.time_scale
                if self.steady_started_at is None and vt >= warmup_vt:
                    self.steady_started_at = time.time()
                while i < len(events) and events[i].t <= vt:
                    self._apply(events[i])
                    i += 1
                if vt >= next_solve:
                    if not self.inject_queue_leak:
                        self._solve_tick()
                    else:
                        self._solve_tick_starved()
                    next_solve += self.solve_interval_s
                    if progress is not None:
                        progress(vt, i, len(events))
                if vt >= vt_end and i >= len(events):
                    break
                time.sleep(0.02)
        finally:
            self.monitor.stop()
        self.monitor.tick()
        return self.verdict()

    def _solve_tick_starved(self) -> None:
        """The queue-leak variant: the arrival process keeps running but
        rounds stop serving it (a wedged solver), so pending_pods can
        only grow.  The gauge still needs refreshing — schedule_round
        normally publishes it — so read the queue depth directly."""
        from koordinator_tpu import metrics

        for scheduler in (self._tenant_sched.values()
                          if self._tenant_sched else [self.scheduler]):
            with scheduler.lock:
                depth = len(scheduler.pending)
            metrics.pending_pods.set(
                float(depth),
                labels=({"tenant": scheduler.tenant}
                        if scheduler.tenant else None))
        self._maybe_leak_thread()

    # -- verdict -------------------------------------------------------------

    def verdict(self, window_s: float | None = None) -> dict:
        """The soak's steady-state verdict: trend report (evaluated over
        the run window), SLO breach state, flight-recorder tallies, and
        the bounded-backlog/degraded-time checks the acceptance bar
        names."""
        from koordinator_tpu import metrics

        if window_s is None and self.steady_started_at is not None:
            # post-warmup steady window: jit compilation and allocator
            # ramp happened before it opened
            window_s = max(1.0, time.time() - self.steady_started_at)
        report = self.trend.evaluate(window_s=window_s)
        slo = self.monitor.report()
        rec = self.scheduler.flight_recorder
        tenants_doc = None
        if self.front is not None:
            tenants_doc = {}
            pending = bound = 0
            degraded = False
            records = dumps = overwrites = 0
            for tenant in self.front.tenants():
                sched = tenant.scheduler
                with sched.lock:
                    t_pending = len(sched.pending)
                    t_bound = len(sched.bound)
                    t_degraded = sched.degraded
                fr = sched.flight_recorder
                tenants_doc[tenant.name] = {
                    "weight": tenant.spec.weight,
                    "pending": t_pending,
                    "bound": t_bound,
                    "degraded": t_degraded,
                    "rounds": tenant.rounds,
                    "admitted_total": tenant.admitted_total,
                    "flight_dumps": fr.dumps,
                }
                pending += t_pending
                bound += t_bound
                degraded = degraded or t_degraded
                records += len(fr.records)
                dumps += fr.dumps
                overwrites += fr.overwrites
            flight = {"records": records, "dumps": dumps,
                      "overwrites": overwrites}
        else:
            with self.scheduler.lock:
                pending = len(self.scheduler.pending)
                bound = len(self.scheduler.bound)
                degraded = self.scheduler.degraded
            flight = {
                "records": len(rec.records),
                "dumps": rec.dumps,
                "overwrites": rec.overwrites,
            }
        doc = {
            "trend": report,
            "slo_breached": slo.get("breached", []),
            "slo": {d["name"]: {"breaches_total": d["breaches_total"],
                                "peak_burn": d["peak_burn"]}
                    for d in slo.get("slos", [])},
            "rounds": self.rounds,
            "events_applied": self.events_applied,
            "push_errors": self.push_errors,
            "pending": pending,
            "bound": bound,
            "degraded": degraded,
            "backlog_peak": metrics.sync_binding_backlog_peak.value(),
            "flight": flight,
            "green": (not report["leaking"] and not report["drifting"]
                      and not slo.get("breached") and not degraded),
        }
        if tenants_doc is not None:
            doc["tenants"] = tenants_doc
            doc["cycle"] = {
                "mode": self.front.last_mode,
                "host_wait_fraction": self.front.last_host_wait_fraction,
            }
        return doc

    def close(self) -> None:
        self._leak_release.set()
        for t in self._leaked_threads:
            t.join(timeout=5.0)
        self._leaked_threads.clear()
        if self.monitor is not None:
            self.monitor.stop()
        if self.telemetry is not None:
            self.telemetry.stop()
        for closer in reversed(self._closers):
            try:
                closer()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self._closers.clear()


def smoke_config(seed: int = 0, tenants: int = 1) -> LoadGenConfig:
    """The small, fast, fixed shape the tier-1 smoke and the
    SOAK_LOADGEN=1 hook share: seconds of wall clock, every event kind
    exercised."""
    return LoadGenConfig(
        seed=seed,
        tenants=tenants,
        duration_s=120.0,
        nodes=24,
        node_cpu_milli=32_000,
        node_memory_mib=65_536,
        arrival_rate=1.5,
        diurnal_period_s=60.0,
        pod_lifetime_s=30.0,
        gang_rate=0.05,
        gang_size=(3, 6),
        node_flap_rate=0.03,
        node_outage_s=20.0,
        quotas=2,
        quota_churn_rate=0.08,
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="loadgen",
        description="generate (and inspect) deterministic churn traces; "
                    "tools/soak_report.py replays them against the live "
                    "control plane")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--duration", type=float, default=1800.0,
                        help="virtual seconds of churn")
    parser.add_argument("--nodes", type=int, default=10_000)
    parser.add_argument("--arrival-rate", type=float, default=8.0)
    parser.add_argument("--tenants", type=int, default=1,
                        help="emit one independent churn process per "
                             "tenant (tenant id on every event; "
                             "per-tenant seeds derive from --seed)")
    parser.add_argument("--out", default="",
                        help="write the trace as JSONL here")
    parser.add_argument("--stats", action="store_true",
                        help="print event-kind tallies for the trace")
    args = parser.parse_args(argv)
    cfg = LoadGenConfig(seed=args.seed, duration_s=args.duration,
                        nodes=args.nodes, arrival_rate=args.arrival_rate,
                        tenants=args.tenants)
    events = generate_trace(cfg)
    if args.out:
        write_trace(events, args.out)
        print(f"wrote {len(events)} events to {args.out}")
    if args.stats or not args.out:
        print(json.dumps(trace_stats(events), indent=2))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    raise SystemExit(main())
