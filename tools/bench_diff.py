#!/usr/bin/env python
"""Perf regression sentinel: diff two bench_stages captures.

Compares a candidate bench_stages JSONL capture against a committed
baseline, stage by stage, and exits non-zero when any stage regressed —
the gate ``tools/soak.sh`` runs (``SOAK_BENCH_DIFF=1``) so every soak
self-compares against the repo's committed baseline capture instead of
trusting that "the numbers looked fine".

A stage REGRESSED when BOTH hold (the two-sided bar keeps noise on
microsecond stages from flapping the gate):

  cand_ms > base_ms * (1 + tolerance)      relative slowdown
  cand_ms - base_ms > min-delta-ms         absolute slowdown floor

Also fatal: a baseline stage missing from the candidate, or present but
errored (a stage that stopped compiling is a regression, not a skip).
Stages only the candidate has are reported as NEW and pass — growing
the capture must not require lock-step baseline updates.

Non-stage lines are skipped by name: ``provenance`` (git/mesh metadata,
no timing) and ``rtt_floor`` (the tunnel round-trip floor is machine
state, not code speed).  Baseline stages that ERRORED in the baseline
are skipped too — they never measured anything to regress from.

Usage:
  python tools/bench_diff.py BASELINE.jsonl CANDIDATE.jsonl \
      [--tolerance 0.25] [--min-delta-ms 0.05]

Exit codes: 0 ok, 1 regression(s), 2 unusable input.
"""

from __future__ import annotations

import argparse
import json
import sys

#: lines that are capture metadata, not timed stages
SKIP_STAGES = frozenset({"provenance", "rtt_floor"})


def load_stages(path: str) -> dict[str, dict]:
    """Parse a bench_stages JSONL capture into {stage: record}.

    Malformed lines are ignored (a timeout mid-capture truncates the
    last line by design); an empty result is the caller's error.
    """
    stages: dict[str, dict] = {}
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            stage = rec.get("stage")
            if not isinstance(stage, str) or stage in SKIP_STAGES:
                continue
            stages[stage] = rec
    return stages


def diff_stages(base: dict[str, dict], cand: dict[str, dict],
                tolerance: float,
                min_delta_ms: float) -> tuple[list[dict], list[dict]]:
    """Compare captures; returns (regressions, report_rows).

    Every baseline stage yields one report row with a verdict:
    ``ok`` / ``improved`` / ``regressed`` / ``missing`` / ``errored`` /
    ``skipped`` (baseline itself errored); candidate-only stages get
    ``new``.  Rows are sorted by stage name so the report (and any
    golden-file diff of it) is deterministic.
    """
    regressions: list[dict] = []
    rows: list[dict] = []
    for stage in sorted(base):
        brec = base[stage]
        row: dict = {"stage": stage}
        if "error" in brec or "ms_per_iter" not in brec:
            row["verdict"] = "skipped"
            rows.append(row)
            continue
        base_ms = float(brec["ms_per_iter"])
        row["base_ms"] = base_ms
        crec = cand.get(stage)
        if crec is None:
            row["verdict"] = "missing"
            regressions.append(row)
            rows.append(row)
            continue
        if "error" in crec or "ms_per_iter" not in crec:
            row["verdict"] = "errored"
            row["error"] = str(crec.get("error", "no ms_per_iter"))[:200]
            regressions.append(row)
            rows.append(row)
            continue
        cand_ms = float(crec["ms_per_iter"])
        row["cand_ms"] = cand_ms
        row["ratio"] = round(cand_ms / base_ms, 3) if base_ms > 0 else None
        slow = (cand_ms > base_ms * (1.0 + tolerance)
                and cand_ms - base_ms > min_delta_ms)
        if slow:
            row["verdict"] = "regressed"
            regressions.append(row)
        elif cand_ms < base_ms:
            row["verdict"] = "improved"
        else:
            row["verdict"] = "ok"
        rows.append(row)
    for stage in sorted(set(cand) - set(base)):
        crec = cand[stage]
        row = {"stage": stage, "verdict": "new"}
        if "ms_per_iter" in crec:
            row["cand_ms"] = float(crec["ms_per_iter"])
        rows.append(row)
    return regressions, rows


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="diff two bench_stages JSONL captures; exit 1 on "
                    "regression")
    parser.add_argument("baseline", help="committed baseline capture")
    parser.add_argument("candidate", help="fresh capture to judge")
    parser.add_argument(
        "--tolerance", type=float, default=0.25,
        help="relative slowdown allowed before a stage regresses "
             "(0.25 = 25%%; soak sets this generously because the "
             "committed baseline was captured on different hardware)")
    parser.add_argument(
        "--min-delta-ms", type=float, default=0.05,
        help="absolute slowdown floor: a stage must ALSO be this many "
             "ms/iter slower to regress (keeps sub-0.1ms stages from "
             "flapping on scheduler jitter)")
    args = parser.parse_args(argv)

    try:
        base = load_stages(args.baseline)
        cand = load_stages(args.candidate)
    except OSError as e:
        print(f"bench_diff: cannot read capture: {e}", file=sys.stderr)
        return 2
    if not base:
        print(f"bench_diff: no timed stages in baseline "
              f"{args.baseline}", file=sys.stderr)
        return 2
    if not cand:
        print(f"bench_diff: no timed stages in candidate "
              f"{args.candidate}", file=sys.stderr)
        return 2

    regressions, rows = diff_stages(base, cand, args.tolerance,
                                    args.min_delta_ms)
    for row in rows:
        print(json.dumps(row, sort_keys=True))
    n = len(regressions)
    if n:
        names = ", ".join(r["stage"] for r in regressions)
        print(f"bench_diff: FAIL — {n} stage(s) regressed beyond "
              f"{args.tolerance:.0%} (+{args.min_delta_ms}ms): {names}",
              file=sys.stderr)
        return 1
    print(f"bench_diff: ok — {len(rows)} stage(s) within "
          f"{args.tolerance:.0%}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
