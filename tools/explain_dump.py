#!/usr/bin/env python
"""Pretty-print a pod's placement explanation from a live endpoint.

Fetches ``GET /debug/explain/<pod>`` from a running scheduler binary's
HTTP gateway (or any DebugService-backed server) and renders the
reject-reason breakdown, the candidate score decomposition, and the
trace linkage as an operator-readable block:

    python tools/explain_dump.py --url http://127.0.0.1:10251 --pod my-pod
    python tools/explain_dump.py --url ... --pod my-pod --json   # raw body

Exit codes: 0 = explanation printed, 3 = typed 404 (unknown pod /
reserve-pod), 1 = transport or server error.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.parse
import urllib.request


def render(body: dict) -> str:
    lines = []
    pod = body.get("pod", "?")
    status = body.get("status", "?")
    head = f"pod {pod!r} [{status}"
    if body.get("node"):
        head += f" on {body['node']}"
    head += "]"
    if body.get("trace_id"):
        head += f"  trace={body['trace_id']}"
    lines.append(head)
    exp = body.get("explanation")
    if exp:
        lines.append(f"  round {exp['round']}: {exp['summary']}")
        reasons = sorted(exp.get("reasons", {}).items(),
                         key=lambda kv: (-kv[1], kv[0]))
        total = max(exp.get("total_nodes", 0), 1)
        for name, count in reasons:
            pct = 100.0 * count / total
            lines.append(f"    {name:<22} {count:>8} nodes  ({pct:5.1f}%)")
        if exp.get("quota"):
            lines.append(f"    quota: {exp['quota']}")
        if exp.get("gang"):
            lines.append(f"    gang:  {exp['gang']}")
    elif body.get("explain_enabled") is False:
        lines.append("  (explain accounting disabled: --no-explain)")
    else:
        lines.append("  (no failure explanation recorded)")
    candidates = body.get("candidates")
    if candidates:
        lines.append("  candidates (per-term score decomposition, "
                     "current state):")
        for c in candidates:
            terms = " ".join(f"{t}={v}" for t, v in
                             sorted(c.get("terms", {}).items()))
            winner = " <- winner" if c.get("winner") else ""
            lines.append(f"    {c['node']:<20} total={c['score']:<5} "
                         f"{terms}{winner}")
    elif candidates == []:
        lines.append("  candidates: none feasible right now")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="explain_dump")
    parser.add_argument("--url", required=True,
                        help="base URL of the scheduler's HTTP gateway, "
                             "e.g. http://127.0.0.1:10251")
    parser.add_argument("--pod", required=True)
    parser.add_argument("--json", action="store_true",
                        help="dump the raw endpoint body")
    # the candidate decomposition runs an on-demand (1, N) score pass on
    # a possibly-busy scheduler: leave headroom before declaring it dead
    parser.add_argument("--timeout", type=float, default=30.0)
    args = parser.parse_args(argv)

    url = (args.url.rstrip("/") + "/debug/explain/"
           + urllib.parse.quote(args.pod, safe=""))
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            body = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            doc = json.loads(e.read())
        except (ValueError, OSError):
            doc = {"error": str(e)}
        print(f"{e.code}: {doc.get('error', doc)}", file=sys.stderr)
        return 3 if e.code == 404 else 1
    except (urllib.error.URLError, OSError) as e:
        print(f"unreachable: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body, indent=2, default=str))
    else:
        print(render(body))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
