#!/usr/bin/env python
"""Soak verdict: drive a seeded churn soak, print the per-series trend
table joined to flight records and SLO breaches, fail on a leak.

The steady-state observatory's operator surface (ISSUE 9): replay a
deterministic :mod:`loadgen` trace against the assembled control plane
(scheduler sidecar + manager + feeder over real sockets), sample the
whole run through the shared SLO/trend MetricCache, and turn the run
into ONE verdict document:

- a per-series table — fitted slope, growth, r2, verdict
  (steady/drifting/leaking) for every watched series (RSS, fds,
  threads, alloc blocks, gc, queue depth, deltasync backlog, device
  bytes);
- the SLO join — per-SLO breach counts and peak burn from the same run;
- the flight-record join — for every non-steady series, the slowest
  and any dumped rounds inside the soak window, so "threads are
  leaking" arrives WITH "and round 4812 was the slow degraded one";
- hard bounds — deltasync backlog peak and degraded-mode state.

Exit status: 0 only when the verdict is green (no leaking, no
drifting, no live SLO breach, not degraded, backlog bounded).
``tools/soak.sh`` runs this under ``SOAK_LOADGEN=1`` and fails the
soak tally on a red verdict.

Self-test: ``--inject-leak thread`` (a toy service leaking a parked
thread per cycle) and ``--inject-leak queue`` (completions dropped,
rounds starved) must BOTH turn the verdict red — a leak detector that
never fires on a real leak is a rubber stamp.

    python tools/soak_report.py                       # smoke scale
    python tools/soak_report.py --nodes 10000 --duration 1800 \
        --time-scale 1                                # the real soak
    python tools/soak_report.py --inject-leak thread  # must go red
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

import loadgen  # noqa: E402


def _fmt_rate(doc: dict) -> str:
    rate = doc.get("rate_per_hour")
    if rate is None:
        return "-"
    for unit, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(rate) >= div:
            return f"{rate / div:+.2f}{unit}/h"
    return f"{rate:+.2f}/h"


#: attribution-quality bar (ISSUE 19): a soak whose timeline cannot
#: name this fraction of host wall time is flying on a rotten
#: instrument — the host-wait numbers the turbo work is judged by
#: would be unfalsifiable, so the verdict goes RED
UNATTRIBUTED_RED_FRACTION = 0.05


def host_wait_attribution(cycle_docs: list[dict], top: int = 4) -> dict:
    """Aggregate ``/debug/timeline`` cycle docs into the verdict's
    host-wait section: per-tenant top causes by attributed seconds
    (tenant-tagged segments; the untenanted scheduler's segments land
    under ``-``) and the WALL-WEIGHTED unattributed residual across
    cycles.  Wall-weighted, not a plain mean of per-cycle fractions:
    a degenerate sub-millisecond cycle (an empty round) is ~all
    residual by construction and would swamp a plain mean while
    representing no wall time anyone waits on."""
    per_tenant: dict[str, dict[str, float]] = {}
    resid_s = 0.0
    wall_s = 0.0
    for cyc in cycle_docs:
        wall = float(cyc.get("wall_s", 0.0))
        wall_s += wall
        resid_s += float(cyc.get("unattributed_fraction", 0.0)) * wall
        for seg in cyc.get("segments", []):
            tenant = seg.get("tenant") or "-"
            causes = per_tenant.setdefault(tenant, {})
            dur = float(seg["end"]) - float(seg["start"])
            causes[seg["cause"]] = causes.get(seg["cause"], 0.0) + dur
    mean_resid = (resid_s / wall_s) if wall_s > 0 else 0.0
    return {
        "cycles": len(cycle_docs),
        "tenants": {
            t: [[c, round(s, 6)] for c, s in
                sorted(causes.items(), key=lambda kv: -kv[1])[:top]]
            for t, causes in sorted(per_tenant.items())},
        "unattributed_wall_fraction": round(mean_resid, 6),
        "unattributed_ok": mean_resid <= UNATTRIBUTED_RED_FRACTION,
    }


def attach_host_wait(verdict: dict, timeline_body: dict) -> dict:
    """Fold the host-wait attribution table into the verdict.  An
    armed recorder whose cycles carry a mean unattributed residual
    above the bar flips the verdict RED — the attribution the perf
    work steers by must stay accountable.  A disarmed recorder (kill
    switch) or a run with no reconstructed cycles attaches the empty
    table without judging it."""
    hw = host_wait_attribution(timeline_body.get("cycles", []))
    verdict["host_wait"] = hw
    if (timeline_body.get("enabled") and hw["cycles"]
            and not hw["unattributed_ok"]):
        verdict["green"] = False
        hw["red_reason"] = (
            f"mean unattributed host-wait residual "
            f"{hw['unattributed_wall_fraction']:.3f} > "
            f"{UNATTRIBUTED_RED_FRACTION:.2f}")
    return hw


def attach_journey(verdict: dict) -> dict:
    """Fold the pod-journey ledger's latency table into the verdict —
    the same merge primitive tools/latency_report.py applies to fleet
    JSONL snapshots, run over this process's own sketch rows (ISSUE 20).
    A disabled ledger (kill switch) attaches the empty table without
    judging it; the journey table is evidence, not a gate."""
    import latency_report

    from koordinator_tpu import journey

    rows = (journey.LEDGER.snapshot_doc()["series"]
            if journey.LEDGER.enabled else [])
    table = latency_report.journey_table(rows)
    table["enabled"] = journey.LEDGER.enabled
    verdict["journey"] = table
    return table


def print_report(verdict: dict, harness) -> None:
    trend = verdict["trend"]
    print("== steady-state verdict "
          f"(window {trend['window_s']:.0f}s, "
          f"{verdict['rounds']} rounds, "
          f"{verdict['events_applied']} events, "
          f"{verdict['push_errors']} push errors)")
    print(f"{'series':<44} {'verdict':<9} {'slope':>11} "
          f"{'growth':>12} {'r2':>5} {'n':>5}")
    for doc in trend["series"]:
        labels = ",".join(f"{k}={v}" for k, v in doc["labels"].items())
        name = doc["series"] + (f"{{{labels}}}" if labels else "")
        growth = doc.get("growth")
        print(f"{name:<44} {doc['verdict']:<9} {_fmt_rate(doc):>11} "
              f"{(f'{growth:+.3g}' if growth is not None else '-'):>12} "
              f"{doc.get('r2', 0.0):>5.2f} "
              f"{doc.get('samples', 0):>5}")
    print(f"-- SLO: breached now={verdict['slo_breached'] or 'none'}")
    for name, s in verdict["slo"].items():
        print(f"   {name:<28} breaches={s['breaches_total']} "
              f"peak burn fast={s['peak_burn']['fast']:.2f} "
              f"slow={s['peak_burn']['slow']:.2f}")
    fl = verdict["flight"]
    print(f"-- flight recorder: {fl['records']} records, "
          f"{fl['dumps']} dumps, {fl['overwrites']} overwritten "
          f"(ring {harness.scheduler.flight_recorder.capacity})")
    tenants = verdict.get("tenants")
    if tenants:
        cycle = verdict.get("cycle", {})
        print(f"-- tenants ({len(tenants)}; cycle mode="
              f"{cycle.get('mode', '?')} host-wait="
              f"{cycle.get('host_wait_fraction', 0.0):.3f})")
        print(f"   {'tenant':<8} {'w':>4} {'pending':>8} {'bound':>7} "
              f"{'rounds':>7} {'admitted':>9} {'degraded':>9} "
              f"{'dumps':>6}")
        for name, t in sorted(tenants.items()):
            print(f"   {name:<8} {t['weight']:>4.1f} "
                  f"{t['pending']:>8} {t['bound']:>7} "
                  f"{t['rounds']:>7} {t['admitted_total']:>9} "
                  f"{str(t['degraded']):>9} {t['flight_dumps']:>6}")
    jt = verdict.get("journey")
    if jt and jt["series"]:
        import latency_report

        e2e = [r for r in jt["series"] if r["stage"] == "e2e"]
        print(f"-- pod journey ({len(e2e)} tenant x qos series, "
              f"alpha={jt['alpha']:.0%}; e2e p99 then stage split)")
        latency_report.print_table(jt)
    hw = verdict.get("host_wait")
    if hw and hw["cycles"]:
        print(f"-- host-wait attribution ({hw['cycles']} cycles; "
              f"unattributed wall="
              f"{hw['unattributed_wall_fraction']:.3f} "
              f"bar={UNATTRIBUTED_RED_FRACTION:.2f} "
              f"{'ok' if hw['unattributed_ok'] else 'RED'})")
        for tenant, causes in hw["tenants"].items():
            row = "  ".join(f"{c}={s:.3f}s" for c, s in causes)
            print(f"   {tenant:<8} {row}")
    # the join: every non-steady series arrives WITH the rounds that
    # overlapped it — dumped (slow/degraded/slo) rounds first, else the
    # slowest — so the leak verdict and its "what was happening" flight
    # evidence are one artifact
    flagged = trend["leaking"] + trend["drifting"]
    if flagged:
        rec = harness.scheduler.flight_recorder
        dumped = [r for r in rec.snapshot(8) if r.get("dump_reason")]
        join = dumped or ([rec.slowest()] if rec.slowest() else [])
        print(f"-- flagged series: {flagged}")
        for r in join[:4]:
            print(f"   round {r['round']} trace={r['trace_id'][:12]} "
                  f"dur={r['duration_s']:.3f}s path={r['solve_path']} "
                  f"reason={r.get('dump_reason')} "
                  f"degraded={r['degraded']}")
    print(f"-- backlog peak={verdict['backlog_peak']:.0f} "
          f"degraded={verdict['degraded']} "
          f"pending={verdict['pending']} bound={verdict['bound']}")
    print(f"VERDICT: {'GREEN' if verdict['green'] else 'RED'}")


#: training-record export schema (ISSUE 18 satellite).  Bump when a
#: field changes MEANING; adding optional fields is compatible.  One
#: JSONL line per flight round record:
#:
#:   schema_version  int    — this constant
#:   round           dict   — the RoundRecord doc verbatim (see
#:                            flight_recorder.RoundRecord: solve path,
#:                            phase timings, wall/device split, tenant,
#:                            cycle_seq + the critical-path join)
#:   timeline        dict?  — per-cycle observatory features for the
#:                            cycle the round ran in (null when the
#:                            recorder was off or the cycle aged out of
#:                            the ring): mode, wall_s, attribution
#:                            fractions, unattributed_fraction,
#:                            device_idle_fraction, critical_cause,
#:                            critical_seconds
#:   slo             dict   — the run's SLO burn snapshot keyed by SLO
#:                            name: breaches_total, peak_burn_fast,
#:                            peak_burn_slow (run-level, repeated per
#:                            line so each record is self-contained)
TRAINING_SCHEMA_VERSION = 1


def export_training_records(round_docs: list[dict],
                            cycle_docs: list[dict],
                            slo: dict, path: str) -> int:
    """Join flight records, timeline cycles, and the SLO snapshot into
    the versioned training JSONL (schema above).  Deterministic: same
    inputs yield byte-identical output (sorted keys, stable record
    order is the caller's contract).  Returns lines written."""
    by_cycle = {int(c["cycle"]): c for c in cycle_docs
                if c.get("cycle") is not None}
    slo_snapshot = {
        name: {"breaches_total": s.get("breaches_total", 0),
               "peak_burn_fast": (s.get("peak_burn") or {}).get("fast"),
               "peak_burn_slow": (s.get("peak_burn") or {}).get("slow")}
        for name, s in sorted((slo or {}).items())}
    n = 0
    with open(path, "w", encoding="utf-8") as fh:
        for rec in round_docs:
            cyc = by_cycle.get(rec.get("cycle_seq", -1))
            features = None
            if cyc is not None:
                features = {
                    "mode": cyc.get("mode"),
                    "wall_s": cyc.get("wall_s"),
                    "attribution": cyc.get("attribution"),
                    "unattributed_fraction":
                        cyc.get("unattributed_fraction"),
                    "device_idle_fraction":
                        cyc.get("device_idle_fraction"),
                    "critical_cause": cyc.get("critical_cause"),
                    "critical_seconds": cyc.get("critical_seconds"),
                }
            fh.write(json.dumps(
                {"schema_version": TRAINING_SCHEMA_VERSION,
                 "round": rec, "timeline": features,
                 "slo": slo_snapshot},
                sort_keys=True, default=str) + "\n")
            n += 1
    return n


def gather_training_inputs(harness) -> tuple[list[dict], list[dict]]:
    """Collect (round_docs, cycle_docs) from a finished harness in a
    deterministic order: tenants sorted by name (the untenanted
    scheduler as ""), each ring oldest-first; cycles newest-first from
    the observatory ring."""
    from koordinator_tpu import timeline

    front = getattr(harness, "front", None)
    if front is not None:
        schedulers = sorted(((t.name, t.scheduler)
                             for t in front.tenants()),
                            key=lambda pair: pair[0])
    else:
        schedulers = [("", harness.scheduler)]
    round_docs = []
    for _, sched in schedulers:
        round_docs.extend(
            rec.to_doc() for rec in list(sched.flight_recorder.records))
    cycle_docs = timeline.RECORDER.cycles(limit=1 << 20)
    return round_docs, cycle_docs


def forecast_ab_report(args) -> int:
    """The reactive-vs-predictive A/B scorecard (SOAK_FORECAST=1 /
    --forecast): one seeded diurnal trace through both arms, GREEN only
    when the predictive arm is no worse on breaches AND evictions and
    the proactive path actually ran (a predictive soak that never
    pre-staged a migration proves nothing about rebalance)."""
    from koordinator_tpu.forecast.ab import ABConfig, run_ab

    cfg = ABConfig(seed=args.seed)
    if args.nodes is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, nodes=args.nodes)
    doc = run_ab(cfg)
    print(f"== forecast A/B: seed={doc['seed']} nodes={doc['nodes']} "
          f"ticks={doc['ticks']} period={doc['period_s']:.0f}s "
          f"(one trace, two arms)")
    print(f"-- forecast {'metric':<26} {'reactive':>10} {'predictive':>11}")
    r, p = doc["reactive"], doc["predictive"]
    for key in ("slo_breach_minutes", "reactive_evictions",
                "be_pod_ticks", "prestaged_migrations",
                "migrations_completed"):
        print(f"   {key:<34} {r[key]:>10} {p[key]:>11}")
    err = ", ".join(f"{k}={v}" for k, v in
                    p.get("forecast_error_fraction", {}).items()) or "-"
    print(f"   {'forecast_error_fraction':<34} {'-':>10} {err:>11}")
    print(f"   {'horizon_s':<34} {'-':>10} "
          f"{p.get('horizon_s', 0.0):>11}")
    if args.json:
        print(json.dumps(doc, indent=2, default=str))
    green = doc["predictive_no_worse"] and p["prestaged_migrations"] > 0
    print(f"VERDICT: {'GREEN' if green else 'RED'}"
          + ("" if doc["predictive_no_worse"] else
             " (predictive arm WORSE than reactive)")
          + ("" if p["prestaged_migrations"] > 0 else
             " (zero pre-staged migrations — rebalance never ran)"))
    return 0 if green else 1


def drills_report(args) -> int:
    """The adversarial-drill verdict table (SOAK_DRILLS=1 / --drills):
    every catalog scenario (koordinator_tpu/drills/scenarios.py) runs
    once at the report seed — leader failover, manager restart, rack
    flap storm, quota reorg, tenant sever, warm restart — and the
    per-scenario check + RTO table prints.  GREEN only when every
    scenario's full verdict passed; a RED scenario prints its check
    breakdown and the exact replay handle."""
    import tempfile

    from koordinator_tpu.drills import run_all

    # drills validate at 6x compression (tests/test_drills_e2e.py uses
    # the same); the loadgen --time-scale default is tuned for churn
    # soaks, not for lease/breaker timing, so it is not reused here
    scale = 6.0
    with tempfile.TemporaryDirectory(prefix="koord-drills-") as workdir:
        verdicts = run_all(args.seed, workdir, time_scale=scale)
    print(f"== drills: seed={args.seed} scenarios={len(verdicts)} "
          f"time_scale={scale:g}x")
    print(f"-- drill {'scenario':<21} {'verdict':>7} {'rto_s':>8} "
          f"{'degraded_s':>11}  failed checks")
    all_green = True
    for name, v in verdicts.items():
        all_green = all_green and v.green
        failed = ", ".join(c.name for c in v.failed()) or "-"
        rto = "-" if v.rto_s is None else f"{v.rto_s:.2f}"
        print(f"   {name:<27} {'GREEN' if v.green else 'RED':>7} "
              f"{rto:>8} {v.degraded_s:>11.2f}  {failed}")
    if args.json:
        print(json.dumps({k: v.to_doc() for k, v in verdicts.items()},
                         indent=2, default=str))
    print(f"VERDICT: {'GREEN' if all_green else 'RED'}")
    for name, v in verdicts.items():
        if not v.green:
            print(f"-- {name} RED — replay: python -c \"from "
                  f"koordinator_tpu.drills import run_drill; "
                  f"print(run_drill({name!r}, {args.seed}, "
                  f"'/tmp/drill').render())\"")
            print(v.render())
    return 0 if all_green else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="soak_report")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--duration", type=float, default=None,
                        help="virtual seconds of churn (default: the "
                             "smoke config's 120)")
    parser.add_argument("--nodes", type=int, default=None)
    parser.add_argument("--arrival-rate", type=float, default=None)
    parser.add_argument("--time-scale", type=float, default=12.0,
                        help="virtual:wall compression (1 = real time)")
    parser.add_argument("--tenants", type=int, default=1,
                        help="simulate N clusters on one TenantScheduler "
                             "mesh (one churn process + socket stack per "
                             "tenant; the verdict gains a per-tenant "
                             "section)")
    parser.add_argument("--trace", default="",
                        help="replay this JSONL trace instead of "
                             "generating one from the seed")
    parser.add_argument("--inject-leak", choices=("thread", "queue"),
                        default=None,
                        help="self-test: inject a deliberate leak; the "
                             "verdict MUST come back red (exit flips: 0 "
                             "iff the leak was caught)")
    parser.add_argument("--slo-latency", type=float, default=2.5,
                        help="latency SLO threshold for the run "
                             "(CPU smoke rounds pay jit compilation; "
                             "the paper's bar is 0.2)")
    parser.add_argument("--quality-mode", choices=("off", "lp", "auto"),
                        default="off",
                        help="solve-quality mode for the soaked "
                             "scheduler(s); with a mode other than off "
                             "the report FAILS unless at least one "
                             "round actually solved on the quality "
                             "path (quality_rounds_total > 0) — a "
                             "quality soak that never exercised the "
                             "quality engine proves nothing")
    parser.add_argument("--quality-slack-threshold", type=float,
                        default=0.3,
                        help="auto-mode escalation bar (see the "
                             "scheduler's --quality-slack-threshold)")
    parser.add_argument("--forecast", action="store_true",
                        help="run the reactive-vs-predictive A/B smoke "
                             "instead of the churn soak: both arms "
                             "replay ONE seeded diurnal trace "
                             "(forecast/ab.py), the per-arm scorecard "
                             "prints, and the exit is GREEN only if "
                             "the predictive arm is no worse on "
                             "SLO-breach minutes and reactive "
                             "evictions — and actually pre-staged "
                             "at least one migration")
    parser.add_argument("--drills", action="store_true",
                        help="run the adversarial failure-drill catalog "
                             "instead of the churn soak: every scenario "
                             "(leader failover, manager restart, rack "
                             "storm, quota reorg, tenant sever, warm "
                             "restart) runs once at --seed and the "
                             "per-scenario verdict + RTO table prints; "
                             "exit 0 iff every scenario is GREEN")
    parser.add_argument("--json", action="store_true",
                        help="dump the raw verdict document too")
    parser.add_argument("--export-training-records", metavar="OUT",
                        default="",
                        help="also write the run's per-round training "
                             "records (flight record + per-cycle "
                             "timeline/critical-path features + SLO "
                             "burn snapshot, one JSONL line each; "
                             "schema_version "
                             f"{TRAINING_SCHEMA_VERSION}) to OUT")
    args = parser.parse_args(argv)

    if args.forecast:
        return forecast_ab_report(args)
    if args.drills:
        return drills_report(args)

    cfg = loadgen.smoke_config(seed=args.seed, tenants=args.tenants)
    overrides = {}
    if args.duration is not None:
        overrides["duration_s"] = args.duration
    if args.nodes is not None:
        overrides["nodes"] = args.nodes
    if args.arrival_rate is not None:
        overrides["arrival_rate"] = args.arrival_rate
    if overrides:
        import dataclasses

        cfg = dataclasses.replace(cfg, **overrides)
    events = (loadgen.read_trace(args.trace) if args.trace
              else loadgen.generate_trace(cfg))
    print(f"== churn soak: seed={cfg.seed} nodes={cfg.nodes} "
          f"duration={cfg.duration_s:.0f}s (virtual) "
          f"x{args.time_scale:g} compression — "
          f"{json.dumps(loadgen.trace_stats(events))}")
    with tempfile.TemporaryDirectory(prefix="koord-soak-") as workdir:
        harness = loadgen.SteadyStateHarness(
            cfg, workdir, time_scale=args.time_scale,
            slo_latency_threshold_s=args.slo_latency,
            inject_thread_leak=(args.inject_leak == "thread"),
            inject_queue_leak=(args.inject_leak == "queue"),
            quality_mode=args.quality_mode,
            quality_slack_threshold=args.quality_slack_threshold)
        harness.start()
        try:
            verdict = harness.run(events)
            from koordinator_tpu.scheduler import services as _services

            attach_host_wait(verdict, _services.debug_timeline_body(
                harness.scheduler, {"cycles": 512}))
            attach_journey(verdict)
            print_report(verdict, harness)
            if args.json:
                print(json.dumps(verdict, indent=2, default=str))
            if args.export_training_records:
                rounds, cycles = gather_training_inputs(harness)
                n = export_training_records(
                    rounds, cycles, verdict.get("slo") or {},
                    args.export_training_records)
                print(f"-- training records: {n} written to "
                      f"{args.export_training_records} "
                      f"(schema v{TRAINING_SCHEMA_VERSION})")
        finally:
            harness.close()
    if args.quality_mode != "off":
        from koordinator_tpu import metrics as _m

        quality_rounds = sum(v for _, v in _m.quality_rounds.items())
        print(f"-- quality: mode={args.quality_mode} "
              f"rounds={quality_rounds:g}")
        if quality_rounds <= 0:
            print("ERROR: quality soak ran zero quality rounds "
                  "(quality_rounds_total == 0)", file=sys.stderr)
            return 1
    if args.inject_leak:
        if verdict["trend"]["leaking"]:
            print(f"injected {args.inject_leak} leak CAUGHT: "
                  f"{verdict['trend']['leaking']}")
            return 0
        print(f"ERROR: injected {args.inject_leak} leak NOT caught",
              file=sys.stderr)
        return 1
    return 0 if verdict["green"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
