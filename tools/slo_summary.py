#!/usr/bin/env python
"""SLO-surface smoke: drive a fresh scheduler, scrape /debug/slo live.

Assembles the scheduler binary (HTTP gateway + SLO burn-rate engine),
runs a short synthetic workload — optionally with fault-injected slow
solves so the breach machinery demonstrably fires — then fetches
``GET /debug/slo`` over the gateway exactly as an operator would and
prints one line per SLO: worst burn rate per window and breach count.

The numbers describe THIS driver's synthetic run, not any other
process: the soak's pytest windows run in their own interpreters, so
this is the end-of-soak check that the whole SLO surface (sampling,
burn windows, gateway serving) is alive and readable, printed by
tools/soak.sh alongside the slowest-round flight record (SOAK_SLO=0
disables).  Also useful standalone:

    python tools/slo_summary.py --rounds 40
    python tools/slo_summary.py --slow-solves   # force a breach
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="slo_summary")
    parser.add_argument("--rounds", type=int, default=30)
    parser.add_argument("--pods-per-round", type=int, default=4)
    parser.add_argument(
        "--slow-solves", action="store_true",
        help="inject 50ms solve delays against a 20ms latency SLO so "
             "the fast-burn breach path demonstrably fires")
    parser.add_argument("--json", action="store_true",
                        help="dump the raw /debug/slo body instead of "
                             "the per-SLO summary lines")
    args = parser.parse_args(argv)

    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.cmd.binaries import main_koord_scheduler
    from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec
    from koordinator_tpu.transport.faults import FaultConfig, FaultInjector

    flags = ["--disable-leader-election", "--http-port", "0",
             "--slo-sample-interval-seconds", "0"]
    if args.slow_solves:
        flags += ["--slo-latency-threshold-seconds", "0.02"]
    asm = main_koord_scheduler(flags)
    sched = asm.component
    try:
        if args.slow_solves:
            sched.faults = FaultInjector(seed=1, config=FaultConfig(
                solve_delay_p=1.0, solve_delay_ms=50.0))
            # fire on the first hot evaluation instead of 14.4x budget
            # (the summary run is seconds, not minutes)
            import dataclasses

            from koordinator_tpu.slo_monitor import BurnWindow

            sched.slo_monitor.specs = [
                dataclasses.replace(s, fast=BurnWindow(
                    window_s=s.fast.window_s, fire_burn=1.0))
                for s in sched.slo_monitor.specs]
        sched.snapshot.upsert_node(NodeSpec(
            name="slo-n0",
            allocatable=resource_vector(cpu=1_000_000, memory=1_000_000)))
        seq = 0
        for _ in range(args.rounds):
            for _ in range(args.pods_per_round):
                sched.enqueue(PodSpec(
                    name=f"slo-p{seq}",
                    requests=resource_vector(cpu=100, memory=64)))
                seq += 1
            sched.schedule_round()
            sched.slo_monitor.tick()

        url = f"http://127.0.0.1:{asm.gateway.port}/debug/slo"
        with urllib.request.urlopen(url, timeout=10) as resp:
            body = json.loads(resp.read())
        if args.json:
            print(json.dumps(body, indent=2, default=str))
            return 0
        print("== SLO summary (/debug/slo, fresh synthetic drive — "
              "not a readback of the soak windows)")
        worst_breaches = 0
        for slo in body["slos"]:
            peak = slo["peak_burn"]
            state = "BREACHED" if slo["breached"] else "ok"
            print(f"  {slo['name']:<28} {state:<9} "
                  f"worst burn fast={peak['fast']:.2f} "
                  f"slow={peak['slow']:.2f} "
                  f"breaches={slo['breaches_total']}")
            worst_breaches += slo["breaches_total"]
        if args.slow_solves and worst_breaches == 0:
            print("ERROR: slow solves injected but no SLO breach fired",
                  file=sys.stderr)
            return 1
        return 0
    finally:
        asm.stop()


if __name__ == "__main__":
    raise SystemExit(main())
