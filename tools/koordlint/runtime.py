"""Runtime validation of the static lock-order graph (debug-mode).

The lock-discipline analyzer builds its acquisition-order graph from
``with`` scopes it can resolve statically; this module closes the loop
at RUNTIME: :func:`instrument_locks` swaps an object's lock attributes
for recording wrappers that log every cross-lock acquisition edge a
real thread actually takes, and the concurrency-stress suite
(tests/test_concurrency_stress.py) asserts the OBSERVED edges merged
with the STATIC graph stay acyclic — so a lock order the analyzer
missed (dynamic dispatch, callbacks) still cannot silently invert an
edge the analyzer recorded.

Dependency-free, stdlib-only, and cheap enough to wrap hot locks inside
a test; never imported by production code paths.
"""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass(frozen=True)
class ObservedEdge:
    src: str
    dst: str
    thread: str


class LockOrderRecorder:
    """Per-thread held-lock stacks + the cross-lock edges taken."""

    def __init__(self):
        self._local = threading.local()
        self._mu = threading.Lock()
        self.edges: set[ObservedEdge] = set()
        self.acquisitions = 0

    def _held(self) -> list[str]:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        return held

    def on_acquire(self, name: str) -> None:
        held = self._held()
        new_edges = {ObservedEdge(h, name, threading.current_thread().name)
                     for h in held if h != name}
        held.append(name)
        with self._mu:
            self.acquisitions += 1
            self.edges |= new_edges

    def on_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def edge_pairs(self) -> set[tuple[str, str]]:
        return {(e.src, e.dst) for e in self.edges}


class InstrumentedLock:
    """A Lock/RLock/Condition wrapper that records acquisition order.
    Context-manager and acquire/release protocols both delegate."""

    def __init__(self, inner, name: str, recorder: LockOrderRecorder):
        self._inner = inner
        self._name = name
        self._recorder = recorder

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._recorder.on_acquire(self._name)
        return got

    def release(self):
        self._recorder.on_release(self._name)
        return self._inner.release()

    def __enter__(self):
        self._inner.__enter__()
        self._recorder.on_acquire(self._name)
        return self

    def __exit__(self, *exc):
        self._recorder.on_release(self._name)
        return self._inner.__exit__(*exc)

    def __getattr__(self, item):  # Condition.wait/notify etc.
        return getattr(self._inner, item)


def instrument_locks(obj, recorder: LockOrderRecorder,
                     cls_name: str | None = None) -> list[str]:
    """Swap every lock-like attribute of ``obj`` (has acquire+release
    and a context-manager protocol) for an :class:`InstrumentedLock`
    named ``module.Class.attr`` — the SAME node ids the static analyzer
    uses, so observed and static graphs merge directly.  Returns the
    names instrumented."""
    cls = cls_name or f"{type(obj).__module__}.{type(obj).__name__}"
    names = []
    for attr, value in list(vars(obj).items()):
        if isinstance(value, InstrumentedLock):
            continue
        if (callable(getattr(value, "acquire", None))
                and callable(getattr(value, "release", None))
                and hasattr(value, "__enter__")):
            name = f"{cls}.{attr}"
            setattr(obj, attr, InstrumentedLock(value, name, recorder))
            names.append(name)
    return names


def find_cycle(edges: set[tuple[str, str]]) -> list[str] | None:
    """One cycle in the directed graph (as a node list), or None."""
    adj: dict[str, list[str]] = {}
    for src, dst in sorted(edges):
        adj.setdefault(src, []).append(dst)
        adj.setdefault(dst, [])
    WHITE, GREY, BLACK = 0, 1, 2
    color = {v: WHITE for v in adj}
    stack: list[str] = []

    def dfs(v: str) -> list[str] | None:
        color[v] = GREY
        stack.append(v)
        for w in adj[v]:
            if color[w] == GREY:
                return stack[stack.index(w):] + [w]
            if color[w] == WHITE:
                cyc = dfs(w)
                if cyc is not None:
                    return cyc
        stack.pop()
        color[v] = BLACK
        return None

    for v in sorted(adj):
        if color[v] == WHITE:
            cyc = dfs(v)
            if cyc is not None:
                return cyc
    return None


def static_lock_edges(root: str) -> set[tuple[str, str]]:
    """(src, dst) pairs of the lock-discipline analyzer's static graph
    over the real tree — RLock self-edges excluded, same as the
    analyzer's cycle check."""
    from .analyzers.lock_discipline import LockDisciplineAnalyzer
    from .callgraph import ModuleIndex
    from .core import Project

    analyzer = LockDisciplineAnalyzer()
    index = ModuleIndex(Project(root), package=analyzer.package)
    models = analyzer.build_models(index)
    graph = analyzer.build_graph(index, models)
    return {(e.src, e.dst) for e in graph.edges if e.src != e.dst}
