"""koordlint core: the shared analyzer API.

The tree's worst historical bugs were *invariant* violations no generic
linter sees — the ``ClusterState.zeros`` donation-aliasing bug (PR 1),
the Auditor exists-then-open race (PR 1), the DebugService/HTTP-gateway
route drift PR 6 had to audit by hand.  koordlint makes those invariants
mechanical: a dependency-free, stdlib-``ast`` framework with

- a :class:`Project` file walker + parse cache over the repo,
- a :class:`Finding` model (file:line + rule id + fix hint),
- inline suppressions (``# koordlint: ignore[rule] -- reason``) and a
  baseline file (``tools/koordlint/baseline.json``) where EVERY
  suppression carries a written reason — a reasonless suppression is
  itself a finding,
- intent annotations (``# koordlint: guarded-by(self._lock)``) analyzers
  consume (see analyzers/lock_discipline.py).

Analyzers subclass :class:`Analyzer` and register in
``analyzers/__init__.py``; ``python -m tools.koordlint`` runs them all
and exits non-zero on any unsuppressed finding (wired at the head of
tools/soak.sh and into tier-1 via tests/test_koordlint.py).
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
from typing import Iterable, Optional

#: inline directives.  ``ignore`` silences named rules on that line (the
#: reason after ``--`` is mandatory); ``guarded-by`` declares locking
#: intent (an attribute write, or a whole function when placed on its
#: ``def`` line, is protected by the named lock); ``shape`` seeds the
#: specflow abstract interpreter with a parameter/return contract
#: (``# koordlint: shape[score: Pxk i32 -1..32767]`` — see
#: tools/koordlint/specflow/engine.py and docs/static_analysis.md).
_DIRECTIVE_RE = re.compile(r"#\s*koordlint:\s*"
                           r"(?P<kind>ignore|guarded-by|shape)"
                           r"\s*[\[(](?P<body>[^\])]*)[\])]"
                           r"(?:\s*--\s*(?P<reason>.*\S))?")


@dataclasses.dataclass
class Finding:
    """One analyzer hit: a rule violation at file:line with a fix hint."""

    rule: str
    path: str      # repo-relative, forward slashes
    line: int
    message: str
    hint: str = ""

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Directive:
    """A parsed ``# koordlint:`` comment on one source line."""

    kind: str      # "ignore" | "guarded-by"
    body: str      # rule list / lock expression
    reason: str    # text after " -- " (ignore only; may be empty = bad)
    line: int

    @property
    def rules(self) -> set[str]:
        return {r.strip() for r in self.body.split(",") if r.strip()}


class SourceFile:
    """One parsed source file: text, AST, and inline directives."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.path = relpath.replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text, filename=relpath)
        except SyntaxError as e:  # surfaced as a finding by Runner
            self.parse_error = f"{e.msg} (line {e.lineno})"
        #: line -> Directive (one koordlint directive per line)
        self.directives: dict[int, Directive] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _DIRECTIVE_RE.search(line)
            if m:
                self.directives[i] = Directive(
                    kind=m.group("kind"), body=m.group("body").strip(),
                    reason=(m.group("reason") or "").strip(), line=i)

    def directive_at(self, line: int, kind: str) -> Optional[Directive]:
        """The directive covering ``line``: on the line itself, or in
        the contiguous block of standalone comment lines directly above
        (so a ``guarded-by`` and a ``shape`` directive can stack on one
        ``def``)."""
        d = self.directives.get(line)
        if d is not None and d.kind == kind:
            return d
        prev = line - 1
        while (1 <= prev <= len(self.lines)
               and self.lines[prev - 1].lstrip().startswith("#")):
            d = self.directives.get(prev)
            if d is not None and d.kind == kind:
                return d
            prev -= 1
        return None


class Project:
    """The repo as a set of parsed files (walked once, shared by every
    analyzer so the whole suite stays one parse pass over the tree)."""

    #: directories never walked (caches, VCS, the seeded-bad corpora)
    EXCLUDE_DIRS = {"__pycache__", ".git", "fixtures", "soak_results",
                    "node_modules", ".claude"}
    #: the file sets analyzers care about, relative to the repo root
    DEFAULT_TARGETS = ("koordinator_tpu", "tests", "tools")

    def __init__(self, root: str, targets: Iterable[str] | None = None):
        self.root = os.path.abspath(root)
        self.files: dict[str, SourceFile] = {}
        for target in targets if targets is not None else self.DEFAULT_TARGETS:
            top = os.path.join(self.root, target)
            if os.path.isfile(top) and top.endswith(".py"):
                self._add(top)
                continue
            for dirpath, dirnames, filenames in os.walk(top):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in self.EXCLUDE_DIRS)
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        self._add(os.path.join(dirpath, name))

    def _add(self, abspath: str) -> None:
        rel = os.path.relpath(abspath, self.root)
        self.files[rel.replace(os.sep, "/")] = SourceFile(abspath, rel)

    def glob(self, pattern: str) -> list[SourceFile]:
        return [sf for path, sf in sorted(self.files.items())
                if fnmatch.fnmatch(path, pattern)]

    def get(self, path: str) -> Optional[SourceFile]:
        return self.files.get(path)


class Analyzer:
    """Base analyzer: subclasses set ``name``/``hint_url`` and implement
    :meth:`run` returning findings (pre-suppression; the Runner applies
    inline ignores and the baseline uniformly)."""

    name = "base"
    description = ""

    def run(self, project: Project) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


# -- suppression machinery ----------------------------------------------------


@dataclasses.dataclass
class BaselineEntry:
    """One baseline suppression: rule + path glob (+ optional message
    substring) + a MANDATORY reason.  Line numbers are deliberately not
    part of the match — they drift with every edit and a stale baseline
    that silently stops matching is worse than a slightly wide one."""

    rule: str
    path: str
    reason: str
    contains: str = ""
    matched: int = 0

    def matches(self, f: Finding) -> bool:
        return (f.rule == self.rule
                and fnmatch.fnmatch(f.path, self.path)
                and (self.contains in f.message if self.contains else True))


def load_baseline(path: str) -> tuple[list[BaselineEntry], list[Finding]]:
    """(entries, hygiene-findings).  Every entry must carry a non-empty
    reason; a reasonless entry is a lint-hygiene finding against the
    baseline file itself, so the policy enforces itself."""
    entries: list[BaselineEntry] = []
    problems: list[Finding] = []
    if not os.path.exists(path):
        return entries, problems
    rel = os.path.basename(path)
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except ValueError as e:
        return entries, [Finding("lint-hygiene", rel, 1,
                                 f"baseline is not valid JSON: {e}",
                                 "fix tools/koordlint/baseline.json")]
    for i, raw in enumerate(doc.get("suppressions", [])):
        reason = str(raw.get("reason", "")).strip()
        if not reason:
            problems.append(Finding(
                "lint-hygiene", rel, 1,
                f"baseline suppression #{i} ({raw.get('rule')!r} on "
                f"{raw.get('path')!r}) has no reason",
                "every suppression must say WHY it is safe"))
            continue
        entries.append(BaselineEntry(
            rule=str(raw.get("rule", "")), path=str(raw.get("path", "")),
            reason=reason, contains=str(raw.get("contains", ""))))
    return entries, problems


@dataclasses.dataclass
class RunResult:
    findings: list[Finding]            # unsuppressed — these fail the run
    suppressed: list[tuple[Finding, str]]  # (finding, reason)
    stale_baseline: list[BaselineEntry]    # entries that matched nothing

    @property
    def ok(self) -> bool:
        return not self.findings


def apply_suppressions(project: Project, findings: list[Finding],
                       baseline: list[BaselineEntry]) -> RunResult:
    """Partition findings into live vs suppressed.

    Inline ``# koordlint: ignore[rule] -- reason`` wins on the flagged
    line (or a standalone comment directly above it); a reasonless
    inline ignore does NOT suppress and instead raises a lint-hygiene
    finding of its own.  The baseline catches the rest.
    """
    live: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    hygiene: list[Finding] = []
    seen_bad_ignores: set[tuple[str, int]] = set()
    for f in findings:
        sf = project.get(f.path)
        d = sf.directive_at(f.line, "ignore") if sf else None
        if d is not None and (f.rule in d.rules or "all" in d.rules):
            if d.reason:
                suppressed.append((f, d.reason))
                continue
            if (f.path, d.line) not in seen_bad_ignores:
                seen_bad_ignores.add((f.path, d.line))
                hygiene.append(Finding(
                    "lint-hygiene", f.path, d.line,
                    "inline ignore without a reason",
                    "write `# koordlint: ignore[rule] -- why it is safe`"))
        for entry in baseline:
            if entry.matches(f):
                entry.matched += 1
                suppressed.append((f, entry.reason))
                break
        else:
            live.append(f)
    stale = [e for e in baseline if e.matched == 0]
    return RunResult(live + hygiene, suppressed, stale)
