"""The specflow interpreter: expression/flow evaluation over stdlib ast.

Three layers, each consumed by one or more analyzers:

- **Module constants + annotations.**  :func:`module_consts` evaluates
  simple module-level integer assignments in order (``_TB_BITS = 15``,
  ``_SCORE_CLIP = (1 << 30 - _TB_BITS) - 1``) so downstream intervals
  are exact.  :func:`parse_shape_body` parses the ``# koordlint:
  shape[...]`` annotation — the seed contract for parameters and
  returns where inference cannot see a bound (annotation syntax in
  docs/static_analysis.md):

      # koordlint: shape[score: Pxk i32 -1..32767, ret0: PxN i32 0..100]

  Entries are comma-separated ``name: dims dtype lo..hi layout``; every
  field after the name is optional.  ``retN`` names the N-th returned
  value.  A layout token is ``rep`` or a mesh-axis name.

- **The interval interpreter.**  :class:`FlowInterpreter` executes one
  function body abstractly, in source order: assignments update an
  environment of :class:`~.domain.Interval`s, ``if``/ternary guards
  refine (``_packed_regime(n)`` ⇒ ``n ∈ [1, 2**15]``;
  ``check_node_capacity(n)`` ⇒ ``n ∈ [1, 2**30]``; integer comparisons
  clamp), loops run once with their targets widened to ⊤, and small
  same-package helpers are inlined depth-limited so ``_candidate_tb``'s
  ``% n_total`` bound is visible to its caller.  Analyzer hooks fire at
  every ``<<`` (overflow obligation) and every ``(a << C) | b`` (field-
  width obligation); returns are checked against declared ``retN``
  contracts.

- **SPMD site modelling.**  :func:`extract_spmd_sites` parses every
  ``shard_map``/``pjit`` call into a :class:`SpmdSite` with resolved
  per-position layouts (``P()``/``P("nodes")`` literals, seen through
  module-level spec constants like ``_NODES = P(NODES_AXIS)`` and
  cross-module string constants), the resolved body function (through
  ``functools.partial``), and the live mesh-axis universe.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Optional

from ..callgraph import FunctionInfo, ModuleIndex
from ..core import SourceFile
from .domain import (
    REPLICATED,
    TOP,
    UNKNOWN,
    Interval,
    Layout,
    const,
    sharded,
)

#: statements above this are never inlined (keeps inlining a tool for
#: leaf helpers like _candidate_tb, not a general interpreter)
MAX_INLINE_STMTS = 8
MAX_INLINE_DEPTH = 2

_DTYPES = {"i8", "i16", "i32", "i64", "u8", "u16", "u32", "u64",
           "f16", "bf16", "f32", "f64", "bool", "int", "float"}

#: guard functions the interpreter understands: calling one (as a
#: statement or a branch test) bounds its first argument by the named
#: module constant (with a fallback when the constant is not in scope)
DEFAULT_GUARDS = {
    "_packed_regime": ("PACKED_NODE_CAPACITY", 1 << 15),
    "check_node_capacity": ("MAX_NODE_CAPACITY", 1 << 30),
    "check_shardable": ("MAX_NODE_CAPACITY", 1 << 30),
}


def key_of(node: ast.AST) -> str:
    """Stable structural key for refinement bookkeeping."""
    return ast.dump(node, annotate_fields=False)


# -- shape annotations --------------------------------------------------------


@dataclasses.dataclass
class ShapeSeed:
    """One annotated binding: any subset of dims / dtype / range / layout."""

    dims: Optional[tuple[str, ...]] = None
    dtype: Optional[str] = None
    interval: Optional[Interval] = None
    layout: Optional[Layout] = None


def _parse_range(tok: str) -> Optional[Interval]:
    lo_s, _, hi_s = tok.partition("..")
    try:
        return Interval(int(lo_s), int(hi_s))
    except ValueError:
        return None


def parse_shape_body(body: str) -> dict[str, ShapeSeed]:
    """``score: Pxk i32 -1..32767, ret0: PxN i32 0..100 nodes`` ->
    seeds.  Unparseable entries are skipped (annotations are best-effort
    hints, never load-bearing for soundness)."""
    out: dict[str, ShapeSeed] = {}
    for entry in body.split(","):
        name, colon, rest = entry.partition(":")
        name = name.strip()
        if not colon or not name:
            continue
        seed = ShapeSeed()
        for i, tok in enumerate(rest.split()):
            if ".." in tok and seed.interval is None:
                seed.interval = _parse_range(tok)
            elif tok in _DTYPES and seed.dtype is None:
                seed.dtype = tok
            elif tok == "rep" and seed.layout is None:
                seed.layout = REPLICATED
            elif i == 0 and seed.dims is None:
                # dims are positional (first token only), so an entry
                # that omits them ("x: i32 nodes") still seeds a layout
                seed.dims = tuple(tok.split("x"))
            elif seed.layout is None:
                seed.layout = sharded((tok,))
        out[name] = seed
    return out


def shape_seeds_for(sf: SourceFile, node: ast.AST) -> dict[str, ShapeSeed]:
    """Seeds from the ``shape`` directive on (or directly above) a
    ``def`` line — or any other anchored line, e.g. a jit binding."""
    d = sf.directive_at(getattr(node, "lineno", 0), "shape")
    return parse_shape_body(d.body) if d is not None else {}


# -- module constants ---------------------------------------------------------


def module_consts(index: ModuleIndex, mod: str) -> dict[str, Interval]:
    """Exact intervals for simple module-level integer assignments,
    evaluated in order so constants may reference earlier ones."""
    sf = index.modules.get(mod)
    if sf is None or sf.tree is None:
        return {}
    cache = getattr(index, "_specflow_consts", None)
    if cache is None:
        cache = index._specflow_consts = {}
    if mod in cache:
        return cache[mod]
    consts: dict[str, Interval] = {}
    interp = FlowInterpreter(index, mod, consts)
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            iv = interp.eval(node.value, {}, {})
            if isinstance(iv, Interval) and iv.lo is not None \
                    and iv.lo == iv.hi:
                consts[node.targets[0].id] = iv
    cache[mod] = consts
    return consts


def module_str_consts(index: ModuleIndex) -> dict[str, str]:
    """``fq name -> str value`` for module-level string assignments
    across the whole package (``NODES_AXIS = "nodes"``)."""
    cache = getattr(index, "_specflow_strs", None)
    if cache is not None:
        return cache
    out: dict[str, str] = {}
    for mod, sf in index.modules.items():
        if sf.tree is None:
            continue
        for node in sf.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                out[f"{mod}.{node.targets[0].id}"] = node.value.value
    index._specflow_strs = out
    return out


# -- the interval interpreter -------------------------------------------------


class FlowInterpreter:
    """Abstract execution of one function body over the interval domain.

    ``on_lshift(node, operand, shift, refinements)`` and
    ``on_packed_or(node, width, field, refinements)`` are the analyzer
    hooks; ``returns`` collects (Return node, value, refinements) for
    contract checking.  The interpreter is flow-sensitive but
    path-insensitive beyond one level of branch refinement — exactly
    enough for the guarded packed/wide regime split.
    """

    def __init__(self, index: ModuleIndex, mod: str,
                 consts: dict[str, Interval],
                 guards: dict | None = None,
                 on_lshift: Optional[Callable] = None,
                 on_packed_or: Optional[Callable] = None,
                 depth: int = 0):
        self.index = index
        self.mod = mod
        self.consts = consts
        self.guards = DEFAULT_GUARDS if guards is None else guards
        self.on_lshift = on_lshift
        self.on_packed_or = on_packed_or
        self.depth = depth
        self.returns: list[tuple[ast.Return, object, dict]] = []

    # -- function entry -------------------------------------------------------

    def run(self, fn: FunctionInfo,
            seeds: dict[str, ShapeSeed] | None = None,
            arg_ivs: dict[str, Interval] | None = None) -> None:
        """Execute ``fn``'s body with parameters seeded from annotations
        (and, when inlining, from caller argument intervals)."""
        env: dict[str, object] = {}
        seeds = seeds if seeds is not None else shape_seeds_for(fn.sf,
                                                                fn.node)
        args = fn.node.args
        for a in list(getattr(args, "posonlyargs", [])) + list(args.args) \
                + list(args.kwonlyargs):
            iv = TOP
            seed = seeds.get(a.arg)
            if seed is not None and seed.interval is not None:
                iv = seed.interval
            if arg_ivs and a.arg in arg_ivs:
                got = arg_ivs[a.arg]
                if got.lo is not None or got.hi is not None:
                    iv = got
            env[a.arg] = iv
        self._block(fn.node.body, env, {})

    # -- statements -----------------------------------------------------------

    def _block(self, stmts: list[ast.stmt], env: dict,
               refin: dict) -> None:
        for node in stmts:
            self._stmt(node, env, refin)

    def _stmt(self, node: ast.stmt, env: dict, refin: dict) -> None:
        if isinstance(node, ast.Assign):
            val = self.eval(node.value, env, refin)
            for t in node.targets:
                self._bind(t, val, env)
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name):
                env[node.target.id] = TOP
            self.eval(node.value, env, refin)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None and isinstance(node.target, ast.Name):
                self._bind(node.target,
                           self.eval(node.value, env, refin), env)
        elif isinstance(node, ast.Return):
            val = (self.eval(node.value, env, refin)
                   if node.value is not None else None)
            self.returns.append((node, val, dict(refin)))
        elif isinstance(node, ast.Expr):
            # a bare guard call refines from here on (check_node_capacity)
            self._refine_from_call(node.value, env, refin)
            self.eval(node.value, env, refin)
        elif isinstance(node, ast.If):
            r_true = dict(refin)
            env_true = dict(env)
            self._refine_test(node.test, env_true, r_true)
            self._block(node.body, env_true, r_true)
            env_false = dict(env)
            self._block(node.orelse, env_false, dict(refin))
            self._merge(env, env_true, env_false)
        elif isinstance(node, (ast.For, ast.While)):
            # loop bodies run once with their targets widened: enough to
            # fire the hooks inside, sound because nothing narrows
            if isinstance(node, ast.For):
                self._bind(node.target, TOP, env)
                self.eval(node.iter, env, refin)
            for name in self._assigned_names(node.body):
                env[name] = TOP
            self._block(node.body, env, dict(refin))
            self._block(node.orelse, env, dict(refin))
        elif isinstance(node, (ast.With,)):
            self._block(node.body, env, refin)
        elif isinstance(node, ast.Try):
            self._block(node.body, env, dict(refin))
            for h in node.handlers:
                self._block(h.body, dict(env), dict(refin))
            self._block(node.orelse, env, dict(refin))
            self._block(node.finalbody, env, dict(refin))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs execute with an unknown environment of their
            # own — walked so their shift sites still meet the hooks
            sub_env: dict[str, object] = {}
            self._block(node.body, sub_env, {})
        # everything else (pass, raise, import, global, ...) is inert

    def _bind(self, target: ast.expr, val: object, env: dict) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = val if isinstance(val, Interval) else (
                val if isinstance(val, tuple) else TOP)
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if isinstance(val, tuple) and len(val) == len(elts):
                for t, v in zip(elts, val):
                    self._bind(t, v, env)
            else:
                for t in elts:
                    self._bind(t, TOP, env)
        # attribute/subscript stores don't feed the interval env

    def _assigned_names(self, stmts: list[ast.stmt]) -> set[str]:
        out: set[str] = set()
        for s in stmts:
            for node in ast.walk(s):
                if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Store):
                    out.add(node.id)
        return out

    def _merge(self, env: dict, a: dict, b: dict) -> None:
        for name in set(a) | set(b):
            va = a.get(name, env.get(name, TOP))
            vb = b.get(name, env.get(name, TOP))
            if isinstance(va, Interval) and isinstance(vb, Interval):
                env[name] = va.join(vb)
            elif (isinstance(va, tuple) and isinstance(vb, tuple)
                    and len(va) == len(vb)):
                env[name] = tuple(
                    x.join(y) if isinstance(x, Interval)
                    and isinstance(y, Interval) else TOP
                    for x, y in zip(va, vb))
            else:
                env[name] = TOP

    # -- guard refinement -----------------------------------------------------

    def _guard_bound(self, name: str) -> Optional[int]:
        spec = self.guards.get(name)
        if spec is None:
            return None
        const_name, fallback = spec
        iv = self.consts.get(const_name)
        return iv.hi if iv is not None and iv.hi is not None else fallback

    def _refine_from_call(self, node: ast.expr, env: dict,
                          refin: dict) -> None:
        if not isinstance(node, ast.Call) or not node.args:
            return
        tail = _tail(node.func)
        bound = self._guard_bound(tail) if tail else None
        if bound is None:
            return
        arg = node.args[0]
        refin[key_of(arg)] = Interval(1, bound)
        if isinstance(arg, ast.Name):
            cur = env.get(arg.id, TOP)
            if isinstance(cur, Interval):
                env[arg.id] = cur.clamp_min(1).clamp_max(bound)

    def _refine_test(self, test: ast.expr, env: dict,
                     refin: dict) -> None:
        """True-branch refinement only (the else branch keeps the base
        facts — sound, just less precise)."""
        self._refine_from_call(test, env, refin)
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            for v in test.values:
                self._refine_test(v, env, refin)
        if (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.left, ast.Name)):
            rhs = self.eval(test.comparators[0], env, refin)
            if not isinstance(rhs, Interval):
                return
            cur = env.get(test.left.id, TOP)
            if not isinstance(cur, Interval):
                return
            op = test.ops[0]
            # refinements store the interval OF the named expression;
            # hi_under() derives a bounded_by value's bound as hi - 1
            if isinstance(op, ast.LtE) and rhs.hi is not None:
                cur = cur.clamp_max(rhs.hi)
                refin[key_of(test.left)] = Interval(None, rhs.hi)
            elif isinstance(op, ast.Lt) and rhs.hi is not None:
                cur = cur.clamp_max(rhs.hi - 1)
                refin[key_of(test.left)] = Interval(None, rhs.hi - 1)
            elif isinstance(op, ast.GtE) and rhs.lo is not None:
                cur = cur.clamp_min(rhs.lo)
            elif isinstance(op, ast.Gt) and rhs.lo is not None:
                cur = cur.clamp_min(rhs.lo + 1)
            env[test.left.id] = cur

    # -- expressions ----------------------------------------------------------

    def _eff(self, iv: Interval, refin: dict) -> Interval:
        """The interval with bounded_by provenance resolved under the
        current refinements — what arithmetic that cannot carry the
        provenance should consume."""
        return Interval(iv.lo_under(refin), iv.hi_under(refin))

    def eval(self, node: ast.expr, env: dict, refin: dict) -> object:
        """Interval (or tuple of) for an expression; TOP when unknown."""
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return Interval(0, 1)
            if isinstance(node.value, int):
                return const(node.value)
            return TOP
        if isinstance(node, ast.Name):
            got = env.get(node.id)
            if got is not None:
                return got
            return self.consts.get(node.id, TOP)
        if isinstance(node, ast.Tuple):
            return tuple(self.eval(e, env, refin) for e in node.elts)
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand, env, refin)
            if isinstance(v, Interval) and isinstance(node.op, ast.USub):
                return v.neg()
            return TOP
        if isinstance(node, ast.IfExp):
            r_true = dict(refin)
            env_true = dict(env)
            self._refine_test(node.test, env_true, r_true)
            a = self.eval(node.body, env_true, r_true)
            b = self.eval(node.orelse, env, refin)
            if isinstance(a, Interval) and isinstance(b, Interval):
                return self._eff(a, r_true).join(self._eff(b, refin))
            if (isinstance(a, tuple) and isinstance(b, tuple)
                    and len(a) == len(b)):
                return tuple(
                    x.join(y) if isinstance(x, Interval)
                    and isinstance(y, Interval) else TOP
                    for x, y in zip(a, b))
            return TOP
        if isinstance(node, ast.BinOp):
            return self._eval_binop(node, env, refin)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, refin)
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value, env, refin)
            if isinstance(base, tuple):
                if (isinstance(node.slice, ast.Constant)
                        and isinstance(node.slice.value, int)
                        and -len(base) <= node.slice.value < len(base)):
                    return base[node.slice.value]
                return TOP
            # indexing/slicing an array keeps its element range
            return base if isinstance(base, Interval) else TOP
        if isinstance(node, ast.Compare):
            return Interval(0, 1)
        if isinstance(node, ast.Attribute):
            return TOP
        return TOP

    def _eval_binop(self, node: ast.BinOp, env: dict,
                    refin: dict) -> Interval:
        a = self.eval(node.left, env, refin)
        b = self.eval(node.right, env, refin)
        a = a if isinstance(a, Interval) else TOP
        b = b if isinstance(b, Interval) else TOP
        op = node.op
        if isinstance(op, ast.Add):
            return a.add(b)
        if isinstance(op, ast.Sub):
            # the rotation idiom `(n - 1) - (e % n)` stays in [0, n-1]
            # and KEEPS the `% n` provenance for later guard refinement
            left = node.left
            if (isinstance(left, ast.BinOp) and isinstance(left.op, ast.Sub)
                    and isinstance(left.right, ast.Constant)
                    and left.right.value == 1
                    and b.bounded_by == key_of(left.left)):
                n_iv = self.eval(left.left, env, refin)
                hi = (n_iv.hi - 1 if isinstance(n_iv, Interval)
                      and n_iv.hi is not None else None)
                return Interval(0, hi, bounded_by=b.bounded_by)
            return a.sub(b)
        if isinstance(op, ast.Mult):
            return a.mul(b)
        if isinstance(op, ast.Mod):
            return a.mod(b if b.lo is not None else
                         self._eff(b, refin),
                         bounded_by=key_of(node.right))
        if isinstance(op, ast.LShift):
            a_eff, b_eff = self._eff(a, refin), self._eff(b, refin)
            if self.on_lshift is not None and self.depth == 0:
                self.on_lshift(node, a_eff, b_eff, refin)
            return a_eff.lshift(b_eff)
        if isinstance(op, ast.RShift):
            return self._eff(a, refin).rshift(self._eff(b, refin))
        if isinstance(op, ast.BitOr):
            # packed-key obligation: `(x << C) | field` must keep the
            # field inside its C-bit width or it bleeds into the score
            if (isinstance(node.left, ast.BinOp)
                    and isinstance(node.left.op, ast.LShift)
                    and self.on_packed_or is not None and self.depth == 0):
                width = self.eval(node.left.right, env, refin)
                if (isinstance(width, Interval) and width.lo is not None
                        and width.lo == width.hi):
                    self.on_packed_or(node, width.lo, b, refin)
            return self._eff(a, refin).or_(self._eff(b, refin))
        if isinstance(op, ast.BitAnd):
            return self._eff(a, refin).and_(self._eff(b, refin))
        return TOP

    def _eval_call(self, node: ast.Call, env: dict,
                   refin: dict) -> object:
        tail = _tail(node.func)
        args = node.args
        if tail in ("clip",) and len(args) >= 3:
            lo = self.eval(args[1], env, refin)
            hi = self.eval(args[2], env, refin)
            if isinstance(lo, Interval) and isinstance(hi, Interval):
                return Interval(lo.lo, hi.hi)
            return TOP
        if tail in ("min", "max") and len(args) >= 2 \
                and isinstance(node.func, ast.Name):
            ivs = [self.eval(a, env, refin) for a in args]
            ivs = [self._eff(v, refin) for v in ivs
                   if isinstance(v, Interval)]
            if len(ivs) != len(args):
                return TOP
            if tail == "min":
                his = [v.hi for v in ivs if v.hi is not None]
                los = [v.lo for v in ivs]
                return Interval(
                    min(los) if None not in los else None,
                    min(his) if his else None)
            los = [v.lo for v in ivs if v.lo is not None]
            his = [v.hi for v in ivs]
            return Interval(max(los) if los else None,
                            max(his) if None not in his else None)
        if tail == "where" and len(args) == 3:
            a = self.eval(args[1], env, refin)
            b = self.eval(args[2], env, refin)
            if isinstance(a, Interval) and isinstance(b, Interval):
                return self._eff(a, refin).join(self._eff(b, refin))
            return TOP
        if tail == "arange":
            n = self.eval(args[0], env, refin) if args else TOP
            if isinstance(n, Interval) and n.hi is not None:
                return Interval(0, n.hi - 1, bounded_by=key_of(args[0]))
            return Interval(0, None,
                            bounded_by=key_of(args[0]) if args else None)
        if tail in ("zeros", "zeros_like"):
            return const(0)
        if tail in ("ones", "ones_like"):
            return const(1)
        if tail in ("full", "full_like") and len(args) >= 2:
            v = self.eval(args[1], env, refin)
            return v if isinstance(v, Interval) else TOP
        if tail == "astype" and isinstance(node.func, ast.Attribute):
            return self.eval(node.func.value, env, refin)
        if tail == "axis_index":
            return Interval(0, None)
        if tail in ("abs", "float", "int") and len(args) == 1 \
                and isinstance(node.func, ast.Name):
            v = self.eval(args[0], env, refin)
            if isinstance(v, Interval):
                return v if tail != "abs" else Interval(
                    0, None if v.hi is None or v.lo is None
                    else max(abs(v.lo), abs(v.hi)))
            return TOP
        if tail in self.guards:
            return Interval(0, 1)
        # same-package helper: inline depth-limited, else fall back to
        # its retN annotations (the interprocedural contract seed)
        target = self.index.find_function(
            self.index.resolve(self.mod, node.func))
        if target is not None:
            return self._eval_helper(target, node, env, refin)
        for a in args:
            self.eval(a, env, refin)
        return TOP

    def _eval_helper(self, target: FunctionInfo, node: ast.Call,
                     env: dict, refin: dict) -> object:
        seeds = shape_seeds_for(target.sf, target.node)
        body = [s for s in target.node.body
                if not (isinstance(s, ast.Expr)
                        and isinstance(s.value, ast.Constant))]
        if (self.depth < MAX_INLINE_DEPTH
                and len(body) <= MAX_INLINE_STMTS
                and not any(isinstance(s, (ast.For, ast.While))
                            for s in body)):
            params = [a.arg for a in target.node.args.args]
            if params and params[0] in ("self", "cls"):
                params = params[1:]
            arg_ivs: dict[str, Interval] = {}
            for name, arg in zip(params, node.args):
                v = self.eval(arg, env, refin)
                if isinstance(v, Interval):
                    arg_ivs[name] = self._eff(v, refin)
            for kw in node.keywords:
                if kw.arg:
                    v = self.eval(kw.value, env, refin)
                    if isinstance(v, Interval):
                        arg_ivs[kw.arg] = self._eff(v, refin)
            sub = FlowInterpreter(self.index, target.module, self.consts,
                                  self.guards, depth=self.depth + 1)
            try:
                sub.run(target, seeds=seeds, arg_ivs=arg_ivs)
            except RecursionError:   # pathological self-recursion
                return TOP
            out: object = None
            for _, val, r in sub.returns:
                cur = (sub._eff(val, r) if isinstance(val, Interval)
                       else val)
                if out is None:
                    out = cur
                elif isinstance(out, Interval) and isinstance(cur,
                                                              Interval):
                    out = out.join(cur)
                elif (isinstance(out, tuple) and isinstance(cur, tuple)
                        and len(out) == len(cur)):
                    out = tuple(
                        x.join(y) if isinstance(x, Interval)
                        and isinstance(y, Interval) else TOP
                        for x, y in zip(out, cur))
                else:
                    out = TOP
            if out is not None:
                return out
        # contract fallback: declared retN seeds
        rets = [(int(k[3:]), s.interval) for k, s in seeds.items()
                if k.startswith("ret") and k[3:].isdigit()
                and s.interval is not None]
        if rets:
            n = max(i for i, _ in rets) + 1
            out_t = [TOP] * n
            for i, iv in rets:
                out_t[i] = iv
            return out_t[0] if n == 1 else tuple(out_t)
        return TOP


def call_tail(node: ast.expr) -> Optional[str]:
    """The trailing name of a callee expression (``jnp.stack`` ->
    ``stack``); shared by the engine and every specflow analyzer."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


_tail = call_tail


# -- SPMD (shard_map / pjit) site modelling -----------------------------------


_SPMD_KW = {
    "shard_map": ("in_specs", "out_specs"),
    "pjit": ("in_shardings", "out_shardings"),
}

#: collectives and the position of their axis-name argument
COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1, "axis_index": 0,
}


@dataclasses.dataclass
class SpmdSite:
    """One parsed shard_map/pjit call: resolved layouts + body."""

    sf: SourceFile
    module: str
    line: int
    call: ast.Call
    body_fn: Optional[FunctionInfo]
    bound_positional: int            # positionally partial-bound params
    in_layouts: Optional[list[Layout]]   # None = not a literal tuple
    out_layouts: Optional[list[Layout]]
    axes: frozenset[str]             # mesh axes the specs name (live set)


def _module_value_env(sf: SourceFile) -> dict[str, ast.expr]:
    """Module-level ``NAME = <expr>`` map (resolves spec constants like
    ``_NODES = P(NODES_AXIS)``)."""
    out: dict[str, ast.expr] = {}
    if sf.tree is None:
        return out
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            out[node.targets[0].id] = node.value
    return out


def resolve_axis_name(index: ModuleIndex, mod: str,
                      node: ast.expr) -> Optional[str]:
    """A mesh-axis operand -> its string, through cross-module string
    constants (``NODES_AXIS`` -> ``"nodes"``)."""
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, str) else None
    fq = index.resolve(mod, node)
    if fq is None:
        return None
    strs = module_str_consts(index)
    if fq in strs:
        return strs[fq]
    # bare unresolved globals keep their name: try the site's own module
    return strs.get(f"{mod}.{fq}")


def parse_spec(index: ModuleIndex, mod: str, node: ast.expr,
               value_env: dict[str, ast.expr]) -> Layout:
    """One spec operand -> Layout.  ``P()`` is replicated; ``P("nodes")``
    is sharded; ``None`` and anything unresolvable stay unknown."""
    if isinstance(node, ast.Name) and node.id in value_env:
        node = value_env[node.id]
    if isinstance(node, ast.Constant) and node.value is None:
        return UNKNOWN
    if isinstance(node, ast.Call) and _tail(node.func) in (
            "P", "PartitionSpec"):
        axes = []
        for a in node.args:
            if isinstance(a, ast.Constant) and a.value is None:
                continue
            name = resolve_axis_name(index, mod, a)
            if name is None:
                return UNKNOWN
            axes.append(name)
        return sharded(tuple(axes)) if axes else REPLICATED
    return UNKNOWN


def _parse_specs(index: ModuleIndex, mod: str, node: Optional[ast.expr],
                 value_env: dict) -> Optional[list[Layout]]:
    if node is None:
        return None
    if isinstance(node, ast.Name) and node.id in value_env:
        resolved = value_env[node.id]
        if isinstance(resolved, (ast.Tuple, ast.List)):
            node = resolved
    if isinstance(node, (ast.Tuple, ast.List)):
        return [parse_spec(index, mod, e, value_env) for e in node.elts]
    # a single spec broadcasts: model as None (arity unknown) but keep
    # the axis universe via parse_spec at the call site
    return None


def extract_spmd_sites(index: ModuleIndex) -> list[SpmdSite]:
    """Every shard_map/pjit call in the package, with layouts resolved
    through module spec constants and the body seen through partial."""
    cache = getattr(index, "_specflow_sites", None)
    if cache is not None:
        return cache
    sites: list[SpmdSite] = []
    for mod, sf in sorted(index.modules.items()):
        if sf.tree is None or not (
                "shard_map" in sf.text or "pjit" in sf.text):
            continue
        value_env = _module_value_env(sf)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _tail(node.func)
            if tail not in _SPMD_KW:
                continue
            in_kw, out_kw = _SPMD_KW[tail]
            kwargs = {k.arg: k.value for k in node.keywords if k.arg}
            in_l = _parse_specs(index, mod, kwargs.get(in_kw), value_env)
            out_l = _parse_specs(index, mod, kwargs.get(out_kw), value_env)
            axes: set[str] = set()
            for kw_node in (kwargs.get(in_kw), kwargs.get(out_kw)):
                if kw_node is None:
                    continue
                elts = ([kw_node] if not isinstance(
                    kw_node, (ast.Tuple, ast.List)) else kw_node.elts)
                for e in elts:
                    lay = parse_spec(index, mod, e, value_env)
                    axes.update(lay.axes)
            body_fn, bound = None, 0
            if node.args:
                f = node.args[0]
                if isinstance(f, ast.Call) and _tail(f.func) in (
                        "partial", "_partial"):
                    bound = len(f.args) - 1
                    f = f.args[0] if f.args else None
                if f is not None:
                    body_fn = index.find_function(index.resolve(mod, f))
            sites.append(SpmdSite(
                sf=sf, module=mod, line=node.lineno, call=node,
                body_fn=body_fn, bound_positional=max(bound, 0),
                in_layouts=in_l, out_layouts=out_l,
                axes=frozenset(axes)))
    index._specflow_sites = sites
    return sites
