"""The specflow abstract domain: integer intervals and sharding layouts.

Intervals are the workhorse of the dtype-regime proof.  Two design
points matter more than the arithmetic:

- **Unbounded ends are ``None``** (not a sentinel int), and every
  operation is written to be SOUND under unknowns: when a bound cannot
  be computed the result end is ``None``, never a guess.  Bitwise
  ``|``/``&`` on fixed-width integers can never overflow, so the
  overflow rule only fires on ``<<`` (and the analyzer documents that
  ``*``/``+`` are out of scope — the tree's ranking keys are built from
  shifts and ors).
- **``bounded_by`` provenance.**  ``x % n`` is in ``[0, n-1]`` — but the
  interesting ``n`` (``n_total``) is often refined LATER, by a
  ``_packed_regime(n_total)`` ternary guarding the packed-key branch.  A
  plain interval computed before the guard would keep the unrefined
  ``2**30`` bound and the packed proof would fail on exactly the code it
  must verify.  ``bounded_by`` records "this value is in
  ``[0, key(n)-1]``"; at check time the analyzer re-evaluates the bound
  under the branch's refinements (see :meth:`Interval.hi_under`).  The
  rotation idiom ``(n - 1) - (e % n)`` keeps the provenance — the engine
  recognizes the pattern structurally (engine._eval_sub).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

INT32_MAX = 2**31 - 1
INT32_MIN = -(2**31)


def _min(*vals):
    known = [v for v in vals if v is not None]
    return min(known) if len(known) == len(vals) else None


def _max(*vals):
    known = [v for v in vals if v is not None]
    return max(known) if len(known) == len(vals) else None


@dataclasses.dataclass(frozen=True)
class Interval:
    """A sound integer range; ``None`` ends are unbounded."""

    lo: Optional[int] = None
    hi: Optional[int] = None
    #: refinement key (ast.dump of an expression E) meaning the value is
    #: additionally known to lie in [0, E-1]; consumed by hi_under()
    bounded_by: Optional[str] = None

    # -- queries --------------------------------------------------------------

    @property
    def nonneg(self) -> bool:
        return self.lo is not None and self.lo >= 0

    def hi_under(self, refinements: dict[str, "Interval"]) -> Optional[int]:
        """The upper bound after substituting refinements: the tighter of
        the stored ``hi`` and ``refinement[bounded_by].hi - 1``."""
        hi = self.hi
        if self.bounded_by is not None:
            r = refinements.get(self.bounded_by)
            if r is not None and r.hi is not None:
                hi = _min(hi, r.hi - 1) if hi is not None else r.hi - 1
        return hi

    def lo_under(self, refinements: dict[str, "Interval"]) -> Optional[int]:
        """The lower bound; a ``bounded_by`` value is known nonnegative."""
        if self.bounded_by is not None:
            return 0 if self.lo is None else max(self.lo, 0)
        return self.lo

    # -- arithmetic (sound, drops provenance unless stated) -------------------

    def join(self, other: "Interval") -> "Interval":
        return Interval(_min(self.lo, other.lo), _max(self.hi, other.hi),
                        self.bounded_by if self.bounded_by ==
                        other.bounded_by else None)

    def add(self, other: "Interval") -> "Interval":
        lo = None if None in (self.lo, other.lo) else self.lo + other.lo
        hi = None if None in (self.hi, other.hi) else self.hi + other.hi
        return Interval(lo, hi)

    def sub(self, other: "Interval") -> "Interval":
        lo = None if None in (self.lo, other.hi) else self.lo - other.hi
        hi = None if None in (self.hi, other.lo) else self.hi - other.lo
        return Interval(lo, hi)

    def neg(self) -> "Interval":
        return Interval(None if self.hi is None else -self.hi,
                        None if self.lo is None else -self.lo)

    def mul(self, other: "Interval") -> "Interval":
        ends = [a * b for a in (self.lo, self.hi)
                for b in (other.lo, other.hi)
                if a is not None and b is not None]
        if len(ends) < 4:
            return Interval()
        return Interval(min(ends), max(ends))

    def lshift(self, other: "Interval") -> "Interval":
        """``a << s``: shift amounts are assumed nonnegative (jnp shifts
        by negative amounts are already UB); an unknown shift amount
        yields an unbounded result — which is the point of the rule."""
        s_lo = 0 if other.lo is None else max(other.lo, 0)
        if other.hi is None:
            return Interval()
        lo = None if self.lo is None else (
            self.lo << (other.hi if self.lo < 0 else s_lo))
        hi = None if self.hi is None else (
            self.hi << (other.hi if self.hi > 0 else s_lo))
        return Interval(lo, hi)

    def rshift(self, other: "Interval") -> "Interval":
        """``a >> s`` with s >= 0: magnitudes never grow (arithmetic
        shift keeps sign, so lo >= min(lo, lo>>s) = lo for lo<0)."""
        s_lo = 0 if other.lo is None else max(other.lo, 0)
        lo = None if self.lo is None else (
            self.lo >> s_lo if self.lo < 0 else 0 if other.hi is None
            else self.lo >> min(other.hi, 63))
        # for nonneg hi the largest result is hi >> s_lo; negative hi
        # shifts toward -1
        hi = None if self.hi is None else (
            self.hi >> s_lo if self.hi >= 0 else -1)
        return Interval(lo, hi)

    def or_(self, other: "Interval") -> "Interval":
        """``a | b``: never overflows a fixed width.  For nonneg
        operands ``a | b <= a + b``; any negative operand makes the
        result's sign unknown but still magnitude-bounded, which the
        overflow rule does not care about."""
        if self.nonneg and other.nonneg:
            hi = (None if None in (self.hi, other.hi)
                  else self.hi + other.hi)
            return Interval(max(self.lo, other.lo), hi)
        return Interval(INT32_MIN, INT32_MAX)

    def and_(self, other: "Interval") -> "Interval":
        """``a & b``: bounded by a nonnegative operand's hi."""
        if self.nonneg:
            return Interval(0, self.hi)
        if other.nonneg:
            return Interval(0, other.hi)
        return Interval(INT32_MIN, INT32_MAX)

    def mod(self, other: "Interval",
            bounded_by: Optional[str] = None) -> "Interval":
        """``e % n`` for positive n (Python/jnp semantics: result in
        [0, n-1])."""
        if other.lo is not None and other.lo > 0:
            hi = None if other.hi is None else other.hi - 1
            return Interval(0, hi, bounded_by=bounded_by)
        return Interval()

    def clamp_min(self, lo: int) -> "Interval":
        return Interval(lo if self.lo is None else max(self.lo, lo),
                        self.hi, self.bounded_by)

    def clamp_max(self, hi: int) -> "Interval":
        return Interval(self.lo,
                        hi if self.hi is None else min(self.hi, hi),
                        self.bounded_by)


TOP = Interval()


def const(v: int) -> Interval:
    return Interval(v, v)


# -- sharding layouts ---------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Layout:
    """The sharding half of an abstract value.

    ``kind``:
      - ``"sharded"``  — carries ``axes``, the mesh-axis names the value
        is split over (from a ``PartitionSpec`` literal or a ``shape``
        annotation);
      - ``"rep"``      — replicated over the mesh (``P()``);
      - ``"fresh"``    — built replicated inside the body
        (``jnp.zeros(n)``): identical on every shard *until* someone
        scatters owner-local data into it;
      - ``"unknown"``  — no information (the conservative default: rules
        only fire on provably-wrong layouts).
    """

    kind: str = "unknown"
    axes: tuple[str, ...] = ()

    @property
    def is_replicated(self) -> bool:
        return self.kind in ("rep", "fresh")

    @property
    def is_sharded(self) -> bool:
        return self.kind == "sharded"


UNKNOWN = Layout()
REPLICATED = Layout("rep")
FRESH = Layout("fresh")


def sharded(axes: tuple[str, ...]) -> Layout:
    return Layout("sharded", tuple(axes))
