"""specflow: an abstract shape/dtype/sharding interpreter for koordlint.

PR 7 gave the repo pattern-matching analyzers; PR 10's mesh-discipline
rule is purely syntactic ("specs must be literal") and cannot see whether
the specs are *right*.  specflow upgrades koordlint to a small dataflow
engine: it propagates an abstract value per binding — integer intervals
(with symbolic ``value < N`` provenance so a ``% n_total`` bound survives
a later ``_packed_regime(n_total)`` guard), dtype tags, and a sharding
layout (axis→mesh-axis, replicated, fresh, donated/⊥) — through function
bodies, seeded interprocedurally from ``callgraph.ModuleIndex``'s jit
sites and from lightweight ``# koordlint: shape[...]`` annotations where
inference needs a seed (see docs/static_analysis.md for the syntax).

Four analyzers ride on it (analyzers/{spec_consistency,dtype_regime,
donation_flow,tenant_axis}.py); this package holds the shared engine:

- :mod:`domain` — the interval lattice and layout tags;
- :mod:`engine` — module-constant evaluation, the expression/flow
  interpreter with guard refinement and depth-limited helper inlining,
  shape-annotation parsing, and SPMD (shard_map/pjit) site modelling.
"""

from __future__ import annotations

from .domain import INT32_MAX, Interval, Layout
from .engine import (
    FlowInterpreter,
    ShapeSeed,
    SpmdSite,
    extract_spmd_sites,
    module_consts,
    parse_shape_body,
    resolve_axis_name,
    shape_seeds_for,
)

__all__ = [
    "INT32_MAX", "Interval", "Layout",
    "FlowInterpreter", "ShapeSeed", "SpmdSite",
    "extract_spmd_sites", "module_consts", "parse_shape_body",
    "resolve_axis_name", "shape_seeds_for",
]
