"""Project module index + call graph over ``koordinator_tpu/``.

Shared by the jit-centric analyzers (jit_host_sync, donation_safety):

- :class:`ModuleIndex` maps every module under the package to its parsed
  source, records every function/method with a qualified name, and
  resolves names through each module's import aliases (``import jax``,
  ``from koordinator_tpu.ops import batch_assign as _ba``, relative
  imports, function-local imports included).
- :func:`extract_jit_sites` finds every ``jax.jit`` call site — the
  plain-call form (``jax.jit(fn, donate_argnums=...)``, possibly nested
  inside a wrapper like ``insp.instrument(jax.jit(...), ...)``), the
  ``@jax.jit`` decorator, and the ``@functools.partial(jax.jit,
  static_argnames=...)`` decorator — with its static argnames, donated
  positions, and the binding it is assigned to (``Scheduler._pass1``).
- :func:`reachable_functions` walks call edges from the jitted entry
  functions so device-purity rules apply to the whole traced closure,
  not just the entry point.

Everything is best-effort static resolution: a name that cannot be
resolved simply produces no edge.  The self-test corpora pin what the
resolution MUST handle.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from .core import Project, SourceFile


def get_index(project: Project, package: str) -> "ModuleIndex":
    """One shared ModuleIndex per (project, package): building it is the
    dominant per-analyzer cost, and every analyzer wants the same one."""
    cache = getattr(project, "_koordlint_index_cache", None)
    if cache is None:
        cache = project._koordlint_index_cache = {}
    if package not in cache:
        cache[package] = ModuleIndex(project, package=package)
    return cache[package]


def module_name(path: str) -> Optional[str]:
    """repo-relative path -> dotted module name (None for non-package
    files like tools/ scripts)."""
    if not path.endswith(".py"):
        return None
    parts = path[:-3].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclasses.dataclass
class FunctionInfo:
    module: str
    qualname: str          # "gang_assign" or "Scheduler.__init__"
    node: ast.AST          # FunctionDef / AsyncFunctionDef / Lambda
    sf: SourceFile

    @property
    def fq(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclasses.dataclass
class JitSite:
    sf: SourceFile
    module: str                     # module containing the jit site
    line: int
    func_fq: Optional[str]          # resolved jitted callable, if named
    func_node: Optional[ast.AST]    # Lambda / decorated def, if inline
    static_argnames: frozenset[str]
    donate_argnums: tuple[int, ...]
    binding: Optional[str]          # "Scheduler._pass1" / "_row_set_donating"
    binding_class: Optional[str]    # class owning the binding, if a method


class ModuleIndex:
    """Parsed view of the package: modules, functions, import aliases."""

    def __init__(self, project: Project, package: str = "koordinator_tpu"):
        self.project = project
        self.package = package
        self.modules: dict[str, SourceFile] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ast.ClassDef] = {}
        #: module -> local name -> fully-qualified dotted target
        self.aliases: dict[str, dict[str, str]] = {}
        for path, sf in sorted(project.files.items()):
            if not path.startswith(package + "/") or sf.tree is None:
                continue
            mod = module_name(path)
            self.modules[mod] = sf
            self.aliases[mod] = self._collect_aliases(mod, sf.tree)
            self._collect_defs(mod, sf, sf.tree, prefix="")

    # -- indexing -------------------------------------------------------------

    def _collect_aliases(self, mod: str, tree: ast.Module) -> dict[str, str]:
        out: dict[str, str] = {}
        for node in ast.walk(tree):  # function-local imports included
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against this module
                    parts = mod.split(".")
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    out[a.asname or a.name] = f"{base}.{a.name}"
        return out

    def _collect_defs(self, mod: str, sf: SourceFile, node: ast.AST,
                      prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                self.functions[f"{mod}.{qual}"] = FunctionInfo(
                    mod, qual, child, sf)
                self._collect_defs(mod, sf, child, prefix=f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                self.classes[f"{mod}.{prefix}{child.name}"] = child
                self._collect_defs(mod, sf, child,
                                   prefix=f"{prefix}{child.name}.")

    # -- resolution -----------------------------------------------------------

    def resolve(self, mod: str, node: ast.AST) -> Optional[str]:
        """Best-effort fully-qualified dotted name for an expression."""
        if isinstance(node, ast.Name):
            alias = self.aliases.get(mod, {})
            if node.id in alias:
                return alias[node.id]
            local = f"{mod}.{node.id}"
            if local in self.functions or local in self.classes:
                return local
            return node.id  # builtins / unresolved globals keep bare names
        if isinstance(node, ast.Attribute):
            base = self.resolve(mod, node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    def find_function(self, fq: Optional[str]) -> Optional[FunctionInfo]:
        """FunctionInfo for a dotted name, seeing through re-exports and
        method qualnames (``pkg.mod.Class.method``)."""
        if not fq:
            return None
        if fq in self.functions:
            return self.functions[fq]
        # "pkg.mod.symbol" where the alias chain crossed modules: try
        # splitting at every known module prefix
        parts = fq.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            if mod in self.modules:
                cand = f"{mod}.{'.'.join(parts[cut:])}"
                if cand in self.functions:
                    return self.functions[cand]
                # from-import alias one more hop deep
                alias = self.aliases.get(mod, {})
                head = parts[cut]
                if head in alias:
                    return self.find_function(
                        ".".join([alias[head]] + parts[cut + 1:]))
                return None
        return None

    # -- call graph -----------------------------------------------------------

    def callees(self, fn: FunctionInfo) -> list[tuple[FunctionInfo, ast.Call]]:
        """Project-internal callees of a function, with the call node
        (argument-level detail for taint propagation)."""
        out: list[tuple[FunctionInfo, ast.Call]] = []
        cls = fn.qualname.rsplit(".", 1)[0] if "." in fn.qualname else None
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            target: Optional[FunctionInfo] = None
            f = node.func
            if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                    and f.value.id in ("self", "cls") and cls):
                target = self.find_function(f"{fn.module}.{cls}.{f.attr}")
            else:
                target = self.find_function(self.resolve(fn.module, f))
            if target is not None and target.fq != fn.fq:
                out.append((target, node))
        return out


# -- jit-site extraction ------------------------------------------------------


def _const_strs(node: Optional[ast.AST]) -> frozenset[str]:
    if node is None:
        return frozenset()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        return frozenset(e.value for e in node.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str))
    return frozenset()


def _const_ints(node: Optional[ast.AST]) -> tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def extract_jit_sites(index: ModuleIndex,
                      paths: Optional[list[str]] = None) -> list[JitSite]:
    """Every ``jax.jit`` site in the given repo-relative files (default:
    all indexed modules), with donated positions and assignment binding.
    Cached per index + path set (several analyzers ask for the same).
    """
    cache = getattr(index, "_site_cache", None)
    if cache is None:
        cache = index._site_cache = {}
    key = tuple(sorted(paths)) if paths is not None else None
    if key in cache:
        return cache[key]
    sites: list[JitSite] = []
    for mod, sf in sorted(index.modules.items()):
        if paths is not None and sf.path not in paths:
            continue
        parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(sf.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and (
                    index.resolve(mod, node.func) == "jax.jit"):
                sites.append(_site_from_call(index, mod, sf, node, parents))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    site = _site_from_decorator(index, mod, sf, node, deco,
                                                parents)
                    if site is not None:
                        sites.append(site)
    cache[key] = sites
    return sites


def _binding_of(index: ModuleIndex, mod: str, call: ast.Call,
                parents: dict) -> tuple[Optional[str], Optional[str]]:
    """(binding, owning class) for the assignment a jit call lands in:
    ``self._pass1 = insp.instrument(jax.jit(...), ...)`` ->
    ("_pass1", "Scheduler"); module-level ``_x = jax.jit(...)`` ->
    ("_x", None)."""
    node: ast.AST = call
    while node in parents:
        node = parents[node]
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            owner: Optional[str] = None
            up = node
            while up in parents:
                up = parents[up]
                if isinstance(up, ast.ClassDef):
                    owner = up.name
                    break
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                return target.attr, owner
            if isinstance(target, ast.Name):
                return target.id, None
            return None, None
        if isinstance(node, (ast.FunctionDef, ast.ClassDef, ast.Module)):
            break
    return None, None


def _site_from_call(index: ModuleIndex, mod: str, sf: SourceFile,
                    call: ast.Call, parents: dict) -> JitSite:
    fn = call.args[0] if call.args else None
    func_fq, func_node = None, None
    if isinstance(fn, ast.Lambda):
        func_node = fn
    elif fn is not None:
        if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
                and fn.value.id == "self"):
            # jax.jit(self._method): owner class found via the binding walk
            _, owner = _binding_of(index, mod, call, parents)
            if owner:
                func_fq = f"{mod}.{owner}.{fn.attr}"
        else:
            func_fq = index.resolve(mod, fn)
    binding, binding_class = _binding_of(index, mod, call, parents)
    return JitSite(
        sf=sf, module=mod, line=call.lineno, func_fq=func_fq,
        func_node=func_node,
        static_argnames=_const_strs(_kw(call, "static_argnames")),
        donate_argnums=_const_ints(_kw(call, "donate_argnums")),
        binding=binding, binding_class=binding_class)


def _site_from_decorator(index: ModuleIndex, mod: str, sf: SourceFile,
                         fn: ast.AST, deco: ast.AST,
                         parents: dict) -> Optional[JitSite]:
    """``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` decorators."""
    static, donate = frozenset(), ()
    if index.resolve(mod, deco) == "jax.jit":
        pass
    elif (isinstance(deco, ast.Call)
          and index.resolve(mod, deco.func) in ("functools.partial",
                                                "partial")
          and deco.args
          and index.resolve(mod, deco.args[0]) == "jax.jit"):
        static = _const_strs(_kw(deco, "static_argnames"))
        donate = _const_ints(_kw(deco, "donate_argnums"))
    else:
        return None
    # qualify through enclosing classes/functions so a decorated METHOD
    # resolves to its real index key (pkg.mod.Class.method)
    qual: list[str] = [fn.name]
    owner = None
    node: ast.AST = fn
    while node in parents:
        node = parents[node]
        if isinstance(node, (ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            if owner is None and isinstance(node, ast.ClassDef):
                owner = node.name
            qual.insert(0, node.name)
    return JitSite(sf=sf, module=mod, line=fn.lineno,
                   func_fq=f"{mod}.{'.'.join(qual)}",
                   func_node=fn, static_argnames=static,
                   donate_argnums=donate, binding=fn.name,
                   binding_class=owner)


def reachable_functions(index: ModuleIndex,
                        roots: list[FunctionInfo]) -> dict[str, FunctionInfo]:
    """Transitive project-internal closure of the given entry points."""
    seen: dict[str, FunctionInfo] = {}
    stack = list(roots)
    while stack:
        fn = stack.pop()
        if fn.fq in seen:
            continue
        seen[fn.fq] = fn
        for callee, _ in index.callees(fn):
            if callee.fq not in seen:
                stack.append(callee)
    return seen
