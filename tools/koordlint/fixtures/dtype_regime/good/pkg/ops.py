"""Regime-disciplined twins of the bad corpus (must-pass)."""

import jax.numpy as jnp

_TB_BITS = 15
_SCORE_CLIP = (1 << 30 - _TB_BITS) - 1
PACKED_NODE_CAPACITY = 1 << _TB_BITS
MAX_NODE_CAPACITY = 1 << 30


def check_node_capacity(n):
    if n > MAX_NODE_CAPACITY:
        raise ValueError("past the ranking-key ceiling")


def _packed_regime(n_total):
    return n_total <= PACKED_NODE_CAPACITY


def guarded_key(scores, feasible, ids, rot, n_total):
    # the real _rank_parts shape: capacity guard, clipped score,
    # rotation-idiom tie-break, packed/wide split behind the regime gate
    check_node_capacity(n_total)
    q = jnp.clip(scores, 0, _SCORE_CLIP)
    tb = (n_total - 1) - ((ids - rot) % n_total)
    key = ((q << _TB_BITS) | tb) if _packed_regime(n_total) else q
    return jnp.where(feasible, key, -1)


# koordlint: shape[score: Pxk i32 -1..32767]
def seeded_key(score, node, rot, n_total):
    # an annotation-seeded parameter proves where inference cannot see
    if _packed_regime(n_total):
        return (score << _TB_BITS) | ((node - rot) % n_total)
    return score


# koordlint: shape[ret0: P i32 0..100]
def honest_contract(x):
    return jnp.clip(x, 0, 100)


def literal_comparison_guard(scores, ids, rot, n_total):
    # a literal `<=` comparison at exactly the regime wall is as good a
    # guard as _packed_regime(): tb's true max is 2**15 - 1, which
    # just fits the 15-bit field (refinements store the INCLUSIVE
    # bound of the guarded name)
    check_node_capacity(n_total)
    q = jnp.clip(scores, 0, _SCORE_CLIP)
    tb = (n_total - 1) - ((ids - rot) % n_total)
    if n_total <= PACKED_NODE_CAPACITY:
        return (q << _TB_BITS) | tb
    return q
