"""Seeded dtype-regime violations (must-flag corpus).

``overflowing_key`` and ``unguarded_packed_key`` reconstruct the 2**15
ranking-key wall PR 10 deleted: a packed int32 key whose score field is
too wide (the shift overflows int32) and a packed composition with no
``_packed_regime`` guard (the tie-break bleeds into the score bits the
moment a capacity crosses 2**15).
"""

import jax.numpy as jnp

_TB_BITS = 15
# BAD: the clip admits 2**20 score buckets, so `q << 15` reaches 2**35
_SCORE_CLIP = (1 << 20) - 1


def overflowing_key(scores, feasible, n_total):
    q = jnp.clip(scores, 0, _SCORE_CLIP)
    tb = jnp.arange(n_total) % n_total
    key = (q << _TB_BITS) | tb
    return jnp.where(feasible, key, -1)


def unguarded_packed_key(scores, ids, rot, n_total):
    # BAD (the pre-PR-10 wall): nothing bounds n_total below 2**15, so
    # the rotated tie-break can exceed its 15-bit field
    q = jnp.clip(scores, 0, (1 << 15) - 1)
    tb = (n_total - 1) - ((ids - rot) % n_total)
    return (q << _TB_BITS) | tb


def unprovable_shift(score, spread_bits):
    # BAD: `score` has no clip, guard, or shape annotation — the packed
    # key cannot be proven to fit int32
    return (score >> spread_bits) << _TB_BITS


# koordlint: shape[ret0: P i32 0..100]
def lying_contract(x):
    # BAD: the declared return contract says <= 100 but the clip
    # admits 1000 — callers seed their proofs from the annotation
    return jnp.clip(x, 0, 1000)
