"""Fixture codec home: the v1 compatibility path loops dumps per event
by design (pre-v4 peers need one JSON doc per event) — the analyzer is
constructed with this file as ``codec_home`` and must stay silent."""

import enum
import json


class FrameType(enum.IntEnum):
    HELLO = 1
    SNAPSHOT = 2
    DELTA = 3
    ACK = 4
    STATE_PUSH = 13


def pack_events_v1(batch):
    # legacy per-event encoding for pre-v4 peers: exempt here, and
    # ONLY here
    rows = [json.dumps(e, sort_keys=True) for e in batch]
    return {"frame": int(FrameType.DELTA), "events": rows}
