"""Seeded-good corpus: the columnar shapes the rule should accept.

One dumps per FRAME on a columnar frame type, a dumps loop in a
function that handles no columnar frame at all, and a batch path that
defers encoding to the codec home.
"""

import json

from . import wire
from .wire import FrameType


def push_batch(conn, events, rv):
    # GOOD: one frame, one dumps — per-frame encoding
    doc = {"rv": rv, "events_v2": len(events)}
    conn.send(wire.FrameType.STATE_PUSH, json.dumps(doc))


def snapshot_once(conn, state, rv):
    # GOOD: the loop builds rows; serialization happens once, outside it
    rows = []
    for name, rec in sorted(state.items()):
        rows.append((name, rec))
    conn.send(FrameType.SNAPSHOT, json.dumps({"rv": rv, "rows": rows}))


def audit_log(path, records):
    # GOOD: dumps in a loop, but no columnar frame in sight — the audit
    # trail is a different subsystem with different constraints
    with open(path, "a") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")


def delta_via_codec(conn, batch, rv):
    # GOOD: per-event work delegated to the codec home's packer
    conn.send(FrameType.DELTA, wire.pack_events_v1(batch))
