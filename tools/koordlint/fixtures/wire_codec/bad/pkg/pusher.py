"""Seeded-bad corpus: per-event JSON on columnar frames.

Three regressions to the pre-v4 shape, one per columnar frame type:
a per-event STATE_PUSH send loop, a DELTA payload built from a
comprehension of per-event dumps, and a SNAPSHOT chunker that
serializes inside a while loop.
"""

import json

from . import wire
from .wire import FrameType


def push_one_per_event(conn, events):
    # BAD: K tiny frames, K dumps — the exact pre-v4 hot path
    for ev in events:
        conn.send(wire.FrameType.STATE_PUSH, {"event": json.dumps(ev)})


def delta_from_per_event_docs(conn, batch, rv):
    # BAD: one frame, but its payload is K per-event dumps
    rows = [json.dumps(e, sort_keys=True) for e in batch]
    conn.send(FrameType.DELTA, {"rv": rv, "events": rows})


def snapshot_in_chunks(conn, state):
    # BAD: while-loop per-chunk serialization on the SNAPSHOT frame
    pending = list(state.items())
    while pending:
        chunk, pending = pending[:64], pending[64:]
        conn.send(FrameType.SNAPSHOT, {"chunk": json.dumps(chunk)})
