"""Fixture wire module: just enough FrameType for the pusher to name."""

import enum


class FrameType(enum.IntEnum):
    HELLO = 1
    SNAPSHOT = 2
    DELTA = 3
    ACK = 4
    STATE_PUSH = 13
