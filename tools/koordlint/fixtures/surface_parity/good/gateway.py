"""Seeded known-GOOD corpus for surface-parity: route set, shared
builders, and DebugApiError mapping all mirror services.py."""
import re


class HttpGateway:
    _TRACE = re.compile(r"^/debug/trace/(.+)$")

    def __init__(self, scheduler):
        self.scheduler = scheduler

    def _route(self, req, method):
        path = req.path
        if method == "GET" and path == "/debug/rounds":
            return self._debug_rounds(req)
        m = self._TRACE.match(path)
        if m and method == "GET":
            return self._debug_trace(req, m.group(1))
        req._reply(404, {"error": "no route"})

    def _debug_rounds(self, req):
        from .services import debug_rounds_body

        return req._reply(200, debug_rounds_body(self.scheduler, 32))

    def _debug_trace(self, req, pod):
        from .services import DebugApiError, debug_trace_body

        try:
            return req._reply(200, debug_trace_body(self.scheduler, pod))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})
