"""Seeded known-BAD corpus for surface-parity (miniature gateway):
misses /debug/rounds, serves a /debug/trace/ prefix the DebugService
never registers, and calls the DebugApiError-raising trace builder
without mapping the typed status."""
import re


class HttpGateway:
    _TRACE = re.compile(r"^/debug/trace/(.+)$")

    def __init__(self, scheduler):
        self.scheduler = scheduler

    def _route(self, req, method):
        path = req.path
        if method == "GET" and path == "/debug/slo":
            return self._debug_slo(req)
        m = self._TRACE.match(path)
        if m and method == "GET":
            return self._debug_trace(req, m.group(1))
        req._reply(404, {"error": "no route"})

    def _debug_slo(self, req):
        from .services import DebugApiError, debug_slo_body

        try:
            return req._reply(200, debug_slo_body(self.scheduler))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _debug_trace(self, req, pod):
        from .services import debug_trace_body

        # BAD: debug_trace_body raises DebugApiError (typed 404) but this
        # handler never maps it -> blanket 500
        return req._reply(200, debug_trace_body(self.scheduler, pod))
