"""Seeded known-BAD corpus for surface-parity (miniature services.py):
/debug/rounds is registered here but missing from the gateway;
/debug/slo is served WITHOUT the shared builder; the gateway's
/debug/trace/ prefix route is never registered here."""
import threading


class DebugApiError(Exception):
    def __init__(self, status, message):
        super().__init__(message)
        self.status = status
        self.message = message


def debug_rounds_body(scheduler, size):
    return {"rounds": scheduler.rounds[:size]}


def debug_slo_body(scheduler):
    monitor = scheduler.slo_monitor
    if monitor is None:
        raise DebugApiError(501, "no SLO monitor attached")
    return monitor.report()


def debug_trace_body(scheduler, pod):
    trace = scheduler.traces.get(pod)
    if trace is None:
        raise DebugApiError(404, f"no trace for {pod!r}")
    return trace


class DebugService:
    def __init__(self, scheduler):
        self.scheduler = scheduler
        self._routes = {}
        self._lock = threading.Lock()
        self._register_builtin()

    def register(self, path, handler):
        with self._lock:
            self._routes[path] = handler

    def register_prefix(self, prefix, handler):
        with self._lock:
            self._routes[prefix] = handler

    def handle(self, path, params=None):
        handler = self._routes.get(path)
        if handler is None:
            return 404, {"error": "no route"}
        try:
            return 200, handler(params or {})
        except DebugApiError as e:
            return e.status, {"error": e.message}

    def _register_builtin(self):
        self.register("/debug/rounds", self._rounds)
        self.register("/debug/slo", self._slo)

    def _rounds(self, params):
        return debug_rounds_body(self.scheduler, int(params.get("size", 32)))

    def _slo(self, params):
        # BAD: hand-rolled body instead of debug_slo_body
        return self.scheduler.slo_monitor.report()
