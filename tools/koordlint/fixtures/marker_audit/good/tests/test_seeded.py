"""Seeded known-GOOD corpus for marker-audit: chaos always rides slow
(decorator or module pytestmark) and jax is deferred to test bodies."""
from typing import TYPE_CHECKING

import pytest

if TYPE_CHECKING:
    import jax  # ok: annotation-only, never executes at collection

pytestmark = [pytest.mark.chaos, pytest.mark.slow]


def test_chaos_soak_module_marked():
    import jax.numpy as jnp  # ok: deferred to the test body

    assert jnp.zeros(1).shape == (1,)


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_soak_decorated():
    assert True
