"""Seeded known-BAD corpus for marker-audit: a chaos test without the
slow marker (tier-1 would run the soak) and a module-scope jax import
(pytest collection pays it even with every test deselected)."""
import jax.numpy as jnp  # BAD: module-scope jax import in a test file
import pytest


@pytest.mark.chaos
def test_chaos_soak_without_slow():  # BAD: chaos without slow
    assert jnp.zeros(1).shape == (1,)


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_soak_properly_marked():
    assert True
