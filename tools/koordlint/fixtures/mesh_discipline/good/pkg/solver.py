"""Mesh-disciplined twins of the bad corpus (must-pass)."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from pkg.ops import select_candidates


def full_specs(mesh, f, x):
    # explicit placement for every argument and output
    return shard_map(f, mesh=mesh, in_specs=(P("nodes"),),
                     out_specs=P("nodes"))(x)


def donated_with_specs(mesh, f, state, pods):
    # every donated position carries a literal spec entry
    fn = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(P("nodes"), P()),
                  out_specs=(P(), P("nodes"))),
        donate_argnums=(0,))
    return fn(state, pods)


def guarded_by_the_owner(state, pods, cfg):
    # capacity enforcement rides inside the selection entry point —
    # callers never re-guard
    return select_candidates(state, pods, cfg)


def pipelined_handoff_explicit(mesh, f, state, batch):
    # the double-buffer hand-off, disciplined: the donated stacked
    # state carries an explicit literal spec, so the in-flight buffers
    # stay in place across the device/host halves
    fn = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(P("nodes"), P()),
                  out_specs=P("nodes")),
        donate_argnums=(0,))
    return fn(state, batch)
