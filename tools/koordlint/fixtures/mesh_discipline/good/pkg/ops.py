"""The module that OWNS the capacity guard (exempt by path config)."""


def check_node_capacity(n):
    if n > 1 << 30:
        raise ValueError("ceiling")


def select_candidates(state, pods, cfg):
    check_node_capacity(state.capacity)
    return state, pods
