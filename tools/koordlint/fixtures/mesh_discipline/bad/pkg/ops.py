"""Stub guard so the bad corpus imports resolve."""


def check_node_capacity(n):
    if n > 1 << 30:
        raise ValueError("ceiling")
