"""Seeded mesh-discipline violations (must-flag corpus)."""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from pkg.ops import check_node_capacity


def no_specs(mesh, f, x):
    # BAD: placement left to inference — no in_specs/out_specs
    return shard_map(f, mesh=mesh)(x)


def donated_without_spec(mesh, f, state, pods):
    # BAD: position 1 is donated but in_specs has no entry for it
    fn = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(P("nodes"),),
                  out_specs=P("nodes")),
        donate_argnums=(1,))
    return fn(state, pods)


def donated_none_spec(mesh, f, state):
    # BAD: the donated position's spec is an explicit None (inferred)
    fn = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(None,), out_specs=P("nodes")),
        donate_argnums=(0,))
    return fn(state)


def reguarded_capacity(n):
    # BAD: the ceiling guard belongs to ops/batch_assign, not callers
    check_node_capacity(n)
    return n


def pipelined_handoff_inferred(mesh, f, state, batch):
    # BAD (double-buffer hand-off idiom): the pipelined dispatch
    # donates the stacked state at position 0 but leaves its placement
    # to inference (None spec) — a resharding copy would silently
    # defeat the in-place hand-off
    fn = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(None, P()),
                  out_specs=P("nodes")),
        donate_argnums=(0,))
    return fn(state, batch)
