"""Disciplined twins of the forecast bad corpus (must-pass).

The horizon/growth scalars stay device-side through the whole jitted
flow (``jnp.where`` instead of a host branch, multiplicative math
instead of host step counts), and the sharded percentile carries
explicit specs with the donated bank position covered.
"""
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def predicted_peaks(weights, total, horizon, growth):
    # the horizon stays a traced scalar: extrapolation is pure device
    # math, and the falling-trend clamp is a where, not a branch
    peak = jnp.max(weights, axis=1) * total
    stretch = 1.0 + jnp.maximum(growth, 0.0) * (horizon / 3600.0)
    return peak * stretch


predicted_peaks_jit = jax.jit(predicted_peaks)


def sharded_percentile(mesh, f, weights):
    # explicit placement: the bank shards its node axis, the result
    # comes back node-sharded
    return shard_map(f, mesh=mesh, in_specs=(P("nodes"),),
                     out_specs=P("nodes"))(weights)


def sharded_bank_update(mesh, f, weights, samples):
    # the donated bank position carries a literal spec entry, so the
    # in-place update survives placement
    fn = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(P("nodes"), P()),
                  out_specs=P("nodes")),
        donate_argnums=(0,))
    return fn(weights, samples)
