"""Seeded known-BAD corpus for the forecast kernels (ISSUE 15).

Two bug classes the real ``forecast/kernels.py`` must never regress
into:

- **jit-host-sync on the horizon scalar**: the prediction horizon and
  the trend growth rate ride as device scalars end to end; a host cast
  (``float(horizon)``), a step count (``int(horizon // 60)``) or a
  data-dependent branch on the slope inside the jitted flow is a
  silent device sync per refresh.
- **mesh-discipline on the sharded percentile**: the bank's shard_map
  must carry explicit in/out specs, and the donated bank position must
  have a literal spec entry — inferred placement turns the in-place
  bank update into a reshard-and-copy.
"""
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def predicted_peaks(weights, total, horizon, growth):
    h = float(horizon)                    # BAD: host cast of the horizon
    steps = int(horizon // 60)            # BAD: host cast of the horizon
    peak = jnp.max(weights, axis=1) * (total + steps)
    if growth > 0:                        # BAD: data-dependent branch
        peak = peak * (1.0 + growth * h / 3600.0)
    return peak


predicted_peaks_jit = jax.jit(predicted_peaks)


def sharded_percentile_no_specs(mesh, f, weights):
    # BAD: the sharded percentile's placement left to inference
    return shard_map(f, mesh=mesh)(weights)


def sharded_bank_update_donated_unspecced(mesh, f, weights, samples):
    # BAD: the donated bank position has no explicit in_spec entry
    fn = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(None, P()),
                  out_specs=P("nodes")),
        donate_argnums=(0,))
    return fn(weights, samples)
