"""Seeded donation-flow violations (must-flag corpus).

The ISSUE-11 double-buffer hand-off, done wrong three ways: a dispatch
that never performs the blessed swap (an interprocedural kill every
caller inherits), a host half that reads the dead state through two
call hops, and the stash-the-donated-buffer tenancy anti-idiom (the
pre-dispatch stash points at the consumed buffer even after the swap).
"""

import jax


def _pass1_impl(state, batch):
    return batch, state


class SolverKit:
    def __init__(self):
        self.pass1 = jax.jit(_pass1_impl, donate_argnums=(0,))


class Pipeline:
    def __init__(self, snapshot):
        self.kit = SolverKit()
        # binding alias through the typed kit attribute — donation
        # contracts must survive this hop
        self.solve = self.kit.pass1
        self.snapshot = snapshot

    def dispatch_without_swap(self, batch):
        # BAD: donates snapshot.state and never re-points it — the
        # buffer is dead at exit and every caller inherits ⊥
        a, _ = self.solve(self.snapshot.state, batch)
        return a

    def round(self, batch):
        a = self.dispatch_without_swap(batch)
        # BAD: commit() reads the state the dispatch left dead
        return self.commit(a)

    def commit(self, a):
        return self.snapshot.state, a

    def stash_the_buffer(self, batch):
        # BAD (the tenancy anti-idiom): the pre-dispatch stash keeps
        # pointing at the consumed buffer even after the blessed swap
        old = self.snapshot.state
        a, new_state = self.solve(self.snapshot.state, batch)
        self.snapshot.state = new_state
        return old.mean(), a

    def swap_through_rebound_alias(self, batch, fresh):
        # BAD: `snap` was REBOUND to a different object before the
        # store, so `snap.state = ...` is NOT the blessed swap — the
        # real self.snapshot.state stays dead at the read
        snap = self.snapshot
        a, new_state = self.solve(self.snapshot.state, batch)
        snap = fresh
        snap.state = new_state
        return self.snapshot.state, a
