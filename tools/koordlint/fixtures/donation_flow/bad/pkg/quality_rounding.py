"""Seeded donation-flow violations: the quality rounding loop's
residual re-solve, done wrong (must-flag corpus, ISSUE 13).

The LP quality round is a two-dispatch pattern: the packing solve
donates the snapshot state, the blessed swap re-points it, and the
residual (rescue) re-solve donates it AGAIN — plus the first pass's
assignment buffer.  Three ways to read a consumed buffer doing this:
the re-solve against a never-swapped state, a pre-re-solve stash of
the state, and a residual re-solve that donates the pass-1 assignment
buffer and then reads it.
"""

import jax


def _lp_impl(state, batch):
    return batch, state


def _rescue_impl(state, assignments, batch):
    return assignments, state


class QualityKit:
    def __init__(self):
        self.lp_pack = jax.jit(_lp_impl, donate_argnums=(0,))
        self.rescue = jax.jit(_rescue_impl, donate_argnums=(0, 1))


class QualityRounds:
    def __init__(self, snapshot):
        self.kit = QualityKit()
        self.solve = self.kit.lp_pack
        self.rescue = self.kit.rescue
        self.snapshot = snapshot
        self.last_assignments = None

    def residual_without_swap(self, batch):
        # BAD: the merge after the residual re-solve reads the state
        # the re-solve consumed — the SECOND blessed swap is missing
        a, new_state = self.solve(self.snapshot.state, batch)
        self.snapshot.state = new_state
        r, newer = self.rescue(self.snapshot.state, a, batch)
        return self.snapshot.state.sum(), r

    def stash_across_residual(self, batch):
        # BAD: the pre-re-solve stash keeps pointing at the buffer the
        # residual re-solve consumed, even though the swap happened
        a, new_state = self.solve(self.snapshot.state, batch)
        self.snapshot.state = new_state
        stash = self.snapshot.state
        r, newer = self.rescue(self.snapshot.state, a, batch)
        self.snapshot.state = newer
        return stash.sum(), r

    def residual_reads_donated_assignments(self, batch):
        # BAD: the residual re-solve donates the pass-1 assignment
        # buffer (rescue's arg 1); merging from it afterwards reads a
        # consumed buffer
        a, new_state = self.solve(self.snapshot.state, batch)
        self.snapshot.state = new_state
        self.last_assignments = a
        r, newer = self.rescue(self.snapshot.state,
                               self.last_assignments, batch)
        self.snapshot.state = newer
        return self.last_assignments.sum(), r
