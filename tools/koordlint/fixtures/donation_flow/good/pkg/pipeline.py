"""Donation-disciplined twins of the bad corpus (must-pass)."""

import jax


def _pass1_impl(state, batch):
    return batch, state


class SolverKit:
    def __init__(self):
        self.pass1 = jax.jit(_pass1_impl, donate_argnums=(0,))


class Pipeline:
    def __init__(self, snapshot):
        self.kit = SolverKit()
        self.solve = self.kit.pass1
        self.snapshot = snapshot

    def dispatch(self, batch):
        # the blessed swap: re-point the snapshot at the in-flight
        # result before anything can read the dead buffers
        a, new_state = self.solve(self.snapshot.state, batch)
        self.snapshot.state = new_state
        return a

    def round(self, batch):
        a = self.dispatch(batch)
        return self.commit(a)

    def commit(self, a):
        # legal: dispatch() swapped before returning
        return self.snapshot.state, a

    def metadata_survives(self, batch):
        a, new_state = self.solve(self.snapshot.state, batch)
        rows = self.snapshot.state.shape  # metadata outlives donation
        self.snapshot.state = new_state
        return a, rows

    def swap_through_method(self, batch):
        # the swap may live inside the owning object's method
        # (Scheduler._reservation_prepass adopts through the snapshot)
        a, new_state = self.solve(self.snapshot.state, batch)
        self.snapshot.adopt_state(new_state)
        return self.snapshot.state, a

    def rebind_idiom(self, state, batch):
        # `x = f(x, ...)`: the donated name is dead and immediately
        # rebound to the result — the intended idiom
        batch2, state = self.solve(state, batch)
        return state, batch2

    def rebound_alias_is_fresh(self, batch, fresh):
        # a local that once aliased self.snapshot but was REBOUND to a
        # different object before the read: its attrs are not the dead
        # path (the alias map must drop the binding at the rebind)
        snap = self.snapshot
        a, new_state = self.solve(self.snapshot.state, batch)
        snap = fresh
        scratch = snap.state
        self.snapshot.state = new_state
        return a, scratch
