"""Donation-disciplined twins of the quality rounding-loop corpus
(must-pass, ISSUE 13): swap between the passes, merge BEFORE donating
the assignment buffer, rebind the residual's output."""

import jax


def _lp_impl(state, batch):
    return batch, state


def _rescue_impl(state, assignments, batch):
    return assignments, state


class QualityKit:
    def __init__(self):
        self.lp_pack = jax.jit(_lp_impl, donate_argnums=(0,))
        self.rescue = jax.jit(_rescue_impl, donate_argnums=(0, 1))


class QualityRounds:
    def __init__(self, snapshot):
        self.kit = QualityKit()
        self.solve = self.kit.lp_pack
        self.rescue = self.kit.rescue
        self.snapshot = snapshot
        self.last_assignments = None

    def residual_with_swaps(self, batch):
        # the blessed swap lands between the two donating dispatches,
        # and the pass-1 assignments are REBOUND to the residual's
        # merged output (the x = f(x) idiom) — nothing reads a consumed
        # buffer
        a, new_state = self.solve(self.snapshot.state, batch)
        self.snapshot.state = new_state
        a, newer = self.rescue(self.snapshot.state, a, batch)
        self.snapshot.state = newer
        return a

    def merge_before_donating(self, batch):
        # reads of the assignment buffer all happen BEFORE the residual
        # re-solve consumes it; the stored path is re-pointed at the
        # merged result before any later read
        a, new_state = self.solve(self.snapshot.state, batch)
        self.snapshot.state = new_state
        placed = a.sum()
        self.last_assignments = a
        merged, newer = self.rescue(self.snapshot.state,
                                    self.last_assignments, batch)
        self.snapshot.state = newer
        self.last_assignments = merged
        return placed, self.last_assignments
