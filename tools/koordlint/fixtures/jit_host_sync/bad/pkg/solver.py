"""Seeded known-BAD corpus for jit-host-sync: every construct here is a
silent device sync (or trace-time crash) inside a jitted closure.  The
self-test (tests/test_koordlint.py) asserts each marked line is flagged.
"""
import jax
import jax.numpy as jnp
import numpy as np


def _helper(scores, limit):
    # reachable from the jit root below: taint flows interprocedurally
    if scores.sum() > limit:          # BAD: data-dependent branch
        return scores * 2
    return scores


def solve(state, pods, k=8):
    total = jnp.sum(state)
    best = float(total)               # BAD: host cast of a traced value
    n = int(jnp.argmax(state))        # BAD: host cast of a traced value
    flag = bool(total > 0)            # BAD: host cast of a traced value
    host = np.asarray(pods)           # BAD: np materialization
    scalar = total.item()             # BAD: .item() device round-trip
    scores = _helper(state * pods, k)
    if total > 0:                     # BAD: data-dependent branch
        scores = scores + 1
    while jnp.any(scores > 0):        # BAD: data-dependent loop
        scores = scores - 1
    for row in scores:                # BAD: host iteration over traced
        pods = pods + row
    return scores, best, n, flag, host, scalar


solve_jit = jax.jit(solve, static_argnames=("k",))
