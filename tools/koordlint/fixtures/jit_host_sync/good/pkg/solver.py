"""Seeded known-GOOD corpus for jit-host-sync: host-static idioms the
analyzer must NOT flag (shape branches, static argnames, string-default
params, None checks, vararg unrolling, post-jit host reads)."""
import jax
import jax.numpy as jnp
import numpy as np


def combine(*masks):
    out = masks[0]
    for m in masks[1:]:               # ok: *args tuple unrolls statically
        out = out & m
    return out


def solve(state, pods, quota=None, k=8, method="auto", spread=(5, 15)):
    if method == "auto":              # ok: string compare is host-static
        method = "exact"
    if state.shape[0] > 64:           # ok: shape branch (bucketed jit)
        k = min(k, state.shape[0])
    if quota is None:                 # ok: pytree-None check is static
        quota = jnp.zeros_like(pods)
    splits = [k // 2, k - k // 2]
    parts = []
    for sb, k_i in zip(spread, splits):   # ok: host tuples
        if k_i == 0:                  # ok: host int branch
            continue
        parts.append(jnp.clip(state * sb, 0, k_i))
    mask = combine(pods > 0, state > 0)
    scores = jnp.where(mask, sum(parts), -1)
    return scores, quota


solve_jit = jax.jit(solve, static_argnames=("k",))


def caller(state, pods):
    # never passes method/spread: their defaults stay Python constants
    scores, quota = solve_jit(state, pods, k=4)
    total = float(np.asarray(scores).sum())  # ok: OUTSIDE the jit
    return total
