"""Seeded ISSUE-14 violation: pod-axis all-gather INSIDE the round loop
of a 2-D (pods x nodes) shard_map body — the pod batch re-gathers every
round instead of once before the loop."""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

NODES_AXIS = "nodes"
PODS_AXIS = "pods"


def _rounds2d_body(state, batch, *, rounds):
    def round_body(carry):
        i, acc = carry
        # BAD: the pod batch re-gathers over the pods axis EVERY round
        full = jax.lax.all_gather(batch, PODS_AXIS, axis=0, tiled=True)
        contrib = jax.lax.psum(state.sum() + full.sum(), NODES_AXIS)
        return i + 1, acc + contrib

    def cond(carry):
        return carry[0] < rounds

    _, acc = jax.lax.while_loop(cond, round_body, (0, jnp.int32(0)))
    return acc


def rounds2d(mesh, state, batch):
    fn = shard_map(partial(_rounds2d_body, rounds=4), mesh=mesh,
                   in_specs=(P(NODES_AXIS), P(PODS_AXIS)),
                   out_specs=P())
    return fn(state, batch)
