"""Seeded spec-consistency violations (must-flag corpus)."""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

NODES_AXIS = "nodes"


def _wrong_axis_body(x):
    # BAD: the enclosing site's specs only declare "nodes" live
    return jax.lax.psum(x, "pods")


def wrong_axis(mesh, x):
    fn = shard_map(_wrong_axis_body, mesh=mesh,
                   in_specs=(P(NODES_AXIS),), out_specs=P())
    return fn(x)


def _two_arg_body(a, b):
    return a, b


def arity_drift(mesh, a, b):
    # BAD: two positional body args, three in_specs entries — every
    # layout lands one position off
    fn = shard_map(_two_arg_body, mesh=mesh,
                   in_specs=(P(NODES_AXIS), P(), P()),
                   out_specs=(P(NODES_AXIS), P()))
    return fn(a, b)


def _three_out_body(x):
    return x, x, x


def out_arity_drift(mesh, x):
    # BAD: the body returns three values, out_specs declares two
    fn = shard_map(_three_out_body, mesh=mesh, in_specs=(P(NODES_AXIS),),
                   out_specs=(P(NODES_AXIS), P()))
    return fn(x)


def _diverging_body(rows, vals, *, n):
    # BAD: owner-local scatter into a replicated fresh buffer — each
    # shard writes only its own rows, the replicas silently diverge
    off = jax.lax.axis_index(NODES_AXIS) * rows.shape[0]
    return jnp.zeros(n).at[rows + off].add(vals)


def replicated_scatter(mesh, rows, vals, n):
    fn = shard_map(partial(_diverging_body, n=n), mesh=mesh,
                   in_specs=(P(), P()), out_specs=P())
    return fn(rows, vals)


def _identity_body(x):
    return x


def layout_mismatch(mesh, x):
    produce = shard_map(_identity_body, mesh=mesh,
                        in_specs=(P(NODES_AXIS),),
                        out_specs=(P(NODES_AXIS),))
    consume = shard_map(_identity_body, mesh=mesh,
                        in_specs=(P(),), out_specs=(P(),))
    part = produce(x)
    # BAD: part carries the node-sharded out layout but the next site
    # declares its position replicated
    return consume(part)
