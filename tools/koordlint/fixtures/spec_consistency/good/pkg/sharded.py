"""Spec-consistent twins of the bad corpus (must-pass)."""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

NODES_AXIS = "nodes"


def _nodes_body(x):
    off = jax.lax.axis_index(NODES_AXIS)
    return jax.lax.psum(x + off, NODES_AXIS)


def right_axis(mesh, x):
    fn = shard_map(_nodes_body, mesh=mesh,
                   in_specs=(P(NODES_AXIS),), out_specs=P())
    return fn(x)


def _two_arg_body(a, b):
    return a, b


def aligned_arity(mesh, a, b):
    fn = shard_map(_two_arg_body, mesh=mesh,
                   in_specs=(P(NODES_AXIS), P()),
                   out_specs=(P(NODES_AXIS), P()))
    return fn(a, b)


# koordlint: shape[st_local: NxR i32 nodes]
def _owner_scatter_body(st_local, rows, vals, *, n):
    # owner-local scatter into the SHARDED accounting: the legal idiom
    # (the annotation documents the layout the in_specs also declare)
    off = jax.lax.axis_index(NODES_AXIS) * rows.shape[0]
    return jnp.zeros_like(st_local).at[rows + off].add(vals)


def owner_scatter(mesh, st, rows, vals, n):
    fn = shard_map(partial(_owner_scatter_body, n=n), mesh=mesh,
                   in_specs=(P(NODES_AXIS), P(), P()),
                   out_specs=P(NODES_AXIS))
    return fn(st, rows, vals)


def _identity_body(x):
    return x


def matched_layouts(mesh, x):
    produce = shard_map(_identity_body, mesh=mesh,
                        in_specs=(P(NODES_AXIS),),
                        out_specs=(P(NODES_AXIS),))
    consume = shard_map(_identity_body, mesh=mesh,
                        in_specs=(P(NODES_AXIS),),
                        out_specs=(P(NODES_AXIS),))
    part = produce(x)
    return consume(part)
