"""The spec-consistent 2-D twin (must-pass): the pod batch gathers over
the pods axis ONCE, above the round loop; the loop itself only psums
node-owned contributions.  Exercises two-axis in/out-spec arity and
pod-axis collective liveness on a pods x nodes site."""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

NODES_AXIS = "nodes"
PODS_AXIS = "pods"


def _rounds2d_body(state, batch, *, rounds):
    # ONE pod-axis gather, before the loop (the _gather_pods idiom)
    full = jax.lax.all_gather(batch, PODS_AXIS, axis=0, tiled=True)

    def round_body(carry):
        i, acc = carry
        contrib = jax.lax.psum(state.sum() + full.sum(), NODES_AXIS)
        return i + 1, acc + contrib

    def cond(carry):
        return carry[0] < rounds

    _, acc = jax.lax.while_loop(cond, round_body, (0, jnp.int32(0)))
    return acc, full.sum()


def rounds2d(mesh, state, batch):
    fn = shard_map(partial(_rounds2d_body, rounds=4), mesh=mesh,
                   in_specs=(P(NODES_AXIS), P(PODS_AXIS)),
                   out_specs=(P(), P()))
    return fn(state, batch)
