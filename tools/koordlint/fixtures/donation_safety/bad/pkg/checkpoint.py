"""Seeded known-BAD corpus for donation-safety on the warm-restart
checkpoint path (ISSUE 17): the restore rebuilds the accounting pytree
from host rows, hands it to the donating repack solve — and then
serialises the SAME reference into the next checkpoint, a read of a
buffer that died when the call started.  ``RestoredState.restore`` adds
the construction-side hazard: one ``asarray`` buffer aliased across two
fields of the restored pytree.
"""
import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class RestoredState:
    requested: jax.Array
    allocatable: jax.Array

    @classmethod
    def restore(cls, rows):
        buf = jnp.asarray(rows)
        return cls(requested=buf, allocatable=buf)  # BAD: one buffer, 2 fields


def _repack(state, batch):
    return state


repack = jax.jit(_repack, donate_argnums=(0,))


class Restorer:
    """Warm-restart catch-up done WRONG: the delta replay donates the
    restored state into the repack solve, then the checkpoint writer
    reads the pre-call reference to build the next snapshot doc."""

    def __init__(self, state, batch):
        self.state = state
        self.batch = batch

    def catch_up(self):
        new = repack(self.state, self.batch)
        doc = {"requested": self.state.requested}  # BAD: read after donation
        self.state = new
        return doc
