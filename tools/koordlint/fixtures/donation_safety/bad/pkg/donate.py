"""Seeded known-BAD corpus for donation-safety.

``State.zeros`` reconstructs the PR-1 ``ClusterState.zeros`` bug
verbatim in miniature: one ``jnp.zeros`` buffer aliased across three
pytree fields, so the donating solve consumes them together.  The
caller below adds the two call-side hazards: reading a donated buffer
after the call, and passing the donated expression twice.
"""
import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class State:
    alloc: jax.Array
    used: jax.Array
    usage: jax.Array

    @classmethod
    def zeros(cls, n):
        z = jnp.zeros((n, 4), jnp.int32)
        return cls(alloc=z, used=z, usage=z)   # BAD: one buffer, 3 fields


def _solve(state, batch):
    return state


solve = jax.jit(_solve, donate_argnums=(0,))


class Scheduler:
    def __init__(self, state, batch):
        self.state = state
        self.batch = batch

    def round(self):
        new = solve(self.state, self.batch)
        stale = self.state + 1            # BAD: read after donation
        self.state = new
        return stale

    def aliased(self):
        return solve(self.state, self.state)  # BAD: donated arg aliased


class Pipeline:
    """Double-buffered round pipeline (ISSUE 11), done WRONG: the
    device half donates ``self.state`` at dispatch, then stashes the
    donated in-flight buffer on the handle "for the host half" — the
    buffer is dead the moment the call starts, and the host half will
    read garbage (or RuntimeError) when it commits."""

    def __init__(self, state, batch):
        self.state = state
        self.batch = batch
        self.inflight = None

    def dispatch(self):
        new = solve(self.state, self.batch)
        self.inflight = self.state   # BAD: stashes the donated buffer
        self.state = new
        return new
