"""Seeded known-GOOD corpus for donation-safety on the warm-restart
checkpoint path: the intended idioms — one fresh buffer per restored
pytree field, the checkpoint doc captured BEFORE the donating repack,
and the rebind-in-the-call-statement swap for the delta replay."""
import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class RestoredState:
    requested: jax.Array
    allocatable: jax.Array

    @classmethod
    def restore(cls, rows, caps):
        return cls(requested=jnp.asarray(rows),
                   allocatable=jnp.asarray(caps))  # one buffer per field


def _repack(state, batch):
    return state


repack = jax.jit(_repack, donate_argnums=(0,))


class Restorer:
    """Warm-restart catch-up, the blessed order: snapshot the doc from
    the live buffer first, then rebind ``self.state`` to the donating
    call's result in the call statement itself."""

    def __init__(self, state, batch):
        self.state = state
        self.batch = batch

    def catch_up(self):
        doc = {"requested": self.state.requested + 0}  # ok: read BEFORE
        self.state = repack(self.state, self.batch)    # ok: rebind idiom
        n = self.state.requested.shape[0]              # ok: NEW buffer
        return doc, n
