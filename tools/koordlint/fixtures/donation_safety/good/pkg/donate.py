"""Seeded known-GOOD corpus for donation-safety: the intended idioms —
one fresh buffer per pytree field, immediate rebind of the donated
name, metadata reads after donation, reads before the call."""
import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class State:
    alloc: jax.Array
    used: jax.Array
    usage: jax.Array

    @classmethod
    def zeros(cls, n):
        def z():
            return jnp.zeros((n, 4), jnp.int32)

        return cls(alloc=z(), used=z(), usage=z())  # one buffer per field


def _solve(state, batch):
    return state


solve = jax.jit(_solve, donate_argnums=(0,))


class Scheduler:
    def __init__(self, state, batch):
        self.state = state
        self.batch = batch

    def round(self):
        before = self.state + 0           # ok: read BEFORE the donation
        self.state = solve(self.state, self.batch)  # ok: rebind idiom
        n = self.state.shape[0]           # ok: reads the NEW buffer
        return before, n

    def rebind_local(self):
        state = self.state
        cap = state.shape                 # ok: metadata before
        state = solve(state, self.batch)  # ok: tuple-free rebind
        return state, cap


class Pipeline:
    """Double-buffered round pipeline (ISSUE 11), the blessed swap:
    the dispatch rebinds ``self.state`` to the donating call's result
    IN the call statement, so between the halves every reader sees the
    in-flight (live) buffer and the dead one is unreachable; the host
    half blocks on the handle's arrays, never the pre-dispatch state."""

    def __init__(self, state, batch):
        self.state = state
        self.batch = batch
        self.inflight = None

    def dispatch(self):
        self.state = solve(self.state, self.batch)  # the blessed swap
        self.inflight = self.state    # ok: references the NEW buffer
        return self.inflight

    def commit(self):
        done = self.inflight          # ok: the live in-flight result
        self.inflight = None
        return done
