"""Seeded tenant-axis violations (must-flag corpus)."""

import jax
import jax.numpy as jnp


def _pass1(state, batch):
    return state


class Kit:
    def __init__(self):
        # koordlint: shape[arg0: NxR i32 nodes]
        self.pass1 = jax.jit(_pass1, donate_argnums=(0,))


class Front:
    @staticmethod
    def _stack(trees):
        return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    @staticmethod
    def _unstack(tree, i):
        return jax.tree.map(lambda x: x[i], tree)

    def cycle(self, states, batches, tenants):
        stacked_state = self._stack(states)
        stacked_batch = self._stack(batches)
        a, st, est = self._batched(stacked_state, stacked_batch)
        for i, t in enumerate(tenants):
            # BAD: every adopted slice still carries the leading T axis
            t.scheduler.round_adopt_batched(a, st, est)
        return a

    def cycle_kit(self, states, batches, kit):
        stacked_state = self._stack(states)
        # BAD: the kit binding's shape annotation declares a per-tenant
        # arg0 but the call hands it the whole stacked tensor
        return kit.pass1(stacked_state, batches)

    # koordlint: shape[state: TxNxR i32]
    def adopt_annotated(self, state, tenants):
        # BAD: the T-leading annotated parameter is passed whole
        t = tenants[0]
        t.scheduler.round_adopt_batched(state)

    def _batched(self, state, batch):
        return state, batch, state
