"""Tenant-axis-disciplined twins of the bad corpus (must-pass)."""

import jax
import jax.numpy as jnp


def _pass1(state, batch):
    return state


class Kit:
    def __init__(self):
        # koordlint: shape[arg0: NxR i32 nodes]
        self.pass1 = jax.jit(_pass1, donate_argnums=(0,))


class Front:
    @staticmethod
    def _stack(trees):
        return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    @staticmethod
    def _unstack(tree, i):
        return jax.tree.map(lambda x: x[i], tree)

    def cycle(self, states, batches, tenants):
        stacked_state = self._stack(states)
        stacked_batch = self._stack(batches)
        a, st, est = self._batched(stacked_state, stacked_batch)
        for i, t in enumerate(tenants):
            # every slice explicitly reduced before the per-tenant sink
            t.scheduler.round_adopt_batched(
                self._unstack(a, i), self._unstack(st, i), est[i])
        return None

    def cycle_kit(self, states, batches, kit):
        for i, state in enumerate(states):
            # per-tenant dispatch feeds per-tenant shapes
            kit.pass1(state, batches[i])

    # koordlint: shape[state: TxNxR i32]
    def adopt_annotated(self, state, tenants):
        for i, t in enumerate(tenants):
            t.scheduler.round_adopt_batched(self._unstack(state, i))

    def unstack_inside_branch(self, states, handle, single):
        # the taint is discarded INSIDE the if body; the sink call that
        # follows must see the updated state, not the compound
        # statement's entry state
        a = self._stack(states)
        if single:
            a = self._unstack(a, 0)
            handle.scheduler.round_adopt_batched(handle, a)
        return a

    def _batched(self, state, batch):
        return state, batch, state
