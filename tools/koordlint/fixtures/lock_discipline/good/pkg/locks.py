"""Seeded known-GOOD corpus for lock-discipline: one-directional lock
nesting (no cycle), consistently-guarded writes, a caller-holds-the-lock
helper declared with guarded-by, and an RLock reentrancy self-call."""
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []
        self.count = 0

    def commit(self, item):
        with self._lock:
            self.items.append(item)
            self._bump_locked()

    # koordlint: guarded-by(self._lock)
    def _bump_locked(self):
        self.count = len(self.items)   # ok: caller holds the lock

    def reset(self):
        with self._lock:
            self.items = []
            self.count = 0


class Informer:
    """Acquisition order is one-directional: Informer -> Store only."""

    def __init__(self, store: Store):
        self.lock = threading.RLock()
        self.store = store
        self.rev = 0

    def push(self, item):
        with self.lock:
            self.rev += 1
            self.store.commit(item)    # ok: consistent outer->inner order

    def flush(self, items):
        with self.lock:
            for item in items:
                self.push(item)        # ok: RLock reentrancy, no self-edge
