"""Seeded known-GOOD corpus for lock-discipline on the checkpoint path:
the blessed one-way order — capture under the round lock, encode and
write OUTSIDE every lock — plus guarded-by declarations on the replay
cursor and the writer's counters."""
import threading


class RoundScheduler:
    def __init__(self):
        self.lock = threading.Lock()
        self.rv = 0   # koordlint: guarded-by(self.lock)

    def round(self):
        with self.lock:
            self.rv += 1

    def restore(self, doc):
        with self.lock:
            self.rv = doc["rv"]            # guarded, as declared

    def capture(self):
        with self.lock:
            return {"rv": self.rv}


class CheckpointWriter:
    """Capture borrows the scheduler's round lock, the file write
    happens lock-free: one global acquisition order, no reverse path."""

    def __init__(self, scheduler: RoundScheduler):
        self._lock = threading.Lock()
        self.scheduler = scheduler
        self.saves = 0

    def _record_locked(self):  # koordlint: guarded-by(self._lock)
        self.saves += 1

    def save_now(self):
        doc = self.scheduler.capture()     # round lock, then released
        with self._lock:
            self._record_locked()
        return doc
