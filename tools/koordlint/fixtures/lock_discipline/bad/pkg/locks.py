"""Seeded known-BAD corpus for lock-discipline: an A->B / B->A
lock-order cycle across two classes (deadlock candidate), and an
attribute written guarded in one method but bare in another (race
candidate)."""
import threading


class Informer:
    def __init__(self, store: "Store"):
        self._lock = threading.Lock()
        self.store = store

    def push(self, item):
        with self._lock:
            # BAD half of the cycle: Informer._lock -> Store._lock
            self.store.commit(item)

    def peek(self):
        with self._lock:
            return self.store


class Store:
    def __init__(self, informer: Informer):
        self._lock = threading.Lock()
        self.informer = informer
        self.items = []
        self.count = 0

    def commit(self, item):
        with self._lock:
            self.items.append(item)
            self.count = len(self.items)   # guarded write

    def rebuild(self):
        with self._lock:
            # BAD other half: Store._lock -> Informer._lock
            self.informer.push(None)

    def reset(self):
        self.count = 0                     # BAD: bare write (race)


class Combined:
    """Multi-item `with a, b:` acquires in sequence — its order edge
    must reverse-check against the nested acquisition in flip()."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def both(self, items):
        with self._a, self._b:             # BAD: a->b ...
            items.append(1)

    def flip(self, items):
        with self._b:
            with self._a:                  # BAD: ... while flip does b->a
                items.append(2)
