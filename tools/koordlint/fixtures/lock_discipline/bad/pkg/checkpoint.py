"""Seeded known-BAD corpus for lock-discipline on the checkpoint path
(ISSUE 17): the checkpoint writer and the round loop each take their own
lock and then call into the other — a writer-lock / round-lock order
cycle (deadlock candidate) — and the restore path writes the replay
cursor bare while the round loop writes it guarded (race candidate)."""
import threading


class RoundScheduler:
    def __init__(self, writer: "CheckpointWriter"):
        self.lock = threading.Lock()
        self.writer = writer
        self.rv = 0

    def round(self):
        with self.lock:
            self.rv += 1                   # guarded write
            # BAD half of the cycle: RoundScheduler.lock ->
            # CheckpointWriter._lock
            self.writer.flush({"rv": self.rv})

    def restore(self, doc):
        self.rv = doc["rv"]                # BAD: bare write (race)


class CheckpointWriter:
    def __init__(self, scheduler: RoundScheduler):
        self._lock = threading.Lock()
        self.scheduler = scheduler
        self.saves = 0

    def flush(self, doc):
        with self._lock:
            self.saves += 1

    def save_now(self):
        with self._lock:
            # BAD other half: CheckpointWriter._lock ->
            # RoundScheduler.lock (capture under the round lock while
            # still holding the writer lock)
            self.scheduler.round()
