"""Seeded-good corpus: round-scoped deltas and ledger-routed journeys."""

import time


class Binder:
    def __init__(self, ledger, histogram):
        self.ledger = ledger
        self.histogram = histogram

    def commit(self, binds, round_start):
        # GOOD: ONE round-scoped delta, however many pods the round
        # carried — not a per-pod measurement
        commit_t0 = time.perf_counter()
        for pod, node in binds:
            self.bind(pod, node)
        self.histogram.observe(time.perf_counter() - commit_t0)
        # GOOD: per-pod latency routed through the journey ledger
        self.ledger.record_bind_batch(
            "default", [pod for pod, _node in binds],
            round_start_perf=round_start, commit_perf=commit_t0)

    def enqueue(self, pod):
        # GOOD: stamping (no subtraction) is how stamps reach the ledger
        self.ledger.note_enqueue(pod.name, getattr(pod, "arrival_ts", 0.0))

    def bind(self, pod, node):
        pass
