"""Seeded-bad corpus: ad-hoc per-pod latency deltas outside the homes."""

import time


class Binder:
    def __init__(self):
        self.enqueue_ts = {}
        self.latency = {}

    def commit(self, binds):
        # BAD: clock delta inside a per-pod loop — an inline latency
        # ledger with no merge, no kill switch, a syscall per pod
        for pod, node in binds:
            waited = time.perf_counter() - self.enqueue_ts[pod.name]
            self.latency[pod.name] = waited

    def sweep(self, pending):
        t0 = time.time()
        for name in pending:
            # BAD: tainted stamp subtracted per pod
            age = t0 - self.enqueue_ts[name]
            if age > 30.0:
                print(name, age)

    def stamp(self, pod, started):
        # BAD: per-pod keyed store of a clock delta (no loop needed)
        self.latency[pod.name] = time.time() - started
