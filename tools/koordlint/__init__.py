"""koordlint: repo-native static analysis for the invariants generic
linters cannot see — jit purity, buffer-donation safety, lock
discipline, debug-surface parity, dashboard drift, and test-marker
conventions.  ``python -m tools.koordlint`` runs the whole suite; see
docs/static_analysis.md for the rule catalog and suppression policy.
"""

from __future__ import annotations

import os

from .analyzers import ALL_ANALYZERS, make_all
from .core import (
    Analyzer,
    Finding,
    Project,
    RunResult,
    apply_suppressions,
    load_baseline,
)

#: the shipped baseline (suppressions with reasons)
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


def run(root: str, rules: list[str] | None = None,
        baseline_path: str | None = BASELINE_PATH,
        only_paths: set[str] | None = None) -> RunResult:
    """Run the suite over a repo root and apply suppressions.

    ``rules`` filters analyzers by name; ``baseline_path=None`` skips
    the baseline (raw findings — what ``--no-baseline`` shows).

    ``only_paths`` (repo-relative, forward slashes) restricts the
    REPORTED findings to the given files — the ``--changed-only`` fast
    path.  Analysis (and the call graph the interprocedural rules seed
    from) still runs whole-tree, so a change in one file that breaks an
    invariant in another is attributed to whichever file holds the
    finding; baseline staleness stays computed against the full set.
    """
    project = Project(root)
    analyzers = [a for a in make_all()
                 if rules is None or a.name in rules]
    findings: list[Finding] = []
    for analyzer in analyzers:
        findings.extend(analyzer.run(project))
    for path, sf in sorted(project.files.items()):
        if sf.parse_error:
            findings.append(Finding("lint-hygiene", path, 1,
                                    f"file does not parse: "
                                    f"{sf.parse_error}", ""))
    baseline, hygiene = ([], []) if baseline_path is None else (
        load_baseline(baseline_path))
    if rules is not None:
        # a filtered run only consults (and staleness-checks) the
        # entries of the rules that actually ran
        baseline = [e for e in baseline if e.rule in rules]
    result = apply_suppressions(project, findings, baseline)
    result.findings.extend(hygiene)
    if only_paths is not None:
        result.findings = [f for f in result.findings
                           if f.path in only_paths]
    return result


__all__ = ["run", "Project", "Finding", "Analyzer", "RunResult",
           "ALL_ANALYZERS", "make_all", "apply_suppressions",
           "load_baseline", "BASELINE_PATH"]
