"""Analyzer registry: the rule catalog ``python -m tools.koordlint``
runs (docs/static_analysis.md documents each rule + how to add one)."""

from __future__ import annotations

from .dashboard_drift import DashboardDriftAnalyzer
from .donation_flow import DonationFlowAnalyzer
from .donation_safety import DonationSafetyAnalyzer
from .dtype_regime import DtypeRegimeAnalyzer
from .jit_host_sync import JitHostSyncAnalyzer
from .latency_home import LatencyHomeAnalyzer
from .lock_discipline import LockDisciplineAnalyzer
from .marker_audit import MarkerAuditAnalyzer
from .mesh_discipline import MeshDisciplineAnalyzer
from .spec_consistency import SpecConsistencyAnalyzer
from .surface_parity import SurfaceParityAnalyzer
from .tenant_axis import TenantAxisAnalyzer
from .wire_codec import WireCodecAnalyzer

ALL_ANALYZERS = (
    JitHostSyncAnalyzer,
    DonationSafetyAnalyzer,
    LockDisciplineAnalyzer,
    SurfaceParityAnalyzer,
    DashboardDriftAnalyzer,
    MarkerAuditAnalyzer,
    MeshDisciplineAnalyzer,
    # specflow dataflow rules (ISSUE 12)
    SpecConsistencyAnalyzer,
    DtypeRegimeAnalyzer,
    DonationFlowAnalyzer,
    TenantAxisAnalyzer,
    # protocol v4 columnar codec (ISSUE 19)
    WireCodecAnalyzer,
    # pod-journey ledger (ISSUE 20)
    LatencyHomeAnalyzer,
)


def make_all() -> list:
    return [cls() for cls in ALL_ANALYZERS]
