"""surface-parity: the two debug surfaces must expose the same /debug API.

The scheduler serves its debug endpoints twice — on the transport-
agnostic :class:`DebugService` (``scheduler/services.py``) and on the
HTTP gateway (``transport/http_gateway.py``).  PR 6 had to hand-audit
the two after they drifted; this analyzer turns the audit into a lint:

- every exact ``/debug/<x>`` route registered on the DebugService
  (``self.register("/debug/x", ...)``) must appear as a ``path ==
  "/debug/x"`` dispatch in the gateway's ``_route``, and vice versa;
- every prefix route (``self.register_prefix("/debug/x/", ...)``) must
  have a matching gateway regex (``re.compile(r"^/debug/x/(.+)$")``),
  and vice versa;
- each ``/debug/<x>`` route must be served through the ONE shared
  body builder ``debug_<x>_body`` on BOTH surfaces (the convention that
  makes drift structurally impossible) — a surface that hand-rolls its
  own body is flagged;
- a builder that raises :class:`DebugApiError` (typed statuses) must be
  called under an ``except DebugApiError`` mapping on the gateway side,
  and the DebugService ``handle`` dispatcher must map it too — so both
  surfaces serve the same status + body for the same failure.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ..core import Analyzer, Finding, Project

SERVICES_PATH = "koordinator_tpu/scheduler/services.py"
GATEWAY_PATH = "koordinator_tpu/transport/http_gateway.py"

_PREFIX_RX = re.compile(r"\^(/debug/[\w/-]+/)\(")


class SurfaceParityAnalyzer(Analyzer):
    name = "surface-parity"
    description = ("DebugService vs HTTP-gateway /debug route and "
                   "typed-error parity")

    def __init__(self, services_path: str = SERVICES_PATH,
                 gateway_path: str = GATEWAY_PATH):
        self.services_path = services_path
        self.gateway_path = gateway_path

    def run(self, project: Project) -> list[Finding]:
        svc = project.get(self.services_path)
        gw = project.get(self.gateway_path)
        if svc is None or gw is None or svc.tree is None or gw.tree is None:
            return []
        findings: list[Finding] = []

        s_exact, s_prefix, s_line = self._service_routes(svc.tree)
        g_exact, g_prefix, g_line = self._gateway_routes(gw.tree)
        builders = self._builders(svc.tree)

        for route in sorted(s_exact - g_exact):
            findings.append(Finding(
                "surface-parity", gw.path, 1,
                f"DebugService serves {route!r} but the HTTP gateway has "
                "no matching dispatch",
                f"add `if method == \"GET\" and path == \"{route}\":` to "
                "HttpGateway._route"))
        for route in sorted(g_exact - s_exact):
            findings.append(Finding(
                "surface-parity", svc.path, 1,
                f"HTTP gateway serves {route!r} but DebugService never "
                "registers it",
                f"register(\"{route}\", ...) in _register_builtin"))
        for route in sorted(s_prefix - g_prefix):
            findings.append(Finding(
                "surface-parity", gw.path, 1,
                f"DebugService serves prefix {route!r} but the gateway "
                "has no matching regex route",
                f"add re.compile(r\"^{route}(.+)$\") dispatch"))
        for route in sorted(g_prefix - s_prefix):
            findings.append(Finding(
                "surface-parity", svc.path, 1,
                f"HTTP gateway serves prefix {route!r} but DebugService "
                "never registers it",
                f"register_prefix(\"{route}\", ...) in _register_builtin"))

        # shared-builder + typed-error parity per route on BOTH surfaces
        svc_refs = self._builder_refs_by_method(svc.tree)
        gw_refs = self._builder_refs_by_method(gw.tree)
        for route in sorted((s_exact | g_exact | s_prefix | g_prefix)):
            expected = "debug_{}_body".format(
                route[len("/debug/"):].strip("/").replace("/", "_"))
            if expected not in builders:
                findings.append(Finding(
                    "surface-parity", svc.path,
                    s_line.get(route) or g_line.get(route, 1),
                    f"route {route!r} has no shared builder "
                    f"{expected}() in scheduler/services.py",
                    "both surfaces must serve one body builder so they "
                    "cannot drift"))
                continue
            raises = builders[expected]
            for side, refs, sf, line_map in (
                    ("DebugService", svc_refs, svc, s_line),
                    ("HTTP gateway", gw_refs, gw, g_line)):
                using = [m for m, names in refs.items() if expected in names]
                if (route in (s_exact | s_prefix
                              if side == "DebugService"
                              else g_exact | g_prefix) and not using):
                    findings.append(Finding(
                        "surface-parity", sf.path, line_map.get(route, 1),
                        f"{side} serves {route!r} without calling the "
                        f"shared builder {expected}()",
                        "hand-rolled bodies drift; call the builder"))
            if raises:
                for m in [m for m, names in gw_refs.items()
                          if expected in names]:
                    if not self._catches_debug_api_error(gw.tree, m):
                        findings.append(Finding(
                            "surface-parity", gw.path,
                            g_line.get(route, 1),
                            f"{expected}() raises DebugApiError but "
                            f"gateway handler {m}() does not map it "
                            "(typed status would become a blanket 500)",
                            "wrap the call in try/except DebugApiError "
                            "and reply e.status"))
        if not self._catches_debug_api_error(svc.tree, "handle"):
            findings.append(Finding(
                "surface-parity", svc.path, 1,
                "DebugService.handle does not map DebugApiError to a "
                "typed status",
                "except DebugApiError as e: return e.status, ..."))
        return findings

    # -- extraction -----------------------------------------------------------

    def _service_routes(self, tree) -> tuple[set, set, dict]:
        exact: set[str] = set()
        prefix: set[str] = set()
        lines: dict[str, int] = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("register", "register_prefix")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            route = node.args[0].value
            if not route.startswith("/debug/"):
                continue
            (prefix if node.func.attr == "register_prefix"
             else exact).add(route)
            lines[route] = node.lineno
        return exact, prefix, lines

    def _gateway_routes(self, tree) -> tuple[set, set, dict]:
        exact: set[str] = set()
        prefix: set[str] = set()
        lines: dict[str, int] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Compare):
                for side in [node.left] + node.comparators:
                    if (isinstance(side, ast.Constant)
                            and isinstance(side.value, str)
                            and side.value.startswith("/debug/")):
                        exact.add(side.value)
                        lines[side.value] = node.lineno
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "compile" and node.args
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, str)):
                m = _PREFIX_RX.search(node.args[0].value)
                if m:
                    prefix.add(m.group(1))
                    lines[m.group(1)] = node.lineno
        return exact, prefix, lines

    def _builders(self, tree) -> dict[str, bool]:
        """Module-level ``debug_*_body`` builders -> raises DebugApiError?"""
        out: dict[str, bool] = {}
        for node in tree.body:
            if (isinstance(node, ast.FunctionDef)
                    and re.fullmatch(r"debug_\w+_body", node.name)):
                raises = any(
                    isinstance(n, ast.Raise) and n.exc is not None
                    and "DebugApiError" in ast.dump(n.exc)
                    for n in ast.walk(node))
                out[node.name] = raises
        return out

    def _builder_refs_by_method(self, tree) -> dict[str, set[str]]:
        """method name -> set of debug_*_body names it references."""
        out: dict[str, set[str]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            names = {n.id for n in ast.walk(node)
                     if isinstance(n, ast.Name)
                     and re.fullmatch(r"debug_\w+_body", n.id)}
            if names:
                out[node.name] = names
        return out

    def _catches_debug_api_error(self, tree, method: str) -> bool:
        fn: Optional[ast.FunctionDef] = None
        for node in ast.walk(tree):
            if (isinstance(node, ast.FunctionDef)
                    and node.name == method):
                fn = node
                break
        if fn is None:
            return False
        for node in ast.walk(fn):
            if isinstance(node, ast.ExceptHandler) and node.type is not None:
                if "DebugApiError" in ast.dump(node.type):
                    return True
        return False
