"""mesh-discipline: shard_map/pjit spec hygiene + capacity-guard locality.

Two invariants of the sharded solve path (ISSUE 10):

- **explicit specs at every shard_map/pjit site.**  A ``shard_map``
  without explicit ``in_specs``/``out_specs`` (or a ``pjit`` without
  ``in_shardings``/``out_shardings``) leaves placement to inference —
  exactly the ambiguity that silently turns an in-place donated update
  into a cross-device reshard-and-copy.  Additionally, when such a site
  is wrapped DIRECTLY in a donating ``jax.jit(..., donate_argnums=...)``,
  every donated position must have an explicit, non-``None`` entry in a
  literal ``in_specs`` tuple: a donated buffer whose spec is inferred
  can legally come back with a different layout, and the aliasing
  quietly degrades to a copy.
- **the node-capacity guard lives in one place.**  A raw
  ``check_node_capacity`` call outside ``ops/batch_assign.py`` is a
  finding: the ranking-key ceiling is enforced inside the key
  computation itself (``_rank_parts``), and scattered re-guards drift
  when the ceiling moves (the 32,768 wall removed by ISSUE 10 was
  exactly such a constant).  The rule scopes to the package — tests
  asserting the guard's behavior are exempt by path.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Analyzer, Finding, Project

#: callables treated as SPMD entry sites, with their spec kwarg names
_SPMD_SITES = {
    "shard_map": ("in_specs", "out_specs"),
    "pjit": ("in_shardings", "out_shardings"),
}


def _tail_name(node: ast.expr) -> Optional[str]:
    """'shard_map' for both ``shard_map(...)`` and ``x.y.shard_map(...)``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _literal_ints(node: ast.expr) -> Optional[list[int]]:
    """[0, 1] from a literal int tuple/list/constant, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return out
    return None


class MeshDisciplineAnalyzer(Analyzer):
    name = "mesh-discipline"
    description = ("shard_map/pjit sites must declare in/out specs "
                   "(explicit per donated argument); the node-capacity "
                   "guard stays in ops/batch_assign")

    #: module that OWNS check_node_capacity (calls there are the guard
    #: itself, not a re-guard)
    def __init__(self, package: str = "koordinator_tpu",
                 capacity_home: tuple[str, ...] = (
                     "koordinator_tpu/ops/batch_assign.py",)):
        self.package = package
        self.capacity_home = capacity_home

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for path, sf in sorted(project.files.items()):
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                callee = _tail_name(node.func)
                if callee in _SPMD_SITES:
                    findings.extend(self._check_specs(path, node, callee))
                elif callee == "jit":
                    findings.extend(self._check_donated(path, node))
                elif (callee == "check_node_capacity"
                      and path.startswith(self.package + "/")
                      and path not in self.capacity_home):
                    findings.append(Finding(
                        self.name, path, node.lineno,
                        "raw check_node_capacity call outside "
                        "ops/batch_assign: the ranking-key ceiling is "
                        "enforced inside the key computation "
                        "(_rank_parts) and scattered re-guards drift "
                        "when the ceiling moves",
                        hint="call the select/refresh entry points and "
                             "let batch_assign own the guard"))
        return findings

    def _check_specs(self, path: str, call: ast.Call,
                     callee: str) -> list[Finding]:
        in_name, out_name = _SPMD_SITES[callee]
        missing = [name for name in (in_name, out_name)
                   if _kw(call, name) is None]
        if not missing:
            return []
        return [Finding(
            self.name, path, call.lineno,
            f"{callee} site omits {' and '.join(missing)}: placement "
            "left to inference can silently reshard (and break donation "
            "aliasing) instead of running the declared layout",
            hint=f"declare {in_name}= and {out_name}= explicitly at "
                 "every SPMD entry")]

    def _check_donated(self, path: str, call: ast.Call) -> list[Finding]:
        """jax.jit(shard_map(...), donate_argnums=...) sites: every
        donated position needs an explicit non-None in_specs entry."""
        donate = _kw(call, "donate_argnums")
        if donate is None or not call.args:
            return []
        inner = call.args[0]
        if not (isinstance(inner, ast.Call)
                and _tail_name(inner.func) in _SPMD_SITES):
            return []
        in_name = _SPMD_SITES[_tail_name(inner.func)][0]
        specs = _kw(inner, in_name)
        donated = _literal_ints(donate)
        if donated is None:
            return []
        if not isinstance(specs, (ast.Tuple, ast.List)):
            # absent in_specs is already a finding from _check_specs; a
            # non-literal spec expression is unverifiable here
            return []
        findings = []
        for pos in donated:
            spec = (specs.elts[pos] if 0 <= pos < len(specs.elts)
                    else None)
            if spec is None or (isinstance(spec, ast.Constant)
                                and spec.value is None):
                findings.append(Finding(
                    self.name, path, call.lineno,
                    f"donated argument {pos} has no explicit in_spec: "
                    "an inferred layout can come back different and "
                    "silently degrade the in-place donation to a copy",
                    hint=f"give {in_name} a literal entry (e.g. "
                         "P('nodes')) for every donated position"))
        return findings
