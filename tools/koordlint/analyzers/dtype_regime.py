"""dtype-regime: an interval proof over the packed int32 ranking key.

The batched solver packs (quantized score, rotated tie-break) into ONE
int32 — ``(q << _TB_BITS) | tb`` — and PR 10 split the key into a
packed regime (node capacity ≤ 2**15) and a wide two-operand regime
precisely because the tie-break field silently overflows its 15-bit
width past that wall.  Today that split is only guarded by runtime
convention; this rule makes it a CHECKED invariant, proved by the
specflow interval interpreter on every analysis run:

- **shift-overflow** — every ``a << s`` in the target modules must have
  a provable result within int32.  ``jnp.clip``/``%``/``min``/``max``
  bounds, module constants (``_SCORE_CLIP``), ``# koordlint: shape``
  parameter seeds and depth-limited helper inlining feed the proof; an
  UNPROVABLE shift is a finding, because an unbounded operand is
  exactly how the next 2**15-class wall ships.
- **field-collision** — every packed composition ``(a << C) | b`` must
  prove ``b ∈ [0, 2**C)``: the tie-break may not bleed into the score
  bits.  The proof typically goes through a ``_packed_regime(n_total)``
  guard: the engine refines ``n_total ≤ PACKED_NODE_CAPACITY`` in the
  guarded branch and the ``% n_total`` provenance carries the bound to
  the or-site — remove the guard and the rule fails the build (the
  demonstration test in tests/test_koordlint.py does exactly that to
  the real ops/batch_assign.py).
- **contract check** — a function annotated with ``retN`` ranges must
  provably stay inside them (callers consume the annotation as a seed,
  so a violated contract would poison downstream proofs silently).

Multiplication/addition overflow is out of scope (the ranking keys are
built from shifts and ors; ``*``/``+`` bounds over unknown pod counts
would drown the rule in noise).  Scoped to the ranking-key modules.
"""

from __future__ import annotations

import ast

from ..callgraph import get_index
from ..core import Analyzer, Finding, Project
from ..specflow.domain import INT32_MAX, INT32_MIN, Interval
from ..specflow.engine import (
    FlowInterpreter,
    module_consts,
    shape_seeds_for,
)


class DtypeRegimeAnalyzer(Analyzer):
    name = "dtype-regime"
    description = ("interval proof that packed int32 ranking-key "
                   "arithmetic cannot overflow and tie-break fields "
                   "stay below the 2**15 regime wall")

    def __init__(self, package: str = "koordinator_tpu",
                 targets: tuple[str, ...] = (
                     "koordinator_tpu/ops/batch_assign.py",)):
        self.package = package
        self.targets = targets

    def run(self, project: Project) -> list[Finding]:
        index = get_index(project, self.package)
        findings: list[Finding] = []
        seen: set[tuple[str, int, str]] = set()

        def emit(f: Finding) -> None:
            k = (f.path, f.line, f.message[:60])
            if k not in seen:
                seen.add(k)
                findings.append(f)

        for mod, sf in sorted(index.modules.items()):
            if sf.path not in self.targets or sf.tree is None:
                continue
            consts = module_consts(index, mod)

            def on_lshift(node, a, s, refin, _sf=sf):
                # magnitude bound: |a| << s_max must stay inside int32
                # (negative operands overflow toward INT32_MIN)
                if a.hi is None or a.lo is None or s.hi is None:
                    hi = lo = None
                else:
                    hi = max(a.hi, 0) << s.hi
                    lo = -((-min(a.lo, 0)) << s.hi)
                if hi is None or lo is None:
                    emit(Finding(
                        self.name, _sf.path, node.lineno,
                        "left-shift operand has no provable bound: the "
                        "packed ranking key cannot be proven to fit "
                        "int32",
                        hint="bound the operand (jnp.clip / % / guard) "
                             "or seed it with a `# koordlint: "
                             "shape[x: ... lo..hi]` annotation"))
                elif hi > INT32_MAX or lo < INT32_MIN:
                    emit(Finding(
                        self.name, _sf.path, node.lineno,
                        f"left-shift can reach {max(hi, -lo)} "
                        f"(> int32 max {INT32_MAX}): packed ranking-key "
                        "arithmetic overflows",
                        hint="tighten the clip / quantization so the "
                             "shifted field fits below bit 31"))

            def on_packed_or(node, width, field, refin, _sf=sf):
                f_hi = field.hi_under(refin)
                f_lo = field.lo_under(refin)
                if f_hi is None or f_lo is None:
                    emit(Finding(
                        self.name, _sf.path, node.lineno,
                        f"tie-break field of a packed `(x << {width}) | "
                        "field` key has no provable bound: past the "
                        f"2**{width} regime wall it silently corrupts "
                        "the score bits",
                        hint="gate the packed composition behind "
                             "_packed_regime(n_total) (the wide regime "
                             "carries the tie-break separately)"))
                elif f_hi >= (1 << width) or f_lo < 0:
                    emit(Finding(
                        self.name, _sf.path, node.lineno,
                        f"tie-break field can reach {f_hi} but the "
                        f"packed key reserves only {width} bits "
                        f"(< {1 << width}): the field bleeds into the "
                        "score and ranking aliases",
                        hint="bound the field below the regime wall or "
                             "route these shapes to the wide regime"))

            # module-level constant expressions get the shift check too
            top = FlowInterpreter(index, mod, consts,
                                  on_lshift=on_lshift,
                                  on_packed_or=on_packed_or)
            for stmt in sf.tree.body:
                if isinstance(stmt, ast.Assign):
                    top.eval(stmt.value, {}, {})

            for fq, fn in sorted(index.functions.items()):
                if fn.sf is not sf:
                    continue
                interp = FlowInterpreter(index, mod, consts,
                                         on_lshift=on_lshift,
                                         on_packed_or=on_packed_or)
                interp.run(fn)
                findings.extend(self._check_contracts(fn, interp, emit))
        return sorted(findings, key=lambda f: (f.path, f.line))

    def _check_contracts(self, fn, interp: FlowInterpreter, emit) -> list:
        """Declared retN ranges are promises callers consume as seeds:
        a provable violation is a finding (unprovable stays silent —
        the annotation remains a trusted hint, as documented)."""
        seeds = shape_seeds_for(fn.sf, fn.node)
        declared = {int(k[3:]): s.interval for k, s in seeds.items()
                    if k.startswith("ret") and k[3:].isdigit()
                    and s.interval is not None}
        if not declared:
            return []
        for node, val, refin in interp.returns:
            vals = val if isinstance(val, tuple) else (val,)
            for i, d in declared.items():
                if i >= len(vals) or not isinstance(vals[i], Interval):
                    continue
                hi = vals[i].hi_under(refin)
                lo = vals[i].lo_under(refin)
                if (hi is not None and d.hi is not None and hi > d.hi) \
                        or (lo is not None and d.lo is not None
                            and lo < d.lo):
                    emit(Finding(
                        self.name, fn.sf.path, node.lineno,
                        f"{fn.qualname} returns ret{i} in "
                        f"[{lo}, {hi}] but its shape annotation "
                        f"declares [{d.lo}, {d.hi}]: callers seed "
                        "their proofs from the annotation",
                        hint="fix the annotation or the computation — "
                             "a stale contract poisons downstream "
                             "interval proofs"))
        return []
