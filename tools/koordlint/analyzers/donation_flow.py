"""donation-flow: the double-buffer hand-off, verified interprocedurally.

PR 11 split the round into device/host halves with a donation-based
hand-off: the dispatched solve DONATES ``snapshot.state``'s buffers and
the snapshot must be re-pointed at the returned in-flight arrays before
anything else reads it — the *blessed swap*.  The existing
donation-safety rule polices single-function idioms only; this rule
runs the specflow dataflow over the whole call graph:

- **binding resolution through the kit.**  Donating jit bindings are
  found not just at ``self._x = jax.jit(...)`` sites but through typed
  attributes (``self.kit = SolverKit(...)`` ⇒ ``self._pass1 =
  self.kit.pass1`` inherits SolverKit.pass1's donate_argnums), local
  aliases (``pass1_fn = self._pass1_sh if use_mesh else self._pass1``
  donates the union), and factory summaries (a function whose return
  value is a donating jit — tenancy's ``_batched_fn`` — makes
  ``fn = self._batched_fn(key); fn(state, ...)`` a donating call).
- **⊥ after dispatch.**  A donated argument path's abstract value
  becomes ⊥ (dead) at the call; a *store* to the same path (the blessed
  swap) revives it.  Any load of a dead path — directly, or through a
  **stash alias** captured before the dispatch (``old =
  self.snapshot.state`` … ``dispatch()`` … ``old.sum()``) — is a
  finding.  A stash stays dead even after the swap: the name still
  points at the consumed buffer.
- **interprocedural summaries.**  Each function summarizes which
  ``self.*`` paths it kills (donates without re-storing before exit)
  and which it reads before storing; a caller that invokes a killing
  method and then a reading method (or reads directly) is a finding at
  the reading site.  Summaries reach a fixpoint in a few passes over
  the call graph.

Source-order linearization (like donation-safety): exception edges and
loop-carried reads are out of scope; ``.shape``-class metadata reads
survive donation and are exempt.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from ..callgraph import FunctionInfo, ModuleIndex, extract_jit_sites, get_index
from ..core import Analyzer, Finding, Project
from .donation_safety import dotted_path
from .jit_host_sync import HOST_SAFE_ATTRS

#: attribute probes that are ABOUT deadness (the recovery path's
#: `leaf.is_deleted()` check) — reading them is not consuming the buffer
_DEADNESS_PROBES = {"is_deleted"}

_FIXPOINT_PASSES = 4


@dataclasses.dataclass
class Summary:
    """Per-function donation facts over canonical ``self.*`` paths."""

    kills: frozenset[str] = frozenset()        # dead at exit
    reads_first: frozenset[str] = frozenset()  # read before any store
    stores_first: frozenset[str] = frozenset()  # stored before any read


class DonationFlowAnalyzer(Analyzer):
    name = "donation-flow"
    description = ("interprocedural double-buffer verification: a "
                   "donated buffer is dead until the blessed swap; "
                   "stashes and cross-function reads are findings")

    def __init__(self, package: str = "koordinator_tpu"):
        self.package = package

    # -- binding discovery ----------------------------------------------------

    def _attr_classes(self, index: ModuleIndex) -> dict[tuple[str, str], str]:
        """``(module.Class, attr) -> attribute's class fq`` from
        ``self.X = ClassName(...)`` in ``__init__`` (ternary arms
        included) — the typed-attribute resolution lock-discipline
        already uses, rebuilt here for donation bindings."""
        out: dict[tuple[str, str], str] = {}
        for fq, fn in index.functions.items():
            if not fq.endswith(".__init__"):
                continue
            cls = fq[: -len(".__init__")]
            for node in ast.walk(fn.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"):
                    continue
                attr = node.targets[0].attr
                values = [node.value]
                if isinstance(node.value, ast.IfExp):
                    values = [node.value.body, node.value.orelse]
                for v in values:
                    if isinstance(v, ast.Call):
                        target = index.resolve(fn.module, v.func)
                        if target in index.classes:
                            out[(cls, attr)] = target
        return out

    def _collect_bindings(self, index: ModuleIndex):
        """(class_bindings, name_bindings, factory_returns): donated
        positions per binding, plus functions returning donating jits."""
        class_bindings: dict[tuple[str, str], tuple[int, ...]] = {}
        name_bindings: dict[str, tuple[int, ...]] = {}
        for s in extract_jit_sites(index):
            if not s.donate_argnums:
                continue
            if s.binding and s.binding_class:
                key = (f"{s.module}.{s.binding_class}", s.binding)
                class_bindings[key] = tuple(sorted(
                    set(class_bindings.get(key, ()) + s.donate_argnums)))
            elif s.binding:
                name_bindings[f"{s.module}.{s.binding}"] = s.donate_argnums

        attr_cls = self._attr_classes(index)
        # attribute-to-attribute aliases: self._pass1 = self.kit.pass1
        # (two passes so a chain through one alias level resolves)
        for _ in range(2):
            for fq, fn in index.functions.items():
                if not fq.endswith(".__init__"):
                    continue
                cls = fq[: -len(".__init__")]
                for node in ast.walk(fn.node):
                    if not (isinstance(node, ast.Assign)
                            and len(node.targets) == 1
                            and isinstance(node.targets[0], ast.Attribute)
                            and isinstance(node.targets[0].value, ast.Name)
                            and node.targets[0].value.id == "self"
                            and isinstance(node.value, ast.Attribute)
                            and isinstance(node.value.value,
                                           ast.Attribute)
                            and isinstance(node.value.value.value,
                                           ast.Name)
                            and node.value.value.value.id == "self"):
                        continue
                    via = attr_cls.get((cls, node.value.value.attr))
                    if via is None:
                        continue
                    donated = class_bindings.get((via, node.value.attr))
                    if donated:
                        key = (cls, node.targets[0].attr)
                        class_bindings[key] = tuple(sorted(
                            set(class_bindings.get(key, ()) + donated)))

        # factory summaries: `fn = jax.jit(..., donate_argnums=...)` +
        # `return fn` makes the function a donating-callable factory
        factory: dict[str, tuple[int, ...]] = {}
        for fq, fn in index.functions.items():
            local_jits: dict[str, tuple[int, ...]] = {}
            for node in ast.walk(fn.node):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    d = self._jit_donate(index, fn.module, node.value)
                    if d:
                        local_jits[node.targets[0].id] = d
            if not local_jits:
                continue
            for node in ast.walk(fn.node):
                if (isinstance(node, ast.Return)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in local_jits):
                    factory[fq] = tuple(sorted(set(
                        factory.get(fq, ())
                        + local_jits[node.value.id])))
        return class_bindings, name_bindings, factory, attr_cls

    def _jit_donate(self, index, mod, node) -> tuple[int, ...]:
        """donate_argnums of a (possibly wrapped) jax.jit expression."""
        for call in ast.walk(node) if isinstance(node, ast.AST) else []:
            if isinstance(call, ast.Call) and (
                    index.resolve(mod, call.func) == "jax.jit"):
                for kw in call.keywords:
                    if kw.arg == "donate_argnums":
                        if isinstance(kw.value, ast.Constant) \
                                and isinstance(kw.value.value, int):
                            return (kw.value.value,)
                        if isinstance(kw.value, (ast.Tuple, ast.List)):
                            return tuple(
                                e.value for e in kw.value.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int))
        return ()

    # -- the analysis ---------------------------------------------------------

    def run(self, project: Project) -> list[Finding]:
        index = get_index(project, self.package)
        (self._class_b, self._name_b, self._factory,
         self._attr_cls) = self._collect_bindings(index)
        if not (self._class_b or self._name_b or self._factory):
            return []
        summaries: dict[str, Summary] = {}
        findings: list[Finding] = []
        for i in range(_FIXPOINT_PASSES):
            new: dict[str, Summary] = {}
            last = i == _FIXPOINT_PASSES - 1
            out = findings if last else []
            for fq, fn in sorted(index.functions.items()):
                new[fq] = self._scan(index, fn, summaries,
                                     out if last else None)
            if new == summaries:
                if not last:
                    # stable early: one reporting pass and stop
                    for fq, fn in sorted(index.functions.items()):
                        self._scan(index, fn, summaries, findings)
                break
            summaries = new
        dedup: dict[tuple, Finding] = {}
        for f in findings:
            dedup.setdefault((f.path, f.line, f.message), f)
        return sorted(dedup.values(), key=lambda f: (f.path, f.line))

    def _donated_positions(self, index, fn, cls, call,
                           local_callables) -> tuple[int, ...]:
        f = call.func
        if isinstance(f, ast.Name) and f.id in local_callables:
            return local_callables[f.id]
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and cls):
            return self._class_b.get((f"{fn.module}.{cls}", f.attr), ())
        resolved = index.resolve(fn.module, f)
        if resolved:
            if "." not in resolved:
                resolved = f"{fn.module}.{resolved}"
            return self._name_b.get(resolved, ())
        return ()

    def _callee_fq(self, index, fn, cls, call) -> Optional[str]:
        f = call.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and cls):
            return f"{fn.module}.{cls}.{f.attr}"
        resolved = index.resolve(fn.module, f)
        target = index.find_function(resolved)
        return target.fq if target is not None else None

    def _scan(self, index: ModuleIndex, fn: FunctionInfo,
              summaries: dict[str, Summary],
              findings: Optional[list[Finding]]) -> Summary:
        """One source-order pass over a function: tracks dead paths,
        stash aliases and local donating callables; emits findings when
        a report list is given; returns the function's summary."""
        cls = fn.qualname.rsplit(".", 1)[0] if "." in fn.qualname else None
        prefix_alias: dict[str, str] = {}   # snap -> self.snapshot
        stash_alias: dict[str, str] = {}    # old -> self.snapshot.state
        local_callables: dict[str, tuple[int, ...]] = {}
        dead: dict[str, int] = {}           # path -> donating line
        dead_names: set[str] = set()
        first_event: dict[str, str] = {}    # path -> "read" | "store"

        def canon(path: Optional[str]) -> Optional[str]:
            if path is None:
                return None
            head, _, rest = path.partition(".")
            if head in prefix_alias:
                return prefix_alias[head] + ("." + rest if rest else "")
            return path

        def note(path: str, kind: str) -> None:
            if path.startswith("self.") and path not in first_event:
                first_event[path] = kind

        # collect statements in source order; nested defs excluded (a
        # closure's execution point is its CALL, which we cannot place)
        nested: set[int] = set()
        for sub in ast.walk(fn.node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and sub is not fn.node:
                for inner in ast.walk(sub):
                    nested.add(id(inner))
        events: list[tuple[int, int, str, object]] = []
        order = 0
        for node in ast.walk(fn.node):
            if id(node) in nested:
                continue
            if isinstance(node, ast.Assign):
                events.append((node.lineno, order, "assign", node))
            elif isinstance(node, ast.Call):
                events.append((node.lineno, order, "call", node))
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                events.append((node.lineno, order, "load_name", node))
            elif isinstance(node, ast.Attribute):
                events.append((node.lineno, order, "attr", node))
            order += 1
        events.sort(key=lambda e: (e[0], e[1]))

        parents = {c: p for p in ast.walk(fn.node)
                   for c in ast.iter_child_nodes(p)}

        def rebinds(call: ast.Call, path: str) -> bool:
            node: ast.AST = call
            while node in parents:
                node = parents[node]
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        ts = (t.elts if isinstance(
                            t, (ast.Tuple, ast.List)) else [t])
                        if any(canon(dotted_path(x)) == path
                               for x in ts):
                            return True
                    return False
                if isinstance(node, (ast.stmt,)):
                    return False
            return False

        def report(line: int, msg: str, hint: str) -> None:
            if findings is not None:
                findings.append(Finding(self.name, fn.sf.path, line,
                                        msg, hint))

        for line, _, kind, node in events:
            if kind == "assign":
                self._handle_assign(index, fn, node, prefix_alias,
                                    stash_alias, local_callables,
                                    dead, dead_names, first_event,
                                    canon, note)
            elif kind == "call":
                end = getattr(node, "end_lineno", line)
                donated = self._donated_positions(
                    index, fn, cls, node, local_callables)
                if donated:
                    for pos in donated:
                        if pos >= len(node.args):
                            continue
                        p = canon(dotted_path(node.args[pos]))
                        if p is None:
                            continue
                        note(p, "read")
                        if not rebinds(node, p):
                            dead[p] = end
                        # a PRE-dispatch stash dies with the buffer
                        # whether or not the path itself is rebound
                        for n, tgt in stash_alias.items():
                            if tgt == p:
                                dead_names.add(n)
                # a method call ON the object owning a dead path may BE
                # the blessed swap (`self.snapshot.adopt_state(new)`
                # re-points .state inside): conservatively revive paths
                # under an ATTRIBUTE receiver.  Bare-self methods stay
                # precise through the summaries below.
                if isinstance(node.func, ast.Attribute):
                    recv = canon(dotted_path(node.func.value))
                    if recv is not None and "." in recv:
                        for p in [p for p in dead
                                  if p.startswith(recv + ".")]:
                            dead.pop(p, None)
                # interprocedural: same-class callee summaries
                callee = self._callee_fq(index, fn, cls, node)
                summ = summaries.get(callee) if callee else None
                if summ is not None:
                    hit = sorted(p for p in set(summ.reads_first) & set(dead)
                                 if line > dead[p])
                    if hit:
                        report(
                            line,
                            f"{callee.rsplit('.', 1)[-1]}() reads "
                            f"{hit[0]!r}, which a donating dispatch "
                            "left dead (no blessed swap re-pointed it "
                            "before this call)",
                            "store the solve's returned state back to "
                            "the path before running host-half work")
                    for p in summ.kills:
                        dead[p] = end
                        note(p, "read")
                        for n, tgt in stash_alias.items():
                            if tgt == p:
                                dead_names.add(n)
                    for p in summ.stores_first:
                        dead.pop(p, None)
            elif kind == "load_name":
                if node.id in dead_names:
                    par = parents.get(node)
                    if (isinstance(par, ast.Attribute)
                            and par.attr in (HOST_SAFE_ATTRS
                                             | _DEADNESS_PROBES)):
                        continue
                    report(
                        line,
                        f"{node.id!r} stashes a buffer that was later "
                        f"donated ({stash_alias.get(node.id)!r}): the "
                        "stash points at the consumed buffer even "
                        "after the blessed swap",
                        "drop the stash, or capture what you need "
                        "(shapes, copies) before the dispatch")
            elif kind == "attr":
                p = canon(dotted_path(node))
                if p is None:
                    continue
                if isinstance(node.ctx, ast.Store):
                    dead.pop(p, None)
                    if p.startswith("self."):
                        first_event.setdefault(p, "store")
                    continue
                par = parents.get(node)
                if (isinstance(par, ast.Attribute)
                        and par.attr in (HOST_SAFE_ATTRS
                                         | _DEADNESS_PROBES)):
                    continue   # metadata survives donation; not a read
                note(p, "read")
                if p in dead and line > dead[p]:
                    report(
                        line,
                        f"{p!r} read after its buffers were donated: "
                        "the value is dead until the blessed swap "
                        "re-points it at the solve's returned state",
                        "rebind the result first "
                        "(path = solve(path, ...)), or move the read "
                        "before the dispatch")
        return Summary(
            kills=frozenset(p for p in dead if p.startswith("self.")),
            reads_first=frozenset(p for p, k in first_event.items()
                                  if k == "read"),
            stores_first=frozenset(p for p, k in first_event.items()
                                   if k == "store"))

    def _handle_assign(self, index, fn, node, prefix_alias, stash_alias,
                       local_callables, dead, dead_names, first_event,
                       canon, note) -> None:
        if len(node.targets) != 1:
            return
        target = node.targets[0]
        # donating-callable locals: jax.jit directly, a self-binding, a
        # ternary of self-bindings, or a factory call
        if isinstance(target, ast.Name):
            d = self._local_callable(index, fn, node.value)
            if d:
                local_callables[target.id] = d
                prefix_alias.pop(target.id, None)
                stash_alias.pop(target.id, None)
                dead_names.discard(target.id)
                return
            # `snap = self.snapshot` is BOTH an object-prefix alias
            # (so `snap.state` canonicalizes to the real path) and a
            # stash (reading `snap` after `self.snapshot` itself is
            # donated reads the dead buffer)
            src = canon(dotted_path(node.value))
            if src is not None and "." in src:
                prefix_alias[target.id] = src
                stash_alias[target.id] = src
                dead_names.discard(target.id)
                if src in dead:
                    dead_names.add(target.id)
                return
            dead_names.discard(target.id)
        targets = (target.elts if isinstance(target,
                                             (ast.Tuple, ast.List))
                   else [target])
        for t in targets:
            # a rebound name no longer aliases the old self.* path —
            # reads AND stores through it must stop canonicalizing
            if isinstance(t, ast.Name):
                dead_names.discard(t.id)
                stash_alias.pop(t.id, None)
                prefix_alias.pop(t.id, None)

    def _local_callable(self, index, fn, value) -> tuple[int, ...]:
        cls = fn.qualname.rsplit(".", 1)[0] if "." in fn.qualname else None

        def of(node) -> tuple[int, ...]:
            if isinstance(node, ast.IfExp):
                return tuple(sorted(set(of(node.body) + of(node.orelse))))
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self" and cls):
                return self._class_b.get(
                    (f"{fn.module}.{cls}", node.attr), ())
            if isinstance(node, ast.Call):
                d = self._jit_donate(index, fn.module, node)
                if d:
                    return d
                callee = self._callee_fq_simple(index, fn, cls, node)
                if callee in self._factory:
                    return self._factory[callee]
            return ()

        return of(value)

    def _callee_fq_simple(self, index, fn, cls, call) -> Optional[str]:
        f = call.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and cls):
            return f"{fn.module}.{cls}.{f.attr}"
        target = index.find_function(index.resolve(fn.module, f))
        return target.fq if target is not None else None
