"""dashboard-drift: dashboard PromQL vs the metrics registries.

Folded in from ``tools/check_dashboards.py`` (PR 5 satellite; that
script remains as a thin CLI shim over this analyzer so its entry point
and soak.sh wiring stay byte-compatible).  Every metric name referenced
by a PromQL ``expr`` in ``dashboards/*.json`` must be a series the
registries in ``koordinator_tpu/metrics.py`` actually register
(histograms expand to ``_bucket``/``_sum``/``_count``) — a renamed or
deleted instrument otherwise leaves a silently-empty panel an operator
only notices mid-incident.

This is the one analyzer that imports repo code (``koordinator_tpu.
metrics`` — dependency-free, no JAX) instead of parsing it: the registry
is built by module-level instrument constructors, so importing IS the
static ground truth.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

from ..core import Analyzer, Finding, Project

#: metric-name shapes our registries can produce (see metrics.Registry
#: prefixes); anything else inside an expr is PromQL syntax, not a metric
METRIC_RE = re.compile(r"\b(koord_[a-z0-9_]+|koordlet_[a-z0-9_]+)\b")

#: floor on total references checked across the shipped dashboards: a
#: regex or schema rot that silently matched nothing would otherwise
#: turn the check into a rubber stamp
MIN_REFERENCES = 10


def known_series(root: str | None = None) -> set[str]:
    """Every series name the component registries expose (histogram
    sub-series included).

    Validates against the IMPORTED ``koordinator_tpu.metrics`` — when
    the package is already loaded in this process, ``root`` cannot
    redirect the import (Python module caching); ``root`` only helps a
    cold process find the package.  The inserted path is removed again
    so the probe never leaks into ``sys.path``.
    """
    inserted = None
    if root and not any(os.path.abspath(p) == os.path.abspath(root)
                        for p in sys.path):
        inserted = root
        sys.path.insert(0, root)
    try:
        from koordinator_tpu import metrics as m
    finally:
        if inserted is not None:
            try:
                sys.path.remove(inserted)
            except ValueError:
                pass

    names: set[str] = set()
    for reg in m.ALL_REGISTRIES:
        for full, metric in reg.items():
            names.add(full)
            if isinstance(metric, m.Histogram):
                names.update({f"{full}_bucket", f"{full}_sum",
                              f"{full}_count"})
    return names


def check_file(path: str, known: set[str]) -> tuple[list[str], int]:
    """(errors, references_checked) for one dashboard JSON."""
    errors: list[str] = []
    checked = 0
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable dashboard JSON: {e}"], 0
    for panel in doc.get("panels", []):
        title = panel.get("title", "?")
        for target in panel.get("targets", []):
            expr = target.get("expr", "")
            for name in METRIC_RE.findall(expr):
                checked += 1
                if name not in known:
                    errors.append(
                        f"{path}: panel {title!r} references "
                        f"unregistered metric {name!r}")
    return errors, checked


def check_dashboards(paths: list[str] | None = None,
                     known: set[str] | None = None,
                     root: str | None = None) -> tuple[list[str], int]:
    """(errors, total references checked) over the given dashboards
    (default: the repo's dashboards/*.json)."""
    default_set = paths is None
    if paths is None:
        base = root or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", "..", "..")
        paths = sorted(glob.glob(os.path.join(base, "dashboards", "*.json")))
        if not paths:
            return ["no dashboards found under dashboards/"], 0
    known = known if known is not None else known_series(root)
    errors: list[str] = []
    checked = 0
    for path in paths:
        errs, n = check_file(path, known)
        errors.extend(errs)
        checked += n
    if default_set and checked < MIN_REFERENCES:
        errors.append(
            f"only {checked} metric references found across the shipped "
            f"dashboards (< {MIN_REFERENCES}): the extractor regex or "
            "dashboard schema drifted and the check is no longer "
            "checking anything")
    return errors, checked


class DashboardDriftAnalyzer(Analyzer):
    name = "dashboard-drift"
    description = ("dashboard PromQL exprs must reference registered "
                   "metric series")

    def run(self, project: Project) -> list[Finding]:
        errors, _ = check_dashboards(root=project.root)
        findings = []
        for err in errors:
            # per-dashboard errors are "<path>: message"; suite-level
            # errors (no dashboards found, MIN_REFERENCES floor) carry
            # no path and anchor on the dashboards/ dir as a whole
            head, sep, rest = err.partition(": ")
            if sep and head.endswith(".json"):
                rel = (os.path.relpath(head, project.root)
                       if os.path.isabs(head) else head)
                path, message = rel.replace(os.sep, "/"), rest
            else:
                path, message = "dashboards", err
            findings.append(Finding(
                "dashboard-drift", path, 1, message,
                "rename the panel expr to a registered series, or "
                "register the instrument in koordinator_tpu/metrics.py"))
        return findings
