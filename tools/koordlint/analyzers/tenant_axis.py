"""tenant-axis: the leading T axis must be reduced before per-tenant code.

The PR 11 batched cycle stacks every tenant's state on a leading tenant
axis (``self._stack(states)``), runs ONE vmapped program, and hands
each tenant its own slice back through ``round_adopt_batched``.  Every
output of the batched program carries the T axis; forgetting a
``_unstack`` hands tenant 0's scheduler a (T, N, R) tensor where its
snapshot expects (N, R) — rank drift that surfaces rounds later as a
shape error (or, worse, silently broadcasts one tenant's accounting
over another's).  specflow tracks the tenant axis as a taint:

- **introduced** by ``_stack``/``jnp.stack`` calls and by parameters
  whose ``# koordlint: shape[...]`` annotation declares T-leading dims;
- **propagated** through any call/expression consuming a stacked value
  (the batched jit program's outputs are stacked because its inputs
  are), tuple unpacking included;
- **eliminated** by ``_unstack``/indexing (``x[i]``) — the explicit
  per-tenant slice.

Findings fire when a stacked value reaches a per-tenant sink: the
configured sink names (``round_adopt_batched``), or a SolverKit entry
whose binding carries a per-tenant ``shape`` annotation (``argN`` dims
not T-leading) — the kit's compiled programs are per-tenant contracts,
and feeding them a stacked tensor solves every tenant with tenant 0's
capacity row.  Scoped to the tenancy front-end module(s).
"""

from __future__ import annotations

import ast

from ..callgraph import get_index
from ..core import Analyzer, Finding, Project
from ..specflow.engine import (
    call_tail as _tail,
    parse_shape_body,
    shape_seeds_for,
)

#: call tails that introduce / eliminate the tenant axis
_STACKERS = {"_stack"}
_STACK_FQS = {"jax.numpy.stack", "jnp.stack", "numpy.stack", "np.stack"}
_UNSTACKERS = {"_unstack"}
#: results of these never carry an array axis at all
_SCALAR_FNS = {"len", "int", "float", "bool", "str", "range", "print",
               "enumerate", "zip", "sorted", "list", "dict", "set",
               "tuple", "min", "max", "sum", "isinstance", "getattr",
               "perf_counter", "time"}


class TenantAxisAnalyzer(Analyzer):
    name = "tenant-axis"
    description = ("a leading tenant axis (vmap/stacked pytrees) must "
                   "be _unstack'd before reaching per-tenant sinks "
                   "(round_adopt_batched, annotated kit entries)")

    def __init__(self, package: str = "koordinator_tpu",
                 targets: tuple[str, ...] = (
                     "koordinator_tpu/scheduler/tenancy.py",),
                 sinks: tuple[str, ...] = ("round_adopt_batched",)):
        self.package = package
        self.targets = targets
        self.sinks = set(sinks)

    # -- per-tenant kit contracts from shape annotations ----------------------

    def _kit_contracts(self, index) -> dict[str, set[int]]:
        """``attr -> per-tenant arg positions`` from ``shape``
        annotations on ``self.<attr> = ...`` jit-binding assigns whose
        ``argN`` dims are NOT T-leading (the SolverKit entry-point
        seeds the issue names)."""
        out: dict[str, set[int]] = {}
        for mod, sf in index.modules.items():
            if sf.tree is None or "koordlint: shape" not in sf.text:
                continue
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"):
                    continue
                d = sf.directive_at(node.lineno, "shape")
                if d is None:
                    continue
                for name, seed in parse_shape_body(d.body).items():
                    if (name.startswith("arg") and name[3:].isdigit()
                            and seed.dims is not None
                            and seed.dims[0] != "T"):
                        out.setdefault(node.targets[0].attr,
                                       set()).add(int(name[3:]))
        return out

    # -- the analysis ---------------------------------------------------------

    def run(self, project: Project) -> list[Finding]:
        index = get_index(project, self.package)
        kit_contracts = self._kit_contracts(index)
        findings: list[Finding] = []
        for mod, sf in sorted(index.modules.items()):
            if sf.path not in self.targets or sf.tree is None:
                continue
            for fq, fn in sorted(index.functions.items()):
                if fn.sf is sf:
                    findings.extend(self._scan(index, fn, kit_contracts))
        dedup: dict[tuple, Finding] = {}
        for f in findings:
            dedup.setdefault((f.path, f.line, f.message), f)
        return sorted(dedup.values(), key=lambda f: (f.path, f.line))

    def _scan(self, index, fn, kit_contracts) -> list[Finding]:
        findings: list[Finding] = []
        stacked: set[str] = set()

        def is_stacked(node: ast.expr) -> bool:
            """Does this expression carry the leading tenant axis?"""
            if isinstance(node, ast.Name):
                return node.id in stacked
            if isinstance(node, ast.Subscript):
                return False                  # x[i] slices the T axis off
            if isinstance(node, ast.IfExp):
                return is_stacked(node.body) or is_stacked(node.orelse)
            if isinstance(node, ast.Attribute):
                return is_stacked(node.value)
            if isinstance(node, ast.Call):
                tail = _tail(node.func)
                if tail in _UNSTACKERS:
                    return False
                if tail in _STACKERS or (
                        index.resolve(fn.module, node.func)
                        in _STACK_FQS):
                    return True
                if tail in _SCALAR_FNS:
                    return False
                return any(is_stacked(a) for a in node.args) or any(
                    is_stacked(k.value) for k in node.keywords)
            if isinstance(node, (ast.Tuple, ast.List)):
                return any(is_stacked(e) for e in node.elts)
            return False

        # seeds: parameters annotated with T-leading dims
        for name, seed in shape_seeds_for(fn.sf, fn.node).items():
            if seed.dims is not None and seed.dims and seed.dims[0] == "T":
                stacked.add(name)

        statements: list[ast.stmt] = []
        for node in ast.walk(fn.node):
            if isinstance(node, ast.stmt):
                statements.append(node)
        statements.sort(key=lambda s: (s.lineno, 0))

        # each call is checked only at its INNERMOST enclosing statement
        # so taint updates inside a compound statement's body land
        # before the sink calls that follow them in source order
        parents = {c: p for p in ast.walk(fn.node)
                   for c in ast.iter_child_nodes(p)}
        own_calls: dict[int, list[ast.Call]] = {}
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            holder: ast.AST = node
            while holder in parents and not isinstance(holder, ast.stmt):
                holder = parents[holder]
            own_calls.setdefault(id(holder), []).append(node)

        for stmt in statements:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
                hit = is_stacked(stmt.value)
                targets = (target.elts
                           if isinstance(target, (ast.Tuple, ast.List))
                           else [target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        (stacked.add if hit else
                         stacked.discard)(t.id)
            for call in own_calls.get(id(stmt), []):
                tail = _tail(call.func)
                if tail in self.sinks:
                    for i, arg in enumerate(call.args):
                        if is_stacked(arg):
                            findings.append(Finding(
                                self.name, fn.sf.path, call.lineno,
                                f"argument {i} of per-tenant sink "
                                f"{tail}() still carries the leading "
                                "tenant axis: rank drift across the "
                                "batched cycle (the adopting scheduler "
                                "expects one tenant's slice)",
                                hint="slice the tenant first "
                                     "(self._unstack(x, i) / x[i])"))
                elif tail in kit_contracts:
                    for i in kit_contracts[tail]:
                        if i < len(call.args) and is_stacked(
                                call.args[i]):
                            findings.append(Finding(
                                self.name, fn.sf.path, call.lineno,
                                f"argument {i} of kit entry {tail}() "
                                "is tenant-stacked but the binding's "
                                "shape annotation declares a "
                                "per-tenant contract: one compiled "
                                "program would solve every tenant "
                                "with tenant 0's shapes",
                                hint="unstack per tenant, or use the "
                                     "tenant-axis batched program "
                                     "(_batched_fn) that declares the "
                                     "T axis"))
        return findings
