"""donation-safety: ``donate_argnums`` discipline, caught at parse time.

Buffer donation is how the solve path updates the (N, R) accounting in
place instead of reallocating it — and it is the sharpest knife in the
tree.  Two bug classes have already shipped here:

- **read-after-donate**: the caller passes a buffer at a donated
  position, XLA aliases the output into it, and any later host read of
  the SAME reference sees a deleted buffer (best case: a loud
  ``RuntimeError``; worst case on some backends: garbage).  Rule: after
  a call through a donating jit binding, the donated argument expression
  must not be READ again in that function before it is reassigned.
- **donation-aliasing** (the PR-1 ``ClusterState.zeros`` bug): one
  array bound to several fields of a donated pytree means XLA donates
  one buffer that five fields think they own — they die together.
  Rule: a local name holding a freshly-created array must not be passed
  to more than one field of a ``flax.struct.dataclass`` constructor,
  and the same expression must not appear at a donated position AND
  another position of one donating call.

Bindings are found through wrappers (``insp.instrument(jax.jit(...))``)
and matched at call sites by attribute name on the owning class
(``self._pass1(...)``) or module-level name.  The read-after scan is
linear in source order within the calling function — the bug class this
targets is sequential code; loop-carried reads are out of scope (see
docs/static_analysis.md).
"""

from __future__ import annotations

import ast
from typing import Optional

from ..callgraph import ModuleIndex, extract_jit_sites, get_index
from ..core import Analyzer, Finding, Project
from .jit_host_sync import HOST_SAFE_ATTRS

#: fresh-array constructors whose result aliased across pytree fields
#: reproduces the PR-1 bug
ARRAY_CREATORS = {"zeros", "ones", "full", "empty", "arange", "asarray",
                  "array", "zeros_like", "ones_like", "full_like"}


def dotted_path(node: ast.AST) -> Optional[str]:
    """'self.snapshot.state' for a pure Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class DonationSafetyAnalyzer(Analyzer):
    name = "donation-safety"
    description = ("read-after-donate and donated-pytree aliasing around "
                   "donate_argnums jit sites")

    def __init__(self, package: str = "koordinator_tpu"):
        self.package = package
        #: per-function parent map / call->assign index, built once and
        #: reused across every donated argument of every call in it
        self._parents_cache: dict[int, dict] = {}
        self._assign_cache: dict[int, dict] = {}

    def _parents(self, fn) -> dict:
        cached = self._parents_cache.get(id(fn.node))
        if cached is None:
            cached = {c: p for p in ast.walk(fn.node)
                      for c in ast.iter_child_nodes(p)}
            self._parents_cache[id(fn.node)] = cached
        return cached

    def _assign_of_call(self, fn) -> dict:
        """call node id -> enclosing ast.Assign (one walk per fn)."""
        cached = self._assign_cache.get(id(fn.node))
        if cached is None:
            cached = {}
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign):
                    for c in ast.walk(node.value):
                        if isinstance(c, ast.Call):
                            cached[id(c)] = node
            self._assign_cache[id(fn.node)] = cached
        return cached

    def run(self, project: Project) -> list[Finding]:
        index = get_index(project, self.package)
        findings: list[Finding] = []
        sites = [s for s in extract_jit_sites(index) if s.donate_argnums]

        # binding -> donated positions, keyed two ways.  Module-level
        # bindings key by FULLY-QUALIFIED name — a same-named function
        # in another module must not match (and two same-named bindings
        # in different modules keep their own donated positions)
        class_bindings: dict[tuple[str, str], tuple[int, ...]] = {}
        name_bindings: dict[str, tuple[int, ...]] = {}
        for s in sites:
            if s.binding and s.binding_class:
                # module-qualified class key: a same-named class in
                # another module must not inherit donated positions
                key = (f"{s.module}.{s.binding_class}", s.binding)
                class_bindings[key] = tuple(
                    sorted(set(class_bindings.get(key, ()) +
                               s.donate_argnums)))
            elif s.binding:
                name_bindings[f"{s.module}.{s.binding}"] = s.donate_argnums

        struct_classes = self._struct_dataclasses(index)
        for fq, fn in sorted(index.functions.items()):
            cls = (fn.qualname.rsplit(".", 1)[0]
                   if "." in fn.qualname else None)
            for call in ast.walk(fn.node):
                if not isinstance(call, ast.Call):
                    continue
                donated = self._donated_positions(
                    index, fn.module, cls, call, class_bindings,
                    name_bindings)
                if donated:
                    findings += self._check_call(fn, call, donated)
            findings += self._check_alias_construction(
                index, fn, struct_classes)
        return sorted(findings, key=lambda f: (f.path, f.line))

    # -- binding / site matching ---------------------------------------------

    def _struct_dataclasses(self, index: ModuleIndex) -> set[str]:
        """Fully-qualified names of @flax.struct.dataclass classes (the
        donated-pytree universe), plus their bare class names for
        ``cls(...)`` resolution inside their own classmethods."""
        out: set[str] = set()
        for fq, node in index.classes.items():
            mod = fq.rsplit(".", 1)[0]
            for deco in node.decorator_list:
                r = index.resolve(mod, deco) or ""
                if r.endswith("struct.dataclass"):
                    out.add(fq)
        return out

    def _donated_positions(self, index, mod, cls, call,
                           class_bindings, name_bindings):
        f = call.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and cls):
            return class_bindings.get((f"{mod}.{cls}", f.attr), ())
        # module-level bindings: resolve the callee to a fully-qualified
        # name — a from-import lands on the binding module, a bare local
        # name lands on the caller's own module
        resolved = index.resolve(mod, f)
        if resolved:
            if "." not in resolved:
                resolved = f"{mod}.{resolved}"
            return name_bindings.get(resolved, ())
        return ()

    # -- rule: read-after-donate + same-call aliasing -------------------------

    def _check_call(self, fn, call: ast.Call,
                    donated: tuple[int, ...]) -> list[Finding]:
        findings: list[Finding] = []
        paths: dict[int, str] = {}
        for pos in donated:
            if pos < len(call.args):
                p = dotted_path(call.args[pos])
                if p:
                    paths[pos] = p
        # aliasing inside the call itself: the donated expression also
        # passed at another position
        all_paths = [dotted_path(a) for a in call.args]
        for pos, p in paths.items():
            for j, other in enumerate(all_paths):
                if j != pos and other == p:
                    findings.append(Finding(
                        "donation-safety", fn.sf.path, call.lineno,
                        f"argument {p!r} is donated (position {pos}) but "
                        f"also passed at position {j}: XLA would alias "
                        "one buffer to both",
                        "pass an independent copy, or drop the donation"))
        end = getattr(call, "end_lineno", call.lineno)
        for pos, p in paths.items():
            if self._rebinds(fn, call, p):
                continue  # `x = f(x, ...)`: the donated name is dead and
                # immediately rebound to the result — the intended idiom
            findings += self._reads_after(fn, p, end, call.lineno)
        return findings

    def _rebinds(self, fn, call: ast.Call, path: str) -> bool:
        """Does the statement holding the donating call assign the
        donated path among its own targets?"""
        node = self._assign_of_call(fn).get(id(call))
        if node is None:
            return False
        for t in node.targets:
            targets = (t.elts if isinstance(t, (ast.Tuple, ast.List))
                       else [t])
            if any(dotted_path(x) == path for x in targets):
                return True
        return False

    def _reads_after(self, fn, path: str, after_line: int,
                     call_line: int) -> list[Finding]:
        """Loads of ``path`` after the donating call and before any store
        to it, by source order within the calling function."""
        events: list[tuple[int, str]] = []  # (line, "load"|"store")
        parents = self._parents(fn)
        for node in ast.walk(fn.node):
            if dotted_path(node) != path:
                continue
            par = parents.get(node)
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, ast.Store):
                events.append((node.lineno, "store"))
            elif isinstance(ctx, ast.Load):
                # a parent Attribute means a LONGER chain rooted here
                # (path.<attr>): .shape/.dtype metadata reads survive
                # donation, anything else consumes the dead buffer
                if (isinstance(par, ast.Attribute)
                        and par.attr in HOST_SAFE_ATTRS):
                    continue
                events.append((node.lineno, "load"))
        findings = []
        for line, kind in sorted(events):
            if line <= after_line:
                continue
            if kind == "store":
                break
            findings.append(Finding(
                "donation-safety", fn.sf.path, line,
                f"{path!r} read after being donated at line {call_line}: "
                "the buffer is dead once the donating jit call starts",
                "rebind the result first (x = f(x, ...)), or read what "
                "you need before the call"))
        return findings

    # -- rule: aliased fields in a struct-dataclass construction -------------

    def _check_alias_construction(self, index, fn,
                                  struct_classes: set[str]) -> list[Finding]:
        findings: list[Finding] = []
        cls = fn.qualname.rsplit(".", 1)[0] if "." in fn.qualname else None
        fresh: set[str] = set()
        for node in ast.walk(fn.node):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                r = index.resolve(fn.module, node.value.func) or ""
                if r.rsplit(".", 1)[-1] in ARRAY_CREATORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            fresh.add(t.id)
        if not fresh:
            return findings
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            target = index.resolve(fn.module, node.func)
            is_struct = target in struct_classes or (
                isinstance(node.func, ast.Name) and node.func.id == "cls"
                and cls and f"{fn.module}.{cls}" in struct_classes)
            if not is_struct:
                continue
            used: dict[str, list[str]] = {}
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Name) and a.id in fresh:
                    used.setdefault(a.id, []).append(f"arg {i}")
            for k in node.keywords:
                if isinstance(k.value, ast.Name) and k.value.id in fresh:
                    used.setdefault(k.value.id, []).append(k.arg or "**")
            for name, slots in used.items():
                if len(slots) > 1:
                    findings.append(Finding(
                        "donation-safety", fn.sf.path, node.lineno,
                        f"array {name!r} aliased across pytree fields "
                        f"({', '.join(slots)}): if this pytree is ever "
                        "donated, one buffer backs them all and they die "
                        "together (the PR-1 ClusterState.zeros bug)",
                        "create one fresh array per field (factory "
                        "function or per-field constructor call)"))
        return findings
