"""spec-consistency: the declared shard_map contract must match the code.

mesh-discipline (PR 10) checks that specs are PRESENT and literal; this
rule — the specflow upgrade — checks that they are RIGHT.  Four
obligations, all driven by the parsed :class:`~..specflow.engine.
SpmdSite` model (layouts resolved through module spec constants like
``_NODES = P(NODES_AXIS)`` and cross-module string constants):

- **axis liveness** — a collective inside a shard_map body's transitive
  closure (``psum``/``all_gather``/``axis_index``/…) may only name a
  mesh axis the site's specs declare live.  A typo'd or stale axis name
  raises at trace time on a mesh but silently "works" (as a no-op axis)
  under some single-device test configurations — exactly the class of
  bug an 8-way parity soak finds and tier-1 does not.
- **in_specs arity** — the literal ``in_specs`` tuple must have one
  entry per unbound positional parameter of the body (``partial``-bound
  leading args subtract).  A miscounted tuple shifts EVERY layout one
  position over.
- **out_specs arity** — the literal ``out_specs`` tuple must match the
  body's returned tuple length (checked when every return agrees).
- **propagated layout** — intra-function, a value produced by a
  shard_map call carries its declared out layout; passing it to another
  shard_map position whose in_spec provably disagrees
  (sharded-over-axes vs replicated) is a finding, as is owner-local
  scatter divergence: inside a body, scattering via an
  ``axis_index``-derived index into a REPLICATED/fresh-built value that
  flows out replicated means the shards' replicas silently diverge —
  gather first, or declare the output sharded.
- **pod-axis gather inside the round loop** (ISSUE 14) — inside a body,
  an ``all_gather`` over the POD axis within a ``while_loop`` /
  ``fori_loop`` / ``scan`` body re-gathers the pod batch EVERY round.
  The 2-D solve's contract is one pod-axis gather per program, before
  the loop (``parallel/sharded._gather_pods``): the per-round form is
  correct-but-quadratic — the exact regression a 2-D refactor most
  easily introduces, invisible to parity tests and murder on ICI.

Parameter layouts seed from in_specs; ``# koordlint: shape[...]``
annotations seed helpers the closure walk cannot see through.  Checks
fire only on PROVABLE mismatches — unknown layouts stay silent.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..callgraph import FunctionInfo, get_index, reachable_functions
from ..core import Analyzer, Finding, Project
from ..specflow.domain import FRESH, Layout, UNKNOWN
from ..specflow.engine import (
    COLLECTIVES,
    SpmdSite,
    call_tail as _tail,
    extract_spmd_sites,
    resolve_axis_name,
    shape_seeds_for,
)


class SpecConsistencyAnalyzer(Analyzer):
    name = "spec-consistency"
    description = ("shard_map/pjit declared specs checked against the "
                   "body: collective axis liveness, in/out arity, "
                   "propagated layouts, replicated-scatter divergence")

    #: lax loop entry -> positional index of its body function
    _LOOP_BODY_ARG = {"while_loop": 1, "fori_loop": 2, "scan": 0}

    def __init__(self, package: str = "koordinator_tpu",
                 pod_axis: str = "pods"):
        self.package = package
        self.pod_axis = pod_axis

    def run(self, project: Project) -> list[Finding]:
        index = get_index(project, self.package)
        sites = extract_spmd_sites(index)
        findings: list[Finding] = []
        seen: set[tuple] = set()

        def emit(f: Finding) -> None:
            k = (f.path, f.line, f.message[:80])
            if k not in seen:
                seen.add(k)
                findings.append(f)

        for site in sites:
            self._check_arity(index, site, emit)
            if site.body_fn is not None and site.axes:
                self._check_axes(index, site, emit)
            if site.body_fn is not None:
                self._check_replicated_scatter(index, site, emit)
                self._check_loop_pod_gather(index, site, emit)
        self._check_layout_flow(index, sites, emit)
        return sorted(findings, key=lambda f: (f.path, f.line))

    # -- arity ----------------------------------------------------------------

    def _positional_params(self, fn: FunctionInfo) -> int:
        args = fn.node.args
        n = len(getattr(args, "posonlyargs", [])) + len(args.args)
        names = [a.arg for a in
                 list(getattr(args, "posonlyargs", [])) + list(args.args)]
        if names and names[0] in ("self", "cls"):
            n -= 1
        return n

    def _return_arity(self, fn: FunctionInfo) -> Optional[int]:
        arities: set[int] = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Return) and node.value is not None:
                arities.add(len(node.value.elts)
                            if isinstance(node.value, ast.Tuple) else 1)
        return arities.pop() if len(arities) == 1 else None

    def _check_arity(self, index, site: SpmdSite, emit) -> None:
        fn = site.body_fn
        if fn is None:
            return
        if site.in_layouts is not None:
            want = self._positional_params(fn) - site.bound_positional
            if want >= 0 and len(site.in_layouts) != want:
                emit(Finding(
                    self.name, site.sf.path, site.line,
                    f"in_specs declares {len(site.in_layouts)} "
                    f"entries but shard_map body {fn.qualname} takes "
                    f"{want} positional arguments: every layout lands "
                    "one position off",
                    hint="one in_specs entry per unbound positional "
                         "parameter of the body"))
        if site.out_layouts is not None:
            ret = self._return_arity(fn)
            if ret is not None and len(site.out_layouts) != ret:
                emit(Finding(
                    self.name, site.sf.path, site.line,
                    f"out_specs declares {len(site.out_layouts)} "
                    f"entries but body {fn.qualname} returns {ret} "
                    "value(s)",
                    hint="match out_specs to the body's returned tuple"))

    # -- collective axis liveness ---------------------------------------------

    def _check_axes(self, index, site: SpmdSite, emit) -> None:
        closure = reachable_functions(index, [site.body_fn])
        for fn in closure.values():
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                tail = _tail(node.func)
                pos = COLLECTIVES.get(tail)
                if pos is None:
                    continue
                axis_node = None
                if len(node.args) > pos:
                    axis_node = node.args[pos]
                else:
                    for kw in node.keywords:
                        if kw.arg in ("axis_name", "axis"):
                            axis_node = kw.value
                axis = (resolve_axis_name(index, fn.module, axis_node)
                        if axis_node is not None else None)
                if axis is not None and axis not in site.axes:
                    emit(Finding(
                        self.name, fn.sf.path, node.lineno,
                        f"collective {tail}(..., {axis!r}) names an "
                        "axis not live in the enclosing shard_map mesh "
                        f"(specs at {site.sf.path}:{site.line} declare "
                        f"axes {sorted(site.axes)})",
                        hint="use the mesh axis the site's specs "
                             "declare, or fix the specs"))

    # -- pod-axis gather inside the round loop (ISSUE 14) ---------------------

    def _check_loop_pod_gather(self, index, site: SpmdSite, emit) -> None:
        """Flag ``all_gather(..., <pod axis>)`` reachable from a
        ``while_loop``/``fori_loop``/``scan`` BODY inside the site's
        closure: the pod batch must gather once, before the loop."""
        closure = reachable_functions(index, [site.body_fn])
        for fn in closure.values():
            nested = {n.name: n for n in ast.walk(fn.node)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                pos = self._LOOP_BODY_ARG.get(_tail(node.func))
                if pos is None or len(node.args) <= pos:
                    continue
                body = self._resolve_loop_body(index, fn, nested,
                                               node.args[pos])
                if body is None:
                    continue
                for mod, gather in self._pod_gathers_in(
                        index, fn.module, nested, body, depth=4):
                    emit(Finding(
                        self.name, fn.sf.path, gather.lineno,
                        f"all_gather over the {self.pod_axis!r} axis "
                        "inside a device loop body: the pod batch is "
                        "re-gathered EVERY round instead of once "
                        "before the loop",
                        hint="hoist the pod-axis gather above the "
                             "while_loop/fori_loop/scan (see "
                             "parallel/sharded._gather_pods) — the "
                             "round loop should only psum node-owned "
                             "contributions"))

    def _resolve_loop_body(self, index, fn: FunctionInfo, nested,
                           arg: ast.expr):
        """A loop's body-function argument -> its AST (lambda, nested
        def, or module-level function), or None."""
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            if arg.id in nested:
                return nested[arg.id]
            target = index.find_function(index.resolve(fn.module, arg))
            if target is not None:
                return target.node
        return None

    def _pod_gathers_in(self, index, module: str, nested, body,
                        depth: int):
        """Yield (module, call) for every pod-axis all_gather reachable
        from ``body`` through nested defs / module-level helpers,
        depth-limited (the closure is tiny: loop body -> round helper ->
        gather helper)."""
        seen_fns: set[int] = set()
        stack = [(module, body, depth)]
        while stack:
            mod, node, d = stack.pop()
            if id(node) in seen_fns:
                continue
            seen_fns.add(id(node))
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                tail = _tail(sub.func)
                if tail == "all_gather":
                    axis_node = (sub.args[1] if len(sub.args) > 1
                                 else None)
                    if axis_node is None:
                        for kw in sub.keywords:
                            if kw.arg == "axis_name":
                                axis_node = kw.value
                    axis = (resolve_axis_name(index, mod, axis_node)
                            if axis_node is not None else None)
                    if axis == self.pod_axis:
                        yield mod, sub
                elif d > 0 and isinstance(sub.func, ast.Name):
                    if sub.func.id in nested:
                        stack.append((mod, nested[sub.func.id], d - 1))
                    else:
                        target = index.find_function(
                            index.resolve(mod, sub.func))
                        if target is not None:
                            stack.append((target.module, target.node,
                                          d - 1))

    # -- replicated owner-local scatter ---------------------------------------

    def _body_param_layouts(self, index, site: SpmdSite) -> dict[str, Layout]:
        fn = site.body_fn
        layouts: dict[str, Layout] = {}
        args = fn.node.args
        names = [a.arg for a in
                 list(getattr(args, "posonlyargs", [])) + list(args.args)]
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        names = names[site.bound_positional:]
        if site.in_layouts is not None:
            for name, lay in zip(names, site.in_layouts):
                layouts[name] = lay
        for name, seed in shape_seeds_for(fn.sf, fn.node).items():
            if seed.layout is not None:
                layouts[name] = seed.layout
        return layouts

    def _check_replicated_scatter(self, index, site: SpmdSite,
                                  emit) -> None:
        """Inside a body: ``base.at[idx].add(...)`` where ``base`` is
        provably replicated and ``idx`` derives from ``axis_index``
        makes the replicas diverge — each shard scatters only its own
        rows into what the out_specs still call one value."""
        fn = site.body_fn
        layouts = dict(self._body_param_layouts(index, site))
        tainted: set[str] = set()

        def expr_tainted(node: ast.expr) -> bool:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    t = _tail(sub.func)
                    if t == "axis_index":
                        return True
                    target = index.find_function(
                        index.resolve(fn.module, sub.func))
                    if target is not None and any(
                            isinstance(c, ast.Call)
                            and _tail(c.func) == "axis_index"
                            for c in ast.walk(target.node)):
                        return True
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return True
            return False

        def layout_of(node: ast.expr) -> Layout:
            if isinstance(node, ast.Name):
                return layouts.get(node.id, UNKNOWN)
            if isinstance(node, ast.Attribute):
                return layout_of(node.value)
            if isinstance(node, ast.Call):
                t = _tail(node.func)
                if t in ("zeros", "ones", "full", "arange"):
                    return FRESH
                if t in ("zeros_like", "ones_like", "full_like") \
                        and node.args:
                    return layout_of(node.args[0])
                if t in ("where", "clip") and node.args:
                    return UNKNOWN
            return UNKNOWN

        for stmt in ast.walk(fn.node):
            if isinstance(stmt, ast.Assign) \
                    and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                if expr_tainted(stmt.value):
                    tainted.add(name)
                lay = layout_of(stmt.value)
                if lay.kind != "unknown":
                    layouts[name] = lay
        for node in ast.walk(fn.node):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("add", "set", "max", "min",
                                           "mul")
                    and isinstance(node.func.value, ast.Subscript)
                    and isinstance(node.func.value.value, ast.Attribute)
                    and node.func.value.value.attr == "at"):
                continue
            base = node.func.value.value.value
            idx = node.func.value.slice
            if layout_of(base).is_replicated and expr_tainted(idx):
                emit(Finding(
                    self.name, fn.sf.path, node.lineno,
                    "owner-local scatter (index derives from "
                    "axis_index) into a replicated value: each shard "
                    "writes only its own rows, so the replicas of "
                    f"{ast.unparse(base) if hasattr(ast, 'unparse') else 'the value'} "
                    "silently diverge",
                    hint="scatter into the node-sharded buffer, or "
                         "all_gather/psum the contributions before "
                         "treating the result as replicated"))

    # -- propagated layout across chained sites -------------------------------

    def _check_layout_flow(self, index, sites: list[SpmdSite],
                           emit) -> None:
        by_call = {id(s.call): s for s in sites}
        for fq, fn in sorted(index.functions.items()):
            contracts: dict[str, SpmdSite] = {}
            value_layouts: dict[str, Layout] = {}

            def handle_wrapped_call(call: ast.Call,
                                    site: SpmdSite) -> list[Layout]:
                if site.in_layouts is not None:
                    for i, arg in enumerate(call.args):
                        if i >= len(site.in_layouts) \
                                or not isinstance(arg, ast.Name):
                            continue
                        got = value_layouts.get(arg.id, UNKNOWN)
                        want = site.in_layouts[i]
                        if got.kind == "unknown" \
                                or want.kind == "unknown":
                            continue
                        if got.is_sharded != want.is_sharded or (
                                got.is_sharded
                                and got.axes != want.axes):
                            emit(Finding(
                                self.name, fn.sf.path, call.lineno,
                                f"argument {i} ({arg.id!r}) carries "
                                f"layout {got.kind}{got.axes or ''} "
                                "from a previous shard_map out_spec "
                                f"but this site declares "
                                f"{want.kind}{want.axes or ''}: the "
                                "propagated layout contradicts the "
                                "declared contract",
                                hint="reshard explicitly (all_gather / "
                                     "device_put) or fix the spec"))
                return site.out_layouts or []

            def site_of(call: ast.Call) -> Optional[SpmdSite]:
                if isinstance(call.func, ast.Name) \
                        and call.func.id in contracts:
                    return contracts[call.func.id]
                if id(call.func) in by_call:
                    return by_call[id(call.func)]
                return None

            handled: set[int] = set()
            nodes = sorted(
                (n for n in ast.walk(fn.node)
                 if isinstance(n, (ast.Assign, ast.Call))),
                key=lambda n: (n.lineno, n.col_offset))
            for stmt in nodes:
                if isinstance(stmt, ast.Assign):
                    if len(stmt.targets) != 1:
                        continue
                    target, value = stmt.targets[0], stmt.value
                    if isinstance(value, ast.Call) \
                            and id(value) in by_call \
                            and isinstance(target, ast.Name):
                        contracts[target.id] = by_call[id(value)]
                        handled.add(id(value))
                        continue
                    if isinstance(value, ast.Call):
                        site = site_of(value)
                        if site is not None:
                            handled.add(id(value))
                            outs = handle_wrapped_call(value, site)
                            targets = (target.elts if isinstance(
                                target, (ast.Tuple, ast.List))
                                else [target])
                            for t, lay in zip(targets, outs):
                                if isinstance(t, ast.Name):
                                    value_layouts[t.id] = lay
                    continue
                if id(stmt) in handled:
                    continue
                site = site_of(stmt)
                if site is not None:
                    handled.add(id(stmt))
                    handle_wrapped_call(stmt, site)
