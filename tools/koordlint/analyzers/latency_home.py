"""latency-home: per-pod latency deltas belong in journey/timeline.

The pod-journey ledger (koordinator_tpu/journey.py, ISSUE 20) is the
ONE home for per-pod scheduling-latency measurement: O(1) mergeable
sketches with a bounded relative error, a kill switch, fleet
aggregation, and a bit-identity guarantee.  The timeline observatory
(timeline.py) is the one home for per-cycle wall attribution.  An
ad-hoc ``time.time()`` / ``time.perf_counter()`` delta computed on a
per-pod path re-invents both badly: it costs a syscall per pod with no
kill switch, its samples are process-local and unmergeable, and — the
review-burn that seeded this rule — it tends to grow into a dict of
per-pod floats that never ages out.

A finding fires when a clock-delta expression (``now - t0`` where
either side traces to ``time.time()``/``time.perf_counter()``/
``time.monotonic()``) is computed

- inside a loop whose target or iterable is pod-shaped (``for pod in
  pods``, ``for name in self.pending``, ``for pod, node in binds``), or
- stored into a container subscripted by a pod identity
  (``lat[pod.name] = now - t0``).

Round-/cycle-scoped deltas (one measurement per round, however many
pods it carried) are fine and stay silent.  The allowed homes —
journey.py, timeline.py — are skipped entirely.  Route new per-pod
measurements through ``journey.LEDGER`` instead.
"""

from __future__ import annotations

import ast

from ..core import Analyzer, Finding, Project

#: attribute/name tails that read a clock
_CLOCK_TAILS = {"time", "perf_counter", "monotonic"}
#: loop targets / iterables that mean "one iteration per pod"
_POD_TOKENS = ("pod", "pending", "binds")
#: the sanctioned measurement homes (never scanned)
_ALLOWED = (
    "koordinator_tpu/journey.py",
    "koordinator_tpu/timeline.py",
)


def _call_tail(node: ast.expr) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_clock_call(node: ast.expr) -> bool:
    return (isinstance(node, ast.Call)
            and _call_tail(node.func) in _CLOCK_TAILS)


def _mentions_pod(text: str) -> bool:
    low = text.lower()
    return any(tok in low for tok in _POD_TOKENS)


class LatencyHomeAnalyzer(Analyzer):
    name = "latency-home"
    description = ("ad-hoc time.time()/perf_counter() latency deltas on "
                   "per-pod paths belong in the journey ledger "
                   "(journey.LEDGER) or the timeline observatory, not "
                   "inline")

    def __init__(self, allowed: tuple[str, ...] = _ALLOWED):
        self.allowed = set(allowed)

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for path, sf in sorted(project.files.items()):
            if sf.tree is None or path in self.allowed:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    findings.extend(self._scan_function(sf, node))
        dedup: dict[tuple, Finding] = {}
        for f in findings:
            dedup.setdefault((f.path, f.line), f)
        return sorted(dedup.values(), key=lambda f: (f.path, f.line))

    # -- one function ---------------------------------------------------------

    def _scan_function(self, sf, fn) -> list[Finding]:
        # names assigned from a clock read anywhere in the function —
        # per-pod code re-reading a stashed stamp is the same smell
        tainted: set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and _is_clock_call(node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)

        def is_clockish(node: ast.expr) -> bool:
            if _is_clock_call(node):
                return True
            return isinstance(node, ast.Name) and node.id in tainted

        def is_delta(node: ast.expr) -> bool:
            return (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and (is_clockish(node.left)
                         or is_clockish(node.right)))

        findings: list[Finding] = []

        def flag(node: ast.AST, where: str) -> None:
            findings.append(Finding(
                self.name, sf.path, node.lineno,
                f"per-pod latency delta computed inline ({where}): "
                "a clock subtraction on a per-pod path is an ad-hoc "
                "latency ledger — unmergeable, unkillable, and a "
                "syscall per pod",
                hint="record through journey.LEDGER (note_enqueue / "
                     "record_bind_batch) or a timeline section instead"))

        # (a) clock deltas inside pod-shaped loops
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.AsyncFor)):
                continue
            context = (ast.unparse(loop.target) + " "
                       + ast.unparse(loop.iter))
            if not _mentions_pod(context):
                continue
            for sub in ast.walk(loop):
                if sub is not loop.iter and is_delta(sub):
                    flag(sub, f"inside `for {ast.unparse(loop.target)} "
                              f"in {ast.unparse(loop.iter)}`")

        # (b) clock deltas stored keyed by a pod identity
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and is_delta(node.value)):
                continue
            key = ast.unparse(node.targets[0].slice)
            if _mentions_pod(key):
                flag(node, f"stored per pod under [{key}]")
        return findings
