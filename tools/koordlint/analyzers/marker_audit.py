"""marker-audit: test-suite conventions that protect tier-1 become lints.

Two conventions from pytest.ini / the chaos-soak discipline:

- **chaos implies slow**: every ``chaos``-marked test must ALSO carry
  ``slow`` (module ``pytestmark``, class mark, or decorator), because
  tier-1 deselects with ``-m "not slow"`` — a chaos test without
  ``slow`` would drag a multi-second seeded socket soak into CI.
- **no module-scope jax import in test files**: ``import jax`` at
  module scope runs at pytest COLLECTION, before any deselect marker
  applies.  conftest.py deliberately imports jax first (it must pin the
  platform before anyone else touches it) and is exempt by scope; every
  other ``tests/test_*.py`` should defer jax to test/fixture bodies so
  collection of a deselected file stays free.  The pre-koordlint suites
  that predate this rule are grandfathered in the baseline — the rule
  holds the line for NEW files.
"""

from __future__ import annotations

import ast

from ..core import Analyzer, Finding, Project


def _is_type_checking(test: ast.expr) -> bool:
    """``if TYPE_CHECKING:`` / ``if typing.TYPE_CHECKING:`` guards."""
    return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or (
        isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING")


def _marks(decorators: list[ast.expr]) -> set[str]:
    """Mark names from @pytest.mark.<x> / @pytest.mark.<x>(...)."""
    out: set[str] = set()
    for deco in decorators:
        node = deco.func if isinstance(deco, ast.Call) else deco
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "mark"):
            out.add(node.attr)
    return out


def _pytestmark_marks(stmts: list[ast.stmt]) -> set[str]:
    out: set[str] = set()
    for stmt in stmts:
        if not isinstance(stmt, ast.Assign):
            continue
        for t in stmt.targets:
            if isinstance(t, ast.Name) and t.id == "pytestmark":
                values = (stmt.value.elts
                          if isinstance(stmt.value, (ast.List, ast.Tuple))
                          else [stmt.value])
                out |= _marks(values)
    return out


class MarkerAuditAnalyzer(Analyzer):
    name = "marker-audit"
    description = ("chaos tests must also be slow; no module-scope jax "
                   "import in test files")

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for sf in project.glob("tests/test_*.py"):
            if sf.tree is None:
                continue
            module_marks = _pytestmark_marks(sf.tree.body)
            self._walk(sf, sf.tree.body, module_marks, findings)
            findings += self._jax_imports(sf)
        return sorted(findings, key=lambda f: (f.path, f.line))

    def _walk(self, sf, stmts, inherited: set[str],
              findings: list[Finding]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.ClassDef):
                marks = (inherited | _marks(stmt.decorator_list)
                         | _pytestmark_marks(stmt.body))
                self._walk(sf, stmt.body, marks, findings)
            elif (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt.name.startswith("test")):
                marks = inherited | _marks(stmt.decorator_list)
                if "chaos" in marks and "slow" not in marks:
                    findings.append(Finding(
                        "marker-audit", sf.path, stmt.lineno,
                        f"{stmt.name} is marked chaos but not slow: "
                        "tier-1 (-m 'not slow') would run this seeded "
                        "socket soak in CI",
                        "add pytest.mark.slow next to the chaos mark "
                        "(see pytest.ini)"))

    def _jax_imports(self, sf) -> list[Finding]:
        findings: list[Finding] = []

        def scan(stmts: list[ast.stmt]) -> None:
            for stmt in stmts:
                if isinstance(stmt, ast.Import):
                    for a in stmt.names:
                        if a.name == "jax" or a.name.startswith("jax."):
                            findings.append(self._jax_finding(sf, stmt))
                elif isinstance(stmt, ast.ImportFrom):
                    mod = stmt.module or ""
                    if stmt.level == 0 and (
                            mod == "jax" or mod.startswith("jax.")):
                        findings.append(self._jax_finding(sf, stmt))
                elif isinstance(stmt, (ast.If, ast.Try, ast.With)):
                    # still executes at import time — except the
                    # annotation-only `if TYPE_CHECKING:` body, which
                    # never runs and costs collection nothing
                    if (isinstance(stmt, ast.If)
                            and _is_type_checking(stmt.test)):
                        scan(stmt.orelse)
                        continue
                    for field in ("body", "orelse", "finalbody"):
                        scan(getattr(stmt, field, []) or [])
                    for h in getattr(stmt, "handlers", []):
                        scan(h.body)

        scan(sf.tree.body)
        return findings

    def _jax_finding(self, sf, stmt) -> Finding:
        return Finding(
            "marker-audit", sf.path, stmt.lineno,
            "module-scope jax import in a test file: pytest collection "
            "pays it even when every test here is deselected",
            "import jax inside the test/fixture body (conftest.py "
            "already pinned the platform)")
