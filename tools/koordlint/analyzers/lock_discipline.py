"""lock-discipline: lock-order cycles and half-guarded attribute writes.

The tree has ~40 ``with self._lock:`` sites across transport/, scheduler/
and koordlet/ threading seams.  The invariants that keep them honest
lived in reviewers' heads; this analyzer makes two of them mechanical:

- **lock-order graph**: every ``with self.<lock>:`` scope is extracted;
  acquiring a second lock inside one (directly, or through a method call
  this analyzer can resolve — same-class ``self.m()`` and typed
  attributes ``self.informer.push()`` where ``__init__`` pins the type)
  adds an edge.  A cycle in the graph is a deadlock candidate.  Locks
  are identified per module.Class.attribute (instances are conflated — a
  self-edge on a non-reentrant ``Lock`` is flagged, on an ``RLock`` it
  is the reentrancy it was bought for and ignored).
- **guard consistency**: an attribute written under a lock at some sites
  and bare at others is a race candidate — the bare sites are flagged.
  ``__init__`` writes are construction (happens-before publication) and
  exempt.

Intent annotations close the gap static scoping cannot see:

- ``def _solve_locked(self):  # koordlint: guarded-by(self.lock)``
  declares the CALLER holds the lock — the body counts as guarded (the
  Clang thread-safety ``REQUIRES()`` idea).
- ``self.pending = {}  # koordlint: guarded-by(self.lock)`` on the
  ``__init__`` line declares the attribute's guard, so even a class with
  no currently-guarded writes gets bare writes flagged.

Manual ``.acquire()/.release()`` pairs are not scoped (non-lexical);
those sites are skipped — keep them rare.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Optional

from ..callgraph import ModuleIndex, get_index
from ..core import Analyzer, Finding, Project
from .donation_safety import dotted_path

LOCK_TYPES = {"threading.Lock": "Lock", "threading.RLock": "RLock",
              "threading.Condition": "Condition"}

_GUARD_RE = re.compile(r"guarded-by\(\s*self\.(\w+)\s*\)")


@dataclasses.dataclass
class LockWrite:
    attr: str
    method: str
    line: int
    held: frozenset[str]    # lock ids held at the write


@dataclasses.dataclass
class Edge:
    src: str
    dst: str
    path: str
    line: int
    via: str                # human-readable evidence


@dataclasses.dataclass
class ClassModel:
    module: str
    name: str
    node: ast.ClassDef
    sf: object
    locks: dict[str, str] = dataclasses.field(default_factory=dict)
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)
    methods: dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict)
    writes: list[LockWrite] = dataclasses.field(default_factory=list)
    declared: dict[str, tuple[str, int]] = dataclasses.field(
        default_factory=dict)  # attr -> (lock id, decl line)

    def lock_id(self, attr: str) -> str:
        # module-qualified: two same-named classes in different modules
        # must not merge into one node (false shared-lock cycles)
        return f"{self.module}.{self.name}.{attr}"


class LockGraph:
    """The cross-class lock-acquisition-order graph."""

    def __init__(self):
        self.edges: list[Edge] = []
        self.lock_kinds: dict[str, str] = {}
        self._seen: set[tuple[str, str, str, int]] = set()

    def add_edge(self, edge: Edge) -> None:
        key = (edge.src, edge.dst, edge.path, edge.line)
        if key not in self._seen:
            self._seen.add(key)
            self.edges.append(edge)

    def adjacency(self) -> dict[str, set[str]]:
        adj: dict[str, set[str]] = {}
        for e in self.edges:
            adj.setdefault(e.src, set()).add(e.dst)
            adj.setdefault(e.dst, set())
        return adj

    def cycles(self) -> list[list[str]]:
        """Elementary cycles via SCC: every SCC with >1 node, plus
        self-edges on non-reentrant locks."""
        adj = self.adjacency()
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w in sorted(adj.get(v, ())):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        out = [sorted(c) for c in sccs if len(c) > 1]
        for e in self.edges:
            if (e.src == e.dst
                    and self.lock_kinds.get(e.src, "Lock") != "RLock"):
                out.append([e.src])
        return out


class LockDisciplineAnalyzer(Analyzer):
    name = "lock-discipline"
    description = ("lock-order cycles (deadlock candidates) and attribute "
                   "writes guarded at some sites but bare at others")

    def __init__(self, package: str = "koordinator_tpu"):
        self.package = package

    def run(self, project: Project) -> list[Finding]:
        index = get_index(project, self.package)
        models = self.build_models(index)
        graph = self.build_graph(index, models)
        findings: list[Finding] = []
        findings += self._cycle_findings(graph)
        for model in models.values():
            findings += self._guard_findings(model)
        return sorted(findings, key=lambda f: (f.path, f.line))

    # -- model construction ---------------------------------------------------

    def build_models(self, index: ModuleIndex) -> dict[str, ClassModel]:
        models: dict[str, ClassModel] = {}
        for fq, node in sorted(index.classes.items()):
            mod = fq[: -len(node.name) - 1]
            if mod not in index.modules:
                continue  # nested classes: keyed by owner module anyway
            model = ClassModel(module=mod, name=node.name, node=node,
                               sf=index.modules[mod])
            for child in node.body:
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    model.methods[child.name] = child
            init = model.methods.get("__init__")
            if init is not None:
                self._scan_init(index, mod, model, init)
            for name, m in model.methods.items():
                self._scan_method(index, model, name, m)
            models[fq] = model
        return models

    def _scan_init(self, index, mod, model: ClassModel,
                   init: ast.FunctionDef) -> None:
        ann: dict[str, str] = {}
        for arg in init.args.args + init.args.kwonlyargs:
            if arg.annotation is not None:
                r = index.resolve(mod, _strip_optional(arg.annotation))
                if r and index.find_function(r) is None:
                    ann[arg.arg] = r
        for stmt in ast.walk(init):
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
                continue
            t = stmt.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            if isinstance(stmt.value, ast.Call):
                r = index.resolve(mod, stmt.value.func)
                if r in LOCK_TYPES:
                    model.locks[t.attr] = LOCK_TYPES[r]
                elif r in index.classes:
                    model.attr_types[t.attr] = r
            elif (isinstance(stmt.value, ast.Name)
                  and stmt.value.id in ann
                  and ann[stmt.value.id] in index.classes):
                model.attr_types[t.attr] = ann[stmt.value.id]

    def _method_guard(self, model: ClassModel,
                      m: ast.FunctionDef) -> frozenset[str]:
        """Locks declared held by the caller via a guarded-by directive
        on (or right above) the def line — or above the FIRST decorator
        when the def is decorated (the comment sits on top)."""
        d = model.sf.directive_at(m.lineno, "guarded-by")
        if d is None and m.decorator_list:
            first = min(dec.lineno for dec in m.decorator_list)
            d = model.sf.directive_at(first, "guarded-by")
        if d is None:
            return frozenset()
        g = _GUARD_RE.search(f"guarded-by({d.body})")
        return frozenset({model.lock_id(g.group(1))}) if g else frozenset()

    def _scan_method(self, index, model: ClassModel, name: str,
                     m: ast.FunctionDef) -> None:
        base = self._method_guard(model, m)

        def walk(stmts, held: frozenset[str]) -> None:
            for stmt in stmts:
                if isinstance(stmt, ast.With):
                    inner = held
                    for item in stmt.items:
                        p = dotted_path(item.context_expr)
                        if (p and p.startswith("self.")
                                and p[5:] in model.locks):
                            inner = inner | {model.lock_id(p[5:])}
                    walk(stmt.body, inner)
                    continue
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        self._record_write(model, name, t, held)
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    if getattr(stmt, "value", True) is not None:
                        self._record_write(model, name, stmt.target, held)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if isinstance(sub, list) and not isinstance(
                            stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        walk(sub, held)
                if isinstance(stmt, ast.Try):
                    for h in stmt.handlers:
                        walk(h.body, held)

        walk(m.body, base)

    def _record_write(self, model: ClassModel, method: str, target: ast.AST,
                      held: frozenset[str]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._record_write(model, method, e, held)
            return
        if not (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            return
        attr = target.attr
        if attr in model.locks:
            return
        d = model.sf.directive_at(target.lineno, "guarded-by")
        if d is not None:
            g = _GUARD_RE.search(f"guarded-by({d.body})")
            if g and attr not in model.declared:
                model.declared[attr] = (model.lock_id(g.group(1)),
                                        target.lineno)
        model.writes.append(LockWrite(attr=attr, method=method,
                                      line=target.lineno, held=held))

    # -- lock-order graph -----------------------------------------------------

    def build_graph(self, index: ModuleIndex,
                    models: dict[str, ClassModel]) -> LockGraph:
        graph = LockGraph()
        for model in models.values():
            for attr, kind in model.locks.items():
                graph.lock_kinds[model.lock_id(attr)] = kind

        # (class fq, method) -> locks running it may acquire, computed
        # as a global FIXPOINT over direct acquisitions + call edges —
        # a recursive memo would cache truncated sets at call-graph
        # cycles (mutually recursive methods) and silently drop edges
        direct: dict[tuple[str, str], set[str]] = {}
        calls: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for cls_fq, model in models.items():
            for mname, m in model.methods.items():
                key = (cls_fq, mname)
                direct[key] = set()
                calls[key] = set()
                for node in ast.walk(m):
                    if isinstance(node, ast.With):
                        for item in node.items:
                            p = dotted_path(item.context_expr)
                            if (p and p.startswith("self.")
                                    and p[5:] in model.locks):
                                direct[key].add(model.lock_id(p[5:]))
                    elif isinstance(node, ast.Call):
                        tgt = self._callee(index, model, node)
                        if tgt is not None:
                            calls[key].add(tgt)
        closure = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for key, callees in calls.items():
                for tgt in callees:
                    add = closure.get(tgt, set()) - closure[key]
                    if add:
                        closure[key] |= add
                        changed = True

        def acquired(cls_fq: str, method: str) -> frozenset[str]:
            return frozenset(closure.get((cls_fq, method), ()))

        for cls_fq, model in models.items():
            for mname, m in model.methods.items():
                base = self._method_guard(model, m)
                self._edge_walk(index, models, model, cls_fq, mname,
                                m.body, base, graph, acquired)
        return graph

    def _callee(self, index, model: ClassModel,
                call: ast.Call) -> Optional[tuple[str, str]]:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        if isinstance(f.value, ast.Name) and f.value.id == "self":
            if f.attr in model.methods:
                return (f"{model.module}.{model.name}", f.attr)
            return None
        # self.<attr>.<method>() on a typed attribute
        if (isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"
                and f.value.attr in model.attr_types):
            return (model.attr_types[f.value.attr], f.attr)
        return None

    def _edge_walk(self, index, models, model: ClassModel, cls_fq: str,
                   method: str, stmts, held: frozenset[str],
                   graph: LockGraph, acquired) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                inner = held
                for item in stmt.items:
                    p = dotted_path(item.context_expr)
                    if p and p.startswith("self.") and p[5:] in model.locks:
                        new = model.lock_id(p[5:])
                        # edges come from INNER, not held: items of one
                        # `with a, b:` acquire in sequence, so b's edge
                        # set must include a
                        for h in inner:
                            if h == new and graph.lock_kinds.get(
                                    new) == "RLock":
                                continue
                            graph.add_edge(Edge(
                                h, new, model.sf.path, stmt.lineno,
                                f"{model.name}.{method} acquires "
                                f"{new} while holding {h}"))
                        inner = inner | {new}
                self._edge_walk(index, models, model, cls_fq, method,
                                stmt.body, inner, graph, acquired)
                continue
            if held:
                # only THIS statement's own expressions: nested blocks
                # are covered by the recursion below (scanning the full
                # subtree here would re-visit each call once per level)
                for node in _own_expr_nodes(stmt):
                    if isinstance(node, ast.Call):
                        tgt = self._callee(index, model, node)
                        if tgt is None:
                            continue
                        for lock in sorted(acquired(tgt[0], tgt[1])):
                            for h in held:
                                if h == lock and graph.lock_kinds.get(
                                        lock) == "RLock":
                                    continue
                                graph.add_edge(Edge(
                                    h, lock, model.sf.path, node.lineno,
                                    f"{model.name}.{method} holds {h} and "
                                    f"calls {tgt[0].rsplit('.', 1)[-1]}."
                                    f"{tgt[1]} which acquires {lock}"))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and not isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._edge_walk(index, models, model, cls_fq, method,
                                    sub, held, graph, acquired)
            if isinstance(stmt, ast.Try):
                for h in stmt.handlers:
                    self._edge_walk(index, models, model, cls_fq, method,
                                    h.body, held, graph, acquired)

    # -- findings -------------------------------------------------------------

    def _cycle_findings(self, graph: LockGraph) -> list[Finding]:
        findings = []
        for cycle in graph.cycles():
            members = set(cycle)
            evidence = [e for e in graph.edges
                        if e.src in members and e.dst in members]
            if not evidence:
                continue
            first = min(evidence, key=lambda e: (e.path, e.line))
            chain = " -> ".join(cycle + [cycle[0]])
            detail = "; ".join(
                f"{e.via} ({e.path}:{e.line})"
                for e in sorted(evidence, key=lambda e: (e.path, e.line))[:4])
            findings.append(Finding(
                "lock-discipline", first.path, first.line,
                f"lock-order cycle (deadlock candidate): {chain}. {detail}",
                "pick one global acquisition order and release the outer "
                "lock before taking the inner one on the reverse path"))
        return findings

    def _guard_findings(self, model: ClassModel) -> list[Finding]:
        findings = []
        by_attr: dict[str, list[LockWrite]] = {}
        for w in model.writes:
            if w.method != "__init__":
                by_attr.setdefault(w.attr, []).append(w)
        for attr, writes in sorted(by_attr.items()):
            declared = model.declared.get(attr)
            if declared is not None:
                lock = declared[0]
                for w in writes:
                    if lock not in w.held:
                        findings.append(Finding(
                            "lock-discipline", model.sf.path, w.line,
                            f"{model.name}.{attr} is declared guarded-by"
                            f"({lock}) but written without it in "
                            f"{w.method}()",
                            f"wrap the write in `with {_self(lock)}:` or "
                            "mark the method "
                            f"`# koordlint: guarded-by({_self(lock)})`"))
                continue
            guarded = [w for w in writes if w.held]
            bare = [w for w in writes if not w.held]
            if guarded and bare:
                locks = sorted({lk for w in guarded for lk in w.held})
                for w in bare:
                    findings.append(Finding(
                        "lock-discipline", model.sf.path, w.line,
                        f"{model.name}.{attr} is written under "
                        f"{'/'.join(locks)} in "
                        f"{sorted({g.method for g in guarded})} but bare "
                        f"in {w.method}() — race candidate",
                        f"hold {locks[0]} here, or declare intent with "
                        f"`# koordlint: guarded-by({_self(locks[0])})` / "
                        "an ignore with reason"))
        return findings


def _own_expr_nodes(stmt: ast.stmt):
    """The expression nodes belonging to one statement, NOT descending
    into nested statement blocks (body/orelse/finalbody/handlers) — the
    edge walker recurses into those itself."""
    stack: list[ast.AST] = []
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        stack.extend(v for v in (value if isinstance(value, list)
                                 else [value])
                     if isinstance(v, ast.AST))
    while stack:
        node = stack.pop()
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _self(lock_id: str) -> str:
    return f"self.{lock_id.rsplit('.', 1)[1]}"


def _strip_optional(node: ast.AST) -> ast.AST:
    """``Foo | None`` / ``Optional[Foo]`` -> ``Foo`` for type inference."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        for side in (node.left, node.right):
            if not (isinstance(side, ast.Constant) and side.value is None):
                return side
    if isinstance(node, ast.Subscript):
        base = node.value
        if isinstance(base, ast.Name) and base.id == "Optional":
            return node.slice
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            return ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return node
    return node
