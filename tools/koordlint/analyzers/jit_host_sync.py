"""jit-host-sync: no silent device syncs inside the traced closure.

The <200ms-p99 solve target dies quietly when host-sync creeps into a
jitted function: ``.item()`` / ``float()`` / ``int()`` / ``bool()`` /
``np.asarray`` on a traced value forces a device round-trip per call (or
a ConcretizationTypeError at the first real trace), and a data-dependent
``if`` on a traced value recompiles per branch value.  This analyzer
finds every ``jax.jit`` site, walks the project call graph to the whole
traced closure, and taint-tracks traced values through it:

- a jitted entry's parameters are traced except ``static_argnames``,
- any ``jax.*`` / ``jax.numpy`` call result is traced,
- taint propagates through assignment, arithmetic, and project-internal
  calls (callee parameters inherit the caller's argument taint),
- ``.shape`` / ``.ndim`` / ``.dtype`` / ``.size`` / ``.capacity`` reads,
  ``len()``, and ``x is None`` tests are host-static and NOT tainted
  (shape-driven branches are how bucketed jit is supposed to work).

Flagged on tainted values: host-cast calls (``int/float/bool/np.asarray/
np.array``), sync methods (``.item()/.tolist()``), data-dependent
``if``/``while`` tests, host iteration (``for _ in traced``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from ..callgraph import (
    FunctionInfo,
    ModuleIndex,
    extract_jit_sites,
    get_index,
)
from ..core import Analyzer, Finding, Project

#: attribute reads that are static under tracing (shape-bucketing reads)
HOST_SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "capacity"}
#: builtins whose call on a traced value is a host sync
HOST_CAST_BUILTINS = {"int", "float", "bool", "complex"}
#: method calls on a traced value that force a device round-trip
SYNC_METHODS = {"item", "tolist", "to_py", "__array__"}
#: resolved dotted callees that materialize on host
HOST_CAST_FUNCS = {"numpy.asarray", "numpy.array", "numpy.float64",
                   "numpy.float32", "numpy.int32", "numpy.int64"}

#: ``None`` = every ``jax.jit`` site in the package seeds the analysis
#: (the scheduler's solve entry points in scheduler.py / batch_assign /
#: explain per ISSUE 7, plus the deviceshare/numa decorator kernels,
#: quota overuse-revoke and manager noderesource jits); everything
#: reachable through the call graph is checked.  A list of
#: repo-relative paths narrows the seeding (fixture corpora use this).
DEFAULT_ROOT_PATHS = None


@dataclasses.dataclass
class _Ctx:
    fn: FunctionInfo
    tainted_params: frozenset[str]


class JitHostSyncAnalyzer(Analyzer):
    name = "jit-host-sync"
    description = ("host-sync calls and data-dependent branches on traced "
                   "values reachable from jax.jit entry points")

    def __init__(self, root_paths: Optional[list[str]] = None,
                 package: str = "koordinator_tpu"):
        self.root_paths = root_paths if root_paths is not None else (
            DEFAULT_ROOT_PATHS)
        self.package = package

    def run(self, project: Project) -> list[Finding]:
        index = get_index(project, self.package)
        paths = (None if self.root_paths is None else
                 [p for p in self.root_paths
                  if project.get(p) is not None])
        sites = extract_jit_sites(index, paths=paths)
        findings: dict[tuple, Finding] = {}
        #: fn.fq -> taint set already analyzed (worklist merges upward)
        analyzed: dict[str, frozenset[str]] = {}
        work: list[_Ctx] = []

        for site in sites:
            if site.func_node is not None and site.func_fq is None:
                # inline lambda: analyze directly, every param traced.
                # The line disambiguates multiple lambdas per module in
                # the worklist key (they'd otherwise dedupe as one).
                fn = FunctionInfo(module_of(index, site),
                                  f"<lambda@{site.line}>",
                                  site.func_node, site.sf)
                params = _param_names(site.func_node)
                work.append(_Ctx(fn, frozenset(params)))
                continue
            fn = index.find_function(site.func_fq)
            if fn is None:
                continue
            host = set(site.static_argnames) | _host_static_params(
                index, site, fn)
            params = [p for p in _param_names(fn.node)
                      if p not in host and p != "self"]
            work.append(_Ctx(fn, frozenset(params)))

        while work:
            ctx = work.pop()
            prev = analyzed.get(ctx.fn.fq, frozenset())
            taint = prev | ctx.tainted_params
            if ctx.fn.fq in analyzed and taint == prev:
                continue
            analyzed[ctx.fn.fq] = taint
            visitor = _TaintVisitor(index, ctx.fn, taint, findings)
            visitor.run()
            for callee, call, callee_taint in visitor.calls_out:
                work.append(_Ctx(callee, frozenset(callee_taint)))
        return sorted(findings.values(), key=lambda f: (f.path, f.line))


def module_of(index: ModuleIndex, site) -> str:
    for mod, sf in index.modules.items():
        if sf is site.sf:
            return mod
    return "?"


def _defaults_by_param(node: ast.AST) -> dict[str, ast.AST]:
    a = node.args
    out: dict[str, ast.AST] = {}
    pos = a.posonlyargs + a.args
    for param, default in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        out[param.arg] = default
    for param, default in zip(a.kwonlyargs, a.kw_defaults):
        if default is not None:
            out[param.arg] = default
    return out


def _host_static_params(index: ModuleIndex, site,
                        fn: FunctionInfo) -> set[str]:
    """Defaulted parameters that are static in practice.

    Two cases keep a non-``static_argnames`` parameter on the host side:

    - a **string default** (``method="auto"``): strings are not valid
      JAX types, so passing one at trace time errors LOUDLY — the value
      only ever exists as a baked-in Python constant;
    - a defaulted parameter **never supplied at any call site of the
      jit binding** (``spread_bits=(5, 15)``): the default is closed
      over at trace time, never traced.  Only applies when at least one
      call site of the binding is visible — with zero observed callers
      the conservative all-traced seeding stands.
    """
    defaults = _defaults_by_param(fn.node)
    host = {p for p, d in defaults.items()
            if isinstance(d, ast.Constant) and isinstance(d.value, str)}
    if not site.binding:
        return host
    params = [p for p in _param_names(fn.node) if p != "self"]
    supplied: set[str] = set()
    seen_call = False
    attr_calls, fq_calls = _call_site_index(index)
    if site.binding_class is not None:
        calls = attr_calls.get(
            (f"{site.module}.{site.binding_class}", site.binding), [])
    else:
        # call sites are indexed by RESOLVED fully-qualified callee, so
        # from-import aliases count and a same-named function in another
        # module does not
        binding_fq = f"{site.module}.{site.binding}"
        seen_ids: set[int] = set()
        calls = []
        for fq in {binding_fq, site.func_fq} - {None}:
            for c, m in fq_calls.get(fq, []):
                if id(c) not in seen_ids:
                    seen_ids.add(id(c))
                    calls.append((c, m))
    for call, _mod in calls:
        seen_call = True
        if any(isinstance(a, ast.Starred) for a in call.args) or any(
                k.arg is None for k in call.keywords):
            return host  # *args/**kwargs caller: anything may flow
        supplied |= set(params[: len(call.args)])
        supplied |= {k.arg for k in call.keywords if k.arg}
    if seen_call:
        host |= {p for p in defaults if p not in supplied}
    return host


def _call_site_index(index: ModuleIndex):
    """One pass over every indexed function: ``self.<attr>`` calls
    grouped by (module.Class, attr); every other call grouped by its
    RESOLVED fully-qualified callee (import aliases included, bare
    locals qualified with the caller's module).  Cached on the index."""
    cached = getattr(index, "_jit_call_sites", None)
    if cached is not None:
        return cached
    attr_calls: dict[tuple[str, str], list] = {}
    fq_calls: dict[str, list] = {}
    for caller in index.functions.values():
        cls = (caller.qualname.rsplit(".", 1)[0]
               if "." in caller.qualname else None)
        for call in ast.walk(caller.node):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self" and cls):
                attr_calls.setdefault(
                    (f"{caller.module}.{cls}", f.attr), []).append(
                    (call, caller.module))
                continue
            resolved = index.resolve(caller.module, f)
            if not resolved:
                continue
            if "." not in resolved:
                resolved = f"{caller.module}.{resolved}"
            fq_calls.setdefault(resolved, []).append(
                (call, caller.module))
    index._jit_call_sites = (attr_calls, fq_calls)
    return index._jit_call_sites


def _param_names(node: ast.AST) -> list[str]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class _TaintVisitor:
    """One pass over one function body with a fixed entry taint set.

    Statement order is respected (assignments untaint / taint names as
    they execute); two passes run so names bound later in the body (rare
    helper-closure style) still settle.
    """

    def __init__(self, index: ModuleIndex, fn: FunctionInfo,
                 tainted_params: frozenset[str], findings: dict):
        self.index = index
        self.fn = fn
        self.mod = fn.module
        self.findings = findings
        self.tainted_params = tainted_params
        self.tainted: set[str] = set(tainted_params)
        #: *args / **kwargs names: PYTHON containers of traced leaves —
        #: iterating them unrolls statically (fine); their ELEMENTS are
        #: traced (subscripts stay tainted via the tainted set)
        a = getattr(fn.node, "args", None)
        self.containers: set[str] = {
            n.arg for n in (a.vararg, a.kwarg) if n is not None
        } if a is not None else set()
        #: (callee, call node, tainted callee params) discovered
        self.calls_out: list[tuple[FunctionInfo, ast.Call, set[str]]] = []

    def run(self) -> None:
        body = (self.fn.node.body if isinstance(self.fn.node.body, list)
                else [ast.Expr(value=self.fn.node.body)])  # Lambda
        for _ in range(2):
            self.calls_out.clear()
            self._block(body)

    def _flag(self, node: ast.AST, what: str, hint: str) -> None:
        key = (self.fn.fq, node.lineno, what)
        if key not in self.findings:
            self.findings[key] = Finding(
                "jit-host-sync", self.fn.sf.path, node.lineno,
                f"{what} in {self.fn.qualname!r} (reachable from a "
                f"jax.jit entry point)", hint)

    # -- taint evaluation -----------------------------------------------------

    def _is_none_check(self, node: ast.Compare) -> bool:
        return (all(isinstance(c, ast.Constant) and c.value is None
                    for c in node.comparators)
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops))

    def _is_str_check(self, node: ast.Compare) -> bool:
        """Comparisons against string constants are host-static: strings
        are not valid JAX types, so the left side cannot be traced (a
        traced value there would already have errored at trace time)."""

        def is_str(n: ast.AST) -> bool:
            if isinstance(n, ast.Constant):
                return isinstance(n.value, str)
            if isinstance(n, (ast.Tuple, ast.List, ast.Set)):
                return bool(n.elts) and all(is_str(e) for e in n.elts)
            return False

        return is_str(node.left) or any(is_str(c) for c in node.comparators)

    def tainted_expr(self, node: ast.AST) -> bool:  # noqa: C901
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in HOST_SAFE_ATTRS:
                return False
            return self.tainted_expr(node.value)
        if isinstance(node, ast.Subscript):
            # shape[i] and friends stay host-static
            if (isinstance(node.value, ast.Attribute)
                    and node.value.attr in HOST_SAFE_ATTRS):
                return False
            return (self.tainted_expr(node.value)
                    or self.tainted_expr(node.slice))
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.Compare):
            if self._is_none_check(node) or self._is_str_check(node):
                return False
            return (self.tainted_expr(node.left)
                    or any(self.tainted_expr(c) for c in node.comparators))
        if isinstance(node, (ast.BinOp,)):
            return self.tainted_expr(node.left) or self.tainted_expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.tainted_expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.tainted_expr(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            if self.tainted_expr(node.test):
                self._flag(node, "data-dependent conditional expression "
                                 "on a traced value",
                           "use jnp.where / lax.select instead of a "
                           "Python conditional")
            return (self.tainted_expr(node.body)
                    or self.tainted_expr(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted_expr(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.tainted_expr(v) for v in node.values if v)
        if isinstance(node, ast.Starred):
            return self.tainted_expr(node.value)
        if isinstance(node, ast.Slice):
            return any(self.tainted_expr(p) for p in
                       (node.lower, node.upper, node.step) if p)
        if isinstance(node, ast.JoinedStr):
            return any(self.tainted_expr(v) for v in node.values)
        if isinstance(node, ast.FormattedValue):
            return self.tainted_expr(node.value)
        return False

    def _call(self, node: ast.Call) -> bool:
        func = node.func
        args_tainted = (any(self.tainted_expr(a) for a in node.args)
                        or any(self.tainted_expr(k.value)
                               for k in node.keywords))
        # builtins that force a concrete host value
        if isinstance(func, ast.Name):
            if func.id in HOST_CAST_BUILTINS and args_tainted:
                self._flag(node, f"host cast {func.id}() of a traced value",
                           "keep device dtype (jnp.asarray / .astype) or "
                           "hoist the cast outside the jit")
                return False
            if func.id == "len":
                return False  # static under tracing
        # sync methods on a traced value
        if (isinstance(func, ast.Attribute) and func.attr in SYNC_METHODS
                and self.tainted_expr(func.value)):
            self._flag(node, f".{func.attr}() on a traced value",
                       "return the array and read it on host after the "
                       "jit boundary")
            return False
        resolved = self.index.resolve(self.mod, func)
        if resolved in HOST_CAST_FUNCS and args_tainted:
            self._flag(node, f"{resolved}() materializes a traced value "
                             "on host",
                       "use jnp inside the jit; np belongs outside")
            return False
        if resolved and (resolved.startswith("jax.") or resolved == "jax"):
            return True  # device-land result
        # project-internal call: propagate taint into the callee
        target = self._target(func)
        if target is not None:
            callee_taint = self._map_args(target, node)
            self.calls_out.append((target, node, callee_taint))
            return args_tainted or self.tainted_expr(func)
        # method on a traced value (.at[..].set, .replace, .astype, ...)
        if isinstance(func, ast.Attribute) and self.tainted_expr(func.value):
            return True
        return args_tainted

    def _iter_info(self, node: ast.AST) -> tuple[bool, bool]:
        """(static_unroll, elements_tainted) for an iteration source.

        ``*args``/``**kwargs`` containers (sliced or not) are PYTHON
        tuples — iterating them unrolls at trace time even when their
        ELEMENTS are traced; zip/enumerate/reversed over such containers
        (or over host values) likewise.  A tainted array iterated
        directly is the real host-sync hazard and returns (False, _).
        """
        if isinstance(node, ast.Name) and node.id in self.containers:
            return True, True
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id in self.containers):
            return True, True
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in ("zip", "enumerate", "reversed")):
            elems = False
            for a in node.args:
                st, et = self._iter_info(a)
                if st:
                    elems = elems or et
                elif self.tainted_expr(a):
                    return False, True
            return True, elems
        return False, False

    def _target(self, func: ast.AST) -> Optional[FunctionInfo]:
        cls = (self.fn.qualname.rsplit(".", 1)[0]
               if "." in self.fn.qualname else None)
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in ("self", "cls") and cls):
            return self.index.find_function(f"{self.mod}.{cls}.{func.attr}")
        return self.index.find_function(self.index.resolve(self.mod, func))

    def _map_args(self, target: FunctionInfo, call: ast.Call) -> set[str]:
        params = _param_names(target.node)
        if params and params[0] == "self":
            params = params[1:]
        out: set[str] = set()
        for i, a in enumerate(call.args):
            if self.tainted_expr(a) and i < len(params):
                out.add(params[i])
        for k in call.keywords:
            if k.arg and self.tainted_expr(k.value) and k.arg in params:
                out.add(k.arg)
        return out

    # -- statements -----------------------------------------------------------

    def _block(self, stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _assign_target(self, target: ast.AST, value_tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if value_tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign_target(e, value_tainted)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, value_tainted)
        # attribute/subscript stores keep their base's taint

    def _stmt(self, stmt: ast.stmt) -> None:  # noqa: C901
        if isinstance(stmt, ast.Assign):
            t = self.tainted_expr(stmt.value)
            for target in stmt.targets:
                self._assign_target(target, t)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign_target(stmt.target,
                                    self.tainted_expr(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            if self.tainted_expr(stmt.value):
                self._assign_target(stmt.target, True)
            else:
                self.tainted_expr(stmt.target)
        elif isinstance(stmt, (ast.If, ast.While)):
            if self.tainted_expr(stmt.test):
                self._flag(stmt, "data-dependent branch on a traced value",
                           "branch on static args / shapes, or use "
                           "jnp.where / lax.cond")
            self._block(stmt.body)
            self._block(stmt.orelse)
        elif isinstance(stmt, ast.For):
            static_unroll, elems_tainted = self._iter_info(stmt.iter)
            if static_unroll:
                self._assign_target(stmt.target, elems_tainted)
            elif self.tainted_expr(stmt.iter):
                self._flag(stmt, "host iteration over a traced value",
                           "use lax.scan / lax.fori_loop, or hoist the "
                           "loop outside the jit")
                self._assign_target(stmt.target, True)
            else:
                self._assign_target(stmt.target, False)
            self._block(stmt.body)
            self._block(stmt.orelse)
        elif isinstance(stmt, ast.Assert):
            if self.tainted_expr(stmt.test):
                self._flag(stmt, "assert on a traced value",
                           "asserts are host control flow; use "
                           "checkify or assert on shapes only")
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self.tainted_expr(stmt.value)
        elif isinstance(stmt, (ast.With,)):
            for item in stmt.items:
                self.tainted_expr(item.context_expr)
            self._block(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._block(stmt.body)
            for h in stmt.handlers:
                self._block(h.body)
            self._block(stmt.orelse)
            self._block(stmt.finalbody)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pass  # nested defs analyzed only if called (via call graph)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self.tainted_expr(stmt.exc)
