"""wire-codec: no per-event JSON on frames with a columnar encoding.

Protocol v4 gave DELTA / SNAPSHOT / STATE_PUSH a columnar ``events_v2``
payload (tools ran ~25x faster on the encode/decode half of
``json_codec`` — see docs/wire_protocol.md).  The regression this rule
guards against is the one the tentpole removed: a caller that loops
``json.dumps`` per event and ships K tiny documents (or one document
built from K per-event dumps) instead of packing ONE columnar frame.
That pattern re-inflates ``pipeline_host_wait_fraction`` quietly — the
frames still validate, the peers still converge, only the soak timeline
shows ``json_codec`` creeping back up.

The rule is lexical and deliberately narrow:

- a function counts as *handling a columnar frame* when it references
  ``FrameType.DELTA`` / ``FrameType.SNAPSHOT`` / ``FrameType.STATE_PUSH``
  (any dotted spelling — ``wire.FrameType.DELTA`` included);
- inside such a function, a ``json.dumps`` call lexically inside a loop
  (``for`` / ``while`` / any comprehension) is a finding — per-frame
  encoding is one dumps per FRAME, never one per event;
- the codec home itself (transport/wire.py, transport/deltasync.py) is
  exempt: the v1 fallback paths there legitimately serialize per event
  for pre-v4 peers, and that is where the one-dumps-per-frame invariant
  is implemented rather than consumed.
"""

from __future__ import annotations

import ast

from ..callgraph import get_index
from ..core import Analyzer, Finding, Project
from .donation_safety import dotted_path

#: frame types that carry a columnar (events_v2) payload in protocol v4
COLUMNAR_FRAMES = ("DELTA", "SNAPSHOT", "STATE_PUSH")

#: where the codec lives — per-event JSON is the v1 compatibility path
#: there, not a regression
DEFAULT_CODEC_HOME = (
    "koordinator_tpu/transport/wire.py",
    "koordinator_tpu/transport/deltasync.py",
)

_LOOPS = (ast.For, ast.AsyncFor, ast.While,
          ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


class WireCodecAnalyzer(Analyzer):
    name = "wire-codec"
    description = ("per-event json.dumps in a loop while handling a "
                   "frame type that has a columnar events_v2 encoding "
                   "(DELTA/SNAPSHOT/STATE_PUSH)")

    def __init__(self, package: str = "koordinator_tpu",
                 codec_home: tuple[str, ...] = DEFAULT_CODEC_HOME):
        self.package = package
        self.codec_home = set(codec_home)

    def run(self, project: Project) -> list[Finding]:
        index = get_index(project, self.package)
        findings: list[Finding] = []
        for fq, fn in sorted(index.functions.items()):
            if fn.sf.path in self.codec_home:
                continue
            frames = _columnar_frames_referenced(fn.node)
            if not frames:
                continue
            for dumps in _loop_dumps_calls(index, fn):
                findings.append(Finding(
                    self.name, fn.sf.path, dumps.lineno,
                    f"per-event json.dumps in a loop in {fn.qualname!r} "
                    f"while handling FrameType.{'/'.join(frames)} — "
                    "these frames have a columnar events_v2 encoding; "
                    "per-event JSON regresses json_codec host-wait",
                    hint="pack the whole batch once (columnar "
                         "events_v2 via the deltasync codec, raw "
                         "arrays via wire.encode_payload) and ship "
                         "ONE frame; see docs/wire_protocol.md"))
        dedup: dict[tuple, Finding] = {}
        for f in findings:
            dedup.setdefault((f.path, f.line), f)
        return sorted(dedup.values(), key=lambda f: (f.path, f.line))


def _columnar_frames_referenced(node: ast.AST) -> list[str]:
    """Columnar FrameType members this function mentions, in enum order."""
    seen: set[str] = set()
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Attribute):
            continue
        if sub.attr not in COLUMNAR_FRAMES:
            continue
        dotted = dotted_path(sub)
        if dotted and dotted.split(".")[-2:-1] == ["FrameType"]:
            seen.add(sub.attr)
    return [f for f in COLUMNAR_FRAMES if f in seen]


def _is_json_dumps(index, mod: str, func: ast.AST) -> bool:
    if (isinstance(func, ast.Attribute) and func.attr == "dumps"
            and isinstance(func.value, ast.Name)
            and func.value.id == "json"):
        return True
    return index.resolve(mod, func) == "json.dumps"


def _loop_dumps_calls(index, fn) -> list[ast.Call]:
    """json.dumps calls lexically inside a loop of this function (the
    loop bodies of nested defs included — a helper closure looping
    dumps inside the handler is the same hot path)."""
    out: list[ast.Call] = []
    seen: set[int] = set()
    for loop in ast.walk(fn.node):
        if not isinstance(loop, _LOOPS):
            continue
        for call in ast.walk(loop):
            if (isinstance(call, ast.Call) and id(call) not in seen
                    and _is_json_dumps(index, fn.module, call.func)):
                seen.add(id(call))
                out.append(call)
    return out
