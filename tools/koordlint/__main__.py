"""CLI: ``python -m tools.koordlint`` from the repo root.

Exit codes: 0 = clean (suppressed findings allowed), 1 = unsuppressed
findings (the CI/soak gate), 2 = bad usage.  Runs at the head of
tools/soak.sh (``--format json``) and inside tier-1 via
tests/test_koordlint.py.

``--format json`` emits machine-readable findings (file/line/rule/
message/fix-hint) for pre-commit hooks and the soak head;
``--changed-only <git-ref>`` reports only findings in files touched
since the ref (the call graph is still built whole-tree, so
interprocedural rules keep their seeds).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from . import BASELINE_PATH, make_all, run


def changed_paths(root: str, ref: str) -> set[str] | None:
    """Repo-relative .py files touched since ``ref`` (committed or
    not), or None when git cannot answer."""
    out: set[str] = set()
    for cmd in (["git", "diff", "--name-only", ref, "--", "*.py"],
                ["git", "ls-files", "--others", "--exclude-standard",
                 "--", "*.py"]):
        try:
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        out.update(line.strip().replace(os.sep, "/")
                   for line in proc.stdout.splitlines() if line.strip())
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.koordlint",
        description="repo-native static analysis (jit purity, donation "
                    "safety, lock discipline, surface parity, dashboard "
                    "drift, marker audit, specflow mesh/dtype/donation/"
                    "tenancy dataflow rules)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: this package's repo)")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="NAME",
                        help="run only the named rule (repeatable)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore baseline.json (show every finding)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text", dest="fmt",
                        help="output format (json = machine-readable "
                             "findings with file/line/rule/fix-hint)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="deprecated alias for --format json")
    parser.add_argument("--changed-only", metavar="GIT_REF",
                        dest="changed_only",
                        help="report only findings in files touched "
                             "since GIT_REF (callgraph still built "
                             "whole-tree)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for a in make_all():
            print(f"{a.name:18s} {a.description}")
        return 0

    root = args.root or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "..")
    root = os.path.abspath(root)
    known = {a.name for a in make_all()} | {"lint-hygiene"}
    for r in args.rules or []:
        if r not in known:
            print(f"unknown rule {r!r}; try --list-rules", file=sys.stderr)
            return 2

    only: set[str] | None = None
    if args.changed_only:
        only = changed_paths(root, args.changed_only)
        if only is None:
            print(f"--changed-only: git diff against "
                  f"{args.changed_only!r} failed in {root}",
                  file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    result = run(root, rules=args.rules,
                 baseline_path=None if args.no_baseline else BASELINE_PATH,
                 only_paths=only)
    elapsed = time.perf_counter() - t0

    if args.as_json or args.fmt == "json":
        print(json.dumps({
            "findings": [f.to_doc() for f in result.findings],
            "suppressed": [{"finding": f.to_doc(), "reason": r}
                           for f, r in result.suppressed],
            "stale_baseline": [e.rule + ":" + e.path
                               for e in result.stale_baseline],
            "changed_only": sorted(only) if only is not None else None,
            "elapsed_s": round(elapsed, 3),
        }, indent=2))
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    for entry in result.stale_baseline:
        print(f"note: stale baseline entry matched nothing: "
              f"[{entry.rule}] {entry.path!r} ({entry.reason})",
              file=sys.stderr)
    status = "FAIL" if result.findings else "OK"
    scope = (f" ({len(only)} changed file(s))"
             if only is not None else "")
    print(f"koordlint {status}: {len(result.findings)} finding(s){scope}, "
          f"{len(result.suppressed)} suppressed-with-reason, "
          f"{elapsed:.2f}s")
    return 0 if result.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
