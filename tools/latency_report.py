#!/usr/bin/env python
"""Fleet-wide pod-journey latency table from per-process sketch snapshots.

Every binary flushes its journey ledger to JSONL on teardown when
``KOORD_JOURNEY_JSONL`` names a path (one line per (tenant, qos, stage)
series, carrying the full log-bucketed sketch — see
koordinator_tpu/journey.py).  This tool merges any number of those
files into ONE journey table: merge is bucket-wise addition, so the
fleet-merged quantiles carry the same <=1% relative-error bound as each
process's own sketches — no raw samples ship, no accuracy is lost to
re-aggregation (the federation-ready primitive, ROADMAP item 4).

    python tools/latency_report.py /var/run/koord/*.journey.jsonl
    python tools/latency_report.py --tenant a --json sched.jsonl mgr.jsonl

Exit status: 0 when at least one series merged, 2 when the inputs held
no journey rows (empty files are a configuration smell, not silence).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

from koordinator_tpu.journey import (  # noqa: E402
    RELATIVE_ACCURACY,
    STAGES,
    merge_snapshot_rows,
)

QUANTILES = (0.5, 0.9, 0.99)


def read_rows(paths: list[str]) -> list[dict]:
    """All journey JSONL rows across the input files (blank lines and
    non-journey records are skipped, not fatal — soak artifacts mix
    record kinds in one directory)."""
    rows = []
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if {"tenant", "qos", "stage", "sketch"} <= set(doc):
                    rows.append(doc)
    return rows


def journey_table(rows: list[dict], tenant: str | None = None) -> dict:
    """Merge snapshot rows into the fleet journey table doc."""
    merged = merge_snapshot_rows(
        r for r in rows if tenant is None or r["tenant"] == tenant)
    series = []
    for (t, qos, stage) in sorted(merged):
        sk = merged[(t, qos, stage)]
        row = {"tenant": t, "qos": qos, "stage": stage,
               "count": sk.count, "mean_s": sk.mean(),
               "max_s": sk.max_value}
        for q in QUANTILES:
            row[f"p{int(q * 100)}_s"] = sk.quantile(q)
        series.append(row)
    return {"alpha": RELATIVE_ACCURACY, "stages": list(STAGES),
            "series": series}


def _fmt_s(v: float | None) -> str:
    if v is None:
        return "-"
    if v >= 1.0:
        return f"{v:.3f}s"
    return f"{v * 1e3:.2f}ms"


def print_table(table: dict, out=None) -> None:
    # resolve stdout at CALL time — a def-time default pins whatever
    # sys.stdout was at import and breaks under redirection
    out = out if out is not None else sys.stdout
    print(f"== pod journey (fleet-merged, "
          f"alpha={table['alpha']:.0%} relative error)", file=out)
    print(f"{'tenant':<10} {'qos':>3} {'stage':<10} {'count':>8} "
          f"{'p50':>10} {'p90':>10} {'p99':>10} {'max':>10}", file=out)
    for row in table["series"]:
        print(f"{row['tenant'] or '-':<10} {row['qos']:>3} "
              f"{row['stage']:<10} {row['count']:>8} "
              f"{_fmt_s(row['p50_s']):>10} {_fmt_s(row['p90_s']):>10} "
              f"{_fmt_s(row['p99_s']):>10} {_fmt_s(row['max_s']):>10}",
              file=out)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="latency_report",
        description="merge journey-ledger JSONL snapshots into one "
                    "fleet-wide latency quantile table")
    parser.add_argument("paths", nargs="+",
                        help="journey JSONL snapshot files "
                             "(KOORD_JOURNEY_JSONL outputs)")
    parser.add_argument("--tenant", default=None,
                        help="only this tenant's series")
    parser.add_argument("--json", action="store_true",
                        help="emit the merged table as JSON instead of "
                             "the aligned text table")
    args = parser.parse_args(argv)
    table = journey_table(read_rows(args.paths), tenant=args.tenant)
    if args.json:
        print(json.dumps(table, indent=2, sort_keys=True))
    else:
        print_table(table)
    if not table["series"]:
        print("no journey series in the inputs (was the ledger off, or "
              "KOORD_JOURNEY_JSONL unset?)", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
