"""Stage-split profiler for the north-star solve.

Times the three stages of ``batch_assign`` separately at the 50k x 10,240
shape so optimization effort lands where the milliseconds are:

  score    — score_pods: the (P, N) filter+score tensor pipeline
  select_* — select_candidates per method (approx / chunked / ...):
             the (P, N) -> (P, k) top-k reduction INCLUDING scoring
             (the stages overlap by design: chunked never
             materialize the full score tensor, so "selection minus
             scoring" is not a physical quantity for them)
  rounds   — _assign_rounds: the propose/accept conflict-resolution
             loop given precomputed candidates (the only stage that is
             sequential in k and rounds)

Methodology matches bench.py: chained fori_loop iterations with a data
dependency through node_usage, pods/candidates as TRACED arguments (not
closure constants), tunnel rtt floor subtracted.  Each stage prints one
JSON line so a timeout keeps the finished stages.

Usage:  python bench_stages.py [--smoke]  (--smoke: tiny shape, any
backend, for CI; the real capture needs the TPU tunnel).
"""

from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp

from bench import K_ITERS, _git_head, _median_readback_seconds

N_NODES = 10_240
N_PODS = 50_000
K = 16
SPREAD = (5, 15)


def _emit(stage: str, seconds: float, extra: dict | None = None) -> None:
    rec = {"stage": stage, "ms_per_iter": round(seconds * 1e3, 2)}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def _time_chained(fn, args, rtt: float, iters: int = K_ITERS, n: int = 3):
    total, value = _median_readback_seconds(jax.jit(fn), args, n=n)
    return max((total - rtt) / iters, 1e-9), value


def main() -> None:
    smoke = "--smoke" in sys.argv
    if smoke:
        # the ambient sitecustomize pins the tunnel backend via
        # jax.config, so JAX_PLATFORMS=cpu alone is not enough (see
        # tests/conftest.py) — and a wedged tunnel would hang the smoke
        jax.config.update("jax_platforms", "cpu")
    n_nodes, n_pods = (256, 1_024) if smoke else (N_NODES, N_PODS)
    n_nodes = int(os.environ.get("KOORD_STAGES_NODES", n_nodes))
    n_pods = int(os.environ.get("KOORD_STAGES_PODS", n_pods))
    methods = tuple(os.environ.get("KOORD_STAGES_METHODS",
                                   "approx,chunked").split(","))
    iters = 2 if smoke else K_ITERS

    from __graft_entry__ import _build_problem
    from koordinator_tpu.ops.assignment import score_pods
    from koordinator_tpu.ops.batch_assign import (_assign_rounds,
                                                  select_candidates)

    state, pods, cfg = _build_problem(n_nodes, n_pods, seed=42)

    # code provenance first: a stage capture promoted into a later zero
    # record (bench._latest_probe_stages) must be tied to the commit it
    # measured, like the headline captures are.  Mesh-shape provenance
    # rides the same line (ISSUE 10): a sharded-path win is meaningless
    # without the device count and axis sizes it was measured on.
    from koordinator_tpu.parallel import mesh as pmesh

    # honor the 2-D env overrides (KOORD_SOLVER_MESH=PxN /
    # KOORD_SOLVER_MESH_PODS) so a staged capture measures the same
    # axis split the scheduler would solve on; fall back to the 1-way
    # all-nodes mesh on a single device (resolve returns None there)
    mesh = pmesh.resolve_solver_mesh("auto") or pmesh.solver_mesh()
    n_shards = pmesh.nodes_shard_count(mesh)
    p_shards = pmesh.pods_shard_count(mesh)
    print(json.dumps({
        "stage": "provenance", **_git_head(),
        "n_devices": len(jax.devices()),
        "mesh_axes": pmesh.mesh_axes(mesh),
        "mesh_axis_names": list(mesh.axis_names),
        "mesh_shape": f"{p_shards}x{n_shards}",
    }), flush=True)

    def rtt_fn(st, p):
        return st.node_allocatable.sum() + p.requests.sum()

    rtt, _ = _median_readback_seconds(jax.jit(rtt_fn), (state, pods))
    _emit("rtt_floor", rtt, {"backend": jax.default_backend(),
                             "shape": f"{n_pods}p_{n_nodes}n", "k": K})
    stage_secs: dict[str, float] = {}

    # -- score: keep the full (P, N) tensor live through the chain
    def score_loop(st0, p):
        def body(i, carry):
            acc, usage = carry
            scores, feasible = score_pods(st0.replace(node_usage=usage), p,
                                          cfg)
            return (acc + scores.sum() + feasible.sum(),
                    usage + (scores[0, :, None] & 1))
        acc, _ = jax.lax.fori_loop(0, iters, body,
                                   (jnp.int32(0), st0.node_usage))
        return acc

    sec, _ = _time_chained(score_loop, (state, pods), rtt, iters)
    stage_secs["score"] = sec
    _emit("score", sec)

    # -- select per method: scoring + top-k reduction to (P, k)
    def select_loop(method):
        def fn(st0, p):
            def body(i, carry):
                acc, usage = carry
                key, node = select_candidates(
                    st0.replace(node_usage=usage), p, cfg, k=K,
                    spread_bits=SPREAD, method=method)
                # scalar perturbation keeps the loop-carried data
                # dependency without caring about (N, dims) layout
                return (acc + key.sum() + node.sum(),
                        usage + (node.sum() & 1))
            acc, _ = jax.lax.fori_loop(0, iters, body,
                                       (jnp.int32(0), st0.node_usage))
            return acc
        return fn

    for method in methods:
        try:
            sec, _ = _time_chained(select_loop(method), (state, pods), rtt,
                                   iters)
            stage_secs[f"select_{method}"] = sec
            _emit(f"select_{method}", sec)
        except Exception as e:  # a broken variant must not cost the run
            print(json.dumps({"stage": f"select_{method}",
                              "error": repr(e)[:200]}), flush=True)

    # -- rounds: propose/accept given precomputed candidates (traced args);
    # scores ride along so the refresh stage below gets a CONSISTENT
    # (key, node, score) triple from the SAME selection
    cand_key, cand_node, cand_score = jax.jit(
        lambda st, p: select_candidates(st, p, cfg, k=K, spread_bits=SPREAD,
                                        method="chunked",
                                        with_scores=True))(state, pods)
    cand_key.block_until_ready()

    def rounds_loop(st0, p, ckey, cnode):
        def body(i, carry):
            acc, usage = carry
            assignments, new_state, _ = _assign_rounds(
                st0.replace(node_usage=usage), p, None, ckey, cnode,
                rounds=12)
            return (acc + (assignments >= 0).sum().astype(jnp.int32),
                    usage + (new_state.node_requested & 1))
        acc, _ = jax.lax.fori_loop(0, iters, body,
                                   (jnp.int32(0), st0.node_usage))
        return acc

    sec, value = _time_chained(rounds_loop, (state, pods, cand_key,
                                             cand_node), rtt, iters)
    stage_secs["rounds"] = sec
    _emit("rounds", sec, {"assigned_per_iter": round(value / iters, 1)})

    # -- incremental refresh: the steady-state replacement for select_* —
    # dirty-COLUMN merge into a resident candidate cache at ~1% dirty
    # nodes (ops/batch_assign.refresh_candidates).  select_* + rounds is
    # the cold-path cost; refresh + rounds is the steady-state cost.
    import numpy as np

    from koordinator_tpu.ops.batch_assign import (CandidateCache,
                                                  refresh_candidates)
    from koordinator_tpu.state.cluster_state import _bucket

    cache = CandidateCache(cand_key, cand_node, cand_score)
    n_dirty = max(n_nodes // 100, 1)
    dpad = _bucket(n_dirty, minimum=64)
    drows = np.zeros(dpad, np.int32)
    drows[:n_dirty] = np.arange(n_dirty)
    dvalid = np.zeros(dpad, bool)
    dvalid[:n_dirty] = True

    def refresh_loop(st0, p, c, dr, dv):
        def body(i, carry):
            acc, usage = carry
            key, c2 = refresh_candidates(
                st0.replace(node_usage=usage), p, cfg, c, dr, dv,
                k=K, spread_bits=SPREAD)
            return (acc + key.sum() + c2.cand_node.sum(),
                    usage + (c2.cand_node.sum() & 1))
        acc, _ = jax.lax.fori_loop(0, iters, body,
                                   (jnp.int32(0), st0.node_usage))
        return acc

    try:
        sec, _ = _time_chained(
            refresh_loop,
            (state, pods, cache, jnp.asarray(drows), jnp.asarray(dvalid)),
            rtt, iters)
        stage_secs["refresh_incremental_1pct"] = sec
        _emit("refresh_incremental_1pct", sec, {"dirty_nodes": n_dirty})
    except Exception as e:
        print(json.dumps({"stage": "refresh_incremental_1pct",
                          "error": repr(e)[:200]}), flush=True)

    # -- quality stages (ISSUE 13): the LP-relaxation packing solve and
    # the topo-gang ranking kernel, so an escalated quality round's
    # per-iteration cost lands in the record next to the greedy stages
    # it replaces (provenance line above covers these captures too)
    from koordinator_tpu.quality.lp_pack import lp_pack_assign

    def lp_pack_loop(st0, p):
        def body(i, carry):
            acc, usage = carry
            a, new_state, _, q_iters = lp_pack_assign(
                st0.replace(node_usage=usage), p, cfg)
            return (acc + (a >= 0).sum().astype(jnp.int32) + q_iters,
                    usage + (new_state.node_requested & 1))
        acc, _ = jax.lax.fori_loop(0, iters, body,
                                   (jnp.int32(0), st0.node_usage))
        return acc

    try:
        sec, value = _time_chained(lp_pack_loop, (state, pods), rtt, iters)
        stage_secs["lp_pack_smoke"] = sec
        _emit("lp_pack_smoke", sec,
              {"vs_rounds_x": round(sec / max(stage_secs["rounds"], 1e-9),
                                    1)})
    except Exception as e:
        print(json.dumps({"stage": "lp_pack_smoke",
                          "error": repr(e)[:200]}), flush=True)

    from koordinator_tpu.ops.network_topology import TopologyTree
    from koordinator_tpu.quality.topo_gang import (
        gang_topo_diameter,
        rank_candidates_quality,
    )

    gang_tree = TopologyTree(["spine", "block", "node"])
    t_leaves = min(n_nodes, 256)
    for i in range(t_leaves):
        gang_tree.add_node([f"s{i // 64}", f"b{i // 8}", f"n{i}"])
    topo = gang_tree.build()
    t = topo.num_topo
    t_cand = jnp.asarray((np.arange(t) % 3) == 0)
    t_slots = jnp.asarray((np.arange(t) % 7).astype(np.int32))
    t_scores = jnp.asarray((np.arange(t) % 11).astype(np.int32))
    t_exist = jnp.asarray((np.arange(t) % 2).astype(np.int32))
    g_rows = jnp.asarray(np.arange(min(t_leaves, 32), dtype=np.int32))
    g_valid = jnp.ones(g_rows.shape[0], bool)

    def topo_rank_loop(cand, slots, scores, exist, rows, rows_valid):
        def body(i, carry):
            acc, perturb = carry
            ranked = rank_candidates_quality(
                topo, cand, slots, scores + perturb, exist)
            dia = gang_topo_diameter(rows, rows_valid, topo)
            return (acc + ranked.sum().astype(jnp.int32) + dia,
                    perturb + (dia & 1))
        acc, _ = jax.lax.fori_loop(0, iters, body,
                                   (jnp.int32(0), jnp.int32(0)))
        return acc

    try:
        sec, _ = _time_chained(
            topo_rank_loop,
            (t_cand, t_slots, t_scores, t_exist, g_rows, g_valid),
            rtt, iters)
        stage_secs["topo_gang_rank"] = sec
        _emit("topo_gang_rank", sec, {"topo_nodes": t})
    except Exception as e:
        print(json.dumps({"stage": "topo_gang_rank",
                          "error": repr(e)[:200]}), flush=True)

    # -- sharded stages (ISSUE 10): the shard_map node-axis path, so a
    # staged capture attributes sharded-path wins per stage.  Runs on
    # the all-devices mesh (1-way on a single chip: same program, no
    # collectives) and reports each program's collective-op counts so
    # the communication profile lands in the record next to the wall.
    from koordinator_tpu.ops import introspection as insp
    from koordinator_tpu.ops import batch_assign as _ba_mod
    from koordinator_tpu.parallel import sharded as psh

    if n_nodes % n_shards == 0 and pods.capacity % p_shards == 0:
        def score_sharded_loop(st0, p):
            def body(i, carry):
                acc, usage = carry
                key, node = psh.sharded_select_candidates(
                    mesh, st0.replace(node_usage=usage), p, cfg, k=K,
                    spread_bits=SPREAD)
                return (acc + key.sum() + node.sum(),
                        usage + (node.sum() & 1))
            acc, _ = jax.lax.fori_loop(0, iters, body,
                                       (jnp.int32(0), st0.node_usage))
            return acc

        def rounds_sharded_loop(st0, p, ckey, cnode):
            def body(i, carry):
                acc, usage = carry
                assignments, new_state, _ = psh.sharded_assign_rounds(
                    mesh, st0.replace(node_usage=usage), p, None, ckey,
                    cnode, rounds=12)
                return (acc + (assignments >= 0).sum().astype(jnp.int32),
                        usage + (new_state.node_requested & 1))
            acc, _ = jax.lax.fori_loop(0, iters, body,
                                       (jnp.int32(0), st0.node_usage))
            return acc

        for label, fn, args in (
            ("score_sharded", score_sharded_loop, (state, pods)),
            ("rounds_sharded", rounds_sharded_loop,
             (state, pods, cand_key, cand_node)),
        ):
            try:
                # collective counts cost one extra AOT compile — opt-in
                # (KOORD_STAGES_COLLECTIVES=1): the wall-clock stage is
                # the scarce evidence at the big capture, and the CI
                # smoke must stay cheap
                hlo = (jax.jit(fn).lower(*args).compile().as_text()
                       if os.environ.get("KOORD_STAGES_COLLECTIVES")
                       else None)
                sec, _ = _time_chained(fn, args, rtt, iters)
                stage_secs[label] = sec
                extra = {"n_devices": n_shards,
                         "mesh_axes": pmesh.mesh_axes(mesh)}
                if hlo is not None:
                    extra["collectives"] = insp.collective_counts(hlo)
                    # per-axis split of the communication profile
                    # (ISSUE 14): which mesh axis the ICI time rides
                    extra["collectives_by_axis"] = (
                        insp.collective_axis_counts(hlo, mesh))
                _emit(label, sec, extra)
            except Exception as e:
                print(json.dumps({"stage": label,
                                  "error": repr(e)[:200]}), flush=True)

        # merge_topk: the cross-shard segmented top-k merge alone —
        # (P, ndev*k) gathered shard winners re-ranked to (P, k) on the
        # global key scale (the kernel sharded selection adds on top of
        # the per-shard local work)
        import numpy as _np

        gn = _np.concatenate(
            [(_np.asarray(cand_node) + 17 * j) % n_nodes
             for j in range(max(n_shards, 2))], axis=1).astype(_np.int32)
        gs = _np.concatenate(
            [_np.asarray(jnp.where(cand_key >= 0, cand_key & 0x7fff, -1))
             for _ in range(max(n_shards, 2))], axis=1).astype(_np.int32)

        def merge_topk_loop(g_node, g_score, p):
            def body(i, carry):
                acc, gs_c = carry
                key = _ba_mod._candidate_keys(
                    gs_c, g_node, p.rot_id, SPREAD[0], n_nodes)
                _, midx = _ba_mod._topk_by_rank(
                    key, _ba_mod._candidate_tb(g_node, p.rot_id, n_nodes),
                    K, n_nodes)
                sel = jnp.take_along_axis(g_node, midx, axis=1)
                return (acc + sel.sum(), gs_c + (sel.sum() & 1))
            acc, _ = jax.lax.fori_loop(
                0, iters, body, (jnp.int32(0), g_score))
            return acc

        try:
            sec, _ = _time_chained(
                merge_topk_loop,
                (jnp.asarray(gn), jnp.asarray(gs), pods), rtt, iters)
            stage_secs["merge_topk"] = sec
            _emit("merge_topk", sec,
                  {"merge_width": int(gn.shape[1]), "k": K})
        except Exception as e:
            print(json.dumps({"stage": "merge_topk",
                              "error": repr(e)[:200]}), flush=True)
    else:
        print(json.dumps({
            "stage": "score_sharded",
            "error": (f"n_nodes {n_nodes} not divisible by "
                      f"{n_shards}-way mesh")}), flush=True)

    # -- 2-D pods x nodes stages (ISSUE 14): the SAME kernels on a
    # pods-split mesh vs the all-nodes mesh over the same devices, at
    # this run's pod-heavy shape (50k pods x 10,240 nodes at the real
    # capture).  Two acceptance observables land in the record:
    # per-device candidate-tensor bytes scaling ~1/pods_axis, and the
    # 2xD/2-vs-1xD aggregate-throughput ratio for the score and rounds
    # stages.  (On virtual CPU devices the devices share one socket, so
    # the throughput ratio reflects per-device WORK — the top-k row
    # count and merge width the pods split removes — not ICI.)
    devs = jax.devices()
    half = len(devs) // 2
    if (len(devs) >= 2 and len(devs) % 2 == 0
            and n_nodes % max(half, 1) == 0
            and pods.capacity % 2 == 0):
        mesh_1d = pmesh.solver_mesh(devs)              # 1 x D
        mesh_2d = pmesh.solver_mesh(devs, pods_axis=2)  # 2 x D/2

        def sharded_loops(m):
            def score_loop2(st0, p):
                def body(i, carry):
                    acc, usage = carry
                    key, node = psh.sharded_select_candidates(
                        m, st0.replace(node_usage=usage), p, cfg, k=K,
                        spread_bits=SPREAD)
                    return (acc + key.sum() + node.sum(),
                            usage + (node.sum() & 1))
                acc, _ = jax.lax.fori_loop(0, iters, body,
                                           (jnp.int32(0), st0.node_usage))
                return acc

            def rounds_loop2(st0, p, ckey, cnode):
                def body(i, carry):
                    acc, usage = carry
                    assignments, new_state, _ = psh.sharded_assign_rounds(
                        m, st0.replace(node_usage=usage), p, None, ckey,
                        cnode, rounds=12)
                    return (acc + (assignments >= 0).sum()
                            .astype(jnp.int32),
                            usage + (new_state.node_requested & 1))
                acc, _ = jax.lax.fori_loop(0, iters, body,
                                           (jnp.int32(0), st0.node_usage))
                return acc

            return score_loop2, rounds_loop2

        base_secs: dict[str, float] = {}
        for mlabel, m in (("1d", mesh_1d), ("2d", mesh_2d)):
            score_fn, rounds_fn = sharded_loops(m)
            axes = pmesh.mesh_axes(m)
            shape_s = f"{axes['pods']}x{axes['nodes']}"
            for kind, fn, args in (
                ("score", score_fn, (state, pods)),
                ("rounds", rounds_fn, (state, pods, cand_key, cand_node)),
            ):
                label = f"{kind}_sharded_{mlabel}"
                try:
                    sec, _ = _time_chained(fn, args, rtt, iters)
                    extra = {"mesh_axes": axes, "mesh_shape": shape_s}
                    if mlabel == "1d":
                        base_secs[kind] = sec
                    elif base_secs.get(kind):
                        # aggregate throughput ratio: the acceptance
                        # asks >= 1.5x for score/rounds at the
                        # pod-heavy shape on real chips
                        extra["speedup_vs_1d"] = round(
                            base_secs[kind] / sec, 3)
                    _emit(label, sec, extra)
                except Exception as e:
                    print(json.dumps({"stage": label,
                                      "error": repr(e)[:200]}),
                          flush=True)

        # per-device footprint of the persistent (P, k) candidate
        # tensors: replicated on the 1xD mesh (every device pays the
        # full copy), pod-sharded on the 2xD/2 mesh (~1/pods_axis)
        try:
            cache = _ba_mod.CandidateCache(cand_key, cand_node,
                                           cand_score)
            per_dev = {}
            for mlabel, m in (("1d", mesh_1d), ("2d", mesh_2d)):
                placed = jax.device_put(cache, pmesh.pod_sharding(m))
                jax.block_until_ready(jax.tree.leaves(placed))
                by = insp.device_bytes_by_mesh_shard(placed, m)
                per_dev[mlabel] = max(by.values())
                del placed
            print(json.dumps({
                "stage": "sharded_2d_footprint",
                "cand_bytes_per_device_1d": per_dev["1d"],
                "cand_bytes_per_device_2d": per_dev["2d"],
                # the acceptance observable: ~1/pods_axis at pods_axis=2
                "ratio": round(per_dev["2d"] / max(per_dev["1d"], 1), 4),
                "mesh_axes_2d": pmesh.mesh_axes(mesh_2d),
            }), flush=True)
        except Exception as e:
            print(json.dumps({"stage": "sharded_2d_footprint",
                              "error": repr(e)[:200]}), flush=True)
    else:
        print(json.dumps({
            "stage": "score_sharded_2d",
            "error": (f"{len(devs)} device(s) cannot split 2x"
                      f"{max(half, 1)}")}), flush=True)

    # -- explain: device-side reject-reason accounting (ISSUE 6 overhead
    # guard).  The solve itself is UNCHANGED by explain — the scheduler
    # runs ops/explain.explain_counts once per round over only the
    # COMPACTED failed rows — so the production overhead is the compact
    # kernel's wall at a representative 1% failure rate, priced against
    # the solve (select + rounds).  The full-batch number (every pod
    # unplaced: the 50k-pending pathology explainability exists FOR) is
    # emitted alongside as the worst case.
    from koordinator_tpu.ops.explain import explain_counts

    # two denominators: the cold-path solve (select + rounds) and the
    # cheaper steady-state solve (incremental refresh + rounds) — an
    # explain cost hiding inside the cold path's margin must not pass
    # the guard while steady-state rounds pay >5%
    solve_sec = (stage_secs.get("select_chunked")
                 or next((stage_secs[k] for k in stage_secs
                          if k.startswith("select_")), 0.0)
                 ) + stage_secs.get("rounds", 0.0)
    steady_sec = (stage_secs.get("refresh_incremental_1pct", 0.0)
                  + stage_secs.get("rounds", 0.0)
                  if "refresh_incremental_1pct" in stage_secs else 0.0)

    def explain_loop(p_batch):
        def fn(st0, p):
            def body(i, carry):
                acc, usage = carry
                counts, feas = explain_counts(
                    st0.replace(node_usage=usage), p, cfg)
                return (acc + counts.sum() + feas.sum(),
                        usage + (feas.sum() & 1))
            acc, _ = jax.lax.fori_loop(0, iters, body,
                                       (jnp.int32(0), st0.node_usage))
            return acc
        return fn

    n_failed = max(n_pods // 100, 1)
    fail_mask = np.zeros(pods.capacity, bool)
    fail_mask[:n_failed] = True
    small, _ = pods.compact(fail_mask)
    for label, batch_arg, extra in (
        ("explain_compact_1pct", small,
         {"failed_rows": n_failed, "compact_capacity": small.capacity}),
        ("explain_full_batch", pods,
         {"note": "worst case: every pod unplaced"}),
    ):
        try:
            sec, _ = _time_chained(explain_loop(batch_arg),
                                   (state, batch_arg), rtt, iters)
            pct = round(100.0 * sec / solve_sec, 2) if solve_sec else None
            steady_pct = (round(100.0 * sec / steady_sec, 2)
                          if steady_sec else None)
            worst = max(p for p in (pct, steady_pct, 0.0)
                        if p is not None)
            _emit(label, sec, {
                **extra,
                "solve_ms": round(solve_sec * 1e3, 2),
                "steady_solve_ms": round(steady_sec * 1e3, 2),
                "pct_of_solve": pct,
                "pct_of_steady_solve": steady_pct,
                # the guard verdict takes the LESS flattering denominator
                "within_5pct": (pct is not None and worst <= 5.0),
            })
        except Exception as e:
            print(json.dumps({"stage": label, "error": repr(e)[:200]}),
                  flush=True)

    # -- host-plane turbo stages (ISSUE 19): the wire codec, the
    # deltasync apply loop, and the bind commit loop.  These are HOST
    # costs — pure perf_counter timing, no device chaining — because
    # the tentpole they instrument is host-wait attribution, not device
    # wall.  Each stage times the batched path and records the legacy
    # per-item path beside it so bench_diff guards the ratio's inputs.
    import time as _htime

    from koordinator_tpu.api.resources import resource_vector as _res
    from koordinator_tpu.transport import deltasync as _ds
    from koordinator_tpu.transport import wire as _wire

    def _host_time(fn, reps: int, trials: int = 3) -> float:
        best = float("inf")
        for _ in range(trials):
            t0 = _htime.perf_counter()
            for _ in range(reps):
                fn()
            best = min(best, (_htime.perf_counter() - t0) / reps)
        return best

    host_reps = 10 if smoke else 50
    ev_count = 64 if smoke else 512
    host_events = []
    for i in range(ev_count):
        host_events.append(
            (i + 1, {"kind": _ds.NODE_USAGE, "name": f"hn{i % 64}"},
             {"usage": _res(cpu=100 + i, memory=64 + i),
              "agg_usage": _res(cpu=90 + i, memory=60 + i)}))

    def _codec(pack):
        packed = pack(host_events)
        payload = _wire.encode_payload(dict(packed[0]), packed[1])
        d, a = _wire.decode_payload(payload)
        return [_ds._unpack_event_arrays(e, a)
                for e in _ds._decode_events(d, a)]

    try:
        v1_s = _host_time(lambda: _codec(_ds._pack_events), host_reps)
        v2_s = _host_time(lambda: _codec(_ds._pack_events_v2), host_reps)
        _emit("wire_codec_v1_vs_v2", v2_s, {
            "events": ev_count, "v1_ms": round(v1_s * 1e3, 3),
            "speedup_vs_v1": round(v1_s / max(v2_s, 1e-12), 2)})
    except Exception as e:
        print(json.dumps({"stage": "wire_codec_v1_vs_v2",
                          "error": repr(e)[:200]}), flush=True)

    from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
    from koordinator_tpu.scheduler.scheduler import SchedulingResult
    from koordinator_tpu.scheduler.snapshot import NodeSpec as _NSpec
    from koordinator_tpu.scheduler.snapshot import PodSpec as _PSpec

    try:
        hsched = Scheduler(ClusterSnapshot(capacity=128))
        for j in range(64):
            hsched.snapshot.upsert_node(_NSpec(
                name=f"hn{j}",
                allocatable=_res(cpu=256_000, memory=1_048_576)))
        hbind = _ds.SchedulerBinding(hsched)
        apply_items = [(e, a) for _rv_, e, a in host_events]

        def _apply_serial():
            for e, a in apply_items:
                _ds._dispatch_event(hbind, e, a)

        serial_s = _host_time(_apply_serial, host_reps)
        batched_s = _host_time(
            lambda: _ds._dispatch_events(hbind, apply_items), host_reps)
        _emit("deltasync_apply_batched", batched_s, {
            "events": ev_count,
            "per_event_ms": round(serial_s * 1e3, 3),
            "speedup_vs_per_event": round(
                serial_s / max(batched_s, 1e-12), 2)})
    except Exception as e:
        print(json.dumps({"stage": "deltasync_apply_batched",
                          "error": repr(e)[:200]}), flush=True)

    try:
        n_binds = 32 if smoke else 256
        bind_trials = 3 if smoke else 10

        def _bind_setup():
            s = Scheduler(ClusterSnapshot(capacity=max(n_binds * 2, 64)))
            for j in range(32):
                s.snapshot.upsert_node(_NSpec(
                    name=f"bn{j}",
                    allocatable=_res(cpu=256_000, memory=1_048_576)))
            binds = []
            for j in range(n_binds):
                p = _PSpec(name=f"bp{j}",
                           requests=_res(cpu=100, memory=64),
                           priority=j)
                s.enqueue(p)
                binds.append((p, f"bn{j % 32}"))
            return s, binds

        def _bind_cost(batched: bool) -> float:
            # commits consume pending state, so setup is rebuilt per
            # trial and excluded from the timed window
            best = float("inf")
            for _ in range(bind_trials):
                s, binds = _bind_setup()
                res = SchedulingResult(assignments={}, failures={})
                t0 = _htime.perf_counter()
                if batched:
                    s._commit_bind_batch(binds, res)
                else:
                    for p, node in binds:
                        s._commit_bind(p, node, res)
                best = min(best, _htime.perf_counter() - t0)
            return best

        loop_s = _bind_cost(batched=False)
        batch_s = _bind_cost(batched=True)
        _emit("bind_commit_batched", batch_s, {
            "binds": n_binds,
            "per_pod_ms": round(loop_s * 1e3, 3),
            "speedup_vs_per_pod": round(
                loop_s / max(batch_s, 1e-12), 2)})
    except Exception as e:
        print(json.dumps({"stage": "bind_commit_batched",
                          "error": repr(e)[:200]}), flush=True)

    # -- multi-tenant round pipeline (ISSUE 11): sustained aggregate
    # pods/s with T simulated clusters on one mesh, serial
    # single-tenant-at-a-time vs the pipelined cycle (round N+1's
    # device solve overlapping round N's host commit).  Device-busy is
    # estimated from the SERIAL run's host block time (serial rounds
    # block for the full solve, so the wait IS the device execution);
    # the pipelined idle fraction divides the SAME device work by the
    # shorter pipelined wall.
    T = int(os.environ.get("KOORD_STAGES_TENANTS",
                           "2" if smoke else "4"))
    if T > 1:
        import time as _time

        import numpy as _np2

        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec
        from koordinator_tpu.scheduler.solver_kit import SolverKit
        from koordinator_tpu.scheduler.tenancy import (
            TenantScheduler,
            TenantSpec,
        )

        tn_nodes = max(min(n_nodes // T, 1024), 16)
        tn_pods = max(min(n_pods // (T * 8), 2048), 32)
        # CI smoke pays one timed cycle per mode (the compiles dominate
        # anyway); the real capture sustains three
        cycles = int(os.environ.get("KOORD_STAGES_TENANT_CYCLES",
                                    "1" if smoke else "3"))
        kit = SolverKit(mesh="off")

        def build_front(pipeline: bool, batched: bool) -> TenantScheduler:
            front = TenantScheduler(
                cycle_pod_budget=1 << 30, pipeline=pipeline,
                batch_tenant_axis=batched, solver_kit=kit)
            for i in range(T):
                t = front.add_tenant(
                    TenantSpec(name=f"bt{i}", node_capacity=tn_nodes),
                    batch_solver_threshold=1)
                for j in range(tn_nodes):
                    t.scheduler.snapshot.upsert_node(NodeSpec(
                        name=f"n{j}",
                        allocatable=resource_vector(cpu=256_000,
                                                    memory=1_048_576)))
            return front

        def fill(front: TenantScheduler, cycle: int) -> None:
            for i, t in enumerate(front.tenants()):
                rng = _np2.random.default_rng(7_001 + 31 * i + cycle)
                for j in range(tn_pods):
                    t.scheduler.enqueue(PodSpec(
                        name=f"c{cycle}-p{j}",
                        requests=resource_vector(
                            cpu=int(rng.integers(50, 400)),
                            memory=int(rng.integers(64, 512))),
                        priority=int(rng.integers(100, 9_999))))

        def run_mode(front: TenantScheduler):
            fill(front, 0)
            front.schedule_cycle()          # warm the jit caches
            placed = 0
            device_s = 0.0
            t0 = _time.perf_counter()
            for c in range(1, cycles + 1):
                fill(front, c)
                res = front.schedule_cycle()
                placed += sum(len(r.assignments) for r in res.values())
                device_s += sum(t.scheduler._solve_device_s
                                for t in front.tenants())
            return _time.perf_counter() - t0, placed, device_s

        try:
            wall_ser, placed_ser, dev_ser = run_mode(
                build_front(pipeline=False, batched=False))
            rate_ser = placed_ser / wall_ser if wall_ser > 0 else 0.0
            _emit("tenancy_serial", wall_ser / cycles, {
                "tenants": T, "nodes_per_tenant": tn_nodes,
                "pods_per_tenant_cycle": tn_pods,
                "agg_pods_per_s": round(rate_ser, 1),
                "device_busy_s": round(dev_ser, 4),
                "device_idle_fraction": round(
                    1.0 - min(dev_ser / wall_ser, 1.0), 4)
                if wall_ser > 0 else None})
            wall_pip, placed_pip, _ = run_mode(
                build_front(pipeline=True, batched=False))
            rate_pip = placed_pip / wall_pip if wall_pip > 0 else 0.0
            _emit("tenancy_pipelined", wall_pip / cycles, {
                "tenants": T,
                "agg_pods_per_s": round(rate_pip, 1),
                "speedup_vs_serial": (round(rate_pip / rate_ser, 3)
                                      if rate_ser > 0 else None),
                # same device work over the pipelined wall: the idle the
                # overlap deleted
                "device_idle_fraction": round(
                    max(1.0 - min(dev_ser / wall_pip, 1.0), 0.0), 4)
                if wall_pip > 0 else None})
            wall_bat, placed_bat, _ = run_mode(
                build_front(pipeline=True, batched=True))
            rate_bat = placed_bat / wall_bat if wall_bat > 0 else 0.0
            _emit("tenancy_batched", wall_bat / cycles, {
                "tenants": T,
                "agg_pods_per_s": round(rate_bat, 1),
                "speedup_vs_serial": (round(rate_bat / rate_ser, 3)
                                      if rate_ser > 0 else None)})
        except Exception as e:
            print(json.dumps({"stage": "tenancy_pipelined",
                              "error": repr(e)[:200]}), flush=True)

        # -- timeline self-overhead (ISSUE 18): the SAME pipelined
        # cycle with the critical-path observatory recording vs with
        # the kill switch thrown.  The observatory is pure host-side
        # perf_counter bookkeeping (decisions are bit-identical either
        # way — tests/test_timeline.py proves it), so this stage bounds
        # the only cost it CAN have: wall time.  The guard test asserts
        # overhead_fraction < 3%; negative values are timing noise.
        try:
            from koordinator_tpu import timeline as _tl

            was_enabled = _tl.RECORDER.enabled
            reps = 10 if smoke else 3

            def one_wall(enabled: bool) -> float:
                _tl.RECORDER.set_enabled(enabled)
                return run_mode(build_front(pipeline=True,
                                            batched=False))[0]

            try:
                # interleaved on/off pairs + min-of-reps: host
                # scheduling jitter at smoke scale (one-digit-ms
                # cycles) dwarfs the instrumentation, and alternating
                # modes keeps slow drift (thermal, page cache) from
                # landing entirely on one side; the MINIMUM wall per
                # mode is the defensible cost floor
                walls_on = []
                walls_off = []
                for _ in range(reps):
                    walls_on.append(one_wall(True))
                    walls_off.append(one_wall(False))
                wall_on, wall_off = min(walls_on), min(walls_off)
            finally:
                _tl.RECORDER.set_enabled(was_enabled)
            overhead = ((wall_on - wall_off) / wall_off
                        if wall_off > 0 else None)
            _emit("timeline_overhead", wall_on / cycles, {
                "tenants": T,
                "off_ms_per_iter": round(wall_off / cycles * 1e3, 2),
                "overhead_fraction": (round(overhead, 4)
                                      if overhead is not None else None)})
        except Exception as e:
            print(json.dumps({"stage": "timeline_overhead",
                              "error": repr(e)[:200]}), flush=True)

        # -- journey-ledger self-overhead (ISSUE 20): the SAME pipelined
        # cycle with the always-on pod-journey ledger recording vs with
        # the kill switch thrown.  The ledger is O(1) host bookkeeping
        # per pod (enqueue stamp + one staged sketch append per
        # committed round; decisions are bit-identical either way —
        # tests/test_journey.py proves it), so its ONLY possible cost is
        # the wall time spent inside its calls.  overhead_fraction is
        # therefore measured directly: the ON reps run with the ledger's
        # hot-path entry points (note_enqueue / forget /
        # record_bind_batch) wrapped in perf_counter accounting, and the
        # fraction is ledger-seconds over cycle wall.  Differencing the
        # on/off walls instead (reported as wall_delta_fraction for the
        # curious) CANNOT resolve a sub-1% effect at smoke scale: host
        # jitter on one-digit-ms cycles is +/-5% even with interleaved
        # min-of-10 reps, so that number is noise.  The timing shims
        # themselves cost more than the ledger calls they wrap and are
        # counted against the ledger, so the reported fraction is a
        # strict upper bound — which is why the shims go on AFTER the
        # warm-up cycle: they must only see the timed window.
        # The guard test asserts overhead_fraction < 1%.
        try:
            from koordinator_tpu import journey as _jn

            journey_was = _jn.LEDGER.enabled
            reps = 10 if smoke else 3
            _HOT = ("note_enqueue", "forget", "record_bind_batch")

            def one_wall_journey(enabled: bool) -> tuple:
                _jn.LEDGER.set_enabled(enabled)
                front = build_front(pipeline=True, batched=False)
                fill(front, 0)
                front.schedule_cycle()      # warm, outside the shims
                spent = [0.0]
                if enabled:
                    def _shim(fn):
                        def w(*a, **kw):
                            t0 = _time.perf_counter()
                            r = fn(*a, **kw)
                            spent[0] += _time.perf_counter() - t0
                            return r
                        return w
                    for n in _HOT:
                        # instance attribute shadows the class method;
                        # delattr below restores the original
                        setattr(_jn.LEDGER, n, _shim(getattr(_jn.LEDGER, n)))
                try:
                    t0 = _time.perf_counter()
                    for c in range(1, cycles + 1):
                        fill(front, c)
                        front.schedule_cycle()
                    wall = _time.perf_counter() - t0
                finally:
                    if enabled:
                        for n in _HOT:
                            delattr(_jn.LEDGER, n)
                return wall, spent[0]

            try:
                # interleaved on/off pairs + min-of-reps for the wall
                # numbers, same rationale as timeline_overhead
                jwalls_on = []
                jledger_s = []
                jwalls_off = []
                for _ in range(reps):
                    w, spent_s = one_wall_journey(True)
                    jwalls_on.append(w)
                    jledger_s.append(spent_s)
                    jwalls_off.append(one_wall_journey(False)[0])
                jwall_on = min(jwalls_on)
                jwall_off = min(jwalls_off)
            finally:
                _jn.LEDGER.set_enabled(journey_was)
            joverhead = (sum(jledger_s) / sum(jwalls_on)
                         if sum(jwalls_on) > 0 else None)
            jdelta = ((jwall_on - jwall_off) / jwall_off
                      if jwall_off > 0 else None)
            _emit("journey_ledger_overhead", jwall_on / cycles, {
                "tenants": T,
                "off_ms_per_iter": round(jwall_off / cycles * 1e3, 2),
                "ledger_ms_per_iter": round(
                    sum(jledger_s) / len(jledger_s) / cycles * 1e3, 4),
                "overhead_fraction": (round(joverhead, 4)
                                      if joverhead is not None else None),
                "wall_delta_fraction": (round(jdelta, 4)
                                        if jdelta is not None else None)})
        except Exception as e:
            print(json.dumps({"stage": "journey_ledger_overhead",
                              "error": repr(e)[:200]}), flush=True)


if __name__ == "__main__":
    main()
