"""Score-fidelity sweep: mean chosen-node score vs exact sequential greedy
(the r2 protocol: 2,048 nodes x 10k pods, same contention ratio as the
north star) across (k, spread_bits) — picks the quality-preserving default
after the north-star-shape assigned-fraction sweep."""
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from __graft_entry__ import _build_problem
from koordinator_tpu.ops.assignment import greedy_assign, score_pods
from koordinator_tpu.ops.batch_assign import batch_assign

N_NODES, N_PODS = 2_048, 10_000
state, pods, cfg = _build_problem(N_NODES, N_PODS, seed=42)
valid = int(np.asarray(pods.valid).sum())
scores = np.asarray(jax.jit(lambda s: score_pods(s, pods, cfg)[0])(state))


def report(name, asn):
    asn = np.asarray(asn)
    sel = asn >= 0
    mean_score = float(scores[np.nonzero(sel)[0], asn[sel]].mean())
    print(f"{name}: assigned {int(sel.sum())}/{valid} "
          f"mean_chosen_score {mean_score:.1f}", flush=True)


t0 = time.perf_counter()
g_asn, _, _ = jax.jit(greedy_assign)(state, pods, cfg)
report("greedy_exact", g_asn)
print(f"  (greedy wall {time.perf_counter()-t0:.0f}s)", flush=True)

for k, sb in [(32, (5, 15)), (16, (5, 15)), (32, 5)]:
    asn, _ = jax.jit(lambda s, k=k, sb=sb: batch_assign(
        s, pods, cfg, k=k, spread_bits=sb, method="approx")[:2])(state)
    report(f"k{k}_sb{sb}", asn)
