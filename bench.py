"""Benchmark: full batched solve + Filter/Score at the north-star shape.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Shape and target from BASELINE.json: 50k pending pods scheduled against
10,240 nodes; the north-star is the full SOLVE (not just scoring) of 50k pods
in <200ms p99 on a v5e-4 => 250k pods/sec (we run on ONE chip).  The headline
metric times ``batch_assign`` end to end — filter, score, top-k candidate
selection and the propose/accept conflict-resolution rounds with capacity
feedback.  The Filter+Score-only number (the round-1 metric) and the other
BASELINE.json configs (quota @5k pods, gang @10k pods, LowNodeLoad @10k
nodes) ride in ``extra`` for round-over-round comparability; a failure in
any extra config records an error string instead of discarding the headline.

Timing methodology: through the axon tunnel, ``block_until_ready`` returns
before remote execution completes, so naive wall-clocking measures dispatch,
not compute. Each kernel therefore runs K iterations inside one jitted
``fori_loop`` (chained through a data dependency so XLA cannot collapse
them), reduced to a scalar whose host readback cannot complete early; the
tunnel round-trip floor is measured separately with a trivial kernel and
subtracted before dividing by K.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

N_NODES = 10_240
N_PODS = 50_000
K_ITERS = 8
BASELINE_PODS_PER_SEC = 250_000.0


def _git_head() -> dict:
    """{"commit": sha, "dirty": bool} of the repo this bench lives in —
    stamped into every record so a probe capture can be matched to the
    code it actually measured (VERDICT r4 weak #2: a capture from commit
    A must not be promoted as the official number of commit B with
    solver changes in between)."""
    import subprocess

    cwd = os.path.dirname(os.path.abspath(__file__))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=cwd, timeout=10).stdout.strip()
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"], capture_output=True,
            text=True, cwd=cwd, timeout=10).stdout.strip())
    except Exception:
        return {"commit": "", "dirty": False}
    return {"commit": sha, "dirty": dirty}


#: paths whose change between a capture's commit and HEAD invalidates the
#: capture as a performance record (docs/tests/bench-extras churn doesn't)
_SOLVER_PATHS = ("koordinator_tpu/", "native/", "__graft_entry__.py",
                 "bench.py")


def _solver_diff(old_commit: str, head: str) -> list[str] | None:
    """Solver-relevant files changed between two commits; None when the
    diff cannot be computed (unknown commit, git failure) — callers must
    treat None as 'assume changed'."""
    import subprocess

    if not old_commit or not head:
        return None
    if old_commit == head:
        return []
    try:
        proc = subprocess.run(
            ["git", "diff", "--name-only", f"{old_commit}..{head}"],
            capture_output=True, text=True, timeout=15,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except Exception:
        return None
    if proc.returncode != 0:
        return None
    return [line for line in proc.stdout.strip().splitlines()
            if line.startswith(tuple(p for p in _SOLVER_PATHS
                                     if p.endswith("/")))
            or line in _SOLVER_PATHS]


def _median_readback_seconds(fn, args, n: int = 5):
    """(median_seconds, value) — the warm-up call's value rides along so
    callers can read the chained loop's accumulator without recompiling."""
    value = float(fn(*args))  # compile + warm
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        float(fn(*args))
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), value


def _chained_loop(assign_fn, iters: int = K_ITERS):
    """The shared chained-iteration scaffold: re-run ``assign_fn(st, pods)``
    ``iters`` times with a data dependency through node_usage so XLA cannot
    dedupe or elide iterations.  The accumulator counts assigned pods per
    iteration (for solve fns; a scalar-returning fn contributes 0/1), so the
    readback doubles as the solve-quality measurement.

    ``pods`` is a TRACED argument, not a closure capture: closed-over pod
    batches become multi-MB HLO constants, and XLA then constant-folds
    pod-dependent work (e.g. the candidate lexsort) at COMPILE time —
    minutes of compile and a solve that silently excludes that work.
    Pod tensors stay loop-invariant, so XLA may still hoist pod-only
    preamble out of the chain; the single-shot latency percentiles
    (solve_latency_ms_p*) include it, the chained mean does not."""

    def fn(st0, pods):
        def body(i, carry):
            acc, usage = carry
            st = st0.replace(node_usage=usage)
            assignments, new_state = assign_fn(st, pods)
            return (acc + (assignments >= 0).sum().astype(jnp.int32),
                    usage + (new_state.node_requested & 1))

        acc, _ = jax.lax.fori_loop(
            0, iters, body, (jnp.int32(0), st0.node_usage))
        return acc

    return fn


def _time_assign(state, pods, assign_fn, rtt: float, n: int = 3,
                 iters: int = K_ITERS):
    """(seconds_per_iter, mean_value_per_iter)."""
    total, value = _median_readback_seconds(
        jax.jit(_chained_loop(assign_fn, iters)), (state, pods), n=n)
    return max((total - rtt) / iters, 1e-9), value / iters


def _bench_quota(rtt: float) -> dict:
    """ElasticQuota LP @ 5k pods x 1,024 nodes, 64-leaf quota tree with
    BINDING constraints: bounded max (checked dims) and contended runtime
    (total min demand ~2x cluster CPU) so admission actually rejects."""
    from __graft_entry__ import _build_problem
    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
    from koordinator_tpu.quota.admission import QuotaDeviceState
    from koordinator_tpu.quota.tree import QuotaTree

    rng = np.random.default_rng(7)
    r = NUM_RESOURCE_DIMS
    state, pods, cfg = _build_problem(1_024, 5_000, seed=7)
    total = np.sum(np.asarray(state.node_allocatable), axis=0, dtype=np.int64)
    tree = QuotaTree(total_resource=total)
    for q in range(64):
        mn = np.zeros(r, np.int64)
        mn[0] = int(total[0]) // 128          # mins sum to half the cluster
        mx = np.maximum(total // 16, 1)       # bounded => checked dims
        tree.add(f"q{q}", min=mn, max=mx)
        tree.set_request(f"q{q}", np.maximum(total // 32, 1))  # contended
    tree.refresh_runtime()
    quota, _ = QuotaDeviceState.from_tree(tree)
    qpods = pods.replace(quota_id=jnp.asarray(
        rng.integers(0, 64, pods.capacity), jnp.int32))

    from koordinator_tpu.ops.batch_assign import batch_assign

    per, count = _time_assign(
        state, qpods,
        lambda st, p: batch_assign(st, p, cfg, quota=quota)[:2],
        rtt)
    return {"quota_solve_pods_per_sec_5000p_1024n_64q": round(5_000 / per, 1),
            "quota_solve_assigned_per_round": round(count, 1)}


def _bench_gang(rtt: float) -> dict:
    """Gang ILP @ 10k pods x 1,024 nodes, 256 gangs of ~16, 2 passes."""
    from __graft_entry__ import _build_problem
    from koordinator_tpu.ops.gang import GangInfo, gang_assign

    rng = np.random.default_rng(8)
    state, pods, cfg = _build_problem(1_024, 10_000, seed=8)
    gangs = GangInfo.build(np.full(256, 16, np.int32))
    gpods = pods.replace(gang_id=jnp.asarray(
        rng.integers(-1, 256, pods.capacity), jnp.int32))

    per, count = _time_assign(
        state, gpods,
        lambda st, p: gang_assign(st, p, cfg, gangs, passes=2,
                                  solver="batch")[:2],
        rtt)
    return {"gang_solve_pods_per_sec_10000p_1024n_256g_batch": round(
        10_000 / per, 1),
            "gang_solve_assigned_per_round": round(count, 1)}


def _bench_lownodeload(rtt: float) -> dict:
    """LowNodeLoad hot-migrate @ 10,240 nodes, 20k bound pods."""
    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
    from koordinator_tpu.descheduler.lownodeload import (
        LowNodeLoadArgs,
        select_victims,
    )

    rng = np.random.default_rng(9)
    r = NUM_RESOURCE_DIMS
    n, p = N_NODES, 20_000
    cap = np.zeros((n, r), np.int32)
    cap[:, 0], cap[:, 1] = 32_000, 131_072
    usage = (cap * rng.uniform(0.1, 0.95, (n, r))).astype(np.int32)
    pod_node = rng.integers(0, n, p).astype(np.int32)
    pod_usage = np.zeros((p, r), np.int32)
    pod_usage[:, 0] = rng.integers(50, 2_000, p)
    pod_usage[:, 1] = rng.integers(64, 4_096, p)
    prio = rng.integers(3000, 9999, p).astype(np.int32)
    args = LowNodeLoadArgs.default()
    iters = 2

    def lnl_loop(usage, cap, pod_node, pod_usage, prio):
        valid = jnp.ones(n, bool)
        evictable = jnp.ones(p, bool)
        counters = jnp.full(n, 10, jnp.int32)

        def body(i, carry):
            acc, u = carry
            victims = select_victims(u, cap, valid, pod_node, pod_usage,
                                     prio, evictable, counters, args)
            return acc + victims.sum(), u + (victims.sum() & 1)

        acc, _ = jax.lax.fori_loop(0, iters, body, (jnp.int32(0), usage))
        return acc

    total, _ = _median_readback_seconds(
        jax.jit(lnl_loop),
        (jnp.asarray(usage), jnp.asarray(cap), jnp.asarray(pod_node),
         jnp.asarray(pod_usage), jnp.asarray(prio)), n=3)
    return {f"lownodeload_ms_per_round_{n}n_{p}p": round(
        max((total - rtt) / iters, 1e-9) * 1e3, 2)}


def _bench_colocation(rtt: float) -> dict:
    """Spark colocation e2e @ 3 nodes (BASELINE.json's kind-demo config):
    webhook admission (BE translation to batch resources) -> scheduler
    round over batch capacity -> bind, repeated over a pod stream.  Host
    control-loop throughput, not a device kernel — ``rtt`` is unused."""
    from koordinator_tpu.api import crds, extension as ext
    from koordinator_tpu.api.qos import QoSClass
    from koordinator_tpu.api.resources import resource_vector
    from koordinator_tpu.manager.webhook import (
        PodMutatingWebhook,
        PodValidatingWebhook,
    )
    from koordinator_tpu.scheduler.scheduler import Scheduler
    from koordinator_tpu.scheduler.snapshot import (
        ClusterSnapshot,
        NodeSpec,
        PodSpec,
    )

    profile = crds.ClusterColocationProfile(
        name="colo", pod_selector={"app": "spark"}, qos_class="BE",
        koordinator_priority=5500, scheduler_name="koord-scheduler")
    mutating = PodMutatingWebhook([profile])
    validating = PodValidatingWebhook()
    snapshot = ClusterSnapshot(capacity=4)
    for i in range(3):
        snapshot.upsert_node(NodeSpec(
            name=f"n{i}",
            allocatable=resource_vector({
                "cpu": 16_000, "memory": 32_768,
                ext.RESOURCE_BATCH_CPU: 12_000,
                ext.RESOURCE_BATCH_MEMORY: 24_576,
            })))
    scheduler = Scheduler(snapshot)

    pods_per_round, rounds = 60, 6
    n_scheduled = 0
    t0 = time.perf_counter()
    for r in range(rounds):
        if r == 1:  # round 0 is the jit warm-up; time the steady state
            n_scheduled, t0 = 0, time.perf_counter()
        for i in range(pods_per_round):
            pod = {
                "metadata": {"name": f"spark-{r}-{i}",
                             "namespace": "default",
                             "labels": {"app": "spark"}},
                "spec": {"containers": [{"name": "m", "resources": {
                    "requests": {"cpu": "500m", "memory": "1Gi"},
                    "limits": {"cpu": "500m", "memory": "1Gi"}}}]},
            }
            mutating.mutate(pod)
            assert validating.validate(pod) == []
            req = pod["spec"]["containers"][0]["resources"]["requests"]
            scheduler.enqueue(PodSpec(
                name=pod["metadata"]["name"],
                requests=resource_vector({
                    ext.RESOURCE_BATCH_CPU: req[ext.RESOURCE_BATCH_CPU],
                    ext.RESOURCE_BATCH_MEMORY:
                        req[ext.RESOURCE_BATCH_MEMORY] // (1 << 20),
                }),
                priority=5500, qos=int(QoSClass.BE)))
        result = scheduler.schedule_round()
        n_scheduled += len(result.assignments)
        for name in result.assignments:
            scheduler.delete_pod(name)  # job completes: free for next wave
    dt = time.perf_counter() - t0
    timed = pods_per_round * (rounds - 1)      # round 0 is untimed warm-up
    if n_scheduled < timed * 0.9:
        return {"bench_colocation_error":
                f"only {n_scheduled}/{timed} scheduled"}
    return {"spark_colocation_e2e_pods_per_sec_3n": round(n_scheduled / dt, 1)}


def _bench_deltasync(rtt: float) -> dict:
    """State-sync path timing (VERDICT r4 next #7): the <200ms p99 budget
    includes host->device delta application (SURVEY §7 hard part (a)),
    and deltasync was correctness-tested but never timed at scale.  Over
    REAL unix sockets: a 10,240-node snapshot bootstrap
    (StateSyncService -> wire -> StateSyncClient -> SchedulerBinding)
    and a 1,024-row node_usage delta burst, each ending in the
    snapshot's dirty-row device scatter (``flush``).  Host control-loop
    path — ``rtt`` is unused (flush's device put is the measured part).
    """
    import tempfile

    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
    from koordinator_tpu.scheduler.scheduler import Scheduler
    from koordinator_tpu.scheduler.snapshot import ClusterSnapshot
    from koordinator_tpu.transport import (
        RpcClient,
        RpcServer,
        StateSyncClient,
        StateSyncService,
    )
    from koordinator_tpu.transport.deltasync import SchedulerBinding

    n_nodes, n_burst = 10_240, 1_024
    rng = np.random.default_rng(13)
    alloc = np.zeros((n_nodes, NUM_RESOURCE_DIMS), np.int32)
    alloc[:, 0] = rng.integers(8_000, 64_000, n_nodes)
    alloc[:, 1] = rng.integers(16_384, 262_144, n_nodes)
    usage = (alloc * 0.3).astype(np.int32)

    service = StateSyncService()
    for i in range(n_nodes):
        service.upsert_node(f"n{i}", alloc[i], usage=usage[i])

    out: dict = {}
    with tempfile.TemporaryDirectory() as tmp:
        server = RpcServer(os.path.join(tmp, "koord.sock"))
        service.attach(server)
        server.start()
        sched = Scheduler(ClusterSnapshot(capacity=n_nodes))
        sync = StateSyncClient(SchedulerBinding(sched))
        client = RpcClient(server.path, on_push=sync.on_push)
        client.connect()
        try:
            t0 = time.perf_counter()
            applied = sync.bootstrap(client)
            sched.snapshot.flush()
            dt = time.perf_counter() - t0
            out["deltasync_bootstrap_rows_per_sec_10240n"] = round(
                n_nodes / dt, 1)
            out["deltasync_bootstrap_wall_s"] = round(dt, 3)
            if applied != n_nodes:
                out["deltasync_bootstrap_error"] = (
                    f"applied {applied}/{n_nodes}")

            # usage burst: the NodeMetric refresh loop's wire shape
            burst_usage = (alloc[:n_burst] * 0.6).astype(np.int32)
            target_rv = service.rv + n_burst
            t0 = time.perf_counter()
            for i in range(n_burst):
                service.update_node_usage(f"n{i}", burst_usage[i])
            deadline = time.time() + 60
            while sync.rv < target_rv and time.time() < deadline:
                time.sleep(0.001)
            shipped = sched.snapshot.flush()
            dt = time.perf_counter() - t0
            out["deltasync_burst_rows_per_sec_1024rows"] = round(
                n_burst / dt, 1)
            out["deltasync_burst_wall_ms"] = round(dt * 1e3, 2)
            if sync.rv < target_rv:
                out["deltasync_burst_error"] = (
                    f"client rv {sync.rv} < {target_rv} after 60s")
            if shipped != n_burst:
                out.setdefault(
                    "deltasync_burst_note",
                    f"flush shipped {shipped} rows (burst {n_burst})")
        finally:
            client.close()
            server.stop()
    return out


def _run_child(argv: list[str], timeout: float,
               env: dict | None = None) -> tuple[dict | None, str]:
    """Run a child bench process; (parsed-last-stdout-line, "") on
    success, (None, error-tail) otherwise.  One copy of the parse/error
    capture for both the --extra configs and the --cpu-quality sweep."""
    import subprocess

    def parse_last_line(stdout: str) -> dict | None:
        # newest complete record wins; scan in reverse because a timeout
        # kill can truncate the final line mid-write, and a stray
        # JSON-parseable line ('[]', '1.0') must not reach extra.update()
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(doc, dict):
                return doc
        return None

    try:
        proc = subprocess.run(
            [sys.executable, __file__, *argv],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired as e:
        # children print cumulative results incrementally, so a timeout
        # keeps whatever had finished instead of losing everything
        partial = parse_last_line(
            e.stdout.decode() if isinstance(e.stdout, bytes)
            else (e.stdout or ""))
        if partial is not None:
            partial.setdefault("child_timeout", f"after {timeout}s")
            return partial, ""
        return None, f"timeout after {timeout}s, no partial output"
    except Exception as e:
        return None, repr(e)[:200]
    if proc.returncode == 0:
        doc = parse_last_line(proc.stdout)
        if doc is not None:
            return doc, ""
        return None, "child produced no parseable dict"
    tail = (proc.stderr or proc.stdout or "").strip()[-200:]
    return None, f"rc={proc.returncode}: {tail}"


#: structured error_kind values of :func:`_device_alive` — recorded in
#: zero records and tools/tpu_probe.sh probe.log so four rounds of
#: "unreachable" (BENCH_r02-r05) become a DIAGNOSIS, not one verdict:
#:   no_devices_enumerated  jax.devices() empty or raised/hung fast
#:   probe_kernel_hung      devices enumerated; the kernel never finished
#:   transfer_stall         kernel completed; the host readback hung
#:   probe_error            the backend errored instead of hanging
DEVICE_ERROR_KINDS = ("no_devices_enumerated", "probe_kernel_hung",
                      "transfer_stall", "probe_error")


def _device_alive(timeout_s: float = 180.0) -> tuple[bool, str, str]:
    """(ok, error_kind, error) — probe the backend with a tiny kernel
    under a thread timeout, recording HOW FAR the probe got.  Through
    the axon tunnel a dead link HANGS readbacks rather than erroring,
    which would wedge the whole bench run; a probe that doesn't come
    back in time means 'record device-unreachable and exit'.  A fast
    backend ERROR (e.g. Connection refused once the tunnel process
    dies, observed 2026-07-31) counts as unreachable too — crashing
    with rc!=0 would cost the round its record, since the driver keeps
    stdout only on rc==0.

    The progress markers split ROADMAP item 1's single "tunnel down"
    verdict into distinguishable failure modes (``error_kind``): a
    tunnel that can't even enumerate devices needs a reconnect, a hung
    kernel points at the remote executor, a transfer stall at the
    readback path.  Tunnel caveat: ``block_until_ready`` can return
    before remote execution completes, so "kernel completed" is as seen
    from the host — a stall after it is classified as transfer_stall.
    """
    import threading

    progress: list[str] = []
    err: list[tuple[str, str]] = []

    def probe():
        try:
            if not jax.devices():
                err.append(("no_devices_enumerated",
                            "jax.devices() returned []"))
                return
            progress.append("devices")
            x = jnp.ones((8, 8))
            y = x @ x
            y.block_until_ready()          # kernel done (as host sees it)
            progress.append("kernel")
            value = float(np.asarray(y).sum())   # device->host readback
            assert value == 8.0 * 8 * 8
            progress.append("readback")
        except Exception as e:     # errored, as opposed to hung
            kind = ("no_devices_enumerated" if "devices" not in progress
                    else "probe_error")
            err.append((kind, repr(e)[:300]))

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if err:
        return False, err[0][0], err[0][1]
    if "readback" in progress:
        return True, "", ""
    if "kernel" in progress:
        return False, "transfer_stall", (
            f"probe kernel completed but the readback hung past "
            f"{timeout_s:.0f}s")
    if "devices" in progress:
        return False, "probe_kernel_hung", (
            f"devices enumerated but the probe kernel hung past "
            f"{timeout_s:.0f}s")
    return False, "no_devices_enumerated", (
        f"jax.devices() did not return within {timeout_s:.0f}s")


def _emit_zero_record(extra: dict,
                      device_down: bool | None = None) -> None:
    """One JSON record, then hard-exit 0: the driver records stdout
    only on rc==0, and a hung device thread must not block exit
    (os._exit skips buffered-IO teardown, hence the flush).

    If the DEVICE IS DOWN and the in-repo prober (tools/tpu_probe.sh)
    caught a tunnel-up window earlier, its captured hardware record is
    the round's real measurement — re-emit it (with provenance) instead
    of a zero.  The promotion is gated on the device actually being
    unreachable (``device_down``; re-probed when the caller doesn't
    know): a solver regression or crash ON A LIVE DEVICE must surface
    as the zero record with its error, not be masked by a stale
    capture.  Otherwise emit the zero record, after running the
    at-shape CPU quality sweep in a child process (JAX_PLATFORMS=cpu —
    the parent's backend is the hung tunnel): a device-down round must
    still leave machine-readable evidence of the solver's quality at
    the north-star shape (VERDICT r3 item 5) instead of only a zero."""
    extra.setdefault("provenance", _git_head())
    # n_devices / the mesh split are unknowable here without touching
    # the (possibly hung) backend — null marks "no device evidence",
    # vs a real count + PxN shape on nonzero records
    extra.setdefault("n_devices", None)
    extra.setdefault("mesh_axes", None)
    if device_down is None:
        # caller hit an error that MIGHT be the tunnel dying mid-run —
        # a fresh probe decides (60s: enough for a healthy tunnel)
        probe_ok, probe_kind, probe_msg = _device_alive(60.0)
        device_down = not probe_ok
        if not probe_ok:
            extra.setdefault("error_kind", probe_kind)
            extra.setdefault("reprobe_error", probe_msg)
    # the prober's own bench runs want a FRESH measurement or a zero
    # that keeps the hunt alive — never a promoted old capture (which
    # would also make the prober mark the round as captured)
    promotion_ok = os.environ.get(
        "KOORD_BENCH_NO_PROBE_PROMOTION", "").lower() in ("", "0", "false")
    skip_notes: list = []
    captured = (_latest_probe_capture(notes=skip_notes)
                if device_down and promotion_ok else None)
    if captured is not None:
        doc, source = captured
        doc.setdefault("extra", {})["probe_capture"] = {
            "source": source,
            "capture_commit": (doc["extra"].get("provenance") or {}
                               ).get("commit", ""),
            "promoted_at_commit": extra["provenance"]["commit"],
            "promoted_at_dirty": extra["provenance"]["dirty"],
            "note": "hardware record captured by tools/tpu_probe.sh "
                    "during a recent tunnel-up window (<12h, see source "
                    "timestamp); the tunnel was down at official bench "
                    "time; no solver-relevant file changed between the "
                    "capture's commit and HEAD",
            "bench_time_error": str(extra.get("error", ""))[:300],
        }
        print(json.dumps(doc))
        sys.stdout.flush()
        os._exit(0)
    if skip_notes:
        extra["probe_capture_refused"] = skip_notes[:4]
    # staged capture with provenance instead of all-or-nothing (ROADMAP
    # item 1): if the prober's bench_stages.py run completed while the
    # full headline could not, its per-stage device walls ride the zero
    # record's extra rather than being discarded
    stage_walls = _latest_probe_stages()
    if stage_walls is not None:
        extra["probe_stage_walls"] = stage_walls
    # Budget: the driver's own wall-clock limit is unknown but was
    # ~3600s historically; probes may already have burned ~660s, so
    # cap the sweep at 1500s — losing the sweep to the cap still
    # emits the zero record below, losing the whole process to the
    # driver's limit would lose even that.
    child_env = dict(os.environ, JAX_PLATFORMS="cpu")
    child_env.pop("XLA_FLAGS", None)
    quality, err = _run_child(["--cpu-quality"], timeout=1500,
                              env=child_env)
    if quality is not None:
        extra.update(quality)
    else:
        extra["cpu_quality_error"] = err
    # the state-sync timing (VERDICT r4 next #7) is host-side — a dead
    # tunnel must not cost the round its delta_apply record
    # 300s cap: ~90s loaded; the whole zero path must stay inside the
    # driver's historical ~3600s budget (probes 660s + quality 1500s)
    sync_extra, sync_err = _run_child(["--extra", "deltasync"],
                                      timeout=300, env=child_env)
    if sync_extra is not None:
        extra.update(sync_extra)
    else:
        extra["bench_deltasync_error"] = sync_err

    print(json.dumps({
        "metric": f"solve_pods_per_sec_{N_PODS}p_{N_NODES}n",
        "value": 0.0, "unit": "pods/s", "vs_baseline": 0.0,
        "extra": extra,
    }))
    sys.stdout.flush()
    os._exit(0)


def metrics_probe_hung_value() -> float:
    """The bench_probe_hung gauge's value, for the zero record's extra
    (1.0 = the last probe WEDGED rather than failing fast — points the
    diagnosis at the remote executor/readback path)."""
    from koordinator_tpu import metrics

    return metrics.bench_probe_hung.value()


def _publish_staged_main() -> int:
    """``bench.py --publish-staged``: publish the newest banked staged
    capture IMMEDIATELY, with provenance (ISSUE 9 satellite / ROADMAP
    item 1 "publish the moment a window opens").

    tools/tpu_probe.sh calls this right after its bench_stages.py run
    completes, so the first successful staged capture becomes a
    publishable artifact (``probe_results/published_<ts>.json`` + one
    JSON line on stdout) the moment it exists — instead of sitting in
    probe_results/ until the NEXT official bench round happens to
    promote it.  Host-side only: no device touch, safe while the tunnel
    is down.  Exit 1 when there is nothing recent to publish."""
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "probe_results")
    doc: dict = {"published_at": time.time(),
                 "publisher_provenance": _git_head()}
    stages = _latest_probe_stages(root)
    if stages is not None:
        doc["staged"] = stages
        # surface the capture's device count AND mesh split at the top
        # level so the perf trajectory distinguishes single-chip from
        # sharded (and 1x8 from 2x4) runs without digging into the
        # stage records
        doc["n_devices"] = stages.get("n_devices")
        doc["mesh_axes"] = stages.get("mesh_axes")
    notes: list = []
    captured = _latest_probe_capture(root, notes=notes)
    if captured is not None:
        headline, source = captured
        doc["headline"] = {"record": headline, "source": source}
    if notes:
        doc["headline_refused"] = notes[:4]
    if stages is None and captured is None:
        print(json.dumps({"error": "no recent staged capture to "
                                   "publish", "root": root}))
        return 1
    os.makedirs(root, exist_ok=True)
    ts = time.strftime("%Y%m%d_%H%M%S")
    out = os.path.join(root, f"published_{ts}.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps({"published": out,
                      "staged_stages": sorted((stages or {}).get(
                          "stages", {})),
                      "staged_caveat": (stages or {}).get("caveat"),
                      "headline": bool(captured)}))
    return 0


MAX_PROBE_CAPTURE_AGE_S = 12 * 3600.0


def _latest_probe_stages(root: str | None = None) -> dict | None:
    """Newest RECENT ``bench_stages.py`` capture the prober banked
    (probe_results/stages_*.jsonl), as ``{"source", "age_s",
    "capture_commit", "stages": {stage -> record}}``; None when none is
    recent.  Unlike the headline promotion (:func:`_latest_probe_capture`,
    which must refuse anything unverifiable), stage walls promote WITH a
    ``caveat`` string when their commit cannot be tied to HEAD — they
    land in ``extra`` as explicitly-provenanced partial evidence, never
    as the headline value."""
    import glob

    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "probe_results")
    head = _git_head()["commit"]
    now = time.time()
    for path in sorted(glob.glob(os.path.join(root, "stages_*.jsonl")),
                       reverse=True):
        name = os.path.basename(path)
        try:
            age = now - os.path.getmtime(path)
            if age > MAX_PROBE_CAPTURE_AGE_S:
                continue
            with open(path) as f:
                lines = [json.loads(line) for line in
                         f.read().strip().splitlines() if line.strip()]
        except (OSError, json.JSONDecodeError):
            continue
        stages = {d["stage"]: d for d in lines
                  if isinstance(d, dict) and "stage" in d}
        prov = stages.pop("provenance", {})
        if not stages:
            continue
        cap_commit = prov.get("commit", "")
        record: dict = {"source": name, "age_s": round(age, 1),
                        "capture_commit": cap_commit, "stages": stages,
                        # mesh-shape provenance (ISSUE 10): which device
                        # count / axis split produced these stage walls
                        "n_devices": prov.get("n_devices"),
                        "mesh_axes": prov.get("mesh_axes")}
        changed = _solver_diff(cap_commit, head)
        if prov.get("dirty"):
            record["caveat"] = (
                f"captured on a dirty tree at {cap_commit[:12]}; "
                "uncommitted solver edits are unverifiable")
        elif changed is None:
            record["caveat"] = (
                f"capture commit {cap_commit[:12] or '(unstamped)'} "
                f"unverifiable vs HEAD {head[:12]}")
        elif changed:
            record["caveat"] = ("solver files changed since capture: "
                                + ", ".join(sorted(changed)[:5]))
        return record
    return None


def _latest_probe_capture(
    root: str | None = None, notes: list | None = None,
) -> tuple[dict, str] | None:
    """Newest RECENT nonzero headline the prober captured, as (record,
    filename); None if none exists.  Only records for the SAME metric
    count — a capture from an older shape must not masquerade as the
    current headline — and only files younger than
    MAX_PROBE_CAPTURE_AGE_S (~one round of wall clock, by mtime):
    probe_results/ persists on disk, and a capture from a PREVIOUS
    round must not be re-reported as this round's measurement.

    Code provenance (VERDICT r4 weak #2): a capture is only promotable
    when its stamped commit (``extra.provenance.commit``) is HEAD, or no
    solver-relevant file (_SOLVER_PATHS) changed between the two —
    doc/test churn between capture and round end is fine, a solver
    change is not.  Unstamped captures are refused (nothing ties them to
    any code).  Skip reasons accumulate into ``notes`` so the zero
    record can say why a capture was not promoted."""
    import glob

    metric = f"solve_pods_per_sec_{N_PODS}p_{N_NODES}n"
    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "probe_results")
    if notes is None:
        notes = []
    head = _git_head()["commit"]
    now = time.time()
    for path in sorted(glob.glob(os.path.join(root, "bench_*.json")),
                       reverse=True):
        name = os.path.basename(path)
        try:
            if now - os.path.getmtime(path) > MAX_PROBE_CAPTURE_AGE_S:
                continue
            with open(path) as f:
                doc = json.loads(f.read().strip().splitlines()[-1])
        except (OSError, json.JSONDecodeError, IndexError):
            continue
        if not (isinstance(doc, dict) and doc.get("metric") == metric
                and isinstance(doc.get("value"), (int, float))
                and doc["value"] > 0
                # a record that is ITSELF a promotion (the prober ran
                # bench.py while the tunnel was flapping and captured a
                # re-emitted old record) must not count as a fresh
                # measurement: accepting it would refresh the stale
                # capture's age window on every promotion, laundering
                # one old measurement into every future round
                and "probe_capture" not in (doc.get("extra") or {})):
            continue
        prov = (doc.get("extra") or {}).get("provenance") or {}
        cap_commit = prov.get("commit", "")
        if prov.get("dirty"):
            # a capture from a dirty tree measured code that no commit
            # records — the solver diff below cannot see uncommitted
            # edits, so the stamp is unverifiable by construction
            notes.append(
                f"{name}: refused — captured on a dirty tree at "
                f"{cap_commit[:12]}; uncommitted solver edits are "
                "unverifiable")
            continue
        changed = _solver_diff(cap_commit, head)
        if changed is None:
            notes.append(
                f"{name}: refused — capture commit "
                f"{cap_commit[:12] or '(unstamped)'} unverifiable vs HEAD "
                f"{head[:12]}")
            continue
        if changed:
            notes.append(
                f"{name}: refused — solver files changed since capture "
                f"commit {cap_commit[:12]}: {sorted(changed)[:5]}")
            continue
        return doc, name
    return None


def main() -> None:
    from __graft_entry__ import _build_problem
    from koordinator_tpu.ops.assignment import score_pods
    from koordinator_tpu.ops.batch_assign import batch_assign

    # Retry window: the tunnel flaps (PERF_NOTES tunnel log) and this run
    # may be the round's one official record — probe a few times before
    # recording a zero.  KOORD_BENCH_PROBE_TRIES overrides (1 = old
    # single-probe behavior); total worst-case wait = tries * 180s + waits.
    tries = int(os.environ.get("KOORD_BENCH_PROBE_TRIES", "3"))
    # probes run through the armed prober (koordinator_tpu.bench_prober):
    # every attempt lands in the metrics registry by outcome/duration,
    # and a hung probe burns the bench_probe_hang SLO instead of being a
    # silent retry — the observability the four BENCH_r02-r05 zeros
    # never had
    from koordinator_tpu.bench_prober import ProbeArmer

    probe_state: dict = {"kind": "", "err": ""}

    def probe() -> tuple[bool, str, str]:
        ok, kind, err = _device_alive()
        probe_state.update(kind=kind, err=err)
        return ok, kind, err

    armer = ProbeArmer(probe, interval_s=60.0, deadline_s=180.0)
    alive = False
    for attempt in range(max(tries, 1)):
        alive = armer.tick()
        if alive:
            break
        if attempt + 1 < tries:
            time.sleep(60)
    if not alive:
        _emit_zero_record({
            "error": "device unreachable: probe did not complete in "
                     f"{max(tries, 1)} attempts (tunnel down?): "
                     f"{probe_state['err']}",
            "error_kind": probe_state["kind"],
            "probe_hung": metrics_probe_hung_value()}, device_down=True)

    state, pods, cfg = _build_problem(N_NODES, N_PODS, seed=42)

    def rtt_floor(state, pods):
        # same traced calling convention as the timed kernels, so the
        # floor includes the pods-pytree dispatch overhead it subtracts
        return state.node_allocatable.sum() + pods.requests.sum()

    rtt, _ = _median_readback_seconds(jax.jit(rtt_floor), (state, pods))

    def score_fn(st, p):
        scores, feasible = score_pods(st, p, cfg)
        # the FULL (P, N) score tensor must stay live (scores.sum()) or XLA
        # may legally slice scoring down to the one row the chain consumes
        return (scores.sum() + feasible.sum(),
                st.replace(node_requested=st.node_requested
                           + (scores[0, :, None] & 1)))

    # k=16 with stratified (5, 15) candidates: the hardware-measured fast
    # point (167.6 ms = 298.4k pods/s = 1.19x at k=16 in the 2026-07-30
    # session) combined with the round-3 quality fix (stratified selection
    # assigns 100% of this exact shape on CPU at k=16, vs 73.6% for the
    # old single-key k=16 — PERF_NOTES.md); solve_assigned_frac below
    # guards the claim on every run.  Every candidate method below is
    # timed; the headline takes the fastest one inside the 1%-of-best
    # quality gate and records all, so the claim is always the measured
    # best rather than a pre-committed guess.
    score_per_iter, _ = _time_assign(state, pods, score_fn, rtt, n=5)
    # method passed EXPLICITLY so the recorded label always matches what
    # ran (default "auto" would silently time the exact path on CPU)
    candidates = {
        "approx": lambda st, p: batch_assign(st, p, cfg, k=16,
                                             method="approx")[:2],
        # k=8 halves candidate-tensor work and assigns 100% at this
        # shape on CPU (PERF_NOTES); the quality gate below keeps it
        # from winning if TPU's approx_max_k recall strands pods
        "approx_k8": lambda st, p: batch_assign(st, p, cfg, k=8,
                                                method="approx")[:2],
        "chunked": lambda st, p: batch_assign(st, p, cfg, k=16,
                                              method="chunked")[:2],
        # the recall-exact TPU fallback (exact top_k at chunked peak
        # memory) — timing it alongside approx prices the flip
        # bench_recall.py's decision rule would trigger
        "chunked_exact": lambda st, p: batch_assign(
            st, p, cfg, k=16, method="chunked_exact")[:2],
    }
    timed = {}
    for method, fn in candidates.items():
        try:
            timed[method] = _time_assign(state, pods, fn, rtt, n=5)
        except Exception as e:  # a broken variant must not cost the run
            timed[f"{method}_error"] = repr(e)[:200]
    measured = {m: t for m, t in timed.items() if isinstance(t, tuple)}
    if not measured:
        _emit_zero_record({"error": "every solve variant failed", **{
            k: v for k, v in timed.items() if isinstance(v, str)}})
    # quality gates speed: only variants whose assigned count is within
    # 1% of the best may win on time — a faster solver that strands pods
    # is not an improvement
    best_count = max(t[1] for t in measured.values())
    eligible = {m: t for m, t in measured.items()
                if t[1] >= 0.99 * best_count}
    best = min(eligible, key=lambda m: eligible[m][0])
    solve_per_iter, solve_count = eligible[best]
    score_pods_per_sec = N_PODS / score_per_iter
    solve_pods_per_sec = N_PODS / solve_per_iter
    # solve QUALITY rides alongside throughput (the chained loop's
    # accumulator counts assigned pods, so no extra compile): the queue at
    # this shape is fully schedulable (capacity = 3.6x demand), so
    # assigned/valid must stay ~1.0 — a faster solver that strands pods is
    # not an improvement
    assigned_frac = solve_count / float(pods.valid.sum())

    from koordinator_tpu.parallel import mesh as _pmesh

    extra = {
        "provenance": _git_head(),
        # the perf trajectory must distinguish single-chip from sharded
        # captures (ISSUE 10): a device count next to every nonzero
        # record, stamped while the backend is provably alive — plus
        # the FULL 2-D axis split it would solve on (ISSUE 14; None =
        # single-device, no mesh)
        "n_devices": len(jax.devices()),
        "mesh_axes": _pmesh.mesh_axes(_pmesh.resolve_solver_mesh("auto")),
        f"filter_score_pods_per_sec_{N_PODS}p_{N_NODES}n": round(
            score_pods_per_sec, 1
        ),
        "solve_ms_per_round": round(solve_per_iter * 1e3, 2),
        "solve_assigned_frac": round(assigned_frac, 4),
        "solve_candidate_method": best,
    }
    # Per-solve latency DISTRIBUTION: BASELINE's target is <200ms p99,
    # not a chained mean (VERDICT r3 missing #4).  Each sample is one
    # single-iteration chained readback minus the separately measured
    # tunnel floor; rtt jitter pollutes the tail, so this is an upper
    # bound on the solver's own p99 — record it rather than nothing.
    try:
        single = jax.jit(_chained_loop(candidates[best], iters=1))
        float(single(state, pods))  # warm/compile
        samples = []
        for _ in range(20):
            t0 = time.perf_counter()
            float(single(state, pods))
            samples.append(max(time.perf_counter() - t0 - rtt, 0.0) * 1e3)
        for q in (50, 90, 99):
            extra[f"solve_latency_ms_p{q}"] = round(
                float(np.percentile(samples, q)), 2)
    except Exception as e:
        extra["solve_latency_error"] = repr(e)[:200]
    for method, t in timed.items():
        if isinstance(t, tuple):
            extra[f"solve_ms_{method}"] = round(t[0] * 1e3, 2)
        else:
            extra[f"solve_{method}"] = t
    # extras run in CHILD processes: even a device OOM abort or backend
    # SIGABRT in a config cannot cost the already-measured headline
    for name in ("quota", "gang", "lownodeload", "colocation",
                 "deltasync"):
        result, err = _run_child(["--extra", name], timeout=900)
        if result is not None:
            extra.update(result)
        else:
            extra[f"bench_{name}_error"] = err

    print(
        json.dumps(
            {
                "metric": f"solve_pods_per_sec_{N_PODS}p_{N_NODES}n",
                "value": round(solve_pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(
                    solve_pods_per_sec / BASELINE_PODS_PER_SEC, 3
                ),
                "extra": extra,
            }
        )
    )


def _bench_incremental(bstate, bpods, bcfg, bp: int, bn: int,
                       dirty_frac: float = 0.01) -> dict:
    """Median-of-3 wall time of one INCREMENTAL steady-state round —
    dirty-node column refresh, compacted dirty-pod rescore, and the
    propose/accept pass over the merged (P, k) candidates — at a given
    dirty fraction, alongside the full pass's number for the ratio.
    CPU tripwire for the delta-scaling claim (steady-state rounds must
    scale with the delta, not the problem)."""
    from koordinator_tpu.ops.batch_assign import (
        CandidateCache,
        assign_round_pass,
        batch_assign,
        refresh_candidates,
        scatter_candidate_rows,
        select_candidates,
    )
    from koordinator_tpu.state.cluster_state import _bucket

    k = 16
    n_dirty_nodes = max(int(bn * dirty_frac), 1)
    n_dirty_pods = max(int(bp * dirty_frac), 1)

    full = jax.jit(lambda s, p: batch_assign(s, p, bcfg, k=k,
                                             method="exact")[0])
    np.asarray(full(bstate, bpods))
    t_full = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(full(bstate, bpods))
        t_full.append(time.perf_counter() - t0)

    sel = jax.jit(lambda s, p: select_candidates(
        s, p, bcfg, k=k, method="exact", with_scores=True))
    cache = CandidateCache(*sel(bstate, bpods))
    dirty = np.arange(n_dirty_nodes, dtype=np.int32)
    dpad = _bucket(n_dirty_nodes, minimum=64)
    drows = np.zeros(dpad, np.int32)
    drows[:n_dirty_nodes] = dirty
    dvalid = np.zeros(dpad, bool)
    dvalid[:n_dirty_nodes] = True
    dirty_pods = np.zeros(bpods.capacity, bool)
    dirty_pods[:n_dirty_pods] = True
    small, idx = bpods.compact(dirty_pods)
    rows_pad = np.full(small.capacity, bpods.capacity, np.int32)
    rows_pad[: len(idx)] = idx

    refresh = jax.jit(lambda s, p, c, dr, dv: refresh_candidates(
        s, p, bcfg, c, dr, dv, k=k))
    sel_small = jax.jit(lambda s, p: select_candidates(
        s, p, bcfg, k=k, method="exact", with_scores=True))
    scatter = jax.jit(scatter_candidate_rows)
    rounds = jax.jit(lambda s, p, ck, cn: assign_round_pass(
        s, p, None, ck, cn, bcfg)[0])

    def inc_round():
        ck, c2 = refresh(bstate, bpods, cache, drows, dvalid)
        sk, sn, ss = sel_small(bstate, small)
        c2 = scatter(c2, rows_pad, sk, sn, ss)
        return np.asarray(rounds(bstate, bpods, c2.cand_key, c2.cand_node))

    inc_round()  # compile + warm
    t_inc = []
    for _ in range(3):
        t0 = time.perf_counter()
        inc_round()
        t_inc.append(time.perf_counter() - t0)

    med_full, med_inc = float(np.median(t_full)), float(np.median(t_inc))
    pct = int(dirty_frac * 100)
    return {
        f"cpu_wall_s_med3_incremental_{pct}pct_{bp}p_{bn}n": round(
            med_inc, 4),
        f"cpu_wall_s_med3_full_exact_k{k}_{bp}p_{bn}n": round(med_full, 3),
        "incremental_dirty_frac_nodes": dirty_frac,
        "incremental_dirty_frac_pods": dirty_frac,
        "incremental_dirty_nodes": n_dirty_nodes,
        "incremental_dirty_pods": n_dirty_pods,
        "incremental_speedup_vs_full": round(
            med_full / max(med_inc, 1e-9), 1),
    }


def _cpu_quality_main() -> None:
    """Child-process entry (JAX_PLATFORMS=cpu): solve quality at the
    north-star shape with the TPU-serving approx candidate path forced —
    the machine-readable form of scratch_quality.py, captured into the
    official record even when the device is unreachable."""
    from __graft_entry__ import _build_problem
    from koordinator_tpu.ops.batch_assign import batch_assign

    out: dict = {"cpu_quality_shape": f"{N_PODS}p_{N_NODES}n"}

    # CPU wall-clock regression bound (VERDICT r4 weak #1): with the
    # tunnel down for three straight rounds, nothing guarded solver
    # SPEED — a slowdown would ride free until hardware returned.
    # Median-of-3 jitted solve wall time per candidate method at a mid
    # shape: not a hardware number, a tripwire cheap enough to repeat
    # that still exposes an accidental O(P*N) materialization or an
    # extra pass.  Runs FIRST so a parent timeout during the expensive
    # at-shape sweep below cannot lose it (children print cumulatively).
    bp, bn = 12_800, 2_560
    bstate, bpods, bcfg = _build_problem(bn, bp, seed=42)
    for method, k in (("exact", 16), ("approx", 16), ("approx", 8),
                      ("chunked", 16), ("chunked_exact", 16)):
        fn = jax.jit(lambda s, p, k=k, m=method: batch_assign(
            s, p, bcfg, k=k, method=m)[0])
        try:
            asn = np.asarray(fn(bstate, bpods))  # compile + warm
            times = []
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(fn(bstate, bpods))
                times.append(time.perf_counter() - t0)
            out[f"cpu_wall_s_med3_{method}_k{k}_{bp}p_{bn}n"] = round(
                float(np.median(times)), 3)
            out[f"cpu_assigned_frac_{method}_k{k}_{bp}p_{bn}n"] = round(
                float((asn >= 0).sum())
                / float(np.asarray(bpods.valid).sum()), 4)
        except Exception as e:
            out[f"cpu_wall_{method}_k{k}_error"] = repr(e)[:200]
        print(json.dumps(out))
        sys.stdout.flush()

    # Incremental delta-scaling claim (ISSUE 1 acceptance criterion): a
    # steady-state round with ~1% dirty nodes AND ~1% dirty pods —
    # dirty-column refresh + compacted dirty-pod rescore + the
    # propose/accept pass — vs the full batch_assign pass above.
    try:
        out.update(_bench_incremental(bstate, bpods, bcfg, bp, bn))
    except Exception as e:
        out["cpu_incremental_error"] = repr(e)[:200]
    print(json.dumps(out))
    sys.stdout.flush()

    state, pods, cfg = _build_problem(N_NODES, N_PODS, seed=42)
    valid = int(np.asarray(pods.valid).sum())
    for k in (16, 32):
        t0 = time.perf_counter()
        asn, st = jax.jit(
            lambda s, p, k=k: batch_assign(s, p, cfg, k=k,
                                           method="approx")[:2])(state, pods)
        asn = np.asarray(asn)
        assigned = int((asn >= 0).sum())
        capacity_ok = bool((np.asarray(st.node_requested)
                            <= np.asarray(st.node_allocatable)).all())
        out[f"cpu_assigned_frac_k{k}_approx"] = round(assigned / valid, 4)
        out[f"cpu_capacity_ok_k{k}_approx"] = capacity_ok
        out[f"cpu_quality_wall_s_k{k}"] = round(time.perf_counter() - t0, 1)
        # cumulative line per k: if the parent's timeout kills us during
        # a later solve, the finished evidence survives on stdout
        print(json.dumps(out))
        sys.stdout.flush()


def _extra_main(name: str) -> None:
    """Child-process entry: run one extra config, print its dict as JSON."""
    state, _, _ = __import__("__graft_entry__")._build_problem(64, 64)

    def rtt_floor(state):
        return state.node_allocatable.sum()

    rtt, _ = _median_readback_seconds(jax.jit(rtt_floor), (state,), n=3)
    fn = {"quota": _bench_quota, "gang": _bench_gang,
          "lownodeload": _bench_lownodeload,
          "colocation": _bench_colocation,
          "deltasync": _bench_deltasync}[name]
    print(json.dumps(fn(rtt)))


if __name__ == "__main__":
    # honor an explicit platform request even under the ambient
    # sitecustomize, which pins the tunnel backend via jax.config (so the
    # env var alone is ignored); lets the extras' child processes — and CPU
    # smoke runs — follow the parent's platform
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    if len(sys.argv) == 3 and sys.argv[1] == "--extra":
        _extra_main(sys.argv[2])
    elif len(sys.argv) == 2 and sys.argv[1] == "--cpu-quality":
        _cpu_quality_main()
    elif len(sys.argv) == 2 and sys.argv[1] == "--publish-staged":
        sys.exit(_publish_staged_main())
    else:
        try:
            main()
        except Exception as e:  # NOT BaseException: a Ctrl-C must abort,
            # not fabricate an official-looking zero record
            # The tunnel can die MID-RUN after a successful probe
            # (observed 2026-07-31: Connection refused inside
            # _build_problem 38 min in, rc!=0, round record lost).
            # Any crash downgrades to the zero record so the driver —
            # which keeps stdout only on rc==0 — still gets the CPU
            # quality evidence.
            _emit_zero_record(
                {"error": f"bench failed mid-run: {e!r}"[:500]})
