"""Benchmark: batched Filter+Score at the north-star shape.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Shape and target from BASELINE.json: 50k pending pods scored against 10,240
nodes; the reference-replacing hot loop is the scheduler's per-node
Filter/Score plugin fan-out (SURVEY.md section 3.1), and the north-star is
50k pods / <200ms p99 on a v5e-4 => 250k pods/sec (we run on ONE chip).

Timing methodology: through the axon tunnel, ``block_until_ready`` returns
before remote execution completes, so naive wall-clocking measures dispatch,
not compute. The kernel therefore runs K iterations inside one jitted
``fori_loop`` (chained through a data dependency so XLA cannot collapse
them), reduced to a scalar whose host readback cannot complete early; the
tunnel round-trip floor is measured separately with a trivial kernel and
subtracted before dividing by K.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

N_NODES = 10_240
N_PODS = 50_000
K_ITERS = 8
BASELINE_PODS_PER_SEC = 250_000.0


def _median_readback_seconds(fn, args, n: int = 5) -> float:
    float(fn(*args))  # compile + warm
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        float(fn(*args))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main() -> None:
    from __graft_entry__ import _build_problem
    from koordinator_tpu.ops.assignment import score_pods

    state, pods, cfg = _build_problem(N_NODES, N_PODS, seed=42)

    def loop(state, pods, cfg):
        def body(i, carry):
            acc, usage = carry
            st = state.replace(node_usage=usage)
            scores, feasible = score_pods(st, pods, cfg)
            # data dependency between iterations: XLA cannot dedupe/elide
            usage = usage + (scores[0, :, None] & 1).astype(jnp.int32)
            return acc + scores.sum() + feasible.sum(), usage

        acc, _ = jax.lax.fori_loop(
            0, K_ITERS, body, (jnp.int32(0), state.node_usage)
        )
        return acc

    def rtt_floor(state, pods, cfg):
        return state.node_allocatable.sum() + pods.requests.sum()

    rtt = _median_readback_seconds(jax.jit(rtt_floor), (state, pods, cfg))
    total = _median_readback_seconds(jax.jit(loop), (state, pods, cfg))
    per_iter = max((total - rtt) / K_ITERS, 1e-9)
    pods_per_sec = N_PODS / per_iter

    print(
        json.dumps(
            {
                "metric": f"filter_score_pods_per_sec_{N_PODS}p_{N_NODES}n",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
