"""Benchmark: full batched solve + Filter/Score at the north-star shape.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Shape and target from BASELINE.json: 50k pending pods scheduled against
10,240 nodes; the north-star is the full SOLVE (not just scoring) of 50k pods
in <200ms p99 on a v5e-4 => 250k pods/sec (we run on ONE chip).  The headline
metric times ``batch_assign`` end to end — filter, score, top-k candidate
selection and the propose/accept conflict-resolution rounds with capacity
feedback.  The Filter+Score-only number (the round-1 metric) is kept in
``extra`` for round-over-round comparability.

Timing methodology: through the axon tunnel, ``block_until_ready`` returns
before remote execution completes, so naive wall-clocking measures dispatch,
not compute. Each kernel therefore runs K iterations inside one jitted
``fori_loop`` (chained through a data dependency so XLA cannot collapse
them), reduced to a scalar whose host readback cannot complete early; the
tunnel round-trip floor is measured separately with a trivial kernel and
subtracted before dividing by K.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

N_NODES = 10_240
N_PODS = 50_000
K_ITERS = 8
BASELINE_PODS_PER_SEC = 250_000.0


def _median_readback_seconds(fn, args, n: int = 5) -> float:
    float(fn(*args))  # compile + warm
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        float(fn(*args))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main() -> None:
    from __graft_entry__ import _build_problem
    from koordinator_tpu.ops.assignment import score_pods
    from koordinator_tpu.ops.batch_assign import batch_assign

    state, pods, cfg = _build_problem(N_NODES, N_PODS, seed=42)

    def score_loop(state, pods, cfg):
        def body(i, carry):
            acc, usage = carry
            st = state.replace(node_usage=usage)
            scores, feasible = score_pods(st, pods, cfg)
            # data dependency between iterations: XLA cannot dedupe/elide
            usage = usage + (scores[0, :, None] & 1).astype(jnp.int32)
            return acc + scores.sum() + feasible.sum(), usage

        acc, _ = jax.lax.fori_loop(
            0, K_ITERS, body, (jnp.int32(0), state.node_usage)
        )
        return acc

    def solve_loop(state, pods, cfg):
        def body(i, carry):
            acc, usage = carry
            st = state.replace(node_usage=usage)
            assignments, new_state, _ = batch_assign(st, pods, cfg)
            usage = usage + (new_state.node_requested & 1)
            return acc + assignments.sum(), usage

        acc, _ = jax.lax.fori_loop(
            0, K_ITERS, body, (jnp.int32(0), state.node_usage)
        )
        return acc

    def rtt_floor(state, pods, cfg):
        return state.node_allocatable.sum() + pods.requests.sum()

    rtt = _median_readback_seconds(jax.jit(rtt_floor), (state, pods, cfg))
    score_total = _median_readback_seconds(jax.jit(score_loop), (state, pods, cfg))
    solve_total = _median_readback_seconds(jax.jit(solve_loop), (state, pods, cfg))
    score_per_iter = max((score_total - rtt) / K_ITERS, 1e-9)
    solve_per_iter = max((solve_total - rtt) / K_ITERS, 1e-9)
    score_pods_per_sec = N_PODS / score_per_iter
    solve_pods_per_sec = N_PODS / solve_per_iter

    print(
        json.dumps(
            {
                "metric": f"solve_pods_per_sec_{N_PODS}p_{N_NODES}n",
                "value": round(solve_pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(
                    solve_pods_per_sec / BASELINE_PODS_PER_SEC, 3
                ),
                "extra": {
                    f"filter_score_pods_per_sec_{N_PODS}p_{N_NODES}n": round(
                        score_pods_per_sec, 1
                    ),
                    "solve_ms_per_round": round(solve_per_iter * 1e3, 2),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
