"""Benchmark: batched Filter+Score throughput at 10k-node scale.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured kernel is the replacement for the reference scheduler's
Filter+Score hot loop (upstream parallel per-node plugin calls;
SURVEY.md section 3.1). Baseline for vs_baseline is the north-star target from
BASELINE.json: 50k pods over 10k nodes in <200 ms p99 => 250k pods/sec.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

N_NODES = 10_240
N_PODS = 512
BASELINE_PODS_PER_SEC = 250_000.0


def main() -> None:
    from __graft_entry__ import _build_problem
    from koordinator_tpu.ops.assignment import score_pods

    state, pods, cfg = _build_problem(N_NODES, N_PODS, seed=42)
    fn = jax.jit(score_pods)

    # Compile + warmup.
    scores, feasible = fn(state, pods, cfg)
    scores.block_until_ready()

    # Timed runs: full batched Filter+Score of N_PODS pods against N_NODES nodes.
    times = []
    for _ in range(20):
        t0 = time.perf_counter()
        scores, feasible = fn(state, pods, cfg)
        scores.block_until_ready()
        feasible.block_until_ready()
        times.append(time.perf_counter() - t0)

    p50 = float(np.median(times))
    pods_per_sec = N_PODS / p50
    print(
        json.dumps(
            {
                "metric": f"filter_score_pods_per_sec_{N_NODES}_nodes",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
