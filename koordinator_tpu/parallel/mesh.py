"""Device mesh + sharding layout for the scheduling solver.

Layout: a 2-D mesh ("pods", "nodes").

- ``score_pods`` shards the (P, N) score/filter matrix over both axes: the
  pod batch is data-parallel over the "pods" axis, node tensors shard over
  "nodes". No communication except the caller's final top-k.
- ``greedy_assign`` runs with node-axis sharding only (the scan is sequential
  over pods); each step's argmax over sharded node scores becomes an
  all-reduce over ICI, inserted by GSPMD from the sharding annotations.
- Quota/colocation reductions (psum over nodes) follow the same layout.

Multi-host: the same code runs under ``jax.distributed`` — mesh axes spanning
hosts ride DCN; we keep the "nodes" axis innermost so its collectives stay on
ICI within a slice.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODES_AXIS = "nodes"
PODS_AXIS = "pods"


def nodes_shard_count(mesh: Mesh | None) -> int:
    """Size of a mesh's nodes axis (1 for no mesh)."""
    return 1 if mesh is None else int(mesh.shape[NODES_AXIS])


def pods_shard_count(mesh: Mesh | None) -> int:
    """Size of a mesh's pods axis (1 for no mesh)."""
    return 1 if mesh is None else int(mesh.shape[PODS_AXIS])


def mesh_axes(mesh: Mesh | None) -> dict | None:
    """{"pods": p, "nodes": n} provenance of a mesh (None for no mesh).

    Every bench record / provenance line stamps this shape (ISSUE 14):
    a sharded-path win is unattributable without the axis split it was
    measured on."""
    if mesh is None:
        return None
    return {PODS_AXIS: pods_shard_count(mesh),
            NODES_AXIS: nodes_shard_count(mesh)}


def resolve_solver_mesh(spec="auto", devices=None) -> Mesh | None:
    """Resolve the scheduler's solve mesh (sharded-by-default policy).

    - a :class:`Mesh` passes through unchanged;
    - ``None`` / ``"off"`` disables sharding;
    - ``"auto"`` (the default) builds the all-devices mesh whenever more
      than one device is visible — every device on the nodes axis unless
      a pods split is requested (below).

    Env overrides of ``"auto"`` (no code changes):

    - ``KOORD_SOLVER_MESH=off`` forces single-device; an integer caps
      the device count (``KOORD_SOLVER_MESH=4`` on an 8-chip host); a
      ``PxN`` shape (``KOORD_SOLVER_MESH=2x4``) builds the explicit 2-D
      pods x nodes mesh over the first ``P*N`` devices.
    - ``KOORD_SOLVER_MESH_PODS=<int>`` sets the pods-axis size while the
      nodes axis takes the rest (the shorthand when the device count
      varies across hosts).  Default 1 — today's all-nodes layout,
      bit-for-bit.
    """
    if isinstance(spec, Mesh):
        return spec
    if spec in (None, "off"):
        return None
    if spec != "auto":
        raise ValueError(f"unknown solver mesh spec {spec!r} "
                         "(Mesh | 'auto' | 'off' | None)")
    env = os.environ.get("KOORD_SOLVER_MESH", "").strip().lower()
    if env in ("off", "0", "none", "single"):
        return None
    devs = list(devices if devices is not None else jax.devices())
    pods_axis = max(int(os.environ.get("KOORD_SOLVER_MESH_PODS", "1")), 1)
    if "x" in env:
        p_s, _, n_s = env.partition("x")
        if not (p_s.isdigit() and n_s.isdigit()):
            raise ValueError(
                f"KOORD_SOLVER_MESH={env!r}: a 2-D shape spells PxN "
                "with integer axis sizes (e.g. 2x4)")
        pods_axis, nodes_axis = max(int(p_s), 1), max(int(n_s), 1)
        if pods_axis * nodes_axis > len(devs):
            raise ValueError(
                f"KOORD_SOLVER_MESH={env} needs {pods_axis * nodes_axis} "
                f"devices, have {len(devs)}")
        devs = devs[: pods_axis * nodes_axis]
    elif env.isdigit():
        devs = devs[:max(int(env), 1)]
    if len(devs) < 2:
        return None
    if len(devs) % pods_axis:
        raise ValueError(
            f"pods_axis={pods_axis} does not divide the "
            f"{len(devs)}-device mesh (KOORD_SOLVER_MESH_PODS)")
    return solver_mesh(devs, pods_axis=pods_axis)


def solver_mesh(devices=None, pods_axis: int = 1) -> Mesh:
    """Build the ("pods", "nodes") mesh over the given (or all) devices.

    ``pods_axis`` devices are allocated to pod-batch data parallelism; the rest
    to the node shard. Default puts every device on the nodes axis, the right
    call for latency-bound single-batch solves.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    n = devs.size
    if n % pods_axis != 0:
        raise ValueError(f"{n} devices not divisible by pods_axis={pods_axis}")
    grid = devs.reshape(pods_axis, n // pods_axis)
    return Mesh(grid, (PODS_AXIS, NODES_AXIS))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """(N, ...) tensors shard their leading (node) axis."""
    return NamedSharding(mesh, P(NODES_AXIS))


def pod_sharding(mesh: Mesh) -> NamedSharding:
    """(P, ...) tensors shard their leading (pod) axis."""
    return NamedSharding(mesh, P(PODS_AXIS))


def matrix_sharding(mesh: Mesh) -> NamedSharding:
    """(P, N) matrices shard over both mesh axes."""
    return NamedSharding(mesh, P(PODS_AXIS, NODES_AXIS))


def shard_cluster_state(state, mesh: Mesh):
    """Place ClusterState node tensors with the node axis sharded over the mesh."""
    ns = node_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, ns), state)


def shard_scheduled_pods(sched, mesh: Mesh):
    """Place ScheduledPods (the preemption victim table) with the victim
    axis sharded over the pods mesh axis: victim candidacy/sorting is
    per-victim elementwise; the per-node reductions ride the mesh
    collectives the same way score reductions do."""
    ps = pod_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, ps), sched)


def shard_reservation_set(rsv, mesh: Mesh):
    """Place a ReservationSet reservation-axis-sharded over the pods mesh
    axis (V is small; its cross terms against nodes are gathered)."""
    ps = pod_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, ps), rsv)


def shard_pod_batch(pods, mesh: Mesh):
    """Place PodBatch tensors pod-axis-sharded; a dense (P, N) feasibility
    matrix shards over both axes, the factored (P, C) selector mask over the
    pod axis only (C is small and replicating it is the point)."""
    ps = pod_sharding(mesh)
    ms = matrix_sharding(mesh)
    return pods.replace(
        requests=jax.device_put(pods.requests, ps),
        priority=jax.device_put(pods.priority, ps),
        qos=jax.device_put(pods.qos, ps),
        gang_id=jax.device_put(pods.gang_id, ps),
        quota_id=jax.device_put(pods.quota_id, ps),
        non_preemptible=jax.device_put(pods.non_preemptible, ps),
        valid=jax.device_put(pods.valid, ps),
        rot_id=jax.device_put(pods.rot_id, ps),
        feasible=(
            jax.device_put(pods.feasible, ms)
            if pods.feasible is not None else None
        ),
        selector_mask=(
            jax.device_put(pods.selector_mask, ps)
            if pods.selector_mask is not None else None
        ),
    )
