"""Device mesh + sharding layout for the scheduling solver.

Layout: a 2-D mesh ("pods", "nodes").

- ``score_pods`` shards the (P, N) score/filter matrix over both axes: the
  pod batch is data-parallel over the "pods" axis, node tensors shard over
  "nodes". No communication except the caller's final top-k.
- ``greedy_assign`` runs with node-axis sharding only (the scan is sequential
  over pods); each step's argmax over sharded node scores becomes an
  all-reduce over ICI, inserted by GSPMD from the sharding annotations.
- Quota/colocation reductions (psum over nodes) follow the same layout.

Multi-host: the same code runs under ``jax.distributed`` — mesh axes spanning
hosts ride DCN; we keep the "nodes" axis innermost so its collectives stay on
ICI within a slice.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODES_AXIS = "nodes"
PODS_AXIS = "pods"


def nodes_shard_count(mesh: Mesh | None) -> int:
    """Size of a mesh's nodes axis (1 for no mesh)."""
    return 1 if mesh is None else int(mesh.shape[NODES_AXIS])


def resolve_solver_mesh(spec="auto", devices=None) -> Mesh | None:
    """Resolve the scheduler's solve mesh (sharded-by-default policy).

    - a :class:`Mesh` passes through unchanged;
    - ``None`` / ``"off"`` disables sharding;
    - ``"auto"`` (the default) builds the all-devices nodes-axis mesh
      whenever more than one device is visible.

    The ``KOORD_SOLVER_MESH`` env var overrides ``"auto"`` without code
    changes: ``off`` forces single-device, an integer caps the device
    count (e.g. ``KOORD_SOLVER_MESH=4`` on an 8-chip host).
    """
    if isinstance(spec, Mesh):
        return spec
    if spec in (None, "off"):
        return None
    if spec != "auto":
        raise ValueError(f"unknown solver mesh spec {spec!r} "
                         "(Mesh | 'auto' | 'off' | None)")
    env = os.environ.get("KOORD_SOLVER_MESH", "").strip().lower()
    if env in ("off", "0", "none", "single"):
        return None
    devs = list(devices if devices is not None else jax.devices())
    if env.isdigit():
        devs = devs[:max(int(env), 1)]
    if len(devs) < 2:
        return None
    return solver_mesh(devs, pods_axis=1)


def solver_mesh(devices=None, pods_axis: int = 1) -> Mesh:
    """Build the ("pods", "nodes") mesh over the given (or all) devices.

    ``pods_axis`` devices are allocated to pod-batch data parallelism; the rest
    to the node shard. Default puts every device on the nodes axis, the right
    call for latency-bound single-batch solves.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    n = devs.size
    if n % pods_axis != 0:
        raise ValueError(f"{n} devices not divisible by pods_axis={pods_axis}")
    grid = devs.reshape(pods_axis, n // pods_axis)
    return Mesh(grid, (PODS_AXIS, NODES_AXIS))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """(N, ...) tensors shard their leading (node) axis."""
    return NamedSharding(mesh, P(NODES_AXIS))


def pod_sharding(mesh: Mesh) -> NamedSharding:
    """(P, ...) tensors shard their leading (pod) axis."""
    return NamedSharding(mesh, P(PODS_AXIS))


def matrix_sharding(mesh: Mesh) -> NamedSharding:
    """(P, N) matrices shard over both mesh axes."""
    return NamedSharding(mesh, P(PODS_AXIS, NODES_AXIS))


def shard_cluster_state(state, mesh: Mesh):
    """Place ClusterState node tensors with the node axis sharded over the mesh."""
    ns = node_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, ns), state)


def shard_scheduled_pods(sched, mesh: Mesh):
    """Place ScheduledPods (the preemption victim table) with the victim
    axis sharded over the pods mesh axis: victim candidacy/sorting is
    per-victim elementwise; the per-node reductions ride the mesh
    collectives the same way score reductions do."""
    ps = pod_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, ps), sched)


def shard_reservation_set(rsv, mesh: Mesh):
    """Place a ReservationSet reservation-axis-sharded over the pods mesh
    axis (V is small; its cross terms against nodes are gathered)."""
    ps = pod_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, ps), rsv)


def shard_pod_batch(pods, mesh: Mesh):
    """Place PodBatch tensors pod-axis-sharded; a dense (P, N) feasibility
    matrix shards over both axes, the factored (P, C) selector mask over the
    pod axis only (C is small and replicating it is the point)."""
    ps = pod_sharding(mesh)
    ms = matrix_sharding(mesh)
    return pods.replace(
        requests=jax.device_put(pods.requests, ps),
        priority=jax.device_put(pods.priority, ps),
        qos=jax.device_put(pods.qos, ps),
        gang_id=jax.device_put(pods.gang_id, ps),
        quota_id=jax.device_put(pods.quota_id, ps),
        non_preemptible=jax.device_put(pods.non_preemptible, ps),
        valid=jax.device_put(pods.valid, ps),
        rot_id=jax.device_put(pods.rot_id, ps),
        feasible=(
            jax.device_put(pods.feasible, ms)
            if pods.feasible is not None else None
        ),
        selector_mask=(
            jax.device_put(pods.selector_mask, ps)
            if pods.selector_mask is not None else None
        ),
    )
