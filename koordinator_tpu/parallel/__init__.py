"""Mesh construction and sharded solves over ICI/DCN.

The scale axis of a cluster scheduler is (pods x nodes), not model weights: the
node axis shards across TPU devices (each chip scores/filters its node shard),
and cross-device reductions (global argmax for assignment, sums for quota) ride
ICI collectives inserted by GSPMD. See SURVEY.md section 2.11 / 5 for the mapping
from the reference's parallelize/informer model.
"""

from koordinator_tpu.parallel.mesh import (
    NODES_AXIS,
    PODS_AXIS,
    nodes_shard_count,
    resolve_solver_mesh,
    shard_cluster_state,
    solver_mesh,
)

__all__ = ["solver_mesh", "shard_cluster_state", "NODES_AXIS", "PODS_AXIS",
           "nodes_shard_count", "resolve_solver_mesh"]
