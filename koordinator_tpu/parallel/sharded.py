"""2-D (pods x nodes) ``shard_map`` solve: the sharded-by-default path.

The batched solver's stages — fused Filter+Score candidate selection,
the propose/accept rounds, the incremental dirty refresh, the gang
all-or-nothing passes and the exact greedy scan — run here as explicit
SPMD programs over the full 2-D ``solver_mesh``:

- **node tensors** (``ClusterState``, ``est_accum``) shard their leading
  axis over ``NODES_AXIS`` and replicate over ``PODS_AXIS``; shard ``s``
  owns global rows ``[s*N/dn, (s+1)*N/dn)``.
- **pod tensors** (``PodBatch``, the (P, k) candidate cache) shard their
  leading axis over ``PODS_AXIS`` and replicate over ``NODES_AXIS``.
  With ``pods_axis == 1`` (the default mesh) this is exactly the PR-10
  replicated layout, bit for bit and program for program.
- the (P, N) score/rank work — the dominant footprint at the 50k-pod
  north-star shape — therefore lands as (P/dp, N/dn) tiles: per-device
  candidate/score bytes scale 1/pods_axis at fixed total devices.

Exactness argument — sharded acceptance decisions are BIT-IDENTICAL to
the single-device solve at every mesh shape:

- **Selection** is per-(pod-shard, node-shard)-tile local top-k with a
  two-stage cross-axis merge.  Stage 1 (within a pod-shard row): each
  tile reduces its local columns to the per-pod per-stratum
  top-``min(k_i, n_local)`` by the GLOBAL ranking key
  (``ops/batch_assign._rank_parts`` with global node ids), the
  (P_loc, m) tile winners ride one ``all_gather`` over ``NODES_AXIS``,
  and every tile re-ranks the gathered union with the same
  ``_topk_by_rank``.  The top-k of a union of per-shard top-k's equals
  the top-k of all columns (an element outside its shard's top-k is
  dominated by k_i better local elements), and rank pairs are unique
  per pod, so each pod row's merged sequence — values AND order —
  equals the single-device output exactly.  Stage 2 (across the pod
  axis): pod rows are INDEPENDENT, so the pod-sharded (P_loc, k)
  results simply reassemble as the (P, k) global array — no cross-pod
  merge exists to be wrong.
- **Rounds**: the (P, k) candidates and per-pod tensors are gathered
  over ``PODS_AXIS`` ONCE, before the round loop (gathering per round
  is the regression koordlint's pod-axis corpus pins); every per-round
  decision (best fitting candidate, priority-prefix acceptance, quota
  admission) is then computed REPLICATED over the pod axis from the
  gathered inputs, exactly as PR 10 computed it replicated over the
  node axis.  The only node-sharded data — per-candidate free capacity
  — is owned along ``NODES_AXIS`` and combined with an int32 ``psum``
  (exact: exactly one shard contributes a nonzero term per candidate).
  The replicated acceptance equals ``ops/batch_assign._assign_rounds``
  term for term; each node shard scatters accepted requests only into
  rows it owns.
- **Refresh**: a dirty node rescores only on the owning
  (pod-shard, node-shard) TILE — pods enter as local rows, unowned
  dirty nodes enter the (P_loc, D) sub-problem as invalid — the
  per-tile dirty winners are all-gathered over ``NODES_AXIS``, and the
  merge re-ranks cached ∪ fresh per pod row on one key scale: the same
  union-of-top-k argument as selection, pod rows independent.
- **Gang / greedy**: the gang pass loop (select + rounds + rollback +
  est accumulation) runs the kernels above per pass with the rollback
  decisions replicated from gathered (P,) flags and the rebuilt
  ``node_requested`` owner-scattered; the greedy scan keeps its
  sequential pod order with each step's argmax merged over
  ``NODES_AXIS`` as (max score, then min global node id among the
  ties) — exactly ``jnp.argmax``'s first-occurrence rule — so neither
  path all-gathers the (P, N) problem the way GSPMD placement did.

Candidate selection here is always recall-EXACT (the per-tile problem
is a factor of ``dp*dn`` smaller, so exact ``top_k`` is affordable
where the single-device path reaches for ``approx_max_k``).

Capacity: the node capacity must divide by the mesh's nodes axis and
the pod-batch capacity by the pods axis — power-of-two capacity
bucketing (state/cluster_state, ``PodBatch.build``/``compact``)
guarantees both for power-of-two axis sizes.  The packed-vs-wide
ranking-key regime (``ops/batch_assign``) is orthogonal: keys are
global in both regimes, which is why sharding composes with the
>32,768-node wide regime.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from koordinator_tpu.ops import batch_assign as ba
from koordinator_tpu.ops.assignment import pod_estimates, score_pods
from koordinator_tpu.parallel.mesh import (
    NODES_AXIS,
    PODS_AXIS,
    nodes_shard_count,
    pods_shard_count,
)
from koordinator_tpu.quota.admission import (
    charge_quota,
    charge_quota_batch,
    quota_admission_mask,
)

_NODES = P(NODES_AXIS)   # leading (node) axis sharded, pods-replicated
_PODS = P(PODS_AXIS)     # leading (pod) axis sharded, nodes-replicated
_REP = P()               # replicated over the whole mesh


def check_shardable(n_total: int, mesh) -> None:
    """Loud trace-time guard: the node capacity must split evenly over
    the mesh's nodes axis."""
    d = nodes_shard_count(mesh)
    if n_total % d:
        raise ValueError(
            f"node capacity {n_total} does not divide over the mesh's "
            f"{d}-way nodes axis; power-of-two capacity bucketing "
            "(state/cluster_state._bucket) guarantees divisibility for "
            "power-of-two device counts")


def check_pod_shardable(p_total: int, mesh) -> None:
    """Loud trace-time guard: the pod-batch capacity must split evenly
    over the mesh's pods axis."""
    d = pods_shard_count(mesh)
    if p_total % d:
        raise ValueError(
            f"pod-batch capacity {p_total} does not divide over the "
            f"mesh's {d}-way pods axis; PodBatch's power-of-two "
            "bucketing (build/compact) guarantees divisibility for "
            "power-of-two pods_axis sizes")


def _shard_offset(n_local: int) -> jnp.ndarray:
    """Global node row of this tile's local node row 0."""
    return jax.lax.axis_index(NODES_AXIS).astype(jnp.int32) * n_local


def _pod_offset(p_local: int) -> jnp.ndarray:
    """Global pod row of this tile's local pod row 0."""
    return jax.lax.axis_index(PODS_AXIS).astype(jnp.int32) * p_local


def _gather_pods(tree):
    """All-gather a pod-sharded pytree over the pods axis — ONCE, before
    any round loop (a per-round pod-axis gather is the regression the
    koordlint spec-consistency corpus pins).  Identity on a 1-way pods
    axis, so the default mesh compiles the PR-10 program unchanged."""
    return jax.tree.map(
        lambda x: jax.lax.all_gather(x, PODS_AXIS, axis=0, tiled=True),
        tree)


# ---------------------------------------------------------------------------
# Selection: per-tile local top-k + cross-axis segmented merge
# ---------------------------------------------------------------------------


# koordlint: shape[st_local: NxR i32 nodes]
def _local_select_body(st_local, pods, cfg, *, k, strata, n_total):
    """Tile-local fused Filter+Score + per-stratum local top-k, then the
    cross-node-shard merge.  ``pods`` holds this tile's LOCAL pod rows;
    returns the pod-sharded (cand_key, cand_node, cand_score) — the
    ``with_scores=True`` shape of ``ops/batch_assign.select_candidates``
    for those rows."""
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    scores, feasible = score_pods(st_local, pods, cfg)    # (P_loc, n_loc)
    node_ids = off + jnp.arange(n_loc, dtype=jnp.int32)
    clipped = jnp.clip(scores, 0, ba._SCORE_CLIP)
    rot = pods.rot_id

    splits = ba._stratum_splits(k, len(strata))
    nodes_out, scores_out = [], []
    for sb, k_i in zip(strata, splits):
        if k_i == 0:
            continue
        key, tb = ba._rank_parts(scores, feasible, sb, rot,
                                 node_ids=node_ids, n_total=n_total)
        m_i = min(k_i, n_loc)
        val, idx = ba._topk_by_rank(key, tb, m_i, n_total)
        sel_node = node_ids[idx]
        sel_score = jnp.where(
            val >= 0, jnp.take_along_axis(clipped, idx, axis=1), -1)
        # cross-shard segmented top-k merge: (P_loc, m) tile winners
        # ride one all_gather over the nodes axis, every tile re-ranks
        # the union globally; pod rows are independent, so no pod-axis
        # merge exists
        g_node = jax.lax.all_gather(sel_node, NODES_AXIS, axis=1,
                                    tiled=True)
        g_score = jax.lax.all_gather(sel_score, NODES_AXIS, axis=1,
                                     tiled=True)
        g_key = ba._candidate_keys(g_score, g_node, rot, sb, n_total)
        mval, midx = ba._topk_by_rank(
            g_key, ba._candidate_tb(g_node, rot, n_total), k_i, n_total)
        nodes_out.append(jnp.take_along_axis(g_node, midx, axis=1))
        scores_out.append(jnp.where(
            mval >= 0, jnp.take_along_axis(g_score, midx, axis=1), -1))

    cand_node = (jnp.concatenate(nodes_out, axis=1)
                 if len(nodes_out) > 1 else nodes_out[0])
    cand_score = (jnp.concatenate(scores_out, axis=1)
                  if len(scores_out) > 1 else scores_out[0])
    cand_key = ba._candidate_keys(cand_score, cand_node, rot,
                                  strata[0], n_total)
    return cand_key, cand_node, cand_score


@lru_cache(maxsize=None)
def _select_program(mesh, n_total, k, strata):
    """Jitted shard_map selection program, memoized on its statics.

    Every sharded entry point memoizes its jitted program this way:
    shard_map traced eagerly re-dispatches op by op on EVERY call (and
    re-traces per fresh ``partial`` closure), which made repeated
    direct calls — the mesh-invariance sweeps, the dirty-node refresh
    loops, bench stages — pay trace + per-op dispatch each time.
    ``Mesh`` hashes by (devices, axis names), so equal meshes share the
    entry (2-D shapes hash by their device GRID, so 2x4 and 1x8 are
    distinct entries), and the kit's outer jit composes (nested jit
    inlines)."""
    return jax.jit(shard_map(
        partial(_local_select_body, k=k, strata=strata, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _PODS, _REP),
        out_specs=(_PODS, _PODS, _PODS), check_rep=False))


def sharded_select_candidates(mesh, state, pods, cfg, k: int = 32,
                              spread_bits=(5, 15),
                              with_scores: bool = False):
    """``select_candidates`` over the 2-D mesh (recall-exact).

    Bit-identical to the single-device ``method="exact"`` selection on
    valid slots (see module docstring); the returned (P, k) tensors are
    pod-axis-sharded."""
    strata = (tuple(spread_bits) if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    n_total = state.capacity
    check_shardable(n_total, mesh)
    check_pod_shardable(pods.capacity, mesh)
    k = min(k, n_total)
    fn = _select_program(mesh, n_total, k, strata)
    cand_key, cand_node, cand_score = fn(state, pods, cfg)
    if with_scores:
        return cand_key, cand_node, cand_score
    return cand_key, cand_node


# ---------------------------------------------------------------------------
# Rounds: pod-axis gather ONCE, replicated acceptance, owner-psum capacity
# ---------------------------------------------------------------------------


def _rounds_local(st_local, pods, quota, cand_key, cand_node, *,
                  rounds, n_total):
    """The propose/accept loop over GATHERED (full-P) pod tensors with
    node tensors shard-local.  Mirrors
    ``ops/batch_assign._assign_rounds`` decision for decision; returns
    (assignments, requested_local, quota)."""
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    cand_valid = cand_key >= 0
    cand_tb = (None if ba._packed_regime(n_total)
               else ba._candidate_tb(cand_node, pods.rot_id, n_total))
    order = jnp.lexsort((jnp.arange(pods.capacity), -pods.priority))
    active0 = pods.valid & jnp.any(cand_valid, axis=1)

    local = cand_node - off
    own = (local >= 0) & (local < n_loc)           # (P, k) owner mask
    local_c = jnp.clip(local, 0, n_loc - 1)

    def round_body(c):
        requested, assignments, active, qstate = c
        free_loc = jnp.where(
            st_local.node_valid[:, None],
            st_local.node_allocatable - requested, 0)
        # per-candidate free capacity: the owning shard contributes, the
        # int32 psum reassembles the exact global gather free[cand_node]
        cand_free = jax.lax.psum(
            jnp.where(own[:, :, None], free_loc[local_c], 0), NODES_AXIS)
        fits = jnp.all(
            (pods.requests[:, None, :] <= cand_free)
            | (pods.requests[:, None, :] == 0),
            axis=-1,
        ) & cand_valid
        best = ba._choose_candidate(cand_key, cand_tb, fits)
        has = jnp.take_along_axis(fits, best[:, None], axis=1)[:, 0]
        choice = jnp.take_along_axis(cand_node, best[:, None], axis=1)[:, 0]

        act = active & has
        if qstate is not None:
            act = act & quota_admission_mask(
                qstate, pods.requests, pods.quota_id, pods.non_preemptible)

        loc_choice = choice - off
        own_c = (loc_choice >= 0) & (loc_choice < n_loc)
        loc_choice_c = jnp.clip(loc_choice, 0, n_loc - 1)
        choice_free = jax.lax.psum(
            jnp.where((own_c & act)[:, None], free_loc[loc_choice_c], 0),
            NODES_AXIS)
        accept = ba._prefix_accept_choice(
            choice, pods.requests, choice_free, n_total, order, act)
        if qstate is not None:
            accept = accept & ba._quota_prefix_accept(
                qstate, pods.requests, pods, order, act)

        add = jnp.where((accept & own_c)[:, None], pods.requests, 0)
        requested = requested.at[loc_choice_c].add(add)
        new_quota = qstate
        if new_quota is not None:
            new_quota = charge_quota_batch(
                new_quota, pods.requests, pods.quota_id, accept,
                pods.non_preemptible)
        return (requested,
                jnp.where(accept, choice, assignments),
                act & ~accept,
                new_quota)

    def cond(loop_carry):
        i, c = loop_carry
        return (i < rounds) & jnp.any(c[2])

    def body(loop_carry):
        i, c = loop_carry
        return i + 1, round_body(c)

    carry = (st_local.node_requested,
             jnp.full(pods.capacity, -1, jnp.int32),
             active0, quota)
    _, carry = jax.lax.while_loop(cond, body, (jnp.int32(0), carry))
    return carry[1], carry[0], carry[3]


# koordlint: shape[st_local: NxR i32 nodes, cand_key: Pxk i32 pods, cand_node: Pxk i32 pods]
def _rounds_body(st_local, pods, quota, cand_key, cand_node, *,
                 rounds, n_total):
    # ONE pod-axis gather, before the round loop: the acceptance oracle
    # (priority prefix over ALL pods) is global by definition
    pods, cand_key, cand_node = _gather_pods((pods, cand_key, cand_node))
    a, requested, new_quota = _rounds_local(
        st_local, pods, quota, cand_key, cand_node,
        rounds=rounds, n_total=n_total)
    return a, st_local.replace(node_requested=requested), new_quota


@lru_cache(maxsize=None)
def _rounds_program(mesh, n_total, rounds):
    """Jitted shard_map rounds program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        partial(_rounds_body, rounds=rounds, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _PODS, _REP, _PODS, _PODS),
        out_specs=(_REP, _NODES, _REP), check_rep=False))


def sharded_assign_rounds(mesh, state, pods, quota, cand_key, cand_node,
                          rounds: int = 12):
    """``_assign_rounds`` over the mesh: (assignments, new_state, quota)."""
    n_total = state.capacity
    check_shardable(n_total, mesh)
    check_pod_shardable(pods.capacity, mesh)
    return _rounds_program(mesh, n_total, rounds)(
        state, pods, quota, cand_key, cand_node)


# koordlint: shape[st_local: NxR i32 nodes, cand_key: Pxk i32 pods, cand_node: Pxk i32 pods]
def _round_pass_body(st_local, pods, quota, cand_key, cand_node, cfg, *,
                     rounds, n_total):
    pods, cand_key, cand_node = _gather_pods((pods, cand_key, cand_node))
    a, requested, _ = _rounds_local(
        st_local, pods, quota, cand_key, cand_node,
        rounds=rounds, n_total=n_total)
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    keep = a >= 0
    est = pod_estimates(pods, cfg)
    loc = a - off
    own = keep & (loc >= 0) & (loc < n_loc)
    est_accum = jnp.zeros_like(st_local.node_usage).at[
        jnp.clip(loc, 0, n_loc - 1)
    ].add(jnp.where(own[:, None], est, 0))
    new_quota = quota
    if quota is not None:
        # in-rounds quota feedback is discarded and recharged whole,
        # exactly as the single-device assign_round_pass does
        new_quota = charge_quota_batch(
            quota, pods.requests, pods.quota_id, keep,
            pods.non_preemptible)
    return (a, st_local.replace(node_requested=requested), new_quota,
            est_accum)


@lru_cache(maxsize=None)
def _round_pass_program(mesh, n_total, rounds):
    """Jitted shard_map pass-1 program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        partial(_round_pass_body, rounds=rounds, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _PODS, _REP, _PODS, _PODS, _REP),
        out_specs=(_REP, _NODES, _REP, _NODES), check_rep=False))


def sharded_assign_round_pass(mesh, state, pods, quota, cand_key,
                              cand_node, cfg, rounds: int = 12):
    """``assign_round_pass`` over the mesh: first solve pass over
    precomputed candidates with est-usage accumulation and whole-batch
    quota recharge.  Returns (assignments, new_state, new_quota,
    est_accum); ``est_accum`` is node-sharded like the state."""
    n_total = state.capacity
    check_shardable(n_total, mesh)
    check_pod_shardable(pods.capacity, mesh)
    return _round_pass_program(mesh, n_total, rounds)(
        state, pods, quota, cand_key, cand_node, cfg)


def _followup_body(st_local, est_local, pods, quota, cfg, *,
                   k, strata, rounds, n_total):
    # candidates re-selected against the est-augmented state; rounds and
    # the commit run against the UN-augmented accounting (the
    # assign_followup_pass rollback-rebuild semantics).  Selection runs
    # on this tile's LOCAL pod rows; the (P_loc, k) winners then ride
    # the one pod-axis gather into the replicated rounds.
    aug = st_local.replace(
        node_usage=st_local.node_usage + est_local,
        node_agg_usage=st_local.node_agg_usage + est_local)
    ck_loc, cn_loc, _ = _local_select_body(
        aug, pods, cfg, k=k, strata=strata, n_total=n_total)
    pods, cand_key, cand_node = _gather_pods((pods, ck_loc, cn_loc))
    a, requested, _ = _rounds_local(
        aug, pods, quota, cand_key, cand_node,
        rounds=rounds, n_total=n_total)
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    keep = (a >= 0) & pods.valid
    est = pod_estimates(pods, cfg)
    loc = a - off
    own = keep & (loc >= 0) & (loc < n_loc)
    loc_c = jnp.clip(loc, 0, n_loc - 1)
    est_accum = est_local.at[loc_c].add(jnp.where(own[:, None], est, 0))
    new_quota = quota
    if quota is not None:
        new_quota = charge_quota_batch(
            quota, pods.requests, pods.quota_id, keep,
            pods.non_preemptible)
    # aug and st_local share node_requested, so the rounds' requested IS
    # the committed accounting (original + accepted requests)
    return (a, st_local.replace(node_requested=requested), new_quota,
            est_accum)


@lru_cache(maxsize=None)
def _followup_program(mesh, n_total, k, strata, rounds):
    """Jitted shard_map follow-up program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        partial(_followup_body, k=k, strata=strata,
                rounds=rounds, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _NODES, _PODS, _REP, _REP),
        out_specs=(_REP, _NODES, _REP, _NODES), check_rep=False))


def sharded_assign_followup_pass(mesh, state, est_accum, pods, quota, cfg,
                                 k: int = 32, rounds: int = 12,
                                 spread_bits=(5, 15)):
    """``assign_followup_pass`` over the mesh (selection is always
    recall-exact here).  Returns (assignments, new_state, new_quota,
    est_accum')."""
    strata = (tuple(spread_bits) if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    n_total = state.capacity
    check_shardable(n_total, mesh)
    check_pod_shardable(pods.capacity, mesh)
    return _followup_program(mesh, n_total, min(k, n_total), strata,
                             rounds)(state, est_accum, pods, quota, cfg)


# ---------------------------------------------------------------------------
# Incremental refresh: owning-tile dirty rescore + nodes-axis merge
# ---------------------------------------------------------------------------


# koordlint: shape[st_local: NxR i32 nodes]
def _refresh_body(st_local, pods, cfg, cache, dirty_rows, dirty_valid, *,
                  k, strata, n_total):
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    rot = pods.rot_id
    d = dirty_rows.shape[0]

    # a dirty node rescores only on its owning TILE: pods enter as this
    # tile's local rows, unowned dirty nodes enter the (P_loc, D)
    # sub-problem as invalid and rank -1
    loc = dirty_rows - off
    own = (loc >= 0) & (loc < n_loc) & dirty_valid
    sub = st_local.gather_rows(jnp.clip(loc, 0, n_loc - 1), own)
    scores, feasible = score_pods(sub, pods, cfg)           # (P_loc, D)
    clipped = jnp.clip(scores, 0, ba._SCORE_CLIP)

    # global dirty mask (nodes-replicated): cached slots pointing at ANY
    # dirty node are stale regardless of which shard owns it
    dirty_mask = jnp.zeros(n_total, bool).at[dirty_rows].max(dirty_valid)
    stale_score = jnp.where(dirty_mask[cache.cand_node], -1,
                            cache.cand_score)

    splits = ba._stratum_splits(k, len(strata))
    nodes_out, scores_out = [], []
    offset = 0
    for sb, k_i in zip(strata, splits):
        if k_i == 0:
            continue
        seg_node = cache.cand_node[:, offset:offset + k_i]
        seg_score = stale_score[:, offset:offset + k_i]
        offset += k_i
        dkey, dtb = ba._rank_parts(scores, feasible, sb, rot,
                                   node_ids=dirty_rows, n_total=n_total)
        m_i = min(k_i, d)
        dval, idx = ba._topk_by_rank(dkey, dtb, m_i, n_total)
        d_node = dirty_rows[idx]
        d_score = jnp.where(
            dval >= 0, jnp.take_along_axis(clipped, idx, axis=1), -1)
        g_node = jax.lax.all_gather(d_node, NODES_AXIS, axis=1, tiled=True)
        g_score = jax.lax.all_gather(d_score, NODES_AXIS, axis=1,
                                     tiled=True)
        # merge re-ranks per pod row: cached ∪ per-shard fresh winners
        # on one key scale (pod rows independent — no pod-axis merge)
        c_key = ba._candidate_keys(seg_score, seg_node, rot, sb, n_total)
        g_key = ba._candidate_keys(g_score, g_node, rot, sb, n_total)
        m_key = jnp.concatenate([c_key, g_key], axis=1)
        m_node = jnp.concatenate([seg_node, g_node], axis=1)
        m_score = jnp.concatenate([seg_score, g_score], axis=1)
        mval, midx = ba._topk_by_rank(
            m_key, ba._candidate_tb(m_node, rot, n_total), k_i, n_total)
        nodes_out.append(jnp.take_along_axis(m_node, midx, axis=1))
        scores_out.append(jnp.where(
            mval >= 0, jnp.take_along_axis(m_score, midx, axis=1), -1))

    cand_node = (jnp.concatenate(nodes_out, axis=1)
                 if len(nodes_out) > 1 else nodes_out[0])
    cand_score = (jnp.concatenate(scores_out, axis=1)
                  if len(scores_out) > 1 else scores_out[0])
    cand_key = ba._candidate_keys(cand_score, cand_node, rot,
                                  strata[0], n_total)
    return cand_key, ba.CandidateCache(cand_key, cand_node, cand_score)


@lru_cache(maxsize=None)
def _refresh_program(mesh, n_total, k, strata):
    """Jitted shard_map refresh program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        partial(_refresh_body, k=k, strata=strata, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _PODS, _REP, _PODS, _REP, _REP),
        out_specs=(_PODS, _PODS), check_rep=False))


def sharded_refresh_candidates(mesh, state, pods, cfg, cache, dirty_rows,
                               dirty_valid, k: int = 32,
                               spread_bits=(5, 15)):
    """``refresh_candidates`` over the mesh: dirty columns rescore on
    their owning (pod, node) tile, the merge re-ranks per pod row.
    Returns (cand_key, new_cache) like the single-device refresh, both
    pod-axis-sharded."""
    strata = (tuple(spread_bits) if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    n_total = state.capacity
    check_shardable(n_total, mesh)
    check_pod_shardable(pods.capacity, mesh)
    return _refresh_program(mesh, n_total, min(k, n_total), strata)(
        state, pods, cfg, cache, dirty_rows, dirty_valid)


# ---------------------------------------------------------------------------
# Gang all-or-nothing + exact greedy: the explicit shard_map twins of the
# GSPMD-placed ops/gang.gang_assign and ops/assignment.greedy_assign paths
# ---------------------------------------------------------------------------


def _greedy_local(st_local, pods, cfg, quota):
    """Shard-local exact greedy scan over GATHERED (full-P) pods:
    mirrors ``ops/assignment._greedy_scan`` (no reservations) step for
    step, with the per-step argmax merged over the nodes axis as
    (max score, then MIN global node id among the ties) — equal to the
    single-device ``jnp.argmax`` first-occurrence rule, because the
    local argmax already picks the lowest local index and global ids
    order identically to local ones within a shard."""
    from koordinator_tpu.ops.assignment import (
        _composite_score,
        _threshold_mask,
    )

    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    node_ids = off + jnp.arange(n_loc, dtype=jnp.int32)
    order = jnp.lexsort((jnp.arange(pods.capacity), -pods.priority))
    pod_est_all = pod_estimates(pods, cfg)

    def step(carry, idx):
        requested, est_added, qstate = carry
        req = pods.requests[idx]
        pod_est = pod_est_all[idx]
        valid = pods.valid[idx]
        free = jnp.where(
            st_local.node_valid[:, None],
            st_local.node_allocatable - requested, 0)
        fits = jnp.all((req[None, :] <= free) | (req[None, :] == 0),
                       axis=-1)
        feasible = (
            fits
            & _threshold_mask(
                cfg,
                st_local.node_usage + est_added,
                st_local.node_agg_usage + est_added,
                st_local.node_allocatable,
                pod_est[None, :],
            )[0]
            & pods.feasible_row(st_local, idx)
            & st_local.node_valid
            & valid)
        if qstate is not None:
            admitted = quota_admission_mask(
                qstate, req[None, :], pods.quota_id[idx][None],
                pods.non_preemptible[idx][None])[0]
            feasible = feasible & admitted
        scores = _composite_score(
            cfg, st_local.node_allocatable, requested,
            st_local.node_usage + est_added,
            req[None, :], pod_est[None, :])[0]
        masked = jnp.where(feasible, scores, -1)
        lbest = jnp.argmax(masked)
        lscore = masked[lbest]
        gscore = jax.lax.pmax(lscore, NODES_AXIS)
        cand = jnp.where(lscore == gscore, node_ids[lbest],
                         jnp.int32(2**30))
        gnode = jax.lax.pmin(cand, NODES_AXIS)
        assigned = gscore >= 0
        node = jnp.where(assigned, gnode, -1)
        loc = gnode - off
        own = assigned & (loc >= 0) & (loc < n_loc)
        loc_c = jnp.clip(loc, 0, n_loc - 1)
        requested = requested.at[loc_c].add(jnp.where(own, req, 0))
        est_added = est_added.at[loc_c].add(jnp.where(own, pod_est, 0))
        if qstate is not None:
            qstate = charge_quota(
                qstate, jnp.where(assigned, req, 0),
                jnp.where(assigned, pods.quota_id[idx], -1),
                non_preemptible=pods.non_preemptible[idx])
        return (requested, est_added, qstate), node

    carry0 = (st_local.node_requested,
              jnp.zeros_like(st_local.node_usage), quota)
    (requested, _, new_quota), nodes_in_order = jax.lax.scan(
        step, carry0, order)
    assignments = jnp.full(pods.capacity, -1, jnp.int32).at[order].set(
        nodes_in_order)
    return assignments, requested, new_quota


# koordlint: shape[st_local: NxR i32 nodes]
def _gang_body(st_local, pods, cfg, gangs, quota, *, passes, solver,
               k, strata, rounds, n_total, p_total):
    """The gang all-or-nothing pass loop as one SPMD program: per pass,
    solve (batch select+rounds or the greedy scan), count per-gang
    placements from replicated flags, roll failed groups back by
    REBUILDING the owner-local ``node_requested`` from the pre-pass
    accounting plus only the kept pods (ops/gang.rollback_failed_gangs'
    exact-rollback rule), accumulate kept pods' estimated usage into the
    owner shard, and recharge quota whole.  Mirrors
    ``ops/gang.gang_assign`` decision for decision."""
    from koordinator_tpu.ops.gang import (
        _group_ok,
        _per_gang_counts,
        pre_enqueue_mask,
    )

    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    p_loc = pods.capacity
    poff = _pod_offset(p_loc)

    # ONE pod-axis gather for the whole pass loop: gang counting, the
    # acceptance oracle and rollback flags are global over pods
    pods_f = _gather_pods(pods)
    g = gangs.capacity
    pre_ok = pre_enqueue_mask(pods_f, gangs)
    active = pods_f.valid & pre_ok                 # (P,)

    total = jnp.full(p_total, -1, jnp.int32)
    kept_so_far = jnp.zeros(p_total, bool)
    requested = st_local.node_requested            # (n_loc, R)
    cur_quota = quota
    pod_est_all = pod_estimates(pods_f, cfg)       # (P, R)
    est_local = jnp.zeros_like(st_local.node_usage)

    for _ in range(passes):
        solve_st = st_local.replace(
            node_requested=requested,
            node_usage=st_local.node_usage + est_local,
            node_agg_usage=st_local.node_agg_usage + est_local)
        act_pods = pods_f.replace(valid=active)
        if solver == "batch":
            # selection runs on this tile's LOCAL pod rows against the
            # est-augmented local node tile; the winners ride the one
            # nodes-axis merge inside and a pod-axis gather after
            loc_active = jax.lax.dynamic_slice(active, (poff,), (p_loc,))
            pods_loc = pods.replace(valid=pods.valid & loc_active)
            ck_loc, cn_loc, _ = _local_select_body(
                solve_st, pods_loc, cfg, k=k, strata=strata,
                n_total=n_total)
            ck, cn = _gather_pods((ck_loc, cn_loc))
            a, _, _ = _rounds_local(
                solve_st, act_pods, cur_quota, ck, cn,
                rounds=rounds, n_total=n_total)
        else:
            a, _, _ = _greedy_local(solve_st, act_pods, cfg, cur_quota)

        # rollback_failed_gangs, replicated flags + owner-local rebuild
        assigned = (a >= 0) & act_pods.valid
        counted = assigned | kept_so_far
        counts = _per_gang_counts(counted, pods_f.gang_id, g)
        gang_ok = (counts >= gangs.min_member) & gangs.valid
        ok = _group_ok(gang_ok, gangs)
        pod_gang = jnp.maximum(pods_f.gang_id, 0)
        keep = assigned & ((pods_f.gang_id < 0) | ok[pod_gang])
        failed = (pods_f.gang_id >= 0) & ~ok[pod_gang] & act_pods.valid
        final = jnp.where(keep, a, -1)

        loc = final - off
        own = keep & (loc >= 0) & (loc < n_loc)
        loc_c = jnp.clip(loc, 0, n_loc - 1)
        requested = requested.at[loc_c].add(
            jnp.where(own[:, None], pods_f.requests, 0))
        est_local = est_local.at[loc_c].add(
            jnp.where(own[:, None], pod_est_all, 0))
        if cur_quota is not None:
            cur_quota = charge_quota_batch(
                cur_quota, pods_f.requests, pods_f.quota_id, keep,
                pods_f.non_preemptible)
        total = jnp.where(keep, final, total)
        kept_so_far = kept_so_far | keep
        # next pass: still-unassigned pods stay in play, but rolled-back
        # gangs back off for the rest of the batch
        active = active & ~keep & ~failed

    return total, st_local.replace(node_requested=requested), cur_quota


@lru_cache(maxsize=None)
def _gang_program(mesh, n_total, p_total, passes, solver, k, strata,
                  rounds):
    """Jitted shard_map gang program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        partial(_gang_body, passes=passes, solver=solver, k=k,
                strata=strata, rounds=rounds, n_total=n_total,
                p_total=p_total),
        mesh=mesh, in_specs=(_NODES, _PODS, _REP, _REP, _REP),
        out_specs=(_REP, _NODES, _REP), check_rep=False))


def sharded_gang_assign(mesh, state, pods, cfg, gangs, quota=None,
                        passes: int = 2, solver: str = "greedy",
                        k: int = 32, rounds: int = 12,
                        spread_bits=(5, 15)):
    """``ops/gang.gang_assign`` over the 2-D mesh — the explicit
    shard_map twin of the GSPMD-placed gang path, for both per-pass
    engines (``solver="batch"`` propose/accept rounds and
    ``solver="greedy"``'s exact sequential scan).  Every default —
    including ``solver="greedy"`` — matches ``gang_assign``'s, and the
    candidate knobs match ``batch_assign``'s, so a drop-in swap of the
    entry point keeps acceptance decisions bit-identical to the
    single-device ``gang_assign`` (selection is recall-exact here, like
    every sharded entry).

    Returns (assignments, new_state, new_quota) with the state
    node-sharded; requires the factored (selector-mask) feasibility
    form — a dense (P, N) ``pods.feasible`` cannot tile."""
    if solver not in ("greedy", "batch"):
        raise ValueError(f"unknown solver {solver!r}")
    if pods.feasible is not None:
        raise ValueError(
            "sharded_gang_assign requires the factored selector-mask "
            "feasibility form; a dense (P, N) feasible matrix does not "
            "tile over the 2-D mesh (build the batch with "
            "selector_mask, or keep the GSPMD gang path)")
    strata = (tuple(spread_bits) if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    n_total = state.capacity
    check_shardable(n_total, mesh)
    check_pod_shardable(pods.capacity, mesh)
    fn = _gang_program(mesh, n_total, pods.capacity, passes, solver,
                       min(k, n_total), strata, rounds)
    return fn(state, pods, cfg, gangs, quota)


# koordlint: shape[state: NxR i32 nodes, reserve: NxR i32 nodes]
def sharded_forecast_gang_assign(mesh, state, reserve, pods, cfg, gangs,
                                 quota=None, passes: int = 2,
                                 solver: str = "greedy", k: int = 32,
                                 rounds: int = 12, spread_bits=(5, 15)):
    """:func:`sharded_gang_assign` with the forecast-headroom reserve
    charged for the duration of the solve — the sharded twin of
    ``forecast/kernels.forecast_gang_assign``.

    The charge and release are elementwise over the node axis, so both
    stay on each shard's slice under the state's NamedSharding (the
    plane pins its reserve under the same placement); the inner solve
    is the unchanged shard_map program, so acceptance decisions are
    bit-identical to the single-device forecast entry."""
    charged = state.replace(node_requested=state.node_requested + reserve)
    a, new_state, new_quota = sharded_gang_assign(
        mesh, charged, pods, cfg, gangs, quota, passes=passes,
        solver=solver, k=k, rounds=rounds, spread_bits=spread_bits)
    return a, new_state.replace(
        node_requested=new_state.node_requested - reserve), new_quota


def sharded_greedy_assign(mesh, state, pods, cfg, quota=None):
    """``ops/assignment.greedy_assign`` over the mesh as one explicit
    shard_map kernel: the sequential scan keeps its exact pod order
    (there is no pod parallelism in a priority scan), node tensors are
    sharded, and each step's argmax merges over the nodes axis — no
    all-gather of the (P, N) problem.  Returns (assignments, new_state,
    new_quota) like the single-device entry."""
    if pods.feasible is not None:
        raise ValueError(
            "sharded_greedy_assign requires the factored selector-mask "
            "feasibility form (see sharded_gang_assign)")
    n_total = state.capacity
    check_shardable(n_total, mesh)
    check_pod_shardable(pods.capacity, mesh)
    return _greedy_program(mesh, n_total)(state, pods, cfg, quota)


# koordlint: shape[st_local: NxR i32 nodes]
def _greedy_body(st_local, pods, cfg, quota):
    pods_f = _gather_pods(pods)
    a, requested, new_quota = _greedy_local(st_local, pods_f, cfg, quota)
    return a, st_local.replace(node_requested=requested), new_quota


@lru_cache(maxsize=None)
def _greedy_program(mesh, n_total):
    """Jitted shard_map greedy program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        _greedy_body,
        mesh=mesh, in_specs=(_NODES, _PODS, _REP, _REP),
        out_specs=(_REP, _NODES, _REP), check_rep=False))


# ---------------------------------------------------------------------------
# Quality mode: the LP-relaxation packing solve over the nodes axis
# ---------------------------------------------------------------------------


# koordlint: shape[st_local: NxR i32 nodes]
def _lp_pack_body(st_local, pods, quota, cfg, *, n_total, ascent_iters,
                  rounding_iters):
    """Shard-local LP-pack body: the SAME ``quality/lp_pack._lp_core``
    the single-device entry runs, with the collectives live.  Scores
    and prices are shard-local columns; the per-pod argmax merges
    per-shard winners on the global integer (key, tb) scale and every
    acceptance decision is replicated — the union-of-bests and
    owner-psum exactness arguments of the greedy rounds apply term for
    term, and all arithmetic is integer, so shard counts can't perturb
    a single bit.

    On a 2-D mesh the LP twin COMPOSES by replicating the pod batch
    over the pods axis (in_spec ``P()``; the price-ascent re-bidding
    loop re-chooses every pod every iteration, so a pod split would put
    a pod-axis all-gather INSIDE the ascent loop — the exact pattern
    the koordlint corpus forbids).  Node work still shards 1/dn;
    docs/sharding.md's axis-sizing guidance says to spend devices on
    the nodes axis when quality mode dominates."""
    from koordinator_tpu.quality.lp_pack import _lp_core

    a, requested, new_quota, iters = _lp_core(
        st_local, pods, quota, cfg, n_total=n_total,
        ascent_iters=ascent_iters, rounding_iters=rounding_iters,
        axis=NODES_AXIS)
    return a, st_local.replace(node_requested=requested), new_quota, iters


@lru_cache(maxsize=None)
def _lp_pack_program(mesh, n_total, ascent_iters, rounding_iters):
    """Jitted shard_map LP program, memoized on (mesh, shape, bounds).

    The LP solve is a while-loop program an order of magnitude pricier
    to trace than the greedy passes; without the memo every direct call
    (the mesh-invariance sweeps, bench stages) re-traces it even at
    identical shapes.  ``Mesh`` hashes by (devices, axis names), so
    equal meshes built by different ``solver_mesh`` calls share the
    entry; the kit's own jit wrapper composes fine on top (nested jit
    inlines)."""
    return jax.jit(shard_map(
        partial(_lp_pack_body, n_total=n_total,
                ascent_iters=ascent_iters,
                rounding_iters=rounding_iters),
        mesh=mesh, in_specs=(_NODES, _REP, _REP, _REP),
        out_specs=(_REP, _NODES, _REP, _REP), check_rep=False))


def sharded_lp_pack_assign(mesh, state, pods, cfg, quota=None,
                           ascent_iters: int | None = None,
                           rounding_iters: int | None = None):
    """``quality/lp_pack.lp_pack_assign`` over the mesh's nodes axis.

    Bit-identical to the single-device LP solve at every mesh shape
    (tests/test_quality.py sweeps shard counts; the 2-D sweep rides
    tests/test_sharded_solve.py): returns (assignments, new_state,
    new_quota, iters) with the state node-sharded like the greedy
    sharded passes.  Pod tensors replicate over the pods axis — see
    :func:`_lp_pack_body` for why that is the composition rule here."""
    from koordinator_tpu.quality import lp_pack as lp

    n_total = state.capacity
    check_shardable(n_total, mesh)
    fn = _lp_pack_program(
        mesh, n_total,
        lp.ASCENT_ITERS if ascent_iters is None else ascent_iters,
        lp.ROUNDING_ITERS if rounding_iters is None else rounding_iters)
    return fn(state, pods, quota, cfg)
