"""Node-axis ``shard_map`` solve: the sharded-by-default batch path.

The batched solver's three stages — fused Filter+Score candidate
selection, the propose/accept rounds, and the incremental dirty-node
candidate refresh — run here as explicit SPMD programs over the
``solver_mesh``'s ``NODES_AXIS``.  Every shard owns a contiguous block
of node rows (``jax.sharding`` splits the leading axis into contiguous
blocks, so global row ``g`` lives on shard ``g // (N / ndev)`` at local
row ``g % (N / ndev)``); pod tensors, quota tensors and the (P, k)
candidate cache are replicated over the axis (the default mesh puts
every device on "nodes").

Exactness argument — sharded acceptance decisions are BIT-IDENTICAL to
the single-device solve:

- **Selection** is a per-shard local top-k followed by a cross-shard
  segmented merge: each shard reduces its local columns to the per-pod
  per-stratum top-``min(k_i, n_local)`` by the GLOBAL ranking key
  (``ops/batch_assign._rank_parts`` with global node ids), the (P, m)
  shard winners ride one ``all_gather``, and every shard re-ranks the
  gathered union with the same ``_topk_by_rank``.  The global top-k of
  a union of per-shard top-k's equals the global top-k of all columns
  (an element outside its shard's top-k is dominated by k_i better
  local elements, so it can never be in the global top-k), and rank
  pairs are unique per pod (the tie-break is a permutation of node
  ids), so the merged sequence — values AND order — equals the
  single-device ``lax.top_k``/two-key-sort output exactly.
- **Rounds**: every per-round decision (best fitting candidate, the
  priority prefix acceptance, quota admission) is computed REPLICATED
  on all shards from replicated inputs; the only node-sharded data —
  per-candidate free capacity — is gathered by the owning shard and
  combined with an int32 ``psum`` (exact: exactly one shard contributes
  a nonzero term per candidate).  The replicated acceptance then equals
  ``ops/batch_assign._assign_rounds`` term for term, and each shard
  scatters accepted requests only into the node rows it owns.
- **Refresh**: a dirty node rescores only on its owning shard (unowned
  rows enter the (P, D) sub-problem as invalid), the per-shard dirty
  winners are all-gathered, and the merge re-ranks cached ∪ fresh
  globally on the same key scale — the same union-of-top-k argument as
  selection.

Candidate selection here is always recall-EXACT (the per-shard problem
is a factor of ``ndev`` smaller, so exact ``top_k`` is affordable where
the single-device path reaches for ``approx_max_k``).

Capacity: the node capacity must divide by the mesh's nodes-axis size —
power-of-two capacity bucketing (state/cluster_state) guarantees this
for power-of-two device counts.  The packed-vs-wide ranking-key regime
(``ops/batch_assign``) is orthogonal: keys are global in both regimes,
which is why sharding composes with the >32,768-node wide regime.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from koordinator_tpu.ops import batch_assign as ba
from koordinator_tpu.ops.assignment import pod_estimates, score_pods
from koordinator_tpu.parallel.mesh import NODES_AXIS, nodes_shard_count
from koordinator_tpu.quota.admission import (
    charge_quota_batch,
    quota_admission_mask,
)

_NODES = P(NODES_AXIS)   # leading (node) axis sharded
_REP = P()               # replicated over the mesh


def check_shardable(n_total: int, mesh) -> None:
    """Loud trace-time guard: the node capacity must split evenly over
    the mesh's nodes axis."""
    d = nodes_shard_count(mesh)
    if n_total % d:
        raise ValueError(
            f"node capacity {n_total} does not divide over the mesh's "
            f"{d}-way nodes axis; power-of-two capacity bucketing "
            "(state/cluster_state._bucket) guarantees divisibility for "
            "power-of-two device counts")


def _shard_offset(n_local: int) -> jnp.ndarray:
    """Global row of this shard's local row 0."""
    return jax.lax.axis_index(NODES_AXIS).astype(jnp.int32) * n_local


# ---------------------------------------------------------------------------
# Selection: per-shard local top-k + cross-shard segmented merge
# ---------------------------------------------------------------------------


# koordlint: shape[st_local: NxR i32 nodes]
def _local_select_body(st_local, pods, cfg, *, k, strata, n_total):
    """Shard-local fused Filter+Score + per-stratum local top-k, then the
    cross-shard merge.  Returns replicated (cand_key, cand_node,
    cand_score) — the ``with_scores=True`` shape of
    ``ops/batch_assign.select_candidates``."""
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    scores, feasible = score_pods(st_local, pods, cfg)      # (P, n_loc)
    node_ids = off + jnp.arange(n_loc, dtype=jnp.int32)
    clipped = jnp.clip(scores, 0, ba._SCORE_CLIP)
    rot = pods.rot_id

    splits = ba._stratum_splits(k, len(strata))
    nodes_out, scores_out = [], []
    for sb, k_i in zip(strata, splits):
        if k_i == 0:
            continue
        key, tb = ba._rank_parts(scores, feasible, sb, rot,
                                 node_ids=node_ids, n_total=n_total)
        m_i = min(k_i, n_loc)
        val, idx = ba._topk_by_rank(key, tb, m_i, n_total)
        sel_node = node_ids[idx]
        sel_score = jnp.where(
            val >= 0, jnp.take_along_axis(clipped, idx, axis=1), -1)
        # cross-shard segmented top-k merge: (P, m) shard winners ride
        # one all_gather, every shard re-ranks the union globally
        g_node = jax.lax.all_gather(sel_node, NODES_AXIS, axis=1,
                                    tiled=True)
        g_score = jax.lax.all_gather(sel_score, NODES_AXIS, axis=1,
                                     tiled=True)
        g_key = ba._candidate_keys(g_score, g_node, rot, sb, n_total)
        mval, midx = ba._topk_by_rank(
            g_key, ba._candidate_tb(g_node, rot, n_total), k_i, n_total)
        nodes_out.append(jnp.take_along_axis(g_node, midx, axis=1))
        scores_out.append(jnp.where(
            mval >= 0, jnp.take_along_axis(g_score, midx, axis=1), -1))

    cand_node = (jnp.concatenate(nodes_out, axis=1)
                 if len(nodes_out) > 1 else nodes_out[0])
    cand_score = (jnp.concatenate(scores_out, axis=1)
                  if len(scores_out) > 1 else scores_out[0])
    cand_key = ba._candidate_keys(cand_score, cand_node, rot,
                                  strata[0], n_total)
    return cand_key, cand_node, cand_score


@lru_cache(maxsize=None)
def _select_program(mesh, n_total, k, strata):
    """Jitted shard_map selection program, memoized on its statics.

    Every sharded entry point memoizes its jitted program this way:
    shard_map traced eagerly re-dispatches op by op on EVERY call (and
    re-traces per fresh ``partial`` closure), which made repeated
    direct calls — the 1/2/4/8 mesh-invariance sweeps, the dirty-node
    refresh loops, bench stages — pay trace + per-op dispatch each
    time.  ``Mesh`` hashes by (devices, axis names), so equal meshes
    share the entry, and the kit's outer jit composes (nested jit
    inlines)."""
    return jax.jit(shard_map(
        partial(_local_select_body, k=k, strata=strata, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _REP, _REP),
        out_specs=(_REP, _REP, _REP), check_rep=False))


def sharded_select_candidates(mesh, state, pods, cfg, k: int = 32,
                              spread_bits=(5, 15),
                              with_scores: bool = False):
    """``select_candidates`` over the mesh's nodes axis (recall-exact).

    Bit-identical to the single-device ``method="exact"`` selection on
    valid slots (see module docstring)."""
    strata = (tuple(spread_bits) if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    n_total = state.capacity
    check_shardable(n_total, mesh)
    k = min(k, n_total)
    fn = _select_program(mesh, n_total, k, strata)
    cand_key, cand_node, cand_score = fn(state, pods, cfg)
    if with_scores:
        return cand_key, cand_node, cand_score
    return cand_key, cand_node


# ---------------------------------------------------------------------------
# Rounds: replicated acceptance, owner-gathered capacity, sharded scatter
# ---------------------------------------------------------------------------


# koordlint: shape[st_local: NxR i32 nodes, cand_key: Pxk i32 rep, cand_node: Pxk i32 rep]
def _rounds_local(st_local, pods, quota, cand_key, cand_node, *,
                  rounds, n_total):
    """The propose/accept loop with node tensors shard-local.  Mirrors
    ``ops/batch_assign._assign_rounds`` decision for decision; returns
    (assignments, requested_local, quota)."""
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    cand_valid = cand_key >= 0
    cand_tb = (None if ba._packed_regime(n_total)
               else ba._candidate_tb(cand_node, pods.rot_id, n_total))
    order = jnp.lexsort((jnp.arange(pods.capacity), -pods.priority))
    active0 = pods.valid & jnp.any(cand_valid, axis=1)

    local = cand_node - off
    own = (local >= 0) & (local < n_loc)           # (P, k) owner mask
    local_c = jnp.clip(local, 0, n_loc - 1)

    def round_body(c):
        requested, assignments, active, qstate = c
        free_loc = jnp.where(
            st_local.node_valid[:, None],
            st_local.node_allocatable - requested, 0)
        # per-candidate free capacity: the owning shard contributes, the
        # int32 psum reassembles the exact global gather free[cand_node]
        cand_free = jax.lax.psum(
            jnp.where(own[:, :, None], free_loc[local_c], 0), NODES_AXIS)
        fits = jnp.all(
            (pods.requests[:, None, :] <= cand_free)
            | (pods.requests[:, None, :] == 0),
            axis=-1,
        ) & cand_valid
        best = ba._choose_candidate(cand_key, cand_tb, fits)
        has = jnp.take_along_axis(fits, best[:, None], axis=1)[:, 0]
        choice = jnp.take_along_axis(cand_node, best[:, None], axis=1)[:, 0]

        act = active & has
        if qstate is not None:
            act = act & quota_admission_mask(
                qstate, pods.requests, pods.quota_id, pods.non_preemptible)

        loc_choice = choice - off
        own_c = (loc_choice >= 0) & (loc_choice < n_loc)
        loc_choice_c = jnp.clip(loc_choice, 0, n_loc - 1)
        choice_free = jax.lax.psum(
            jnp.where((own_c & act)[:, None], free_loc[loc_choice_c], 0),
            NODES_AXIS)
        accept = ba._prefix_accept_choice(
            choice, pods.requests, choice_free, n_total, order, act)
        if qstate is not None:
            accept = accept & ba._quota_prefix_accept(
                qstate, pods.requests, pods, order, act)

        add = jnp.where((accept & own_c)[:, None], pods.requests, 0)
        requested = requested.at[loc_choice_c].add(add)
        new_quota = qstate
        if new_quota is not None:
            new_quota = charge_quota_batch(
                new_quota, pods.requests, pods.quota_id, accept,
                pods.non_preemptible)
        return (requested,
                jnp.where(accept, choice, assignments),
                act & ~accept,
                new_quota)

    def cond(loop_carry):
        i, c = loop_carry
        return (i < rounds) & jnp.any(c[2])

    def body(loop_carry):
        i, c = loop_carry
        return i + 1, round_body(c)

    carry = (st_local.node_requested,
             jnp.full(pods.capacity, -1, jnp.int32),
             active0, quota)
    _, carry = jax.lax.while_loop(cond, body, (jnp.int32(0), carry))
    return carry[1], carry[0], carry[3]


def _rounds_body(st_local, pods, quota, cand_key, cand_node, *,
                 rounds, n_total):
    a, requested, new_quota = _rounds_local(
        st_local, pods, quota, cand_key, cand_node,
        rounds=rounds, n_total=n_total)
    return a, st_local.replace(node_requested=requested), new_quota


@lru_cache(maxsize=None)
def _rounds_program(mesh, n_total, rounds):
    """Jitted shard_map rounds program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        partial(_rounds_body, rounds=rounds, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _REP, _REP, _REP, _REP),
        out_specs=(_REP, _NODES, _REP), check_rep=False))


def sharded_assign_rounds(mesh, state, pods, quota, cand_key, cand_node,
                          rounds: int = 12):
    """``_assign_rounds`` over the mesh: (assignments, new_state, quota)."""
    n_total = state.capacity
    check_shardable(n_total, mesh)
    return _rounds_program(mesh, n_total, rounds)(
        state, pods, quota, cand_key, cand_node)


def _round_pass_body(st_local, pods, quota, cand_key, cand_node, cfg, *,
                     rounds, n_total):
    a, requested, _ = _rounds_local(
        st_local, pods, quota, cand_key, cand_node,
        rounds=rounds, n_total=n_total)
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    keep = a >= 0
    est = pod_estimates(pods, cfg)
    loc = a - off
    own = keep & (loc >= 0) & (loc < n_loc)
    est_accum = jnp.zeros_like(st_local.node_usage).at[
        jnp.clip(loc, 0, n_loc - 1)
    ].add(jnp.where(own[:, None], est, 0))
    new_quota = quota
    if quota is not None:
        # in-rounds quota feedback is discarded and recharged whole,
        # exactly as the single-device assign_round_pass does
        new_quota = charge_quota_batch(
            quota, pods.requests, pods.quota_id, keep,
            pods.non_preemptible)
    return (a, st_local.replace(node_requested=requested), new_quota,
            est_accum)


@lru_cache(maxsize=None)
def _round_pass_program(mesh, n_total, rounds):
    """Jitted shard_map pass-1 program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        partial(_round_pass_body, rounds=rounds, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _REP, _REP, _REP, _REP, _REP),
        out_specs=(_REP, _NODES, _REP, _NODES), check_rep=False))


def sharded_assign_round_pass(mesh, state, pods, quota, cand_key,
                              cand_node, cfg, rounds: int = 12):
    """``assign_round_pass`` over the mesh: first solve pass over
    precomputed candidates with est-usage accumulation and whole-batch
    quota recharge.  Returns (assignments, new_state, new_quota,
    est_accum); ``est_accum`` is node-sharded like the state."""
    n_total = state.capacity
    check_shardable(n_total, mesh)
    return _round_pass_program(mesh, n_total, rounds)(
        state, pods, quota, cand_key, cand_node, cfg)


def _followup_body(st_local, est_local, pods, quota, cfg, *,
                   k, strata, rounds, n_total):
    # candidates re-selected against the est-augmented state; rounds and
    # the commit run against the UN-augmented accounting (the
    # assign_followup_pass rollback-rebuild semantics)
    aug = st_local.replace(
        node_usage=st_local.node_usage + est_local,
        node_agg_usage=st_local.node_agg_usage + est_local)
    cand_key, cand_node, _ = _local_select_body(
        aug, pods, cfg, k=k, strata=strata, n_total=n_total)
    a, requested, _ = _rounds_local(
        aug, pods, quota, cand_key, cand_node,
        rounds=rounds, n_total=n_total)
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    keep = (a >= 0) & pods.valid
    est = pod_estimates(pods, cfg)
    loc = a - off
    own = keep & (loc >= 0) & (loc < n_loc)
    loc_c = jnp.clip(loc, 0, n_loc - 1)
    est_accum = est_local.at[loc_c].add(jnp.where(own[:, None], est, 0))
    new_quota = quota
    if quota is not None:
        new_quota = charge_quota_batch(
            quota, pods.requests, pods.quota_id, keep,
            pods.non_preemptible)
    # aug and st_local share node_requested, so the rounds' requested IS
    # the committed accounting (original + accepted requests)
    return (a, st_local.replace(node_requested=requested), new_quota,
            est_accum)


@lru_cache(maxsize=None)
def _followup_program(mesh, n_total, k, strata, rounds):
    """Jitted shard_map follow-up program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        partial(_followup_body, k=k, strata=strata,
                rounds=rounds, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _NODES, _REP, _REP, _REP),
        out_specs=(_REP, _NODES, _REP, _NODES), check_rep=False))


def sharded_assign_followup_pass(mesh, state, est_accum, pods, quota, cfg,
                                 k: int = 32, rounds: int = 12,
                                 spread_bits=(5, 15)):
    """``assign_followup_pass`` over the mesh (selection is always
    recall-exact here).  Returns (assignments, new_state, new_quota,
    est_accum')."""
    strata = (tuple(spread_bits) if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    n_total = state.capacity
    check_shardable(n_total, mesh)
    return _followup_program(mesh, n_total, min(k, n_total), strata,
                             rounds)(state, est_accum, pods, quota, cfg)


# ---------------------------------------------------------------------------
# Incremental refresh: owner-local dirty rescore + global merge
# ---------------------------------------------------------------------------


def _refresh_body(st_local, pods, cfg, cache, dirty_rows, dirty_valid, *,
                  k, strata, n_total):
    n_loc = st_local.capacity
    off = _shard_offset(n_loc)
    rot = pods.rot_id
    d = dirty_rows.shape[0]

    # a dirty node rescores only on its owning shard: unowned rows enter
    # the (P, D) sub-problem as invalid and rank -1
    loc = dirty_rows - off
    own = (loc >= 0) & (loc < n_loc) & dirty_valid
    sub = st_local.gather_rows(jnp.clip(loc, 0, n_loc - 1), own)
    scores, feasible = score_pods(sub, pods, cfg)           # (P, D)
    clipped = jnp.clip(scores, 0, ba._SCORE_CLIP)

    # global dirty mask (replicated): cached slots pointing at ANY dirty
    # node are stale regardless of which shard owns it
    dirty_mask = jnp.zeros(n_total, bool).at[dirty_rows].max(dirty_valid)
    stale_score = jnp.where(dirty_mask[cache.cand_node], -1,
                            cache.cand_score)

    splits = ba._stratum_splits(k, len(strata))
    nodes_out, scores_out = [], []
    offset = 0
    for sb, k_i in zip(strata, splits):
        if k_i == 0:
            continue
        seg_node = cache.cand_node[:, offset:offset + k_i]
        seg_score = stale_score[:, offset:offset + k_i]
        offset += k_i
        dkey, dtb = ba._rank_parts(scores, feasible, sb, rot,
                                   node_ids=dirty_rows, n_total=n_total)
        m_i = min(k_i, d)
        dval, idx = ba._topk_by_rank(dkey, dtb, m_i, n_total)
        d_node = dirty_rows[idx]
        d_score = jnp.where(
            dval >= 0, jnp.take_along_axis(clipped, idx, axis=1), -1)
        g_node = jax.lax.all_gather(d_node, NODES_AXIS, axis=1, tiled=True)
        g_score = jax.lax.all_gather(d_score, NODES_AXIS, axis=1,
                                     tiled=True)
        # merge re-ranks globally: cached ∪ per-shard fresh winners on
        # one key scale
        c_key = ba._candidate_keys(seg_score, seg_node, rot, sb, n_total)
        g_key = ba._candidate_keys(g_score, g_node, rot, sb, n_total)
        m_key = jnp.concatenate([c_key, g_key], axis=1)
        m_node = jnp.concatenate([seg_node, g_node], axis=1)
        m_score = jnp.concatenate([seg_score, g_score], axis=1)
        mval, midx = ba._topk_by_rank(
            m_key, ba._candidate_tb(m_node, rot, n_total), k_i, n_total)
        nodes_out.append(jnp.take_along_axis(m_node, midx, axis=1))
        scores_out.append(jnp.where(
            mval >= 0, jnp.take_along_axis(m_score, midx, axis=1), -1))

    cand_node = (jnp.concatenate(nodes_out, axis=1)
                 if len(nodes_out) > 1 else nodes_out[0])
    cand_score = (jnp.concatenate(scores_out, axis=1)
                  if len(scores_out) > 1 else scores_out[0])
    cand_key = ba._candidate_keys(cand_score, cand_node, rot,
                                  strata[0], n_total)
    return cand_key, ba.CandidateCache(cand_key, cand_node, cand_score)


@lru_cache(maxsize=None)
def _refresh_program(mesh, n_total, k, strata):
    """Jitted shard_map refresh program (see :func:`_select_program`)."""
    return jax.jit(shard_map(
        partial(_refresh_body, k=k, strata=strata, n_total=n_total),
        mesh=mesh, in_specs=(_NODES, _REP, _REP, _REP, _REP, _REP),
        out_specs=(_REP, _REP), check_rep=False))


def sharded_refresh_candidates(mesh, state, pods, cfg, cache, dirty_rows,
                               dirty_valid, k: int = 32,
                               spread_bits=(5, 15)):
    """``refresh_candidates`` over the mesh: dirty columns rescore on
    their owning shard, the merge re-ranks globally.  Returns
    (cand_key, new_cache) like the single-device refresh."""
    strata = (tuple(spread_bits) if isinstance(spread_bits, (tuple, list))
              else (spread_bits,))
    n_total = state.capacity
    check_shardable(n_total, mesh)
    return _refresh_program(mesh, n_total, min(k, n_total), strata)(
        state, pods, cfg, cache, dirty_rows, dirty_valid)


# ---------------------------------------------------------------------------
# Quality mode: the LP-relaxation packing solve over the nodes axis
# ---------------------------------------------------------------------------


# koordlint: shape[st_local: NxR i32 nodes]
def _lp_pack_body(st_local, pods, quota, cfg, *, n_total, ascent_iters,
                  rounding_iters):
    """Shard-local LP-pack body: the SAME ``quality/lp_pack._lp_core``
    the single-device entry runs, with the collectives live.  Scores
    and prices are shard-local columns; the per-pod argmax merges
    per-shard winners on the global integer (key, tb) scale and every
    acceptance decision is replicated — the union-of-bests and
    owner-psum exactness arguments of the greedy rounds apply term for
    term, and all arithmetic is integer, so shard counts can't perturb
    a single bit."""
    from koordinator_tpu.quality.lp_pack import _lp_core

    a, requested, new_quota, iters = _lp_core(
        st_local, pods, quota, cfg, n_total=n_total,
        ascent_iters=ascent_iters, rounding_iters=rounding_iters,
        axis=NODES_AXIS)
    return a, st_local.replace(node_requested=requested), new_quota, iters


@lru_cache(maxsize=None)
def _lp_pack_program(mesh, n_total, ascent_iters, rounding_iters):
    """Jitted shard_map LP program, memoized on (mesh, shape, bounds).

    The LP solve is a while-loop program an order of magnitude pricier
    to trace than the greedy passes; without the memo every direct call
    (the 1/2/4/8 mesh-invariance sweeps, bench stages) re-traces it even
    at identical shapes.  ``Mesh`` hashes by (devices, axis names), so
    equal meshes built by different ``solver_mesh`` calls share the
    entry; the kit's own jit wrapper composes fine on top (nested jit
    inlines)."""
    return jax.jit(shard_map(
        partial(_lp_pack_body, n_total=n_total,
                ascent_iters=ascent_iters,
                rounding_iters=rounding_iters),
        mesh=mesh, in_specs=(_NODES, _REP, _REP, _REP),
        out_specs=(_REP, _NODES, _REP, _REP), check_rep=False))


def sharded_lp_pack_assign(mesh, state, pods, cfg, quota=None,
                           ascent_iters: int | None = None,
                           rounding_iters: int | None = None):
    """``quality/lp_pack.lp_pack_assign`` over the mesh's nodes axis.

    Bit-identical to the single-device LP solve at every shard count
    (tests/test_quality.py sweeps 1/2/4/8): returns (assignments,
    new_state, new_quota, iters) with the state node-sharded like the
    greedy sharded passes."""
    from koordinator_tpu.quality import lp_pack as lp

    n_total = state.capacity
    check_shardable(n_total, mesh)
    fn = _lp_pack_program(
        mesh, n_total,
        lp.ASCENT_ITERS if ascent_iters is None else ascent_iters,
        lp.ROUNDING_ITERS if rounding_iters is None else rounding_iters)
    return fn(state, pods, quota, cfg)
