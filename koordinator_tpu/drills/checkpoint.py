"""Warm-restart checkpoints: the scheduler's host-side snapshot + the
deltasync replay cursor, serialized with the wire payload codec.

A restarted (or failed-over) scheduler restores this locally and then
catches up via deltasync DELTAs instead of paying a full snapshot
re-bootstrap: the checkpoint carries ``(rv, instance)`` — the replay
cursor ``StateSyncClient`` sends in its HELLO — so the service answers
with ``log.since(rv)`` when the cursor is within retention (see
docs/wire_protocol.md, "State sync").  Recovery time becomes a bounded,
measurable RTO: restore cost is local deserialization, catch-up cost is
proportional to the *downtime*, not to the cluster.

What is captured (one consistent cut under ``scheduler.lock``):

- every node's ``NodeSpec`` (allocatable/usage/agg/prod, labels,
  taints), in snapshot **row order** so the restored ``ClusterSnapshot``
  assigns identical rows — the save→restore roundtrip is bit-identical
  on the state arrays (tests/test_drills.py proves it);
- the pending queue (full ``PodSpec``s, creation stamps included);
- bound pods (``BoundPod``s; their ``node_generation`` is re-stamped to
  the restored snapshot's generations so a later release decrements the
  instance it was actually charged to);
- gang records and the quota-tree spec (+ per-quota ``used`` recharged
  from the restored bound pods);
- the replay cursor.

What is NOT captured: reservations and fine-grained CPU/device
assignments — both re-enter via their own sync events; a checkpoint
taken while reservations are live records ``reservations_dropped`` so
the caller can elect a full re-bootstrap instead.  Solver state is
device-resident and derived: the restored scheduler's first
``flush()`` rebuilds it from the host arrays, so checkpointing cannot
change any scheduling decision (checkpoints off ⇒ bit-identical
rounds).
"""

from __future__ import annotations

import os
import time

import numpy as np

CHECKPOINT_VERSION = 1


def _stack(vectors, dims: int, dtype) -> np.ndarray:
    if not vectors:
        return np.zeros((0, dims), dtype)
    return np.stack([np.asarray(v, dtype) for v in vectors])


def capture(scheduler, sync=None) -> tuple[dict, dict[str, np.ndarray]]:
    """One consistent cut of the scheduler's host state, as a
    ``(doc, arrays)`` pair for :func:`koordinator_tpu.transport.wire.
    encode_payload`.  Holds ``scheduler.lock`` for the whole walk — the
    checkpoint writer must see no half-applied round (lock-discipline:
    never copy scheduler fields outside the round lock)."""
    from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS

    dims = NUM_RESOURCE_DIMS
    doc: dict = {"version": CHECKPOINT_VERSION}
    arrays: dict[str, np.ndarray] = {}
    with scheduler.lock:
        snap = scheduler.snapshot
        # -- nodes, in row order (identical row assignment on restore)
        names = sorted(snap.node_index, key=snap.node_index.__getitem__)
        nodes = []
        alloc, usage, agg, prod = [], [], [], []
        umask, amask, pmask = [], [], []
        zero = np.zeros(dims, np.int32)
        for name in names:
            spec = snap.node_specs[name]
            nodes.append({"name": name,
                          "labels": dict(spec.labels),
                          "taints": dict(spec.taints)})
            alloc.append(spec.allocatable)
            for vec, out, mask in ((spec.usage, usage, umask),
                                   (spec.agg_usage, agg, amask),
                                   (spec.prod_usage, prod, pmask)):
                mask.append(0 if vec is None else 1)
                out.append(zero if vec is None else vec)
        doc["nodes"] = nodes
        doc["snapshot_capacity"] = int(snap.capacity)
        arrays["node_allocatable"] = _stack(alloc, dims, np.int32)
        arrays["node_usage"] = _stack(usage, dims, np.int32)
        arrays["node_agg_usage"] = _stack(agg, dims, np.int32)
        arrays["node_prod_usage"] = _stack(prod, dims, np.int32)
        arrays["node_usage_mask"] = np.asarray(umask, np.int8)
        arrays["node_agg_mask"] = np.asarray(amask, np.int8)
        arrays["node_prod_mask"] = np.asarray(pmask, np.int8)

        # -- pending queue (arrival order preserved: dict order)
        pend, pend_req = [], []
        for pod in scheduler.pending.values():
            pend.append({
                "name": pod.name, "priority": int(pod.priority),
                "qos": int(pod.qos), "gang": pod.gang,
                "quota": pod.quota,
                "non_preemptible": bool(pod.non_preemptible),
                "node_selector": dict(pod.node_selector),
                "tolerations": dict(pod.tolerations),
                "creation": float(pod.creation),
                "labels": dict(pod.labels), "owner": pod.owner,
                "preemption_policy": pod.preemption_policy,
            })
            pend_req.append(pod.requests)
        doc["pending"] = pend
        arrays["pending_requests"] = _stack(pend_req, dims, np.int32)

        # -- bound pods
        bnd, bnd_req = [], []
        for bp in scheduler.bound.values():
            bnd.append({
                "name": bp.name, "node": bp.node,
                "priority": int(bp.priority), "quota": bp.quota,
                "non_preemptible": bool(bp.non_preemptible),
                "labels": dict(bp.labels), "gang": bp.gang,
            })
            bnd_req.append(bp.requests)
        doc["bound"] = bnd
        arrays["bound_requests"] = _stack(bnd_req, dims, np.int32)

        # -- gangs
        doc["gangs"] = [
            {"name": g.name, "min_member": int(g.min_member),
             "group": g.group,
             "wait_time_sec": (None if g.wait_time_sec is None
                               else float(g.wait_time_sec))}
            for g in scheduler.gangs.values()]

        # -- quota tree (BFS from the root so parents restore first)
        tree = scheduler.quota_tree
        if tree is not None:
            from koordinator_tpu.quota.tree import ROOT

            quotas = []
            qmin, qmax, qsw, qg = [], [], [], []
            frontier = list(tree.children.get(ROOT, ()))
            while frontier:
                name = frontier.pop(0)
                q = tree.nodes[name]
                quotas.append({"name": q.name, "parent": q.parent,
                               "allow_lent": bool(q.allow_lent),
                               "enable_scale_min":
                                   bool(q.enable_scale_min)})
                qmin.append(q.min)
                qmax.append(q.max)
                qsw.append(q.shared_weight)
                qg.append(q.guarantee)
                frontier.extend(tree.children.get(name, ()))
            doc["quotas"] = quotas
            doc["quota_scale_min"] = bool(tree.scale_min_enabled)
            arrays["quota_total"] = np.asarray(tree.total_resource,
                                              np.int64)
            arrays["quota_min"] = _stack(qmin, dims, np.int64)
            arrays["quota_max"] = _stack(qmax, dims, np.int64)
            arrays["quota_shared_weight"] = _stack(qsw, dims, np.int64)
            arrays["quota_guarantee"] = _stack(qg, dims, np.int64)

        # -- replay cursor + limitations
        doc["cursor"] = {
            "rv": int(sync.rv) if sync is not None else -1,
            "instance": sync.instance if sync is not None else None,
        }
        doc["reservations_dropped"] = len(scheduler.reservations.specs())
    return doc, arrays


def restore_into(scheduler, doc: dict,
                 arrays: dict[str, np.ndarray], sync=None) -> dict:
    """Apply a captured checkpoint onto a FRESH scheduler (empty
    snapshot/queues; the caller owns its construction — config, bind_fn,
    solver kit, elector).  Primes ``sync``'s replay cursor so its next
    ``bootstrap()`` HELLO asks for deltas since the checkpoint instead
    of a full snapshot.  Returns restore stats."""
    from koordinator_tpu.quota.tree import QuotaTree
    from koordinator_tpu.scheduler.scheduler import BoundPod, GangRecord
    from koordinator_tpu.scheduler.snapshot import NodeSpec, PodSpec

    if doc.get("version") != CHECKPOINT_VERSION:
        raise ValueError(
            f"checkpoint version {doc.get('version')!r} != "
            f"{CHECKPOINT_VERSION}")

    def row(key, i):
        return np.asarray(arrays[key][i], arrays[key].dtype)

    with scheduler.lock:
        if doc.get("quotas"):
            tree = QuotaTree(np.asarray(arrays["quota_total"], np.int64),
                             scale_min_enabled=bool(
                                 doc.get("quota_scale_min", False)))
            for i, q in enumerate(doc["quotas"]):
                tree.add(q["name"],
                         min=row("quota_min", i),
                         max=row("quota_max", i),
                         parent=q["parent"],
                         shared_weight=row("quota_shared_weight", i),
                         guarantee=row("quota_guarantee", i),
                         allow_lent=bool(q["allow_lent"]),
                         enable_scale_min=bool(q["enable_scale_min"]))
            scheduler.quota_tree = tree
        for i, entry in enumerate(doc.get("nodes", ())):
            scheduler.snapshot.upsert_node(NodeSpec(
                name=entry["name"],
                allocatable=row("node_allocatable", i),
                usage=(row("node_usage", i)
                       if arrays["node_usage_mask"][i] else None),
                agg_usage=(row("node_agg_usage", i)
                           if arrays["node_agg_mask"][i] else None),
                prod_usage=(row("node_prod_usage", i)
                            if arrays["node_prod_mask"][i] else None),
                labels=dict(entry.get("labels", {})),
                taints=dict(entry.get("taints", {})),
            ))
        for g in doc.get("gangs", ()):
            scheduler.register_gang(GangRecord(
                name=g["name"], min_member=int(g["min_member"]),
                group=g.get("group"),
                wait_time_sec=g.get("wait_time_sec")))
    # enqueue/add_bound_pod take the lock themselves (RLock — but keep
    # the public entry points on their own acquire so their accounting
    # stays the single audited path)
    for i, p in enumerate(doc.get("pending", ())):
        scheduler.enqueue(PodSpec(
            name=p["name"], requests=row("pending_requests", i),
            priority=int(p["priority"]), qos=int(p["qos"]),
            gang=p.get("gang"), quota=p.get("quota"),
            non_preemptible=bool(p.get("non_preemptible", False)),
            node_selector=dict(p.get("node_selector", {})),
            tolerations=dict(p.get("tolerations", {})),
            creation=float(p.get("creation", 0.0)),
            labels=dict(p.get("labels", {})), owner=p.get("owner"),
            preemption_policy=p.get("preemption_policy",
                                    "PreemptLowerPriority")))
    with scheduler.lock:
        reserve_by_node: dict[str, np.ndarray] = {}
        for i, b in enumerate(doc.get("bound", ())):
            requests = row("bound_requests", i)
            pod = BoundPod(
                name=b["name"], node=b["node"], requests=requests,
                priority=int(b["priority"]), quota=b.get("quota"),
                non_preemptible=bool(b.get("non_preemptible", False)),
                labels=dict(b.get("labels", {})), gang=b.get("gang"),
                # charge the RESTORED node instance, not the dead one's
                # generation — a later release must decrement the
                # instance this restore is about to reserve on
                node_generation=scheduler.snapshot.node_generation.get(
                    b["node"], 0))
            scheduler.bound[pod.name] = pod
            if pod.node in scheduler.snapshot.node_index:
                prev = reserve_by_node.get(pod.node)
                cur = requests.astype(np.int64)
                reserve_by_node[pod.node] = (
                    cur if prev is None else prev + cur)
            # the bind-path mirror: the node reserve below owns node
            # accounting, the quota charge is the caller's
            # (delete_pod releases both)
            scheduler._charge_quota_used(pod, sign=1)
        # one scatter for the whole bound set (bit-identical to per-pod
        # reserve; the per-pod path is what makes restore slower than
        # the re-placement it is supposed to beat)
        scheduler.snapshot.reserve_batch(reserve_by_node)
    if sync is not None:
        cursor = doc.get("cursor") or {}
        sync.rv = int(cursor.get("rv", -1))
        sync.instance = cursor.get("instance")
    return {
        "nodes": len(doc.get("nodes", ())),
        "pending": len(doc.get("pending", ())),
        "bound": len(doc.get("bound", ())),
        "gangs": len(doc.get("gangs", ())),
        "quotas": len(doc.get("quotas", ()) or ()),
        "cursor_rv": int((doc.get("cursor") or {}).get("rv", -1)),
        "reservations_dropped": int(doc.get("reservations_dropped", 0)),
    }


def save(path: str, scheduler, sync=None) -> dict:
    """Capture + atomically persist (tmp file, ``os.replace``) so a
    crash mid-write leaves the previous checkpoint intact."""
    from koordinator_tpu.transport import wire

    doc, arrays = capture(scheduler, sync=sync)
    payload = wire.encode_payload(doc, arrays)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return {"bytes": len(payload), "nodes": len(doc["nodes"]),
            "pending": len(doc["pending"]), "bound": len(doc["bound"])}


def load(path: str) -> tuple[dict, dict[str, np.ndarray]]:
    from koordinator_tpu.transport import wire

    with open(path, "rb") as f:
        return wire.decode_payload(f.read())


class CheckpointWriter:
    """Periodic warm-restart checkpointing (the scheduler binary's
    ``--checkpoint-path`` / ``--checkpoint-interval-seconds``).

    Owns one daemon thread; ``stop()`` writes a final cut so a PLANNED
    restart resumes from the freshest state, not the last interval.
    Lock discipline: the writer itself never holds ``scheduler.lock`` —
    each :func:`save` acquires it only for the capture walk, so rounds
    are blocked for the copy, never for serialization or disk I/O."""

    def __init__(self, path: str, scheduler, sync=None,
                 interval_s: float = 30.0):
        import threading

        self.path = path
        self.scheduler = scheduler
        self.sync = sync
        self.interval_s = float(interval_s)
        self.saves = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="ckpt-writer", daemon=True)

    def start(self) -> "CheckpointWriter":
        self._thread.start()
        return self

    def save_now(self) -> dict | None:
        try:
            stats = save(self.path, self.scheduler, self.sync)
            self.saves += 1
            return stats
        except Exception:
            # checkpointing is an optimization: a failed save must never
            # take the scheduler down (the fallback is the full
            # re-bootstrap warm restart replaces)
            self.errors += 1
            return None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.save_now()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        self.save_now()


def restore(path: str, scheduler, sync=None) -> dict:
    """load + restore_into, observing
    ``checkpoint_restore_duration_seconds``."""
    from koordinator_tpu import metrics

    start = time.monotonic()
    doc, arrays = load(path)
    stats = restore_into(scheduler, doc, arrays, sync=sync)
    stats["duration_s"] = time.monotonic() - start
    metrics.checkpoint_restore_duration_seconds.observe(
        stats["duration_s"])
    return stats
