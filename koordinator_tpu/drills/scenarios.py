"""Drill scenarios as data: each drill is a declarative phase list
(warmup → inject → hold → heal → verify) whose actions the engine
interprets — replayable from one seed, diffable in review, and
composable without touching engine code.

Phase taxonomy (docs/robustness.md "Drill catalog"):

- ``warmup``  — fault-free: nodes register, the jit cache warms, the
  thread/fd baseline is taken at the end;
- ``inject``  — the adversarial event fires (storm/kill/restart/reorg);
- ``hold``    — the system runs *with* the failure: churn continues,
  probabilistic chaos stays on, invariants are live-checked;
- ``heal``    — faults end (``FaultInjector.heal()``), dead components
  restart;
- ``verify``  — fault-free reconvergence window; the verdict engine's
  fixpoint clock runs here.

Durations are VIRTUAL seconds: the engine compresses them by its
``time_scale``, and the churn trace + storm schedules are evaluated on
the same virtual clock, so one seed replays identically at any
compression.
"""

from __future__ import annotations

import dataclasses
import random

from koordinator_tpu.transport.faults import PARTITION

#: loadgen-compatible event kinds (tools/loadgen.py uses the same
#: strings; DrillHarness accepts either generator's events duck-typed)
POD_ADD = "pod_add"
POD_DEL = "pod_del"
GANG_BURST = "gang_burst"
QUOTA_UPDATE = "quota_update"


@dataclasses.dataclass(frozen=True)
class DrillEvent:
    t: float
    kind: str
    name: str
    payload: dict


@dataclasses.dataclass(frozen=True)
class Phase:
    """One drill phase; ``actions`` fire at phase START, ``chaos``
    keeps the probabilistic injector enabled for the phase's span."""

    name: str
    duration_s: float
    actions: tuple = ()
    chaos: bool = False


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    description: str
    phases: tuple
    replicas: int = 2
    racks: int = 2
    tenants: tuple = ("t-a",)
    with_manager: bool = True
    #: verdict budgets (wall seconds / counts)
    rto_budget_s: float = 60.0
    degraded_budget_s: float = 30.0
    slo_breach_budget: int = 10
    expected_failovers: int = 0
    #: churn_trace overrides (rate, del_fraction, gang_every_s, ...)
    churn: dict = dataclasses.field(default_factory=dict)

    def phase(self, name: str) -> Phase:
        for p in self.phases:
            if p.name == name:
                return p
        raise KeyError(name)


def churn_trace(seed: int, duration_s: float, tenants=("t-a",),
                rate: float = 1.2, del_fraction: float = 0.25,
                gang_every_s: float = 6.0, gang_size: int = 3,
                cpu: int = 1_000, memory: int = 1_024
                ) -> list[DrillEvent]:
    """Seeded churn load in the loadgen trace shape: Poisson pod
    arrivals with exponential lifetimes, periodic gang bursts, tenants
    round-robined.  Small by construction — every live pod must fit the
    drill cluster so the reconvergence fixpoint is reachable."""
    rng = random.Random(seed)
    events: list[DrillEvent] = []
    seq = 0
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        name = f"dp-{seed}-{seq}"
        tenant = tenants[seq % len(tenants)]
        seq += 1
        events.append(DrillEvent(t, POD_ADD, name, {
            "cpu": cpu, "memory": memory, "priority": 1000,
            "quota": tenant, "tenant": tenant, "gang": None}))
        if rng.random() < del_fraction:
            events.append(DrillEvent(
                t + rng.expovariate(1.0 / (duration_s / 3.0)),
                POD_DEL, name, {"tenant": tenant}))
    g = 0
    tg = gang_every_s
    while tg < duration_s and gang_every_s > 0:
        tenant = tenants[g % len(tenants)]
        events.append(DrillEvent(tg, GANG_BURST, f"dg-{seed}-{g}", {
            "size": gang_size, "cpu": cpu, "memory": memory,
            "priority": 1000, "quota": tenant, "tenant": tenant}))
        g += 1
        tg += gang_every_s
    events.sort(key=lambda e: (e.t, e.kind, e.name))
    return events


def _storm(domains, mode=PARTITION):
    return {"op": "storm", "domains": tuple(domains), "mode": mode}


#: the drill catalog — every ISSUE-17 scenario, one seed replays each
SCENARIOS: dict[str, Scenario] = {}


def _register(s: Scenario) -> Scenario:
    SCENARIOS[s.name] = s
    return s


LEADER_FAILOVER = _register(Scenario(
    name="leader_failover",
    description="Kill the lease-holding scheduler mid-trace: the warm "
                "standby (shared jit cache) takes the lease and resumes "
                "rounds; the dead replica restarts as the new standby.",
    phases=(
        Phase("warmup", 5.0),
        Phase("inject", 0.5, actions=({"op": "kill_leader"},)),
        # hold must outlast lease expiry + standby acquisition
        # (LEASE_VS + RETRY_VS virtual seconds) with margin, so the
        # failover happens while the dead leader is still dead
        Phase("hold", 12.0, chaos=True),
        Phase("heal", 0.5, actions=({"op": "heal"},
                                    {"op": "restart_dead",
                                     "restore": "snapshot"})),
        Phase("verify", 8.0),
    ),
    replicas=2, expected_failovers=1))

MANAGER_RESTART = _register(Scenario(
    name="manager_restart",
    description="Restart the manager mid-trace: its watch view "
                "re-bootstraps over deltasync and the colocation loop "
                "resumes pushing batch allocatable.",
    phases=(
        Phase("warmup", 5.0),
        Phase("inject", 0.5, actions=({"op": "restart_manager"},)),
        Phase("hold", 6.0, chaos=True),
        Phase("heal", 0.5, actions=({"op": "heal"},)),
        Phase("verify", 8.0),
    ),
    replicas=1))

RACK_STORM = _register(Scenario(
    name="rack_storm",
    description="Correlated rack flap train: every connection in "
                "rack:r0 is partitioned together, repeatedly — breaker "
                "pacing and rv-gap resync both get exercised; the heal "
                "seam must close breakers promptly.",
    phases=(
        Phase("warmup", 5.0),
        Phase("inject", 0.5, actions=(
            {"op": "flaps", "domains": ("rack:r0",),
             "up_s": 1.0, "down_s": 1.0, "flaps": 3},)),
        Phase("hold", 8.0, chaos=True),
        Phase("heal", 0.5, actions=({"op": "heal"},)),
        Phase("verify", 8.0),
    ),
    replicas=1))

QUOTA_REORG = _register(Scenario(
    name="quota_reorg",
    description="Quota-tree reorg mid-flight: tenant maxes rescale "
                "sharply down then restore — admission must follow the "
                "live tree and no bound pod may double-free on the way "
                "back.",
    phases=(
        Phase("warmup", 5.0),
        Phase("inject", 0.5, actions=(
            {"op": "quota_reorg", "scale": 0.25},)),
        Phase("hold", 6.0, chaos=True),
        Phase("heal", 0.5, actions=({"op": "heal"},
                                    {"op": "quota_restore"},)),
        Phase("verify", 8.0),
    ),
    replicas=1, tenants=("t-a", "t-b")))

TENANT_SEVER = _register(Scenario(
    name="tenant_sever",
    description="Per-tenant socket sever: tenant t-b's control feeder "
                "is partitioned (its pods stop arriving); tenant t-a "
                "must keep scheduling unimpaired, and t-b's backlog "
                "drains after heal.",
    phases=(
        Phase("warmup", 5.0),
        Phase("inject", 0.5, actions=(_storm(("tenant:t-b",)),)),
        Phase("hold", 6.0, chaos=True),
        Phase("heal", 0.5, actions=({"op": "heal"},)),
        Phase("verify", 8.0),
    ),
    replicas=1, tenants=("t-a", "t-b")))

WARM_RESTART = _register(Scenario(
    name="warm_restart",
    description="Kill the (only) scheduler, restore from its warm-"
                "restart checkpoint, and catch up via deltasync deltas "
                "— the measured RTO must beat a full-snapshot "
                "re-bootstrap of the same trace.",
    phases=(
        # long dense warmup, short hold: the checkpoint's value is the
        # bound set it carries, so the regime must be
        # |state at checkpoint| >> |churn after it| — the same regime
        # that makes warm restart worth having in production.  Deletes
        # are off (the other five drills churn them): a post-checkpoint
        # delete costs the delta replay a per-event unreserve while the
        # snapshot compacts it to nothing, which at drill scale is
        # noise-of-the-harness, not the regime under test.
        Phase("warmup", 10.0),
        Phase("inject", 0.5, actions=({"op": "checkpoint"},
                                      {"op": "kill_leader"},)),
        Phase("hold", 1.5, chaos=True),
        Phase("heal", 0.5, actions=({"op": "heal"},
                                    {"op": "restart_dead",
                                     "restore": "checkpoint"},)),
        Phase("verify", 8.0),
    ),
    replicas=1, expected_failovers=0,
    churn={"rate": 6.0, "del_fraction": 0.0}))
