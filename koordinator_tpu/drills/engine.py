"""The drill orchestrator: multi-phase adversarial scenarios against
the full socket stack, with a machine-checkable verdict per drill.

Topology (one in-process cluster per drill, all over real unix
sockets so every transport seam — framing, breakers, deltasync, lease
RPCs — is in the blast radius):

- one "apiserver": ``RpcServer`` hosting ``StateSyncService`` (the
  authoritative cluster state, NO local binding) + ``LeaseService``
  over an ``InMemoryLeaseStore``;
- N scheduler replicas, each a full client stack — ``Scheduler`` +
  ``SchedulerBinding`` + ``StateSyncClient`` +
  ``ReconnectingSidecarClient`` (fault-tagged ``sched:<name>``) + a
  ``LeaderElector`` over ``RemoteLeaseStore``.  Replicas share one
  ``SolverKit``: the standby's jit cache is warm the moment it takes
  the lease (the "standby warms its jit cache" leg — in production the
  standby pre-compiles against the same shapes);
- per-rack koordlet feeders (fault domain ``rack:<r>``) pushing node
  registrations + usage heartbeats for their rack's nodes;
- per-tenant control feeders (fault domain ``tenant:<t>``) pushing
  that tenant's pod churn — a tenant sever takes exactly one tenant's
  feed out;
- the manager (fault domain ``manager``): ``ManagerSyncBinding`` +
  ``ColocationLoop`` pushing batch allocatable.

The run loop drives everything on a VIRTUAL clock (wall time ×
``time_scale``): churn events, storm schedules
(``FaultInjector.advance_to``), and phase boundaries all read the same
clock, so one seed replays identically at any compression.  Process
death is modeled at the elector/client seams: a killed replica's
client closes and its elector stops ticking, so the lease expires and
a standby acquires — exactly the observable footprint of SIGKILL
(tests/test_ha_e2e.py proves the real cross-process version; drills
trade process isolation for determinism and speed).

Leadership is decided by the lease alone: ``Scheduler.schedule_round``
self-gates on its elector, so driving every alive replica's rounds is
safe — standbys keep syncing state and decide nothing.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from koordinator_tpu.drills import checkpoint as ckpt
from koordinator_tpu.drills.scenarios import (
    GANG_BURST,
    POD_ADD,
    POD_DEL,
    SCENARIOS,
    Scenario,
    churn_trace,
)
from koordinator_tpu.drills.verdict import DrillVerdict

NODES = 6
NODE_CPU = 16_000
NODE_MEM = 16_384
# lease duration/retry are VIRTUAL seconds (divided by the harness's
# time_scale at replica construction): a killed leader's lease must
# expire INSIDE the compressed hold window at any compression, or the
# heal-phase restart of the same-named replica reclaims its own
# still-held lease by identity and no failover is ever observed
LEASE_VS = 6.0
RETRY_VS = 1.0
TICK_S = 0.05
#: unchanged-usage keepalive period, virtual seconds (koordlet-style
#: report suppression; see _heartbeats)
HB_KEEPALIVE_VS = 5.0


def _counts():
    return threading.active_count(), len(os.listdir("/proc/self/fd"))


class _CountingBinding:
    """SchedulerBinding wrapper counting full-snapshot resets — the
    warm-restart verdict's proof that catch-up rode DELTAs (a primed
    replay cursor makes the HELLO answer without a snapshot, so
    ``resets`` stays 0)."""

    def __init__(self, inner):
        self.inner = inner
        self.resets = 0
        self.service_name = getattr(inner, "service_name", "scheduler")

    def reset(self):
        self.resets += 1
        return self.inner.reset()

    def __getattr__(self, name):
        return getattr(self.inner, name)


class Replica:
    """One scheduler replica: full client stack + elector."""

    def __init__(self, harness, name: str):
        from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient
        from koordinator_tpu.ha import LeaderElector, RemoteLeaseStore
        from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
        from koordinator_tpu.transport import StateSyncClient
        from koordinator_tpu.transport.deltasync import SchedulerBinding

        self.h = harness
        self.name = name
        self.alive = True
        self.oracle_accepts = 0

        def bind_fn(pod_name, node_name):
            self.oracle_accepts += 1
            harness._oracle_check(self, pod_name, node_name)

        self.snapshot = ClusterSnapshot(capacity=32)
        self.scheduler = Scheduler(
            self.snapshot, config=harness.scoring_config(),
            bind_fn=bind_fn, staleness_threshold_sec=10.0,
            quota_tree=harness.build_quota_tree(),
            solver_kit=harness.kit)
        if harness.kit is None:
            harness.kit = self.scheduler.kit
        for record in harness.gang_records.values():
            self.scheduler.register_gang(self._gang_copy(record))
        self.binding = _CountingBinding(SchedulerBinding(self.scheduler))
        self.sync = StateSyncClient(self.binding)

        def bootstrap(client):
            self.sync.bind_client(client)
            self.sync.bootstrap(client)

        self.client = ReconnectingSidecarClient(
            harness.sock, on_push=self.sync.on_push,
            on_connect=bootstrap, retry_policy=harness.retry_policy,
            faults=harness.injector, timeout=10.0,
            fault_domain=f"sched:{name}")
        # lease RPCs ride a DEDICATED client (same fault domain): the
        # elector ticks inside schedule_round under scheduler.lock, and
        # a shared client's ensure() would run the deltasync bootstrap
        # there — scheduler.lock → sync._lock, while the push path on
        # the reader thread takes sync._lock → scheduler.lock (deadlock
        # by lock-order inversion).  Two sockets is also what a real
        # deployment does: leases live on the apiserver, not the watch
        # stream.
        self.lease_client = ReconnectingSidecarClient(
            harness.sock, retry_policy=harness.retry_policy,
            faults=harness.injector, timeout=10.0,
            fault_domain=f"sched:{name}")
        self.scheduler.elector = LeaderElector(
            RemoteLeaseStore(self.lease_client), "drill-sched", name,
            lease_duration=LEASE_VS / harness.time_scale,
            retry_period=RETRY_VS / harness.time_scale)

    @staticmethod
    def _gang_copy(record):
        from koordinator_tpu.scheduler.scheduler import GangRecord

        return GangRecord(name=record.name,
                          min_member=record.min_member,
                          group=record.group,
                          wait_time_sec=record.wait_time_sec)

    def is_leader(self) -> bool:
        elector = self.scheduler.elector
        return bool(elector is not None and elector.is_leader())

    def round(self):
        # the watch connection heals OUTSIDE the round lock (bootstrap
        # applies deltas under scheduler.lock via the binding — taking
        # it here first would invert the sync-then-scheduler lock order)
        try:
            self.client.ensure()
        except Exception:
            pass
        with self.scheduler.lock:
            return self.scheduler.schedule_round()

    def kill(self) -> None:
        """SIGKILL footprint: the connections drop, the elector stops
        renewing (lease expires on its own), rounds stop."""
        self.alive = False
        self.client.close()
        self.lease_client.close()

    def close(self) -> None:
        self.alive = False
        try:
            self.client.close()
            self.lease_client.close()
        finally:
            stop = getattr(self.scheduler, "stop", None)
            if stop is not None:
                stop()


class DrillHarness:
    """One drill run: build, execute phases, render the verdict."""

    def __init__(self, scenario: Scenario, seed: int, workdir: str,
                 time_scale: float = 4.0, events=None):
        from koordinator_tpu.ha import InMemoryLeaseStore, LeaseService
        from koordinator_tpu.transport import (
            FaultConfig,
            FaultInjector,
            RpcServer,
            StateSyncService,
        )
        from koordinator_tpu.transport.retry import RetryPolicy

        self.scenario = scenario
        self.seed = seed
        self.time_scale = time_scale
        self.workdir = workdir
        self.sock = os.path.join(workdir, f"drill-{scenario.name}-{seed}.sock")
        self.ckpt_path = os.path.join(
            workdir, f"drill-{scenario.name}-{seed}.ckpt")
        self.retry_policy = RetryPolicy(
            initial_backoff_s=0.02, max_backoff_s=0.3, multiplier=2.0,
            jitter="equal")
        #: mild probabilistic chaos rides phases marked chaos=True; the
        #: correlated storms are the scenario's actions
        self.injector = FaultInjector(seed=seed, config=FaultConfig(
            connect_refuse_p=0.05, push_drop_p=0.02, push_delay_p=0.02,
            push_delay_ms=2.0, push_duplicate_p=0.02))
        self.injector.enabled = False

        self.server = RpcServer(self.sock, faults=self.injector)
        self.service = StateSyncService(retention=512)
        self.service.attach(self.server)
        self.lease_service = LeaseService(InMemoryLeaseStore())
        self.lease_service.attach(self.server)
        self.server.start()

        self.kit = None
        self.gang_records: dict = {}
        self.violations: list[str] = []
        self.quota_scale = 1.0
        self._quota_extra: set[str] = set()

        self.replicas = [Replica(self, f"rep-{i}")
                         for i in range(scenario.replicas)]
        self._build_feeders()
        self.manager = None
        if scenario.with_manager:
            self.manager = self._build_manager()

        self._hb_last: dict[int, float] = {}
        self.events = (list(events) if events is not None
                       else churn_trace(
                           seed, duration_s=self._churn_horizon(),
                           tenants=scenario.tenants,
                           **scenario.churn))
        self._event_i = 0
        self._unsent: list = []
        self.live_pods: set[str] = set()

        self.verdict = DrillVerdict(scenario=scenario.name, seed=seed)
        self._t0 = None
        self._last_leader = None
        self.failovers = 0
        self.inject_at = None
        self.reconverged_at = None
        self.degraded_s = 0.0
        self.round_durations: list[float] = []
        self._baseline = None
        self._dead: list[Replica] = []
        self._restore_stats = None

    # -- construction helpers ------------------------------------------------

    def scoring_config(self):
        import jax.numpy as jnp

        from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
        from koordinator_tpu.ops.assignment import ScoringConfig

        return ScoringConfig.default().replace(
            usage_thresholds=jnp.zeros(NUM_RESOURCE_DIMS, jnp.int32),
            estimator_defaults=jnp.zeros(NUM_RESOURCE_DIMS, jnp.int32))

    def build_quota_tree(self):
        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.quota.tree import QuotaTree

        total = np.asarray(
            resource_vector(cpu=NODES * NODE_CPU,
                            memory=NODES * NODE_MEM), np.int64)
        tree = QuotaTree(total)
        share = np.maximum(total // max(len(self.scenario.tenants), 1), 1)
        for tenant in self.scenario.tenants:
            tree.add(tenant, min=share // 4, max=total)
        return tree

    def _churn_horizon(self) -> float:
        """Churn spans warmup..hold: the trace goes quiet before heal so
        the verify phase converges on a fixed pod population."""
        horizon = 0.0
        for p in self.scenario.phases:
            if p.name == "heal":
                break
            horizon += p.duration_s
        return horizon

    def _node_rack(self, i: int) -> str:
        return f"r{i % self.scenario.racks}"

    def _build_feeders(self) -> None:
        from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient

        self.rack_feeders = {}
        for i in range(self.scenario.racks):
            domain = f"rack:r{i}"
            self.rack_feeders[f"r{i}"] = ReconnectingSidecarClient(
                self.sock, retry_policy=self.retry_policy,
                faults=self.injector, timeout=3.0, fault_domain=domain)
        self.tenant_feeders = {}
        for tenant in self.scenario.tenants:
            self.tenant_feeders[tenant] = ReconnectingSidecarClient(
                self.sock, retry_policy=self.retry_policy,
                faults=self.injector, timeout=3.0,
                fault_domain=f"tenant:{tenant}")

    def _build_manager(self):
        from koordinator_tpu.cmd.binaries import ReconnectingSidecarClient
        from koordinator_tpu.manager.colocation_loop import (
            ColocationLoop,
            ManagerSyncBinding,
        )
        from koordinator_tpu.manager.noderesource_controller import (
            NodeResourceController,
        )
        from koordinator_tpu.transport import StateSyncClient
        from koordinator_tpu.transport.wire import FrameType

        binding = ManagerSyncBinding()
        sync = StateSyncClient(binding)

        def bootstrap(client):
            sync.bind_client(client)
            sync.bootstrap(client)

        client = ReconnectingSidecarClient(
            self.sock, on_push=sync.on_push, on_connect=bootstrap,
            retry_policy=self.retry_policy, faults=self.injector,
            timeout=3.0, fault_domain="manager")

        def push_allocatable(name, allocatable):
            client.call(FrameType.STATE_PUSH,
                        {"kind": "node_allocatable", "name": name},
                        {"allocatable": np.asarray(allocatable,
                                                   np.int32)})

        loop = ColocationLoop(NodeResourceController(), binding,
                              push_allocatable, ensure_fn=client.ensure)
        return {"binding": binding, "sync": sync, "client": client,
                "loop": loop}

    # -- oracle --------------------------------------------------------------

    def _oracle_check(self, replica: Replica, pod_name: str,
                      node_name: str) -> None:
        """Bind-time never-overcommit re-check (runs under the round
        lock, so the replica's host sums and snapshot agree)."""
        from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS

        sched = replica.scheduler
        spec = sched.snapshot.node_specs.get(node_name)
        if spec is None:
            self.violations.append(
                f"{replica.name}: {pod_name} bound to unknown node "
                f"{node_name}")
            return
        total = np.zeros(NUM_RESOURCE_DIMS, np.int64)
        for bp in sched.bound.values():
            if bp.node == node_name:
                total += bp.requests.astype(np.int64)
        if not np.all(total <= spec.allocatable.astype(np.int64)):
            self.violations.append(
                f"{replica.name}: overcommit on {node_name} accepting "
                f"{pod_name}: bound={total.tolist()} "
                f"allocatable={spec.allocatable.tolist()}")

    # -- churn application ---------------------------------------------------

    def _push(self, feeder, ftype, doc, arrays=None) -> bool:
        from koordinator_tpu.transport.channel import (
            RpcError,
            RpcRemoteError,
        )

        try:
            feeder.call(ftype, doc, arrays)
            return True
        except (RpcError, RpcRemoteError, OSError):
            return False

    def _register_nodes(self) -> None:
        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.transport.wire import FrameType

        alloc = np.asarray(resource_vector(cpu=NODE_CPU, memory=NODE_MEM),
                           np.int32)
        for i in range(NODES):
            rack = self._node_rack(i)
            ok = self._push(
                self.rack_feeders[rack], FrameType.STATE_PUSH,
                {"kind": "node_upsert", "name": f"dn{i}",
                 "labels": {"rack": rack}},
                {"allocatable": alloc})
            if not ok:
                raise RuntimeError(f"warmup node dn{i} never registered")

    def _heartbeats(self) -> None:
        """Per-node usage reports with koordlet-style suppression: a
        node whose usage is unchanged pushes only a periodic keepalive
        (every ``HB_KEEPALIVE_VS`` virtual seconds).  Without this the
        delta log floods with no-op usage events and warm-restart
        catch-up pays for the flood instead of the actual churn."""
        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.transport.wire import FrameType

        vt = self._vt() if self._t0 is not None else 0.0
        usage = {
            "usage": np.asarray(resource_vector(cpu=2_000, memory=4_096),
                                np.int32),
            "sys_usage": np.asarray(resource_vector(cpu=500, memory=512),
                                    np.int32),
            "hp_usage": np.asarray(
                resource_vector(cpu=3_000, memory=2_048), np.int32),
            "hp_request": np.asarray(
                resource_vector(cpu=3_000, memory=2_048), np.int32),
            "hp_max_used_req": np.asarray(
                resource_vector(cpu=3_000, memory=2_048), np.int32),
        }
        for i in range(NODES):
            last = self._hb_last.get(i)
            if last is not None and vt - last < HB_KEEPALIVE_VS:
                continue
            rack = self._node_rack(i)
            if self._push(self.rack_feeders[rack], FrameType.STATE_PUSH,
                          {"kind": "node_usage", "name": f"dn{i}",
                           "usage_time": time.time()}, usage):
                self._hb_last[i] = vt

    def _apply_event(self, ev) -> None:
        """One churn event; a failed push goes to the retry queue (the
        tenant-sever backlog drains from here after heal)."""
        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.transport.wire import FrameType

        tenant = (ev.payload or {}).get("tenant") or self.scenario.tenants[0]
        feeder = self.tenant_feeders[tenant]
        if ev.kind == POD_ADD:
            req = np.asarray(resource_vector(
                cpu=int(ev.payload.get("cpu", 1_000)),
                memory=int(ev.payload.get("memory", 1_024))), np.int32)
            doc = {"kind": "pod_add", "name": ev.name,
                   "priority": int(ev.payload.get("priority", 1000)),
                   "quota": ev.payload.get("quota"),
                   "gang": ev.payload.get("gang"),
                   # journey-ledger ingest stamp (ISSUE 20): the drill
                   # harness is the manager-leg analog, so e2e latency
                   # under churn includes the deltasync hop
                   "arrival_ts": time.time()}
            doc = {k: v for k, v in doc.items() if v is not None}
            if self._push(feeder, FrameType.STATE_PUSH, doc,
                          {"requests": req}):
                self.live_pods.add(ev.name)
            else:
                self._unsent.append(ev)
        elif ev.kind == POD_DEL:
            if ev.name not in self.live_pods:
                # the matching add is still queued (or was never sent):
                # keep ordering by retrying the del after it
                self._unsent.append(ev)
                return
            if self._push(feeder, FrameType.STATE_PUSH,
                          {"kind": "pod_remove", "name": ev.name}):
                self.live_pods.discard(ev.name)
            else:
                self._unsent.append(ev)
        elif ev.kind == GANG_BURST:
            self._register_gang(ev.name, int(ev.payload["size"]))
            for m in range(int(ev.payload["size"])):
                member = type(ev)(ev.t, POD_ADD, f"{ev.name}-m{m}",
                                  dict(ev.payload, gang=ev.name))
                self._apply_event(member)

    def _register_gang(self, name: str, size: int) -> None:
        from koordinator_tpu.scheduler.scheduler import GangRecord

        record = GangRecord(name=name, min_member=size)
        self.gang_records[name] = record
        for r in self.replicas:
            if r.alive:
                r.scheduler.register_gang(Replica._gang_copy(record))

    def _drain_events(self, vt: float) -> None:
        retry, self._unsent = self._unsent, []
        for ev in retry:
            self._apply_event(ev)
        while (self._event_i < len(self.events)
               and self.events[self._event_i].t <= vt):
            self._apply_event(self.events[self._event_i])
            self._event_i += 1

    # -- scenario actions ----------------------------------------------------

    def _leader(self):
        for r in self.replicas:
            if r.alive and r.is_leader():
                return r
        return None

    def _any_alive(self):
        for r in self.replicas:
            if r.alive:
                return r
        return None

    def _apply_action(self, action: dict, vt: float) -> None:
        from koordinator_tpu.transport.faults import (
            PARTITION,
            FaultSchedule,
        )

        op = action["op"]
        # scripted adversarial actions count as injected faults too:
        # a kill/restart/reorg IS the drill's fault, and scenarios with
        # no storm and a short chaos window must not fail faults_fired
        # on the dice never landing
        if op not in ("heal", "end_storm", "checkpoint", "quota_restore",
                      "restart_dead"):
            self.injector.injected[f"action_{op}"] += 1
        if op == "storm":
            self.injector.start_storm(action["domains"],
                                      action.get("mode", PARTITION))
        elif op == "end_storm":
            self.injector.end_storm(action.get("domains"))
        elif op == "flaps":
            self.injector.schedule = FaultSchedule(
                FaultSchedule.flap_train(
                    action["domains"], vt + 0.1, action["up_s"],
                    action["down_s"], action["flaps"],
                    action.get("mode", PARTITION)))
        elif op == "heal":
            self.injector.heal()
        elif op == "checkpoint":
            target = self._leader() or self._any_alive()
            if target is not None:
                ckpt.save(self.ckpt_path, target.scheduler, target.sync)
        elif op == "kill_leader":
            target = self._leader() or self._any_alive()
            if target is not None:
                target.kill()
                self._dead.append(target)
        elif op == "restart_dead":
            self._restart_dead(action.get("restore", "snapshot"))
        elif op == "restart_manager":
            self._restart_manager()
        elif op == "quota_reorg":
            self._quota_reorg(float(action.get("scale", 0.5)))
        elif op == "quota_restore":
            self._quota_reorg(1.0)
        else:
            raise ValueError(f"unknown drill action {op!r}")

    def _restart_dead(self, restore: str) -> None:
        while self._dead:
            dead = self._dead.pop()
            dead.close()
            idx = self.replicas.index(dead)
            fresh = Replica(self, dead.name)
            if restore == "checkpoint" and os.path.exists(self.ckpt_path):
                stats = ckpt.restore(self.ckpt_path, fresh.scheduler,
                                     fresh.sync)
                self._restore_stats = stats
            self.replicas[idx] = fresh

    def _restart_manager(self) -> None:
        if self.manager is None:
            return
        self.manager["client"].close()
        self.manager = self._build_manager()

    def _quota_reorg(self, scale: float) -> None:
        """Rescale tenant maxes mid-flight (+ a burst child appears the
        first time): applied under each replica's round lock so no round
        sees a half-reorganized tree."""
        from koordinator_tpu.api.resources import resource_vector

        self.quota_scale = scale
        total = np.asarray(
            resource_vector(cpu=NODES * NODE_CPU,
                            memory=NODES * NODE_MEM), np.int64)
        scaled = np.maximum((total * scale).astype(np.int64), 0)
        for r in self.replicas:
            if not r.alive:
                continue
            with r.scheduler.lock:
                tree = r.scheduler.quota_tree
                if tree is None:
                    continue
                for tenant in self.scenario.tenants:
                    node = tree.nodes.get(tenant)
                    if node is not None:
                        node.max = scaled.copy()
                # the reorg also grows the tree mid-flight: a new
                # ROOT-level sibling (NOT a child of a pod-holding
                # tenant — a tenant with children aggregates request
                # from them and its own pods would starve forever)
                burst = "q-burst"
                if scale < 1.0 and burst not in tree.nodes:
                    tree.add(burst, min=np.zeros_like(total),
                             max=scaled // 2)
                    self._quota_extra.add(burst)

    # -- run loop ------------------------------------------------------------

    def _vt(self) -> float:
        return (time.monotonic() - self._t0) * self.time_scale

    def _tick(self, chaos_phase: bool) -> None:
        vt = self._vt()
        self.injector.advance_to(vt)
        self._drain_events(vt)
        self._heartbeats()
        if self.manager is not None:
            try:
                self.manager["loop"].tick()
            except Exception:
                pass
        t_round = time.monotonic()
        for r in list(self.replicas):
            if not r.alive:
                continue
            try:
                r.round()
            except Exception:
                # a replica that cannot round this tick (lease RPC lost
                # to a storm, transient solver error) retries next tick
                # — the real binaries' count-and-continue posture
                pass
        self.round_durations.append(time.monotonic() - t_round)
        self._observe_leadership()
        leader = self._leader()
        if leader is not None and leader.scheduler.degraded:
            self.degraded_s += TICK_S
        if (self.inject_at is not None and self.reconverged_at is None
                and self._fixpoint()):
            self.reconverged_at = time.monotonic()

    def _observe_leadership(self) -> None:
        from koordinator_tpu import metrics

        cur = None
        for r in self.replicas:
            if r.alive and r.is_leader():
                cur = r.name
                break
        if cur is not None:
            if self._last_leader is not None and cur != self._last_leader:
                self.failovers += 1
                metrics.leader_failovers_total.inc()
            self._last_leader = cur

    def _fixpoint(self) -> bool:
        """The reconvergence fixpoint: every live pod the service knows
        is bound on the current leader, the leader is not degraded, its
        watch view (and the manager's) caught up to the service rv, and
        no churn remains queued."""
        if self._unsent or self._event_i < len(self.events):
            return False
        leader = self._leader()
        if leader is None:
            return False
        want = set(self.service.pods)
        with leader.scheduler.lock:
            ok = (set(leader.scheduler.bound) == want
                  and not leader.scheduler.degraded)
        if not ok:
            return False
        if leader.sync.rv != self.service.rv:
            return False
        if (self.manager is not None
                and self.manager["sync"].rv != self.service.rv):
            return False
        return True

    def run(self) -> DrillVerdict:
        from koordinator_tpu import metrics

        metrics.drill_active.set(1.0,
                                 labels={"scenario": self.scenario.name})
        try:
            return self._run()
        finally:
            metrics.drill_active.set(0.0,
                                     labels={"scenario":
                                             self.scenario.name})
            self.close()

    def _run(self) -> DrillVerdict:
        from koordinator_tpu import metrics

        self._t0 = time.monotonic()
        self._register_nodes()
        phase_end = 0.0
        for phase in self.scenario.phases:
            phase_end += phase.duration_s
            self.injector.enabled = phase.chaos
            if phase.name == "inject":
                self.inject_at = time.monotonic()
            for action in phase.actions:
                self._apply_action(action, self._vt())
            while self._vt() < phase_end:
                self._tick(phase.chaos)
                time.sleep(TICK_S)
            if phase.name == "warmup":
                self._warmup_settle(phase_end)
                self._baseline = _counts()
        # verify overtime: the fixpoint may need a few extra beats past
        # the scripted verify window (wall budget, not virtual)
        deadline = time.monotonic() + 20.0
        while self.reconverged_at is None and time.monotonic() < deadline:
            self._tick(False)
            time.sleep(TICK_S)
        if (self.reconverged_at is not None and self.inject_at is not None):
            self.verdict.rto_s = self.reconverged_at - self.inject_at
            metrics.drill_recovery_duration_seconds.observe(
                self.verdict.rto_s)
        self._render_verdict()
        return self.verdict

    def _warmup_settle(self, boundary_vt: float) -> None:
        """End of warmup: every connection live, the first solve paid
        its jit compile, the watch views are caught up — the thread/fd
        baseline is honest only after all of that.  The virtual clock is
        FROZEN at the warmup boundary while settling, so a slow first
        jit compile can neither eat the inject/hold windows nor drain
        the churn trace early."""
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            self._t0 = time.monotonic() - boundary_vt / self.time_scale
            self._tick(False)
            leader = self._leader()
            if (leader is not None and not self._unsent
                    and leader.sync.rv == self.service.rv
                    and (self.manager is None
                         or self.manager["sync"].rv == self.service.rv)):
                with leader.scheduler.lock:
                    if not leader.scheduler.pending:
                        return
            time.sleep(TICK_S)
        raise RuntimeError("drill warmup never settled")

    # -- verdict -------------------------------------------------------------

    def _render_verdict(self) -> None:
        v = self.verdict
        v.degraded_s = self.degraded_s
        v.measurements["failovers"] = self.failovers
        v.measurements["faults_injected"] = dict(self.injector.injected)
        v.check("no_overcommit", not self.violations,
                "; ".join(self.violations[:3]) if self.violations
                else f"{sum(r.oracle_accepts for r in self.replicas)} "
                     f"accepts re-checked")
        fired = sum(self.injector.injected.values())
        v.check("faults_fired", fired > 0,
                f"{fired} faults/storms injected")
        v.check("reconverged", self.reconverged_at is not None,
                self._fixpoint_detail())
        v.check("gang_atomicity", *self._gang_atomicity())
        rto_ok = (v.rto_s is not None
                  and v.rto_s <= self.scenario.rto_budget_s)
        v.check("bounded_recovery", rto_ok,
                f"rto={v.rto_s if v.rto_s is None else round(v.rto_s, 2)}s"
                f" budget={self.scenario.rto_budget_s}s; "
                f"degraded={self.degraded_s:.2f}s"
                f"/{self.scenario.degraded_budget_s}s"
                if v.rto_s is not None else "never reconverged")
        if v.rto_s is not None:
            v.checks[-1].ok = (rto_ok and self.degraded_s
                               <= self.scenario.degraded_budget_s)
        v.check("no_leak", *self._leak_check())
        breaches = sum(1 for d in self.round_durations if d > 1.0)
        v.check("slo_burn",
                breaches <= self.scenario.slo_breach_budget,
                f"{breaches} slow round-ticks (>1s) / budget "
                f"{self.scenario.slo_breach_budget}")
        if self.scenario.expected_failovers:
            v.check("failover_observed",
                    self.failovers >= self.scenario.expected_failovers,
                    f"{self.failovers} observed, "
                    f">={self.scenario.expected_failovers} scripted")
        if self.scenario.name == "warm_restart":
            self._warm_restart_checks()
        leader = self._leader() or self._any_alive()
        if leader is not None:
            recorder = getattr(leader.scheduler, "flight_recorder", None)
            if recorder is not None:
                try:
                    v.flight = list(recorder.snapshot(8))
                except Exception:
                    pass
            ids = getattr(leader.scheduler, "_pod_trace_ids", None)
            if ids:
                v.trace_ids = dict(list(ids.items())[-10:])

    def _fixpoint_detail(self) -> str:
        leader = self._leader()
        if leader is None:
            return "no leader at verdict time"
        with leader.scheduler.lock:
            missing = sorted(set(self.service.pods)
                             - set(leader.scheduler.bound))[:5]
            return (f"missing={missing} degraded="
                    f"{leader.scheduler.degraded} "
                    f"rv={leader.sync.rv}/{self.service.rv} "
                    f"unsent={len(self._unsent)}")

    def _gang_atomicity(self):
        leader = self._leader() or self._any_alive()
        if leader is None:
            return False, "no replica alive"
        bad = []
        with leader.scheduler.lock:
            for name, record in self.gang_records.items():
                n = sum(1 for bp in leader.scheduler.bound.values()
                        if bp.gang == name)
                if 0 < n < record.min_member:
                    bad.append(f"{name}: {n}/{record.min_member}")
        return (not bad,
                "; ".join(bad) if bad
                else f"{len(self.gang_records)} gangs all-or-nothing")

    def _leak_check(self):
        if self._baseline is None:
            return False, "no baseline taken"
        bt, bf = self._baseline
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            t, f = _counts()
            # restarted replicas/manager swap old threads for new; small
            # fd slack covers the checkpoint file + fresh sockets
            if t <= bt + 2 and f <= bf + 4:
                return True, (f"threads {t} (base {bt}), fds {f} "
                              f"(base {bf})")
            time.sleep(0.1)
        t, f = _counts()
        return False, f"threads {t} vs {bt}, fds {f} vs {bf}"

    def _warm_restart_checks(self) -> None:
        """The warm-restart leg's two proofs: catch-up rode DELTAs (no
        full-snapshot reset on the restored replica) and the measured
        recovery beats a full-snapshot re-bootstrap of the SAME trace,
        run shadow (fresh scheduler, no elector, same warm kit)."""
        v = self.verdict
        restored = self._any_alive()
        stats = self._restore_stats or {}
        v.measurements["checkpoint_restore"] = stats
        delta_ok = (restored is not None and stats
                    and restored.binding.resets == 0)
        v.check("delta_catchup", delta_ok,
                f"restore={stats.get('nodes')}n/{stats.get('bound')}b/"
                f"{stats.get('pending')}p "
                f"snapshot_resets={getattr(restored, 'binding', None) and restored.binding.resets}")
        # interleaved min-of-N: recovery is a few ms of work under ~10ms
        # of shared spin-up noise (replica construct, connect, round
        # cadence), so a single trial per arm flips on scheduler
        # jitter.  The minimum is the honest estimator for "how fast
        # CAN this arm recover"; interleaving full-first means any
        # residual cache warming favors the full arm — conservative
        # for the claim under test.
        ckpt_times, full_times = [], []
        for trial in range(3):
            full_times.append(
                self._measure_recovery(restore=False, trial=trial))
            ckpt_times.append(
                self._measure_recovery(restore=True, trial=trial))
        rto_ckpt = min((t for t in ckpt_times if t is not None),
                       default=None)
        rto_full = min((t for t in full_times if t is not None),
                       default=None)
        v.measurements["rto_checkpoint_s"] = rto_ckpt
        v.measurements["rto_full_bootstrap_s"] = rto_full
        v.measurements["rto_checkpoint_trials_s"] = ckpt_times
        v.measurements["rto_full_bootstrap_trials_s"] = full_times
        ok = (rto_ckpt is not None and rto_full is not None
              and rto_ckpt < rto_full)
        v.check("warm_restart_beats_full", ok,
                f"checkpoint={rto_ckpt and round(rto_ckpt, 4)}s vs "
                f"full={rto_full and round(rto_full, 4)}s")

    def _measure_recovery(self, restore: bool, trial: int = 0):
        """Shadow recovery on the same trace: fresh scheduler (no
        elector, so it decides rounds immediately), either warm-started
        from the checkpoint + delta catch-up or full-snapshot
        re-bootstrapped, timed to the all-bound fixpoint."""
        shadow = Replica(self, f"shadow-{int(restore)}-{trial}")
        shadow.scheduler.elector = None
        want = set(self.service.pods)
        try:
            t0 = time.monotonic()
            if restore and os.path.exists(self.ckpt_path):
                ckpt.restore(self.ckpt_path, shadow.scheduler,
                             shadow.sync)
            shadow.client.ensure()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                try:
                    shadow.round()
                except Exception:
                    pass
                with shadow.scheduler.lock:
                    if set(shadow.scheduler.bound) >= want:
                        return time.monotonic() - t0
                time.sleep(0.005)
            return None
        finally:
            shadow.close()

    def close(self) -> None:
        for r in self.replicas + self._dead:
            try:
                r.close()
            except Exception:
                pass
        for feeder in (list(self.rack_feeders.values())
                       + list(self.tenant_feeders.values())):
            feeder.close()
        if self.manager is not None:
            self.manager["client"].close()
        self.server.stop()


def run_drill(scenario, seed: int, workdir: str,
              time_scale: float = 4.0, events=None) -> DrillVerdict:
    """One drill: scenario (name or Scenario), seed, verdict."""
    if isinstance(scenario, str):
        scenario = SCENARIOS[scenario]
    return DrillHarness(scenario, seed, workdir,
                        time_scale=time_scale, events=events).run()


def run_all(seed: int, workdir: str,
            time_scale: float = 4.0) -> dict[str, DrillVerdict]:
    """The full catalog at one seed (the soak sweep's unit)."""
    return {name: run_drill(name, seed, workdir, time_scale=time_scale)
            for name in SCENARIOS}
