"""The drill verdict engine: machine-checkable pass/fail per drill.

Every drill ends in the same cross-cutting assertions, whatever was
injected (the failure mode changes; the invariants must not):

- ``no_overcommit``   — the bind oracle recorded zero violations at any
  point, including mid-storm and mid-failover;
- ``faults_fired``    — the scenario actually injected something (a
  drill whose schedule never fired proved nothing);
- ``reconverged``     — post-heal fixpoint: every live pod from the
  churn trace is bound on the current leader, the scheduler left
  degraded mode, and every watch view caught up to the service rv;
- ``gang_atomicity``  — no partially-bound gang survives: for every
  registered gang, the leader's bound member count is 0 or
  ≥ min_member (the all-or-nothing contract held across the failover);
- ``bounded_recovery``— the measured RTO (inject → fixpoint) is inside
  the scenario's budget;
- ``no_leak``         — thread and fd counts settle back to the
  post-warmup baseline;
- ``slo_burn``        — SLO breaches observed during the drill stay
  within the scenario's budget.

A verdict is GREEN iff every check passed.  ``flight`` joins the
verdict to the leader's flight-recorder tail and pod trace ids so a RED
drill replays with full context (the seed alone reproduces the run;
the flight records say where it went wrong).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class Check:
    name: str
    ok: bool
    detail: str = ""

    def render(self) -> str:
        return f"[{'PASS' if self.ok else 'FAIL'}] {self.name}" + (
            f" — {self.detail}" if self.detail else "")


@dataclasses.dataclass
class DrillVerdict:
    """One drill's outcome: scenario + seed identify the exact replay;
    checks carry the evidence."""

    scenario: str
    seed: int
    checks: list[Check] = dataclasses.field(default_factory=list)
    #: inject → reconvergence fixpoint, wall seconds (None: no
    #: injection phase measured, e.g. a pure-churn control run)
    rto_s: float | None = None
    #: total wall seconds the leader spent in degraded mode
    degraded_s: float = 0.0
    #: flight-recorder tail + pod trace ids from the leader at verdict
    #: time (diagnosis context for a RED drill)
    flight: list = dataclasses.field(default_factory=list)
    trace_ids: dict = dataclasses.field(default_factory=dict)
    #: free-form measurements (checkpoint vs full-bootstrap RTO, storm
    #: counts, failover count, ...)
    measurements: dict = dataclasses.field(default_factory=dict)

    @property
    def green(self) -> bool:
        return all(c.ok for c in self.checks)

    def check(self, name: str, ok: bool, detail: str = "") -> Check:
        c = Check(name, bool(ok), detail)
        self.checks.append(c)
        return c

    def failed(self) -> list[Check]:
        return [c for c in self.checks if not c.ok]

    def to_doc(self) -> dict:
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "green": self.green,
            "rto_s": self.rto_s,
            "degraded_s": self.degraded_s,
            "checks": [{"name": c.name, "ok": c.ok, "detail": c.detail}
                       for c in self.checks],
            "measurements": dict(self.measurements),
        }

    def render(self) -> str:
        head = (f"drill {self.scenario} seed={self.seed}: "
                f"{'GREEN' if self.green else 'RED'}"
                + (f" rto={self.rto_s:.2f}s" if self.rto_s is not None
                   else ""))
        lines = [head] + ["  " + c.render() for c in self.checks]
        if not self.green and self.flight:
            lines.append("  flight tail:")
            lines.extend(f"    {r}" for r in self.flight[-5:])
        return "\n".join(lines)
