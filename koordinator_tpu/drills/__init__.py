"""Adversarial failure drills: deterministic, seeded, multi-phase
scenarios against the full socket stack with machine-checkable
verdicts (docs/robustness.md).

- :mod:`scenarios` — the drill catalog as declarative data;
- :mod:`engine` — the orchestrator (replicas, feeders, manager, churn,
  virtual clock, fixpoint + RTO measurement);
- :mod:`verdict` — the per-drill check taxonomy;
- :mod:`checkpoint` — the scheduler's warm-restart snapshot (save /
  restore / delta catch-up).
"""

from koordinator_tpu.drills.checkpoint import (
    CHECKPOINT_VERSION,
    CheckpointWriter,
    capture,
    restore,
    restore_into,
    save,
)
from koordinator_tpu.drills.engine import DrillHarness, run_all, run_drill
from koordinator_tpu.drills.scenarios import (
    SCENARIOS,
    DrillEvent,
    Phase,
    Scenario,
    churn_trace,
)
from koordinator_tpu.drills.verdict import Check, DrillVerdict

__all__ = [
    "CHECKPOINT_VERSION",
    "Check",
    "CheckpointWriter",
    "DrillEvent",
    "DrillHarness",
    "DrillVerdict",
    "Phase",
    "SCENARIOS",
    "Scenario",
    "capture",
    "churn_trace",
    "restore",
    "restore_into",
    "run_all",
    "run_drill",
    "save",
]
