"""Host-side device allocation bookkeeping (deviceshare Reserve/Unreserve).

Counterpart of the reference's nodeDevice cache updates
(pkg/scheduler/plugins/deviceshare/device_cache.go) and the
``scheduling.koordinator.sh/device-allocated`` annotation emitted at PreBind
(apis/extension/device_share.go:32): tracks which device minors each pod
holds, mirrors commits into the device tensors, and renders the annotation
payload for the node agent's GPU env-inject hook.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.deviceshare import (
    DEV_BINPACK,
    DeviceState,
    allocate_on_node,
    commit_allocation,
    release_allocation,
    split_request,
)


@dataclasses.dataclass
class DeviceAllocation:
    pod: str
    node: str
    device_type: str
    minors: list[int]
    core: int         # per-device core charged
    memory: int       # per-device memory charged


class DeviceManager:
    """Per-type device tensors + pod allocation records."""

    def __init__(self) -> None:
        self._state: dict[str, DeviceState] = {}
        self._node_rows: dict[str, dict[str, int]] = {}  # per device type
        self._allocs: dict[tuple[str, str], list[DeviceAllocation]] = {}
        #: raw per-node inventory, kept so nodes can register incrementally
        #: (Device CR sync delivers one node at a time)
        self._raw: dict[str, dict[str, list[dict]]] = {}

    def register(
        self, device_type: str, node_names: list[str], per_node_devices: list[list[dict]]
    ) -> None:
        self._state[device_type] = DeviceState.build(per_node_devices)
        self._node_rows[device_type] = {n: i for i, n in enumerate(node_names)}
        self._raw[device_type] = {
            n: list(d) for n, d in zip(node_names, per_node_devices)
        }

    def register_node_devices(
        self, device_type: str, node: str, devices: list[dict]
    ) -> None:
        """Incremental Device-CR sync: (re)register one node's inventory,
        rebuilding the type tensors and re-committing live allocations so
        an inventory update can't silently zero out held capacity."""
        raw = self._raw.setdefault(device_type, {})
        if raw.get(node) == list(devices):
            return   # unchanged heartbeat: skip the O(cluster) rebuild
        raw[node] = list(devices)
        self._rebuild_type(device_type)

    def deregister_node_devices(self, device_type: str, node: str) -> None:
        """Remove one node's row for a type entirely (the type vanished
        from the node's full inventory).  POPPING rather than storing an
        empty list keeps live state identical to what bootstrap replay
        builds — a replayed doc without the type registers nothing, so
        the live side must hold nothing (tested by the randomized
        live-vs-replay parity suite)."""
        raw = self._raw.get(device_type)
        if raw is None or node not in raw:
            return
        raw.pop(node)
        self._rebuild_type(device_type)

    @staticmethod
    def _live_minors(a: DeviceAllocation, dev, row: int) -> list[int]:
        """The subset of a record's minors present in the CURRENT
        inventory.  Records are never pruned destructively: a transient
        inventory clear (a devices-omitting node re-upsert racing the
        koordlet heartbeat that repairs it) must re-commit the grant
        when the inventory returns; a minor that is really gone simply
        never re-commits and is filtered from annotations/release."""
        return [m for m in a.minors
                if m < dev.shape[1] and bool(dev.valid[row, m])]

    def _rebuild_type(self, device_type: str) -> None:
        """Rebuild one type's tensors from raw inventory and re-commit
        the live part of every allocation record (shared by inventory
        updates and node removal)."""
        raw = self._raw.get(device_type)
        if not raw:
            # last node of the type gone: drop the type entirely rather
            # than keeping empty rows around
            self._raw.pop(device_type, None)
            self._state.pop(device_type, None)
            self._node_rows.pop(device_type, None)
            return
        names = sorted(raw)
        self._state[device_type] = DeviceState.build([raw[n] for n in names])
        self._node_rows[device_type] = {n: i for i, n in enumerate(names)}
        for (pod, pnode), allocs in self._allocs.items():
            row = self._node_rows[device_type].get(pnode)
            if row is None:
                continue
            for a in allocs:
                if a.device_type != device_type:
                    continue
                dev = self._state[device_type]
                live = self._live_minors(a, dev, row)
                if not live:
                    continue
                sel = np.zeros(dev.shape[1], bool)
                sel[live] = True
                self._state[device_type] = commit_allocation(
                    dev, jnp.int32(row), jnp.asarray(sel),
                    jnp.int32(a.core), jnp.int32(a.memory),
                )

    def remove_node(self, name: str) -> None:
        """Drop one node's inventory rows across all types (NODE_REMOVE):
        registering empty lists instead would leave a permanent zero row
        per removed node in every type tensor — unbounded growth under
        node churn.  Allocation RECORDS stay: a node flap (NODE_REMOVE
        then re-upsert with devices, e.g. a kubelet restart while pods
        keep running) must re-commit held devices on the rebuild, or a
        second pod gets granted devices the first still uses — the same
        double-grant CPUManager.remove_node stashes orphans against.
        Records are purged when the pod itself is released (pod_remove
        reaches release()), so they are bounded by live pods."""
        for dev_type in list(self._raw):
            self.deregister_node_devices(dev_type, name)

    def registered_types_for(self, node: str) -> set[str]:
        """Device types this node has inventory registered under — lets
        a full-inventory refresh clear types that disappeared."""
        return {dev_type for dev_type, raw in self._raw.items()
                if node in raw}

    def clear(self) -> None:
        """Drop ALL inventory and allocation state — snapshot-resync
        restart semantics (SchedulerBinding.reset): types absent from the
        replayed snapshot must not survive as live allocatable tensors."""
        self._state.clear()
        self._node_rows.clear()
        self._allocs.clear()
        self._raw.clear()

    def state(self, device_type: str) -> DeviceState | None:
        return self._state.get(device_type)

    def allocate(
        self,
        device_type: str,
        node: str,
        pod: str,
        core: int,
        memory: int = 0,
        strategy: int = DEV_BINPACK,
    ) -> list[int] | None:
        """Pick + commit devices for a pod; returns device minors or None."""
        dev = self._state.get(device_type)
        row = self._node_rows.get(device_type, {}).get(node)
        if dev is None or row is None:
            return None
        # Re-allocate for the same pod/type replaces the old grant (a retried
        # bind cycle must not double-charge); restore it if the retry fails.
        old_records = self._allocs.get((pod, node), [])
        old_same_type = [a for a in old_records if a.device_type == device_type]
        if old_same_type:
            old_state = dev
            for a in old_same_type:
                self._release_one(node, a)
                old_records.remove(a)
            dev = self._state[device_type]
        n_whole, per_core, per_mem = split_request(core, memory)
        sel, ok = allocate_on_node(
            dev, jnp.int32(row), jnp.int32(n_whole),
            jnp.int32(per_core), jnp.int32(per_mem), strategy=strategy,
        )
        if not bool(ok):
            if old_same_type:
                self._state[device_type] = old_state
                self._allocs.setdefault((pod, node), []).extend(old_same_type)
            return None
        self._state[device_type] = commit_allocation(
            dev, jnp.int32(row), sel, jnp.int32(per_core), jnp.int32(per_mem)
        )
        minors = sorted(int(i) for i in np.flatnonzero(np.asarray(sel)))
        self._allocs.setdefault((pod, node), []).append(
            DeviceAllocation(pod, node, device_type, minors, per_core, per_mem)
        )
        return minors

    def _release_one(self, node: str, alloc: DeviceAllocation) -> None:
        dev = self._state.get(alloc.device_type)
        row = self._node_rows.get(alloc.device_type, {}).get(node)
        if dev is None or row is None:
            return
        # only the live minors were committed at the last rebuild, so
        # only they release — a dead minor in the record must not drive
        # a nonexistent device's free counter (or the mask index) wrong
        live = self._live_minors(alloc, dev, row)
        if not live:
            return
        sel = np.zeros(dev.shape[1], bool)
        sel[live] = True
        self._state[alloc.device_type] = release_allocation(
            dev, jnp.int32(row), jnp.asarray(sel),
            jnp.int32(alloc.core), jnp.int32(alloc.memory),
        )

    def restore(self, node: str, pod: str, devices: dict) -> bool:
        """Replay a pod's existing device grants at startup from the
        device-allocated annotation payload
        ({type: [{"minor": m, "resources": {"core": c, "memory": b}}]}).
        Idempotent (a re-list that replays the same pod twice releases the
        previous records first) and defensive: annotation data is external,
        so unknown types and out-of-range minors are skipped rather than
        corrupting device accounting.  Returns True when anything landed."""
        self.release(node, pod)
        restored = False
        if not isinstance(devices, dict):
            return False
        for device_type, grants in devices.items():
            dev = self._state.get(device_type)
            row = self._node_rows.get(device_type, {}).get(node)
            if dev is None or row is None or not isinstance(grants, list):
                continue
            for g in grants:
                try:
                    minor = int(g.get("minor", -1))
                    res = g.get("resources", {}) or {}
                    core = int(res.get("core", 0))
                    memory = int(res.get("memory", 0))
                except (TypeError, ValueError, AttributeError):
                    continue
                dev = self._state[device_type]
                # bounds AND the row's valid mask: device capacities pad to
                # a power of two; a stale minor in the padding would drive
                # a nonexistent device's free counter negative
                if not (0 <= minor < dev.shape[1]
                        and bool(dev.valid[row, minor])):
                    continue
                sel = np.zeros(dev.shape[1], bool)
                sel[minor] = True
                self._state[device_type] = commit_allocation(
                    dev, jnp.int32(row), jnp.asarray(sel),
                    jnp.int32(core), jnp.int32(memory),
                )
                self._allocs.setdefault((pod, node), []).append(
                    DeviceAllocation(pod, node, device_type, [minor],
                                     core, memory))
                restored = True
        return restored

    def release(self, node: str, pod: str) -> None:
        for alloc in self._allocs.pop((pod, node), []):
            self._release_one(node, alloc)

    def device_allocated_annotation(self, node: str, pod: str) -> dict | None:
        """The device-allocated annotation payload (device_share.go:32).
        Reports only minors present in the CURRENT inventory: records
        survive transient inventory clears undamaged, but a consumer
        (GPU env inject) must never see a device that is gone."""
        allocs = self._allocs.get((pod, node))
        if not allocs:
            return None
        out: dict = {}
        for a in allocs:
            dev = self._state.get(a.device_type)
            row = self._node_rows.get(a.device_type, {}).get(node)
            minors = (self._live_minors(a, dev, row)
                      if dev is not None and row is not None else [])
            if minors:
                out.setdefault(a.device_type, []).extend(
                    {"minor": m,
                     "resources": {"core": a.core, "memory": a.memory}}
                    for m in minors)
        return out or None
